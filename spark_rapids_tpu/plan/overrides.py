"""Lowering + TpuOverrides: the plan-rewrite/tagging engine.

Reference (SURVEY.md §2.1, §3.2): GpuOverrides wraps every plan node in
a RapidsMeta, tags nodes that cannot run on the accelerator with
reasons (RapidsMeta.willNotWorkOnGpu / tagForGpu,
RapidsMeta.scala:189-216), converts the tagged tree, prints
`spark.rapids.sql.explain`, and GpuTransitionOverrides inserts
transitions.  Here:

* `lower()` turns the logical plan into dual-backend physical execs
  while recording, per node, the expressions it evaluates;
* `TpuOverrides.apply()` tags each node — per-exec conf key
  ``spark.rapids.sql.exec.<Name>``, per-expression key
  ``spark.rapids.sql.expression.<Name>`` plus a device-capability
  check — assigns device/host backends, inserts `BackendSwitchExec`
  at boundaries, and renders the explain tree (``*`` = on TPU,
  ``!`` = falls back, with reasons).
"""
from __future__ import annotations

from dataclasses import dataclass, field

from spark_rapids_tpu import types as T
from spark_rapids_tpu.conf import TpuConf
from spark_rapids_tpu.exec import (CrossJoinExec, FilterExec,
                                   GlobalLimitExec, HashAggregateExec,
                                   HashPartitioning, JoinExec,
                                   ProjectExec, RoundRobinPartitioning,
                                   ShuffleExchangeExec, SortExec, UnionExec,
                                   WindowExec)
from spark_rapids_tpu.exec.core import ExecCtx, PlanNode
from spark_rapids_tpu.exec.transitions import BackendSwitchExec
from spark_rapids_tpu.expr.core import (Alias, Expression, col, output_name)
from spark_rapids_tpu.expr.window import WindowExpression
from spark_rapids_tpu.plan import logical as L

__all__ = ["PlannedNode", "lower", "TpuOverrides"]


@dataclass
class PlannedNode:
    """Physical exec + planning metadata (the RapidsMeta analog)."""
    exec_node: PlanNode
    exprs: list = field(default_factory=list)
    children: list = field(default_factory=list)
    backend: str = "device"
    reasons: list = field(default_factory=list)

    @property
    def name(self) -> str:
        return type(self.exec_node).__name__

    def will_not_work(self, reason: str) -> None:
        if reason not in self.reasons:
            self.reasons.append(reason)


# ---------------------------------------------------------------------------
# lowering
# ---------------------------------------------------------------------------

def lower(node: L.LogicalPlan, conf: TpuConf) -> PlannedNode:
    if isinstance(node, L.Scan):
        return PlannedNode(node.exec_node)
    if isinstance(node, L.Filter):
        c = lower(node.child, conf)
        from spark_rapids_tpu.udf import maybe_compile_udfs
        cond = maybe_compile_udfs([node.condition], conf)[0]
        ex = FilterExec(cond, c.exec_node)
        return PlannedNode(ex, [cond], [c])
    if isinstance(node, L.Project):
        return _lower_project(node, conf)
    if isinstance(node, L.Aggregate):
        return _lower_aggregate(node, conf)
    if isinstance(node, L.Join):
        lc = lower(node.left, conf)
        rc = lower(node.right, conf)
        if node.how in ("inner", "left", "right", "semi", "anti"):
            lc = _aqe_join_exchange(lc, node.left_on, conf)
            rc = _aqe_join_exchange(rc, node.right_on, conf)
        lc, rc = (_aqe_join_reader(c, conf) for c in (lc, rc))
        if node.how == "cross":
            ex = CrossJoinExec(lc.exec_node, rc.exec_node, node.condition)
        elif conf.mesh_device_count > 1 and node.how != "full" \
                and not _schema_has_arrays(lc.exec_node, rc.exec_node):
            # mesh mode: replicated-build join, one probe shard per
            # device (the GpuBroadcastHashJoinExec analog over ICI)
            from spark_rapids_tpu.conf import MESH_JOIN_BUILD_THRESHOLD
            from spark_rapids_tpu.exec.mesh_exec import MeshJoinExec
            ex = MeshJoinExec(lc.exec_node, rc.exec_node, node.left_on,
                              node.right_on, node.how,
                              conf.mesh_device_count, node.condition,
                              build_threshold_bytes=conf.get(
                                  MESH_JOIN_BUILD_THRESHOLD))
        else:
            ex = JoinExec(lc.exec_node, rc.exec_node, node.left_on,
                          node.right_on, node.how, node.condition)
        exprs = list(node.left_on) + list(node.right_on)
        if node.condition is not None:
            exprs.append(node.condition)
        # meta children MUST mirror exec children: JoinExec runs a right
        # join side-swapped, and a tree-rewrite pass (coalesce /
        # transition insertion) reassigns exec children from meta order —
        # un-swapped metas silently flipped the join back (latent until a
        # right join with asymmetric schemas hit a rewrite pass)
        metas = [rc, lc] if getattr(ex, "_swapped", False) else [lc, rc]
        return PlannedNode(ex, exprs, metas)
    if isinstance(node, L.Sort):
        c = lower(node.child, conf)
        orders = _mesh_sort_orders(node.orders, c.exec_node, conf)
        if orders is not None:
            from spark_rapids_tpu.exec.mesh_region import MeshSortExec
            ex = MeshSortExec(orders, c.exec_node, conf.mesh_device_count)
        else:
            ex = SortExec(node.orders, c.exec_node, global_sort=True)
        return PlannedNode(ex, [], [c])
    if isinstance(node, L.Limit):
        if isinstance(node.child, L.Sort):
            # ORDER BY + LIMIT under the mesh: distributed TopN — the
            # broadcast sort keeps only the first n rows on device 0,
            # and the GlobalLimitExec above drains partitions in order
            # so the result passes through with no cross-device gather
            sc = lower(node.child.child, conf)
            orders = _mesh_sort_orders(node.child.orders, sc.exec_node,
                                       conf)
            if orders is not None:
                from spark_rapids_tpu.exec.mesh_region import MeshSortExec
                ms = MeshSortExec(orders, sc.exec_node,
                                  conf.mesh_device_count, limit=node.n)
                smeta = PlannedNode(ms, [], [sc])
                return PlannedNode(GlobalLimitExec(node.n, ms), [],
                                   [smeta])
            c = PlannedNode(SortExec(node.child.orders, sc.exec_node,
                                     global_sort=True), [], [sc])
        else:
            c = lower(node.child, conf)
        return PlannedNode(GlobalLimitExec(node.n, c.exec_node), [], [c])
    if isinstance(node, L.Union):
        cs = [lower(i, conf) for i in node.inputs]
        return PlannedNode(UnionExec([c.exec_node for c in cs]), [], cs)
    if isinstance(node, L.Window):
        c = lower(node.child, conf)
        # partition on the first expression's spec; WindowExec itself
        # validates that every expression shares it (window.py)
        first = node.window_exprs[0]
        inner = first.children[0] if isinstance(first, Alias) else first
        if _mesh_window_ok(c.exec_node, inner.spec, conf,
                           node.window_exprs):
            # the mesh window exchanges (or gathers) in-program, so no
            # planner exchange is inserted on this path
            return _stack_window_execs(c, node.window_exprs, False,
                                       conf=conf, mesh=True)
        cur, keys_partitioned = _ensure_window_distribution(
            c, inner.spec, conf)
        return _stack_window_execs(cur, node.window_exprs,
                                   keys_partitioned)
    if isinstance(node, L.Expand):
        c = lower(node.child, conf)
        from spark_rapids_tpu.exec.expand import ExpandExec
        ex = ExpandExec(node.projections, c.exec_node)
        exprs = [e for proj in node.projections for e in proj]
        return PlannedNode(ex, exprs, [c])
    if isinstance(node, L.Generate):
        c = lower(node.child, conf)
        from spark_rapids_tpu.exec.generate import GenerateExec
        ex = GenerateExec(node.generator, c.exec_node, outer=node.outer,
                          pos=node.pos, output_names=node.output_names)
        return PlannedNode(ex, [node.generator], [c])
    if isinstance(node, L.Repartition):
        c = lower(node.child, conf)
        if node.keys and conf.mesh_device_count > 1 \
                and not _schema_has_arrays(c.exec_node):
            # any hash-partition count rides the mesh collective (rows
            # route to device pid % mesh; round-2 verdict dropped the
            # num_partitions == deviceCount gate)
            from spark_rapids_tpu.exec.mesh_exec import MeshExchangeExec
            ex = MeshExchangeExec(node.keys, c.exec_node,
                                  conf.mesh_device_count,
                                  num_partitions=node.num_partitions)
            return PlannedNode(ex, list(node.keys), [c])
        if node.keys:
            part = HashPartitioning(node.keys, node.num_partitions)
        else:
            part = RoundRobinPartitioning(node.num_partitions)
        ex = ShuffleExchangeExec(part, c.exec_node)
        # NOTE: explicit repartition(n) is never coalesced below n
        # (Spark does not AQE-coalesce user-requested counts); only
        # planner-inserted shuffles (aggregation) get the coalescing
        # reader.  A downstream JOIN may still wrap this exchange in a
        # split-only skew reader (_aqe_join_reader), which can raise —
        # never lower — the effective partition count.  The map-side
        # tiny-input coalescer obeys the same contract: flag the
        # exchange so a sub-advisory map side still keeps all n
        # partitions non-degenerate (REPARTITION_BY_NUM).
        ex._no_map_coalesce = True
        return PlannedNode(ex, list(node.keys), [c])
    if isinstance(node, L.MapInPandas):
        from spark_rapids_tpu.exec.python_exec import MapInPandasExec
        c = lower(node.child, conf)
        ex = MapInPandasExec(node.fn, node.out_schema, c.exec_node)
        return PlannedNode(ex, [], [c])
    if isinstance(node, L.FlatMapGroupsInPandas):
        from spark_rapids_tpu.exec.python_exec import \
            FlatMapGroupsInPandasExec
        c = _cluster_on_keys(lower(node.child, conf), node.keys, conf)
        ex = FlatMapGroupsInPandasExec(
            [output_name(k) for k in node.keys], node.fn, node.out_schema,
            c.exec_node)
        return PlannedNode(ex, list(node.keys), [c])
    if isinstance(node, L.AggregateInPandas):
        from spark_rapids_tpu.exec.python_exec import AggregateInPandasExec
        c = _cluster_on_keys(lower(node.child, conf), node.keys, conf)
        ex = AggregateInPandasExec([output_name(k) for k in node.keys],
                                   node.udfs, c.exec_node)
        return PlannedNode(ex, list(node.keys), [c])
    if isinstance(node, L.FlatMapCoGroupsInPandas):
        from spark_rapids_tpu.exec.python_exec import \
            FlatMapCoGroupsInPandasExec
        lc = _cluster_on_keys(lower(node.left, conf), node.left_keys, conf,
                              force=True)
        rc = _cluster_on_keys(lower(node.right, conf), node.right_keys,
                              conf, force=True)
        ex = FlatMapCoGroupsInPandasExec(
            [output_name(k) for k in node.left_keys],
            [output_name(k) for k in node.right_keys],
            node.fn, node.out_schema, lc.exec_node, rc.exec_node)
        return PlannedNode(ex, list(node.left_keys) + list(node.right_keys),
                           [lc, rc])
    if isinstance(node, L.DataWrite):
        from spark_rapids_tpu.exec.write_exec import CreateDataWriteExec
        c = lower(node.child, conf)
        ex = CreateDataWriteExec(c.exec_node, node.path, node.fmt,
                                 partition_by=node.partition_by,
                                 options=node.options)
        return PlannedNode(ex, [], [c])
    raise TypeError(f"cannot lower {node!r}")


def _cluster_on_keys(c: PlannedNode, keys: list, conf: TpuConf,
                     force: bool = False) -> PlannedNode:
    """Hash-exchange on the grouping keys so every group lands wholly in
    one partition (Spark's ClusteredDistribution requirement for the
    grouped pandas execs); keyless grouped-agg collapses to a single
    partition.  ``force`` exchanges even single-partition children —
    cogrouped sides must agree on partition COUNT and router, not just
    co-locate groups."""
    from spark_rapids_tpu.exec.partitioning import SinglePartitioning
    nparts = c.exec_node.num_partitions(ExecCtx(backend="host"))
    if not keys:
        if nparts <= 1:
            return c
        exch = ShuffleExchangeExec(SinglePartitioning(), c.exec_node)
        return PlannedNode(exch, [], [c])
    if nparts <= 1 and not force:
        return c
    part = HashPartitioning(list(keys), conf.shuffle_partitions)
    exch = ShuffleExchangeExec(part, c.exec_node)
    return PlannedNode(exch, list(keys), [c])


def _mesh_sort_orders(orders, exec_node: PlanNode, conf: TpuConf):
    """Resolved SortOrders when this sort can run as a mesh broadcast
    sort, else None (non-column sort keys, array payloads, or no mesh
    configured keep the in-process global sort)."""
    if conf.mesh_device_count <= 1 or _schema_has_arrays(exec_node):
        return None
    from spark_rapids_tpu.exec.sortexec import resolve_orders
    try:
        return resolve_orders(orders, exec_node.output_schema)
    # enginelint: disable=RL001 (unresolvable sort key falls back to the in-process global sort)
    except Exception:  # noqa: BLE001 - any unresolvable key falls back
        return None


def _schema_has_arrays(*nodes: PlanNode) -> bool:
    """Mesh programs (shard_map bucketize/canonicalize, shard stacking)
    do not handle array payload columns yet; plans carrying them take
    the in-process path."""
    return any(isinstance(f.data_type, T.ArrayType)
               for n in nodes for f in n.output_schema)


def _aqe_join_exchange(c: PlannedNode, keys, conf: TpuConf) -> PlannedNode:
    """Hash-exchange one join side on its join keys, marked
    ``_aqe_inserted`` so the adaptive layer owns it: the stage-boundary
    pass puts a re-plan barrier above the join, and the re-optimizer may
    coalesce its reduce side, switch it to a broadcast, or drop the
    probe copy entirely.  Gated on the shuffled-hash-join conf (the
    engine's static join needs no co-partitioning) and skipped under
    the mesh (joins ride MeshJoinExec there) or when the side already
    exchanges on these keys (explicit repartition)."""
    from spark_rapids_tpu.exec.exchange import (ADAPTIVE_ENABLED,
                                                ShuffleExchangeExec)
    from spark_rapids_tpu.plan.adaptive import AQE_SHUFFLED_JOIN
    if not keys or not conf.get(AQE_SHUFFLED_JOIN) or \
            not conf.get(ADAPTIVE_ENABLED) or conf.mesh_device_count > 1 \
            or isinstance(c.exec_node, ShuffleExchangeExec):
        return c
    ex = ShuffleExchangeExec(
        HashPartitioning(list(keys), conf.shuffle_partitions), c.exec_node)
    ex._aqe_inserted = True
    return PlannedNode(ex, list(keys), [c])


def _aqe_join_reader(c: PlannedNode, conf: TpuConf) -> PlannedNode:
    """Joins read shuffles through an adaptive reader (Spark's
    OptimizeSkewedJoin scope): join sides have per-row semantics, so
    fanning a skewed hash partition out into several reader groups is
    safe — the stream side probes per batch and a build side is fully
    materialized either way.  Coalescing is allowed ONLY for exchanges
    the adaptive layer itself inserted (``_aqe_join_exchange``): an
    explicit ``repartition(n)`` promises n partitions, never REDUCED
    below the user's request (REPARTITION_BY_NUM contract; a skewed
    partition may still fan out, which preserves the requested
    parallelism floor), while an AQE-inserted exchange carries no user
    promise and small reduce partitions may merge to the advisory
    size."""
    from spark_rapids_tpu.exec.exchange import (ADAPTIVE_ENABLED,
                                                AdaptiveShuffleReaderExec,
                                                ShuffleExchangeExec)
    if not conf.get(ADAPTIVE_ENABLED) or \
            not isinstance(c.exec_node, ShuffleExchangeExec):
        return c
    reader = AdaptiveShuffleReaderExec(
        c.exec_node, allow_skew_split=True,
        allow_coalesce=getattr(c.exec_node, "_aqe_inserted", False))
    return PlannedNode(reader, [], [c])


def _split_window_exprs(exprs):
    """Separate window expressions out of a projection list.

    Handles windows at ANY depth: nested occurrences (e.g.
    ``x * 100 / sum(x).over(spec)``) are hoisted into generated columns
    and replaced by references (round-1 advisor finding: the old code
    only split top-level windows, letting nested ones crash projection
    eval)."""
    plain, windows = [], []
    counter = [0]

    def hoist(node):
        if isinstance(node, WindowExpression):
            name = f"_we{counter[0]}"
            counter[0] += 1
            windows.append(node.alias(name))
            return col(name)
        return node

    for e in exprs:
        inner = e.children[0] if isinstance(e, Alias) else e
        if isinstance(inner, WindowExpression):
            # generated name + re-alias: naming the appended window column
            # after an existing child column would shadow it at bind time
            name = f"_we{counter[0]}"
            counter[0] += 1
            windows.append(inner.alias(name))
            plain.append(col(name).alias(output_name(e)))
        else:
            plain.append(e.transform_up(hoist))
    return plain, windows


def _split_pandas_udfs(exprs):
    """Hoist PandasUDF occurrences (any depth) into generated columns
    evaluated by one ArrowEvalPythonExec (reference: Spark plans
    ArrowEvalPython below the projection)."""
    from spark_rapids_tpu.exec.python_exec import PandasUDF
    udfs, counter = [], [0]

    def fresh(u):
        if any(isinstance(s, PandasUDF) for c in u.children
               for s in c.walk()):
            raise ValueError(
                "nested pandas UDFs are not supported; materialize the "
                "inner UDF in a separate select() first")
        name = f"_pyudf{counter[0]}"   # ALWAYS a generated name: reusing a
        counter[0] += 1                # child column name would shadow it
        udfs.append((name, u))
        return name

    def hoist(n):
        if isinstance(n, PandasUDF):
            return col(fresh(n))
        return n

    plain = []
    for e in exprs:
        inner = e.children[0] if isinstance(e, Alias) else e
        if isinstance(inner, PandasUDF):
            plain.append(col(fresh(inner)).alias(output_name(e)))
        else:
            plain.append(e.transform_up(hoist))
    return plain, udfs


def _window_key_names(keys) -> tuple | None:
    """Canonical column-name tuple for a key list, or None when any key
    is not a plain column reference (structural comparison is then not
    attempted and an exchange is inserted conservatively)."""
    from spark_rapids_tpu.expr.core import UnresolvedAttribute
    names = []
    for k in keys:
        if isinstance(k, Alias):
            k = k.children[0]
        if not isinstance(k, UnresolvedAttribute):
            return None
        names.append(k.name)
    return tuple(names)


def _mesh_window_ok(child_exec: PlanNode, spec, conf: TpuConf,
                    windows) -> bool:
    """True when this spec's window functions lower to MeshWindowExec:
    a mesh is active, the conf gate is on, the spec has partition or
    order keys (a fully global unordered window keeps the in-process
    bounded-memory stream — gathering it would be a regression), the
    child schema is mesh-shardable, and no expression is a pandas
    window UDF (a mixed native+UDF spec falls back entirely so both
    halves see the same distribution)."""
    from spark_rapids_tpu.conf import MESH_WINDOW_ENABLED
    if conf.mesh_device_count <= 1 or not conf.get(MESH_WINDOW_ENABLED):
        return False
    if not (spec.partition_by or spec.order_by):
        return False
    if _schema_has_arrays(child_exec):
        return False
    from spark_rapids_tpu.exec.python_exec import PandasWindowUDF
    for w in windows:
        inner = w.children[0] if isinstance(w, Alias) else w
        if isinstance(inner.function, PandasWindowUDF):
            return False
    return True


def _ensure_window_distribution(cur: PlannedNode, spec,
                                conf: TpuConf) -> tuple[PlannedNode, bool]:
    """Hash-partition on the window partition keys so the window program
    runs per partition instead of collapsing all upstream parallelism
    into one global batch (Spark's EnsureRequirements inserts the same
    exchange for ClusteredDistribution; reference GpuWindowExec.scala:92
    needs one batch per partition GROUP only).  Skips the exchange when
    the child is already hash-partitioned on a subset of the window keys
    — rows equal on the window keys are then already co-located."""
    if not spec.partition_by:
        return cur, False
    if cur.exec_node.num_partitions(ExecCtx(backend="host")) <= 1:
        return cur, False
    want = _window_key_names(spec.partition_by)
    if want is not None:
        node = cur.exec_node
        # window output preserves its child's distribution: look through
        # WindowExecs stacked by earlier specs of the same projection
        while isinstance(node, WindowExec) and node._keys_partitioned:
            node = node.children[0]
        if isinstance(node, ShuffleExchangeExec) and \
                isinstance(node.partitioning, HashPartitioning):
            have = _window_key_names(node.partitioning._keys)
            if have and set(have) <= set(want):
                return cur, True
    part = HashPartitioning(list(spec.partition_by),
                            conf.shuffle_partitions)
    exch = ShuffleExchangeExec(part, cur.exec_node)
    return PlannedNode(exch, list(spec.partition_by), [cur]), True


def _lower_project(node: L.Project, conf: TpuConf) -> PlannedNode:
    c = lower(node.child, conf)
    from spark_rapids_tpu.udf import maybe_compile_udfs
    exprs = maybe_compile_udfs(node.exprs, conf)
    exprs, pandas_udfs = _split_pandas_udfs(exprs)
    if pandas_udfs:
        from spark_rapids_tpu.exec.python_exec import ArrowEvalPythonExec
        ex = ArrowEvalPythonExec(pandas_udfs, c.exec_node)
        c = PlannedNode(ex, [u for _, u in pandas_udfs], [c])
    plain, windows = _split_window_exprs(exprs)
    if not windows:
        ex = ProjectExec(exprs, c.exec_node)
        return PlannedNode(ex, list(exprs), [c])
    # one WindowExec per distinct spec (Spark's planner does the same),
    # then the final projection over the appended columns
    by_spec: dict = {}
    for w in windows:
        inner = w.children[0] if isinstance(w, Alias) else w
        by_spec.setdefault(inner.spec, []).append(w)
    cur = c
    for spec, spec_windows in by_spec.items():
        if _mesh_window_ok(cur.exec_node, spec, conf, spec_windows):
            cur = _stack_window_execs(cur, spec_windows, False,
                                      conf=conf, mesh=True)
            continue
        cur, keys_partitioned = _ensure_window_distribution(cur, spec, conf)
        cur = _stack_window_execs(cur, spec_windows, keys_partitioned)
    ex = ProjectExec(plain, cur.exec_node)
    return PlannedNode(ex, list(plain), [cur])


def _stack_window_execs(cur: PlannedNode, spec_windows,
                        keys_partitioned: bool, conf: TpuConf = None,
                        mesh: bool = False) -> PlannedNode:
    """Plan one spec's window expressions, splitting pandas window UDFs
    into WindowInPandasExec (reference GpuWindowInPandasExec) and
    native functions into WindowExec — or MeshWindowExec when the
    caller passed ``mesh=True`` (_mesh_window_ok held, so the list is
    all-native) — stacked over ``cur``."""
    from spark_rapids_tpu.exec.python_exec import (PandasWindowUDF,
                                                   WindowInPandasExec)

    def _is_udf(w):
        inner = w.children[0] if isinstance(w, Alias) else w
        return isinstance(inner.function, PandasWindowUDF)

    native_ws = [w for w in spec_windows if not _is_udf(w)]
    udf_ws = [w for w in spec_windows if _is_udf(w)]
    if native_ws:
        if mesh:
            from spark_rapids_tpu.exec.mesh_region import MeshWindowExec
            ex = MeshWindowExec(native_ws, cur.exec_node,
                                conf.mesh_device_count)
        else:
            ex = WindowExec(native_ws, cur.exec_node,
                            keys_partitioned=keys_partitioned)
        cur = PlannedNode(ex, list(native_ws), [cur])
    if udf_ws:
        ex = WindowInPandasExec(udf_ws, cur.exec_node,
                                keys_partitioned=keys_partitioned)
        cur = PlannedNode(ex, list(udf_ws), [cur])
    return cur


def _lower_aggregate(node: L.Aggregate, conf: TpuConf) -> PlannedNode:
    c = lower(node.child, conf)
    # holistic aggregates (percentile) have no mergeable intermediate:
    # neither the partial/final split nor the mesh program can run
    # them — plan a whole-input complete aggregation
    holistic = any(getattr(sub, "requires_complete", False)
                   for e in node.agg_exprs for sub in e.walk())
    if conf.mesh_device_count > 1 and not holistic \
            and not _schema_has_arrays(c.exec_node):
        # grouped AND grand aggregates both lower to the mesh program
        # (grand: partials merge on device 0 inside the shard_map) — a
        # grand aggregate over a mesh join's per-device outputs must
        # not fall into the single-device complete path (matrix-sweep
        # finding: q96 under mesh8 mixed devices in one jit)
        from spark_rapids_tpu.exec.mesh_exec import MeshAggregateExec
        ex = MeshAggregateExec(node.group_exprs, node.agg_exprs, c.exec_node,
                               conf.mesh_device_count)
        return PlannedNode(ex, list(node.agg_exprs), [c])
    nparts = c.exec_node.num_partitions(ExecCtx(backend="host"))
    if node.group_exprs and nparts > 1 and not holistic:
        partial = HashAggregateExec(node.group_exprs, node.agg_exprs,
                                    c.exec_node, mode="partial")
        pmeta = PlannedNode(partial, list(node.agg_exprs), [c])
        group_cols = [col(n) for n in partial._group_names]
        shuffle = ShuffleExchangeExec(
            HashPartitioning(group_cols, conf.shuffle_partitions), partial)
        smeta = PlannedNode(shuffle, group_cols, [pmeta])
        from spark_rapids_tpu.exec.exchange import (ADAPTIVE_ENABLED,
                                                    AdaptiveShuffleReaderExec)
        agg_child = shuffle
        if conf.get(ADAPTIVE_ENABLED):
            reader = AdaptiveShuffleReaderExec(shuffle)
            smeta = PlannedNode(reader, [], [smeta])
            agg_child = reader
        final = HashAggregateExec.final_from_partial(partial, agg_child)
        return PlannedNode(final, list(node.agg_exprs), [smeta])
    ex = HashAggregateExec(node.group_exprs, node.agg_exprs, c.exec_node,
                           mode="complete")
    return PlannedNode(ex, list(node.agg_exprs), [c])


# ---------------------------------------------------------------------------
# tagging + conversion
# ---------------------------------------------------------------------------

class TpuOverrides:
    """Tag the planned tree and realize backends + transitions."""

    def __init__(self, conf: TpuConf):
        self.conf = conf

    def prepare(self, root: PlannedNode, explain: bool = False) -> PlanNode:
        """The full planning pipeline; ``apply`` and the quiet plan
        builds both run THIS, so every future pass reaches both paths
        (review finding: a hand-duplicated pass list diverged)."""
        verify = self._verifier()
        self._tag(root)
        verify(root, "tag")
        self._insert_coalesce(root)
        verify(root, "coalesce")
        self._insert_transitions(root)
        verify(root, "transitions")
        self._align_mesh_outputs(root)
        verify(root, "mesh_align")
        self._mark_shared_scans(root)
        verify(root, "shared_scans")
        self._stamp_lineage(root)
        verify(root, "stamp_lineage")
        self._lower_cluster(root)
        verify(root, "cluster")
        explain_mode = self.conf.explain
        if explain and explain_mode and explain_mode != "NONE":
            text = self.explain(root, only_fallback=(explain_mode
                                                     == "NOT_ON_TPU"))
            if text:
                print(text)
        if self.conf.test_enabled:
            self._assert_on_tpu(root)
        self._insert_stage_boundaries(root)
        verify(root, "stage_boundaries")
        self._fuse_stages(root)
        verify(root, "fusion")
        self._form_mesh_regions(root)
        verify(root, "mesh_regions")
        return root.exec_node

    def _verifier(self):
        """Invariant verification hook (plan/verify.py).

        Default (``spark.rapids.sql.verify.plan`` on): one walk after
        the FINAL rewrite pass — the interim hooks are no-ops, so the
        steady state pays a single O(nodes) pass per prepare.  With
        ``spark.rapids.sql.verify.plan.everyPass`` (tests, premerge)
        every hook verifies, so a violation names the pass that
        introduced it.  A no-op callable when verification is off."""
        from spark_rapids_tpu.plan.verify import (PLAN_VERIFY,
                                                  PLAN_VERIFY_EVERY_PASS,
                                                  verify_plan)
        if not self.conf.get(PLAN_VERIFY):
            return lambda root, pass_name: None
        every_pass = self.conf.get(PLAN_VERIFY_EVERY_PASS)

        def check(root: PlannedNode, pass_name: str) -> None:
            if every_pass or pass_name == "mesh_regions":
                verify_plan(root.exec_node, self.conf, pass_name)

        return check

    def _insert_stage_boundaries(self, root: PlannedNode) -> None:
        """Wrap each join whose build side reads an AQE-inserted shuffle
        in a ``StageBoundaryExec`` (exec/stage_boundary.py): the barrier
        at which plan/adaptive.py re-plans the join from the build
        stage's materialized statistics.

        Runs on the realized exec tree BEFORE fusion: the boundary is a
        pipeline breaker (never fused), and the dynamic-filter targets
        must be resolved while the probe-side scan is still a visible
        leaf — fusion later hides the operators above it inside a
        FusedStageExec, but the scan object itself stays shared, so the
        captured reference remains live."""
        # express lane: the control plane routed this plan below its
        # learned wall threshold — the AQE stage machinery (boundary
        # insertion + runtime re-planning) costs more than re-planning
        # could save on a sub-threshold query.  Raw settings read: the
        # marker is stamped by control/loop.py, but planning must not
        # import the control package (it may be disabled/absent).
        if str(self.conf.settings.get(
                "spark.rapids.control.express", "")).lower() \
                in ("true", "1", "yes"):
            return
        from spark_rapids_tpu.exec.exchange import ADAPTIVE_ENABLED
        if not self.conf.get(ADAPTIVE_ENABLED):
            return
        from spark_rapids_tpu.exec.joins import JoinExec
        from spark_rapids_tpu.exec.stage_boundary import StageBoundaryExec
        from spark_rapids_tpu.plan.adaptive import (dynamic_filter_targets,
                                                    unwrap_exchange)
        done: dict[int, PlanNode] = {}

        def walk(node: PlanNode) -> PlanNode:
            got = done.get(id(node))
            if got is not None:
                return got
            new_children = tuple(walk(c) for c in node.children)
            if any(a is not b for a, b in zip(new_children, node.children)):
                node.children = new_children
            out = node
            if type(node) is JoinExec and len(node.children) == 2:
                ex = unwrap_exchange(node.children[1])
                if ex is not None and getattr(ex, "_aqe_inserted", False):
                    out = StageBoundaryExec(node,
                                            dynamic_filter_targets(node))
            done[id(node)] = out
            return out

        root.exec_node = walk(root.exec_node)

    def _fuse_stages(self, root: PlannedNode) -> None:
        """Collapse runs of adjacent elementwise operators into
        ``FusedStageExec`` nodes — one jit region and one dispatch per
        batch instead of one per operator (exec/fused.py; the
        whole-stage-codegen analog, PAPER.md §L3).

        Runs LAST, on the realized exec tree only: transitions,
        coalesces, and exchanges are already placed, so a fusible run
        can never cross a backend switch or a pipeline breaker — any
        non-fusible node simply terminates the run.  The meta tree is
        left untouched (conversion EXPLAIN shows per-operator nodes;
        EXPLAIN ANALYZE shows the fused stages with what they
        replaced)."""
        from spark_rapids_tpu.exec.compile_cache import (FUSION_ENABLED,
                                                         FUSION_MIN_OPS)
        if not self.conf.get(FUSION_ENABLED):
            return
        from spark_rapids_tpu.exec.fused import FusedStageExec, fusible
        min_ops = max(2, self.conf.get(FUSION_MIN_OPS))
        done: dict[int, PlanNode] = {}

        def walk(node: PlanNode) -> PlanNode:
            got = done.get(id(node))
            if got is not None:
                return got
            if fusible(node):
                run = [node]  # outermost-first
                cur = node.children[0]
                while fusible(cur):
                    run.append(cur)
                    cur = cur.children[0]
                if len(run) >= min_ops:
                    below = walk(cur)
                    ops = list(reversed(run))  # innermost-first
                    if below is not cur:
                        ops[0].children = (below,)
                    fused = FusedStageExec(ops)
                    done[id(node)] = fused
                    return fused
            new_children = tuple(walk(c) for c in node.children)
            if any(a is not b for a, b in zip(new_children, node.children)):
                node.children = new_children
            done[id(node)] = node
            return node

        root.exec_node = walk(root.exec_node)

        # Donation safety: a fused stage may only donate its input batch
        # when that batch is provably exclusive.  Two producers break
        # exclusivity: a plan-shared subtree (CTE scanned once, joined
        # twice — TPC-DS q1) yields the same batch objects to every
        # parent, and a shared-output scan (io/scan.py share_output:
        # several scan NODES over one table share one parked
        # materialization — TPC-DS q49) aliases device buffers across
        # plan-distinct nodes.  Pass-through nodes can forward either
        # upward unchanged, so any such producer anywhere BELOW the
        # stage disables donation (conservative: a materializing node
        # in between would make it safe again, but proving that per
        # node type is not worth a deleted-buffer crash).
        parent_counts: dict[int, int] = {}
        nodes: dict[int, PlanNode] = {}

        def count(node: PlanNode) -> None:
            if id(node) in nodes:
                return
            nodes[id(node)] = node
            for c in node.children:
                parent_counts[id(c)] = parent_counts.get(id(c), 0) + 1
                count(c)

        count(root.exec_node)

        def exclusive(node: PlanNode, seen: set) -> bool:
            if id(node) in seen:
                return True
            seen.add(id(node))
            if parent_counts.get(id(node), 0) > 1 or \
                    getattr(node, "share_output", False):
                return False
            return all(exclusive(c, seen) for c in node.children)

        for node in nodes.values():
            if isinstance(node, FusedStageExec) and \
                    not exclusive(node.children[0], set()):
                node.donate_ok = False

    def _form_mesh_regions(self, root: PlannedNode) -> None:
        """Grow each mesh collective (aggregate / exchange / sort /
        window) downward into a MeshRegionExec absorbing the contiguous
        pipeline below it — whole-stage fusion's elementwise set
        (filter / non-partition-aware project / FusedStageExec) PLUS
        the collective interiors MeshJoinExec and MeshWindowExec, so a
        region can hold scan→filter→join→project→agg as ONE per-device
        program (exec/mesh_region.py).  The run grows through a join's
        STREAM side (children[0]); its build subtree stays a real plan
        edge (the region drains it host-side and stacks it as an extra
        program input) and is walked separately so nested collectives
        below the build form their own regions.

        Runs after fusion on the realized exec tree: transitions and
        coalesces are placed, so an absorbable run can never cross a
        backend switch.  Members keep their original child links
        (lineage recovery and host fallback replay them per batch);
        ``mesh_regions`` counts formed regions at plan time."""
        from spark_rapids_tpu.conf import MESH_REGIONS_ENABLED
        if self.conf.mesh_device_count <= 1 or \
                not self.conf.get(MESH_REGIONS_ENABLED):
            return
        from spark_rapids_tpu.exec.fused import FusedStageExec, fusible
        from spark_rapids_tpu.exec.mesh_exec import (MeshAggregateExec,
                                                     MeshExchangeExec,
                                                     MeshJoinExec)
        from spark_rapids_tpu.exec.mesh_region import (MeshRegionExec,
                                                       MeshSortExec,
                                                       MeshWindowExec)
        from spark_rapids_tpu.obs.registry import get_registry
        terminals = (MeshAggregateExec, MeshExchangeExec, MeshSortExec,
                     MeshWindowExec)
        done: dict[int, PlanNode] = {}

        def absorbable(n: PlanNode) -> bool:
            return fusible(n) or type(n) is FusedStageExec \
                or type(n) in (MeshJoinExec, MeshWindowExec)

        def walk(node: PlanNode) -> PlanNode:
            got = done.get(id(node))
            if got is not None:
                return got
            if type(node) in terminals:
                run = []  # outermost-first members below the terminal
                cur = node.children[0]
                while absorbable(cur):
                    run.append(cur)
                    cur = cur.children[0]  # join: the STREAM side
                if run:
                    below = walk(cur)
                    members = list(reversed(run))  # innermost-first
                    if below is not cur:
                        members[0].children = \
                            (below,) + tuple(members[0].children[1:])
                    # build subtrees walked BEFORE the region is built:
                    # its children list snapshots each join's build edge
                    for m in members:
                        if isinstance(m, MeshJoinExec):
                            nb = walk(m.children[1])
                            if nb is not m.children[1]:
                                m.children = (m.children[0], nb)
                    region = MeshRegionExec(node, members)
                    # the terminal now yields through the region, which
                    # owns the mesh->single-device boundary
                    region.align_output = node.align_output
                    node.align_output = False
                    get_registry().inc("mesh_regions")
                    done[id(node)] = region
                    return region
            new_children = tuple(walk(c) for c in node.children)
            if any(a is not b for a, b in zip(new_children, node.children)):
                node.children = new_children
            done[id(node)] = node
            return node

        root.exec_node = walk(root.exec_node)

    def apply(self, root: PlannedNode) -> PlanNode:
        return self.prepare(root, explain=True)

    def _stamp_lineage(self, root: PlannedNode) -> None:
        """Stamp every exchange with the effective conf's fingerprint.
        Stage recovery (exec/recovery.py) re-executes lost map
        partitions from the exchange's recorded lineage, which is only
        deterministic under the settings the original map ran with —
        the stamp binds the two so a recompute under a drifted conf
        fails loudly instead of producing a silently different
        shuffle."""
        from spark_rapids_tpu.exec.exchange import ShuffleExchangeExec
        from spark_rapids_tpu.exec.recovery import conf_fingerprint
        fp = conf_fingerprint(self.conf)

        def walk(node) -> None:
            if isinstance(node, ShuffleExchangeExec):
                node._conf_fp = fp
            for c in node.children:
                walk(c)

        walk(root.exec_node)

    def _lower_cluster(self, root: PlannedNode) -> None:
        """Tag exchanges the cluster runtime may shard over the worker
        pool (cluster/exec.py reads the tag at materialization time).
        Gated on the RAW setting so ``cluster.mode=off`` — the default —
        never imports the cluster package and the planned tree is
        byte-identical to the single-process engine.

        Only hash and single partitionings are clusterable: their
        partition ids are a pure per-batch function, so independent
        workers computing them agree.  Round-robin and range
        partitionings build global ``prepare()`` state from ALL map
        batches (a running row offset; sampled range bounds) that
        cannot be split across processes without changing results."""
        if self.conf.settings.get("spark.rapids.cluster.mode",
                                  "off") == "off":
            return
        from spark_rapids_tpu.exec.exchange import ShuffleExchangeExec
        from spark_rapids_tpu.exec.partitioning import (HashPartitioning,
                                                        SinglePartitioning)
        seen: set[int] = set()

        def walk(node) -> None:
            if id(node) in seen:
                return
            seen.add(id(node))
            if isinstance(node, ShuffleExchangeExec) and isinstance(
                    node.partitioning,
                    (HashPartitioning, SinglePartitioning)):
                node._cluster_ok = True
            for c in node.children:
                walk(c)

        walk(root.exec_node)

    def _mark_shared_scans(self, root: PlannedNode) -> None:
        """Scans whose (files, columns, pushdown) fingerprint appears
        more than once in the final exec tree share one spillable
        materialization per partition (io/scan.py share_output).
        TPC-DS q28 reads store_sales 12x through its bucket branches —
        without sharing, each instance re-decodes, re-encodes, and
        re-transfers the same table (reference analog: ReuseExchange
        over identical subtrees, here applied at the leaf)."""
        from spark_rapids_tpu.conf import SCAN_REUSE
        from spark_rapids_tpu.io.scan import FileScanExec
        if not self.conf.get(SCAN_REUSE):
            return
        # count CONSUMPTIONS per fingerprint, not instances: a builder
        # reusing one DataFrame makes the exec tree a DAG whose single
        # scan object is pulled once per referencing branch — each pull
        # re-executes without sharing
        groups: dict = {}

        def walk(n: PlanNode):
            if isinstance(n, FileScanExec):
                groups.setdefault(n.scan_fingerprint(), []).append(n)
            for c in n.children:
                walk(c)

        walk(root.exec_node)
        for g in groups.values():
            if len(g) > 1:
                for n in g:
                    n.share_output = True
                    # consumptions of this fingerprint in the tree: the
                    # LAST consumer to drain a partition closes the
                    # parked spillable entries (io/scan.py), so a shared
                    # table doesn't stay registered until catalog close
                    n.share_consumers = len(g)

    def root_backend(self, root: PlannedNode) -> str:
        return root.backend

    def _assert_on_tpu(self, meta: PlannedNode) -> None:
        """Test mode (spark.rapids.sql.test.enabled): the WHOLE plan
        must run on the device, except exec names listed in
        spark.rapids.sql.test.allowedNonTpu (reference
        GpuTransitionOverrides.assertIsOnTheGpu, :322-367)."""
        from spark_rapids_tpu.conf import TEST_ALLOWED_NONTPU
        allowed = {n.strip() for n in
                   self.conf.get(TEST_ALLOWED_NONTPU).split(",")
                   if n.strip()}
        bad = []

        def walk(m: PlannedNode):
            if m.backend != "device" and m.name not in allowed:
                bad.append(f"{m.name}: {'; '.join(m.reasons) or 'host'}")
            for ch in m.children:
                walk(ch)

        walk(meta)
        if bad:
            raise AssertionError(
                "plan is not fully on the TPU (spark.rapids.sql.test."
                "enabled):\n  " + "\n  ".join(bad))

    # -- tagging -------------------------------------------------------
    def _tag(self, meta: PlannedNode) -> None:
        for ch in meta.children:
            self._tag(ch)
        conf = self.conf
        if not conf.sql_enabled:
            meta.will_not_work("spark.rapids.sql.enabled is false")
        key = f"spark.rapids.sql.exec.{meta.name}"
        if not conf.is_op_enabled(key):
            meta.will_not_work(f"{key} is disabled")
        bound = list(getattr(meta.exec_node, "bound_exprs", []))
        for e in list(meta.exprs) + bound:
            if not isinstance(e, Expression):
                continue
            for sub in e.walk():
                cname = type(sub).__name__
                ekey = f"spark.rapids.sql.expression.{cname}"
                if not conf.is_op_enabled(ekey):
                    meta.will_not_work(f"{ekey} is disabled")
                try:
                    ds = sub.device_supported
                except TypeError:
                    # dtype-dependent check on an unbound tree: the bound
                    # copy (exec_node.bound_exprs) carries the decision
                    ds = True
                if ds is False:
                    meta.will_not_work(
                        f"expression {cname} has no device kernel")
        self._tag_special(meta)
        meta.backend = "host" if meta.reasons else "device"

    def _tag_special(self, meta: PlannedNode) -> None:
        ex = meta.exec_node
        # MapType has no device representation (types.MapType): a node
        # whose OWN output carries a map runs on the host, and so does a
        # node whose CHILD outputs one — the host->device transition
        # would otherwise have to upload the map column (review repro:
        # df.select(k) over a map-carrying scan crashed in
        # host_to_device).  The node ABOVE the map-dropping projection
        # returns to the device (reference: unsupported-type tagging,
        # RapidsMeta.willNotWorkOnGpu).
        if any(isinstance(f.data_type, T.MapType)
               for f in ex.output_schema) or \
           any(isinstance(f.data_type, T.MapType)
               for ch in ex.children for f in ch.output_schema):
            meta.will_not_work("map columns are host-only")
        # the write sink consumes its child's batches directly (Arrow
        # encode is host-side either way) — it follows the child's
        # backend so no transition lands between child and sink, and a
        # device child keeps the cluster runtime attached to the job
        from spark_rapids_tpu.exec.write_exec import CreateDataWriteExec
        if isinstance(ex, CreateDataWriteExec) and any(
                ch.backend != "device" for ch in meta.children):
            meta.will_not_work("write sink follows its host child")
        if isinstance(ex, WindowExec):
            from spark_rapids_tpu.expr import aggregates as A
            for w, dt in zip(ex._wexprs, ex._out_dtypes):
                f = w.function
                if isinstance(f, (A.Min, A.Max)) and isinstance(
                        dt, T.StringType):
                    meta.will_not_work(
                        "windowed min/max over strings has no device kernel")
        from spark_rapids_tpu.exec.mesh_exec import MeshAggregateExec
        agg_ex = ex._layout if isinstance(ex, MeshAggregateExec) else \
            ex if isinstance(ex, HashAggregateExec) else None
        if agg_ex is not None and agg_ex._aggs:
            # float-aggregation gates (reference ENABLE_FLOAT_AGG +
            # the incompat machinery, RapidsConf.scala:461-492):
            # variableFloatAgg=false refuses ANY float aggregation
            # (reduction order varies); exactDoubleAggregation=true
            # refuses DOUBLE ones specifically — TPU f64 is a
            # float32-pair emulation and sums can deviate from exact
            # f64 (quantified in artifacts/f64_pair_error.json).
            # Mesh lowering (MeshAggregateExec) shares the layout, so
            # the gates cover both single-chip and mesh aggregates.
            from spark_rapids_tpu.conf import (ALLOW_FLOAT_AGG,
                                               EXACT_DOUBLE_AGG)
            in_types = [a.input.dtype for a in agg_ex._aggs
                        if a.input is not None]
            if not self.conf.get(ALLOW_FLOAT_AGG) and any(
                    t.fractional for t in in_types):
                meta.will_not_work(
                    "float aggregation disabled "
                    "(spark.rapids.sql.variableFloatAgg.enabled)")
            if self.conf.get(EXACT_DOUBLE_AGG) and any(
                    isinstance(t, T.DoubleType) for t in in_types):
                meta.will_not_work(
                    "double aggregation forced to host for exact f64 "
                    "(spark.rapids.sql.exactDoubleAggregation)")

    # -- mesh output alignment ------------------------------------------
    def _align_mesh_outputs(self, meta: PlannedNode) -> None:
        """Set align_output on mesh execs whose per-device batches flow
        (possibly through per-batch operators, which preserve placement)
        into a non-mesh BATCH-COMBINING consumer — a program jitting
        batches from different devices crashes (q96-under-mesh matrix
        finding).  Per-batch consumers (filter/project/limit) pass
        placement through so the distributed pipeline is not funneled
        through one chip; unconsumed producers at the root stay
        unaligned — collect's per-batch D2H handles any device."""
        from spark_rapids_tpu.exec.mesh_exec import _MeshOutputMixin

        def walk(m: PlannedNode) -> list:
            # returns mesh execs whose (unaligned) per-device output
            # reaches m's own output
            producers = [p for ch in m.children for p in walk(ch)]
            ex = m.exec_node
            if isinstance(ex, _MeshOutputMixin):
                # a mesh exec consumes its children mesh-aware (device
                # affinity in place_shards); only ITS output escapes
                return [ex]
            if producers and ex.combines_batches:
                for p in producers:
                    p.align_output = True
                return []
            return producers

        walk(meta)

    # -- coalesce insertion (reference GpuTransitionOverrides
    # insertCoalesce :224-244 / optimizeCoalesce :96-116) ---------------
    def _insert_coalesce(self, meta: PlannedNode) -> None:
        """Insert CoalesceBatchesExec where an operator's
        children_coalesce_goal demands batching its child does not
        already satisfy.  A declared ``TargetSize(0)`` resolves to
        ``spark.rapids.sql.batchSizeBytes`` (reference: the goal is
        built from conf at planning, GpuExec.scala:71-86 +
        RapidsConf.scala:364)."""
        from spark_rapids_tpu.exec import CoalesceBatchesExec
        from spark_rapids_tpu.exec.core import TargetSize
        for ch in meta.children:
            self._insert_coalesce(ch)
        goals = meta.exec_node.children_coalesce_goal
        if not any(g is not None for g in goals):
            return
        new_children = []
        new_metas = []
        for ch, goal in zip(meta.children, goals):
            if goal is None or ch.exec_node.output_batching is not None \
                    and ch.exec_node.output_batching.satisfies(goal):
                new_children.append(ch.exec_node)
                new_metas.append(ch)
                continue
            if isinstance(goal, TargetSize) and goal.size <= 0:
                goal = TargetSize(self.conf.batch_size_bytes)
            co = CoalesceBatchesExec(goal, ch.exec_node)
            cometa = PlannedNode(co, [], [ch], backend=ch.backend)
            new_children.append(co)
            new_metas.append(cometa)
        assert len(new_children) == len(meta.exec_node.children)
        meta.exec_node.children = tuple(new_children)
        meta.children = new_metas

    # -- transitions ---------------------------------------------------
    def _insert_transitions(self, meta: PlannedNode) -> None:
        for ch in meta.children:
            self._insert_transitions(ch)
        new_children = []
        for ch in meta.children:
            if ch.backend != meta.backend:
                new_children.append(BackendSwitchExec(ch.exec_node,
                                                      ch.backend))
            else:
                new_children.append(ch.exec_node)
        if meta.children:
            kids = list(meta.exec_node.children)
            # planner invariant: the meta tree mirrors the exec tree; a
            # mismatch is a lowering bug and silently skipping it would
            # run a child on the wrong backend (round-1 advisor finding)
            assert len(kids) == len(new_children), (
                f"planner arity mismatch at {meta.name}: exec has "
                f"{len(kids)} children, meta has {len(new_children)}")
            meta.exec_node.children = tuple(new_children)

    # -- explain -------------------------------------------------------
    def explain(self, meta: PlannedNode, only_fallback: bool = False,
                indent: int = 0) -> str:
        marker = "*" if meta.backend == "device" else "!"
        line = "  " * indent + f"{marker} {meta.exec_node.node_desc()}"
        if meta.reasons:
            line += "  <-- " + "; ".join(meta.reasons)
        lines = [] if (only_fallback and not meta.reasons) else [line]
        for ch in meta.children:
            sub = self.explain(ch, only_fallback, indent + 1)
            if sub:
                lines.append(sub)
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# EXPLAIN ANALYZE: post-execution plan annotation
# ---------------------------------------------------------------------------

#: metrics shown inline on every node that recorded them, in this order
_CORE_METRICS = ("totalTime", "numOutputBatches", "numOutputRows")


def _fmt_metric(name: str, v: float) -> str:
    if name.endswith(("Time", "_s")) or isinstance(v, float) and v != int(v):
        return f"{name}={v:.3f}s" if name.endswith(("Time", "_s")) \
            else f"{name}={v:.3f}"
    return f"{name}={int(v)}"


def explain_analyze(plan, ctx) -> str:
    """Render the EXECUTED plan tree annotated with runtime metrics —
    the EXPLAIN ANALYZE counterpart of :meth:`TpuOverrides.explain`
    (reference: GpuExec metrics surfaced in the Spark SQL UI per node).

    ``plan`` is the exec-tree root (a PlanNode); metrics come from the
    ExecCtx the plan ran under, keyed by node identity, so repeated
    EXPLAIN ANALYZE calls over one execution are stable.  Nodes carry
    ``[time=.. batches=.. rows=..]`` plus any extra recorded metrics
    (spills, retries, stage recoveries) sorted by name; a footer gives
    the query/trace ids and the process-wide counters so shuffle and
    memory activity not attributable to a single node is still
    visible."""
    lines: list[str] = []

    def walk(node, indent: int) -> None:
        key = f"{type(node).__name__}@{id(node):x}"
        m = ctx.metrics.get(key)
        line = "  " * indent + f"* {node.node_desc()}"
        if m is not None and m.values:
            parts = [_fmt_metric(k, m.values[k]) for k in _CORE_METRICS
                     if k in m.values]
            parts += [_fmt_metric(k, v) for k, v in sorted(m.values.items())
                      if k not in _CORE_METRICS]
            line += "  [" + ", ".join(parts) + "]"
        lines.append(line)
        for c in node.children:
            walk(c, indent + 1)

    walk(plan, 0)
    lines.append("")
    lines.append(f"query_id={ctx.query_id} trace_id={ctx.trace_id}")
    cat = ctx.cache.get("catalog")
    if cat is not None and getattr(cat, "metrics", None):
        parts = [_fmt_metric(k, v) for k, v in sorted(cat.metrics.items())
                 if isinstance(v, (int, float))]
        if parts:
            lines.append("catalog: " + ", ".join(parts))
    gov = getattr(cat, "governor", None) if cat is not None else None
    if gov is not None:
        # this query's slice of the cross-query HBM ledger: live/pinned/
        # peak device bytes as the governor attributed them
        stats = gov.query_stats(ctx.query_id).get(ctx.query_id)
        if stats:
            parts = [_fmt_metric(k, stats[k]) for k in
                     ("device_bytes", "pinned_bytes", "peak_bytes")
                     if k in stats]
            lines.append("governor: " + ", ".join(parts))
    from spark_rapids_tpu.obs.registry import get_registry
    counters = get_registry().snapshot()["counters"]
    if counters:
        parts = [_fmt_metric(k, v) for k, v in sorted(counters.items())]
        lines.append("counters: " + ", ".join(parts))
    return "\n".join(lines)

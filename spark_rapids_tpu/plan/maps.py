"""Logical rewrite: decompose map columns into array pairs for device
execution.

Reference: the plugin executes GetMapValue / map_keys / map_values on
the GPU over cuDF LIST columns (complexTypeExtractors.scala,
collectionOperations.scala).  Here MapType has no device layout, so a
plan whose EVERY use of a map column is an extraction is rewritten:

* the scan is wrapped in :class:`MapDecomposeExec` (host-side split
  into sorted-keys / aligned-values ARRAY columns), and
* ``GetMapValue(m, k)`` becomes a device ``MapLookup`` over the pair,
  ``map_keys/map_values`` become direct column references, ``size``
  reads the keys array —

after which the physical plan carries no MapType and the tagger keeps
it on the device (the raw host path remains for bare-map uses, string
keys, or any ambiguity; same degradation model as the reference's
willNotWorkOnGpu tagging).
"""
from __future__ import annotations

from spark_rapids_tpu.conf import ConfEntry, TpuConf, _bool, register
from spark_rapids_tpu.exec.maps_exec import (MapDecomposeExec, decomposable,
                                             hashed_decomposable,
                                             key_hash64, keys_name,
                                             size_name, vals_name)
from spark_rapids_tpu.expr.collections import (GetMapValue, MapKeys,
                                               MapLookup, MapValues, Size)
from spark_rapids_tpu.expr.core import Expression, UnresolvedAttribute, col
from spark_rapids_tpu.plan import logical as L

__all__ = ["decompose_maps", "DECOMPOSE_MAPS"]

DECOMPOSE_MAPS = register(ConfEntry(
    "spark.rapids.sql.decomposeMaps", True,
    "Rewrite plans whose map columns are only ever extracted "
    "(m[key]/map_keys/map_values/size) to split each map into "
    "sorted-keys/values array columns at the scan, running the "
    "extractions on the device.", conv=_bool))

# an occurrence of the map attribute is allowed only as the FIRST child
# of one of these.  MapKeys/MapValues are NOT here: the decomposed
# arrays drop null-VALUED entries (no element nulls on device), which
# lookups and the size column absorb exactly but whole-array views
# would observe — those uses keep the raw host path.
_EXTRACTORS = (GetMapValue, Size)

# nodes that pass their child's columns through to their own output
# (a map column surviving to the plan root through these is a bare use)
_PASS_THROUGH = (L.Filter, L.Sort, L.Limit, L.Repartition, L.Union,
                 L.Window, L.Generate)

# nodes whose presence forces the raw host path: their row-level view
# of the child schema (pandas frames) or positional column contracts
# would observably change under decomposition
_DISQUALIFYING = (L.MapInPandas, L.FlatMapGroupsInPandas,
                  L.AggregateInPandas, L.FlatMapCoGroupsInPandas, L.Union)


def _node_exprs(n: L.LogicalPlan) -> list:
    out: list = []
    if isinstance(n, L.Project):
        out += n.exprs
    elif isinstance(n, L.Filter):
        out.append(n.condition)
    elif isinstance(n, L.Aggregate):
        out += list(n.group_exprs) + list(n.agg_exprs)
    elif isinstance(n, L.Join):
        out += list(n.left_on) + list(n.right_on)
        if n.condition is not None:
            out.append(n.condition)
    elif isinstance(n, L.Sort):
        for o in n.orders:
            e = o[0] if isinstance(o, tuple) else o
            if isinstance(e, Expression):
                out.append(e)
    elif isinstance(n, L.Window):
        out += n.window_exprs
    elif isinstance(n, L.Expand):
        out += [e for proj in n.projections for e in proj]
    elif isinstance(n, L.Generate):
        out.append(n.generator)
    elif isinstance(n, L.Repartition):
        out += n.keys
    return out


def _walk(n: L.LogicalPlan):
    yield n
    for c in n.children:
        yield from _walk(c)


def _bare_uses(e: Expression, names: set, bad: set) -> None:
    if isinstance(e, UnresolvedAttribute):
        if e.name in names:
            bad.add(e.name)
        return
    for i, ch in enumerate(getattr(e, "children", ())):
        if isinstance(ch, UnresolvedAttribute) and ch.name in names:
            if not (isinstance(e, _EXTRACTORS) and i == 0):
                bad.add(ch.name)
        else:
            _bare_uses(ch, names, bad)


def _escaping(n: L.LogicalPlan, names: set, bad: set) -> None:
    """Map columns reaching the plan OUTPUT through schema-pass-through
    nodes are bare uses (the user would observe split columns)."""
    if isinstance(n, L.Scan):
        for f in n.schema:
            if f.name in names:
                bad.add(f.name)
        return
    if isinstance(n, _PASS_THROUGH) or not isinstance(
            n, (L.Project, L.Aggregate, L.Expand)):
        for c in n.children:
            _escaping(c, names, bad)


def _rewrite_expr(e: Expression, names: set, hashed: set = frozenset()) \
        -> Expression:
    def rw(node):
        kids = getattr(node, "children", ())
        m = kids[0] if kids else None
        if not (isinstance(m, UnresolvedAttribute) and m.name in names):
            return node
        if isinstance(node, GetMapValue):
            key = node.children[1]
            if m.name in hashed:
                # string-key map: the stored keys are key_hash64 values,
                # so hash the (literal — enforced in decompose_maps)
                # lookup key identically at plan time
                from spark_rapids_tpu import types as T
                from spark_rapids_tpu.expr.core import Literal
                key = Literal(None if key.value is None
                              else key_hash64(key.value), T.LongType())
            return MapLookup(col(keys_name(m.name)), col(vals_name(m.name)),
                             key)
        if isinstance(node, Size):
            # the split's size column counts null-valued entries the
            # keys array dropped, and already encodes legacy
            # size(null)=-1 as a valid -1
            return col(size_name(m.name))
        return node

    return e.transform_up(rw)


def _rebuild(n: L.LogicalPlan, names: set,
             hashed: set = frozenset()) -> L.LogicalPlan:
    from dataclasses import fields as dfields, replace

    if isinstance(n, L.Scan):
        split = [f.name for f in n.schema if f.name in names]
        if split:
            return L.Scan(MapDecomposeExec(n.exec_node, split))
        return n
    kw = {}
    for f in dfields(n):
        v = getattr(n, f.name)
        if isinstance(v, L.LogicalPlan):
            kw[f.name] = _rebuild(v, names, hashed)
        elif isinstance(v, Expression):
            kw[f.name] = _rewrite_expr(v, names, hashed)
        elif isinstance(v, list) and v and isinstance(v[0], list):
            kw[f.name] = [[_rewrite_expr(e, names, hashed) if
                           isinstance(e, Expression) else e for e in inner]
                          for inner in v]
        elif isinstance(v, list):
            kw[f.name] = [
                _rebuild(x, names, hashed) if isinstance(x, L.LogicalPlan)
                else _rewrite_expr(x, names, hashed)
                if isinstance(x, Expression) else x
                for x in v]
    return replace(n, **kw) if kw else n


def decompose_maps(plan: L.LogicalPlan, conf: TpuConf) -> L.LogicalPlan:
    if not conf.get(DECOMPOSE_MAPS):
        return plan
    nodes = list(_walk(plan))
    # candidate map columns: decomposable dtype, unique across scans, no
    # name collision with the reserved split names
    seen: dict[str, int] = {}
    hashed: set = set()
    for n in nodes:
        if isinstance(n, L.Scan):
            for f in n.schema:
                if decomposable(f.data_type):
                    seen[f.name] = seen.get(f.name, 0) + 1
                elif hashed_decomposable(f.data_type):
                    seen[f.name] = seen.get(f.name, 0) + 1
                    hashed.add(f.name)
    all_names = {f.name for n in nodes if isinstance(n, L.Scan)
                 for f in n.schema}
    names = {m for m, cnt in seen.items()
             if cnt == 1 and keys_name(m) not in all_names
             and vals_name(m) not in all_names
             and size_name(m) not in all_names}
    if not names:
        return plan
    if any(isinstance(n, _DISQUALIFYING) for n in nodes):
        return plan
    bad: set = set()
    # alias shadowing: a projection/aggregate output REUSING a map's
    # name (e.g. col("arr").alias("m")) re-scopes that name above it —
    # this pass matches by name with no scoping, so shadowed names keep
    # the raw path (review finding)
    from spark_rapids_tpu.expr.core import output_name as _oname
    for n in nodes:
        if isinstance(n, (L.Project, L.Aggregate, L.Expand)):
            for e in _node_exprs(n):
                try:
                    nm = _oname(e)
                # enginelint: disable=RL001 (expression without an output name cannot collide; skip it)
                except Exception:
                    continue
                if nm in names and not (
                        isinstance(e, UnresolvedAttribute)):
                    bad.add(nm)
        if isinstance(n, L.Generate):
            bad |= set(n.output_names) & names
    for n in nodes:
        for e in _node_exprs(n):
            if isinstance(n, L.Sort):
                # sort-order tuples are not rewritten: ANY reference
                # (even an extraction) keeps the raw path
                bad |= e.references() & names
            else:
                _bare_uses(e, names, bad)
    # hashed (string-key) maps additionally require every lookup key
    # to be a string LITERAL: the stored keys are plan-time hashes, so
    # a data-dependent key expression has nothing to compare against
    from spark_rapids_tpu.expr.core import Literal as _Lit

    def _literal_keys_only(e) -> None:
        for node in e.walk() if hasattr(e, "walk") else ():
            if isinstance(node, GetMapValue):
                m = node.children[0]
                if isinstance(m, UnresolvedAttribute) and m.name in hashed \
                        and not isinstance(node.children[1], _Lit):
                    bad.add(m.name)

    for n in nodes:
        for e in _node_exprs(n):
            _literal_keys_only(e)
    _escaping(plan, names, bad)
    names -= bad
    if not names:
        return plan
    return _rebuild(plan, names, hashed & names)

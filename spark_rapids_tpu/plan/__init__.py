"""Planner layer: logical plan -> physical plan -> TpuOverrides.

Reference L3 (SURVEY.md §2.1): GpuOverrides.scala plan rewriting +
RapidsMeta tagging + GpuTransitionOverrides transition insertion.
"""
from spark_rapids_tpu.plan.logical import (Aggregate, Filter, Join, Limit,
                                           LogicalPlan, Project, Repartition,
                                           Scan, Sort, Union, Window)
from spark_rapids_tpu.plan.overrides import PlannedNode, TpuOverrides

__all__ = ["LogicalPlan", "Scan", "Project", "Filter", "Aggregate", "Join",
           "Sort", "Limit", "Union", "Window", "Repartition",
           "TpuOverrides", "PlannedNode"]

"""Physical-plan invariant verifier: mechanically check, after plan
rewrites, the contracts the planner only promises.

The reference plugin re-walks the rewritten physical plan and asserts
transition and distribution legality (`GpuTransitionOverrides`
`assertIsOnTheGpu` / `validateExecsInGpuPlan`, PAPER.md §L3).  This
engine rewrites plans far more aggressively — overrides, whole-stage
fusion, mesh regions, and runtime AQE re-planning all reparent live
exec nodes — so the verifier re-derives the invariants the downstream
machinery depends on:

* **schema/dtype agreement** — pass-through nodes (exchange, reader,
  coalesce, boundary, transition, limit, broadcast) expose exactly
  their child's fields; join key lists agree in arity and dtype.
* **partitioning legality at exchanges** — every bound partitioning
  key resolves inside the child schema; an adaptive reader still
  bottoms out on a ShuffleExchangeExec after all rewrites.
* **lineage stamps** — once ``_stamp_lineage`` has run, every exchange
  carries a conf fingerprint (stage recovery refuses to recompute
  without one, so a stripped stamp means lost-output recovery is dead).
* **donation exclusivity** — ``FusedStageExec.donate_ok`` implies its
  input subtree has a single consumer and no shared scan below
  (donating a shared batch deletes its buffers under the sibling).
* **AQE boundary legality** — a ``StageBoundaryExec`` sits only above
  a join whose build side reads an AQE-inserted exchange (or, after
  runtime re-planning, its broadcast-strategy rewrite).
* **mesh-region closure** — a region's members are exactly the
  absorbable elementwise set; a host transition captured inside the
  region would silently sync per shard inside one jitted program.

Each violation raises a structured :class:`PlanInvariantError` naming
the node path from the root and the pass after which the broken shape
was observed.

Two gates (docs/developer-guide.md):

* ``spark.rapids.sql.verify.plan`` (default ON): ONE full walk after
  the final rewrite pass plus one after runtime AQE re-planning — the
  walk is a single fused tree pass (no per-node string building, no
  per-call imports), well under 2% of plan-prepare time, so it stays
  on everywhere including the bench path.
* ``spark.rapids.sql.verify.plan.everyPass`` (default off): verify
  after EVERY rewrite pass, so a violation names the pass that
  introduced it rather than the end of the pipeline.  The test suite
  and ci/premerge.sh run with this on; the steady state does not pay
  the 9 extra walks.
"""
from __future__ import annotations

from spark_rapids_tpu import types as T
from spark_rapids_tpu.conf import bool_conf

__all__ = ["PLAN_VERIFY", "PLAN_VERIFY_EVERY_PASS", "PASS_ORDER",
           "PlanInvariantError", "verify_plan", "verify_governor_ledger"]

PLAN_VERIFY = bool_conf(
    "spark.rapids.sql.verify.plan", True,
    "Run the physical-plan invariant verifier over the final rewritten "
    "plan and after adaptive stage re-planning: parent/child schema and "
    "dtype agreement, partitioning legality at exchanges, lineage "
    "stamps on every exchange, donation exclusivity for fused stages, "
    "StageBoundaryExec placement, and mesh-region closure. A violation "
    "raises PlanInvariantError naming the node path and pass. One fused "
    "O(nodes) walk, so it stays on by default "
    "(docs/developer-guide.md).")

PLAN_VERIFY_EVERY_PASS = bool_conf(
    "spark.rapids.sql.verify.plan.everyPass", False,
    "Verify after EVERY plan rewrite pass (tag, coalesce, transitions, "
    "mesh alignment, shared scans, lineage stamping, cluster lowering, "
    "stage boundaries, fusion, mesh regions) instead of once at the "
    "end, so a violation "
    "names the pass that introduced it. The test suite and premerge "
    "gate run with this on; requires spark.rapids.sql.verify.plan.")

#: rewrite passes in execution order; a check only arms once the pass
#: that establishes its invariant has run (e.g. lineage stamps exist
#: only from ``stamp_lineage`` on)
PASS_ORDER = ("tag", "coalesce", "transitions", "mesh_align",
              "shared_scans", "stamp_lineage", "cluster",
              "stage_boundaries", "fusion", "mesh_regions", "aqe_replan")

_PASS_IDX = {name: i for i, name in enumerate(PASS_ORDER)}


class PlanInvariantError(RuntimeError):
    """One broken plan invariant: which node, after which pass, why."""

    def __init__(self, node_path: str, pass_name: str, message: str):
        self.node_path = node_path
        self.pass_name = pass_name
        self.message = message
        super().__init__(
            f"plan invariant violated after pass '{pass_name}' at "
            f"{node_path}: {message}")


def _schema_sig(schema, _memo) -> list:
    out = []
    for f in schema.fields:
        sig = _memo.get(id(f))
        if sig is None:
            # the field object itself is kept in the memo value so its
            # id cannot be recycled while the memo lives
            sig = (f.name, repr(f.data_type), f)
            _memo[id(f)] = sig
        out.append(sig[:2])
    return out


def _bound_refs(expr, out: list) -> None:
    """Collect (index, dtype) of every BoundReference under ``expr``."""
    idx = getattr(expr, "index", None)
    if idx is not None and type(expr).__name__ == "BoundReference":
        out.append((idx, getattr(expr, "dtype", None)))
    for c in getattr(expr, "children", ()) or ():
        _bound_refs(c, out)


_CLS: dict = {}


def _classes() -> dict:
    """Exec-class table, imported once per process (the verifier runs
    on every prepare — per-call imports would dominate the walk)."""
    if not _CLS:
        from spark_rapids_tpu.exec.basic import GlobalLimitExec
        from spark_rapids_tpu.exec.exchange import (AdaptiveShuffleReaderExec,
                                                    BroadcastExchangeExec,
                                                    ShuffleExchangeExec)
        from spark_rapids_tpu.exec.fused import FusedStageExec, fusible
        from spark_rapids_tpu.exec.joins import JoinExec
        from spark_rapids_tpu.exec.sortexec import CoalesceBatchesExec
        from spark_rapids_tpu.exec.stage_boundary import StageBoundaryExec
        from spark_rapids_tpu.exec.transitions import BackendSwitchExec
        from spark_rapids_tpu.plan.adaptive import unwrap_exchange
        _CLS.update(
            ShuffleExchangeExec=ShuffleExchangeExec,
            AdaptiveShuffleReaderExec=AdaptiveShuffleReaderExec,
            BroadcastExchangeExec=BroadcastExchangeExec,
            StageBoundaryExec=StageBoundaryExec,
            BackendSwitchExec=BackendSwitchExec,
            FusedStageExec=FusedStageExec,
            JoinExec=JoinExec,
            fusible=fusible,
            unwrap_exchange=unwrap_exchange,
            passthrough=(ShuffleExchangeExec, AdaptiveShuffleReaderExec,
                         BroadcastExchangeExec, CoalesceBatchesExec,
                         StageBoundaryExec, BackendSwitchExec,
                         GlobalLimitExec))
    return _CLS


# node-kind codes for the learned dispatch table: one dict lookup per
# node replaces the isinstance chain on the hot walk
_K_NONE, _K_EXCHANGE, _K_READER, _K_JOIN, _K_BOUNDARY, _K_FUSED, \
    _K_REGION = range(7)

#: learned type -> (kind, is_passthrough); grows one entry per exec
#: class ever verified, so it is bounded by the class population
_DISPATCH: dict = {}

#: schema objects proven well-formed, keyed by id with the OBJECT kept
#: as the value so its id cannot be recycled while the memo lives;
#: plans re-prepared from the same logical plan share these objects,
#: so repeat walks skip the per-field validation.  Schemas are treated
#: as immutable engine-wide (a rewrite swaps the schema object, never
#: edits one in place), which is what makes the id-memo sound.  Capped:
#: clearing only costs one re-validation.
_OK_SCHEMAS: dict = {}
_MEMO_CAP = 16384

#: DataType subclasses proven via isinstance once — per-field dtype
#: validation is then one set lookup on the class
_DT_CLASSES: set = set()


def _classify(cls) -> tuple:
    c = _classes()
    if issubclass(cls, c["ShuffleExchangeExec"]):
        kind = _K_EXCHANGE
    elif issubclass(cls, c["AdaptiveShuffleReaderExec"]):
        kind = _K_READER
    elif issubclass(cls, c["JoinExec"]):
        kind = _K_JOIN
    elif issubclass(cls, c["StageBoundaryExec"]):
        kind = _K_BOUNDARY
    elif issubclass(cls, c["FusedStageExec"]):
        kind = _K_FUSED
    elif cls.__name__ == "MeshRegionExec":
        kind = _K_REGION
    else:
        kind = _K_NONE
    entry = (kind, issubclass(cls, c["passthrough"]))
    _DISPATCH[cls] = entry
    return entry


class _Verifier:
    def __init__(self, conf=None, pass_name: str = "mesh_regions"):
        self.c = _classes()
        self._parent_counts: dict[int, int] = {}
        # id(node) -> (parent_node, child_index | -1 for hidden); paths
        # are only rendered on failure, never on the hot path
        self._parents: dict[int, tuple] = {}
        self._sig_memo: dict[int, tuple] = {}
        self.reset(conf, pass_name)

    def reset(self, conf, pass_name: str) -> None:
        self.conf = conf
        self.pass_name = pass_name
        self._pass_idx = _PASS_IDX.get(pass_name, len(PASS_ORDER) - 1)

    def _after(self, pass_name: str) -> bool:
        return self._pass_idx >= _PASS_IDX[pass_name]

    def _path(self, node) -> str:
        """Render the root->node path.  Only ever runs on a failure, so
        the hot walk stores one parent pointer per node and the child
        index / hidden-edge marker is re-derived here."""
        parts = []
        seen = 0
        while node is not None and seen < 256:
            parent = self._parents.get(id(node))
            name = type(node).__name__
            if parent is None:
                parts.append(name)
            else:
                idx = None
                for i, ch in enumerate(parent.children):
                    if ch is node:
                        idx = i
                        break
                parts.append(f"{name}[hidden]" if idx is None
                             else f"{name}[{idx}]")
            node, seen = parent, seen + 1
        return "/".join(reversed(parts))

    def _fail(self, node, message: str):
        raise PlanInvariantError(self._path(node), self.pass_name, message)

    # -- the walk ------------------------------------------------------

    def run(self, root) -> None:
        counts = self._parent_counts
        parents = self._parents
        dispatch = _DISPATCH
        ok_schemas = _OK_SCHEMAS
        armed_boundary = self._pass_idx >= _PASS_IDX["stage_boundaries"]
        armed_fusion = self._pass_idx >= _PASS_IDX["fusion"]
        armed_region = self._pass_idx >= _PASS_IDX["mesh_regions"]
        donate_checks = []
        # the parents map doubles as the visited set (membership =
        # discovered), and schemas fetched while checking a parent's
        # pass-through edge are cached so the child's own visit does
        # not re-run its output_schema property
        parents[id(root)] = None
        schema_cache: dict = {}
        # (node, counting): edges out of hidden-side nodes (fused ops,
        # mesh-region members) MIRROR visible edges — e.g. a fused
        # op's child is also the wrapper's child — so only the visible
        # .children graph contributes to parent counts, exactly like
        # _fuse_stages' own exclusivity scan
        stack = [(root, True)]
        while stack:
            node, counting = stack.pop()
            entry = dispatch.get(node.__class__)
            if entry is None:
                entry = _classify(node.__class__)
            kind, passthrough = entry
            if schema_cache:
                schema = schema_cache.pop(id(node), None)
                if schema is None:
                    schema = node.output_schema
            else:
                schema = node.output_schema
            if ok_schemas.get(id(schema)) is not schema:
                self._validate_schema(node, schema)
            children = node.children
            if passthrough and children:
                child = children[0]
                child_schema = child.output_schema
                schema_cache[id(child)] = child_schema
                if schema is not child_schema:
                    self._check_passthrough(node, schema, child_schema)
            if kind:
                if kind == _K_EXCHANGE:
                    self._check_exchange(node)
                elif kind == _K_READER:
                    self._check_reader(node)
                elif kind == _K_JOIN:
                    self._check_join(node)
                elif kind == _K_BOUNDARY:
                    if armed_boundary:
                        self._check_boundary(node)
                elif kind == _K_FUSED:
                    if armed_fusion and getattr(node, "donate_ok", False):
                        donate_checks.append(node)
                elif armed_region:  # _K_REGION
                    self._check_region(node)
            for ch in children:
                cid = id(ch)
                if counting:
                    counts[cid] = counts.get(cid, 0) + 1
                if cid not in parents:
                    parents[cid] = node
                    stack.append((ch, counting))
            # fused ops and mesh-region members keep their ORIGINAL
            # child links but are not .children of the wrapper — walk
            # them too so a broken node hidden inside a fused body is
            # still caught
            if kind == _K_FUSED:
                hidden = node.fused_ops
            elif kind == _K_REGION:
                hidden = node._members + (node._terminal,)
            else:
                continue
            for ch in hidden:
                cid = id(ch)
                if cid not in parents:
                    parents[cid] = node
                    stack.append((ch, False))
        # donation exclusivity needs the COMPLETE parent counts, so it
        # is deferred until the walk has seen every edge
        for node in donate_checks:
            self._check_donation(node)

    # -- per-node checks -----------------------------------------------

    def _validate_schema(self, node, schema) -> None:
        if not isinstance(schema, T.Schema):
            self._fail(node, f"output_schema is {type(schema).__name__}, "
                             "not a Schema")
        dt_classes = _DT_CLASSES
        for f in schema.fields:
            dt = getattr(f, "data_type", None)
            if dt.__class__ in dt_classes:
                continue
            if not isinstance(dt, T.DataType):
                self._fail(node, f"field {f!r} carries no DataType")
            dt_classes.add(dt.__class__)
        if len(_OK_SCHEMAS) > _MEMO_CAP:
            _OK_SCHEMAS.clear()
        _OK_SCHEMAS[id(schema)] = schema

    def _check_passthrough(self, node, schema, child_schema) -> None:
        memo = self._sig_memo
        if _schema_sig(schema, memo) != _schema_sig(child_schema, memo):
            self._fail(
                node, "pass-through node schema diverges from its "
                f"child: {_schema_sig(schema, memo)} != "
                f"{_schema_sig(child_schema, memo)}")

    def _check_exchange(self, node) -> None:
        part = node.partitioning
        nparts = getattr(part, "num_partitions", 0)
        if not isinstance(nparts, int) or nparts < 1:
            self._fail(node, f"exchange partitioning has num_partitions="
                             f"{nparts!r}")
        bound = getattr(part, "_bound", ()) or ()
        if bound:
            arity = len(node.children[0].output_schema.fields)
            refs: list = []
            for key in bound:
                _bound_refs(key, refs)
            for idx, _dtype in refs:
                if not 0 <= idx < arity:
                    self._fail(
                        node, f"partitioning key references column {idx} "
                        f"outside the child schema (arity {arity})")
        if self._after("stamp_lineage"):
            fp = getattr(node, "_conf_fp", None)
            if not fp or not isinstance(fp, str):
                self._fail(
                    node, "exchange carries no lineage stamp (_conf_fp): "
                    "stage recovery cannot prove a recompute runs under "
                    "the conf the original map ran with")

    def _check_reader(self, node) -> None:
        if self.c["unwrap_exchange"](node) is None:
            self._fail(
                node, "AdaptiveShuffleReaderExec no longer bottoms out "
                f"on a ShuffleExchangeExec (child is "
                f"{type(node.children[0]).__name__})")

    def _check_join(self, node) -> None:
        lkeys = getattr(node, "_lkeys_b", None)
        rkeys = getattr(node, "_rkeys_b", None)
        if lkeys is None or rkeys is None:
            return
        if len(lkeys) != len(rkeys):
            self._fail(node, f"join key arity mismatch: {len(lkeys)} "
                             f"left vs {len(rkeys)} right")
        for i, (lk, rk) in enumerate(zip(lkeys, rkeys)):
            ld, rd = getattr(lk, "dtype", None), getattr(rk, "dtype", None)
            if ld is not None and rd is not None and \
                    type(ld) is not type(rd):
                self._fail(node, f"join key {i} dtype mismatch: "
                                 f"{ld!r} vs {rd!r}")

    def _check_boundary(self, node) -> None:
        child = node.children[0]
        if not isinstance(child, self.c["JoinExec"]) or \
                len(child.children) != 2:
            self._fail(
                node, "StageBoundaryExec must sit directly above a "
                f"two-child join, found {type(child).__name__}")
        build = child.children[1]
        if self.pass_name == "aqe_replan" and \
                isinstance(build, self.c["BroadcastExchangeExec"]):
            return  # broadcast-strategy rewrite: build side re-wrapped
        ex = self.c["unwrap_exchange"](build)
        if ex is None or not getattr(ex, "_aqe_inserted", False):
            self._fail(
                node, "StageBoundaryExec build side does not unwrap to "
                "an AQE-inserted exchange — the barrier would "
                "materialize a stage AQE never planned for re-decision")

    def _check_donation(self, node) -> None:
        bad = self._non_exclusive(node.children[0], set())
        if bad is not None:
            why = "is consumed by multiple parents" \
                if self._parent_counts.get(id(bad), 0) > 1 \
                else "shares a parked scan materialization"
            self._fail(
                node, f"donate_ok fused stage over a non-exclusive "
                f"input: {type(bad).__name__} below it {why}; donating "
                "its batches would delete buffers under the sibling "
                "consumer")

    def _non_exclusive(self, node, seen: set):
        """First node under ``node`` (inclusive) breaking donation
        exclusivity, or None.  Mirrors _fuse_stages' ``exclusive()``."""
        if id(node) in seen:
            return None
        seen.add(id(node))
        if self._parent_counts.get(id(node), 0) > 1 or \
                getattr(node, "share_output", False):
            return node
        for c in node.children:
            bad = self._non_exclusive(c, seen)
            if bad is not None:
                return bad
        return None

    def _check_region(self, node) -> None:
        terminal = node._terminal
        if type(terminal).__name__ not in ("MeshAggregateExec",
                                           "MeshExchangeExec",
                                           "MeshSortExec",
                                           "MeshWindowExec"):
            self._fail(node, f"mesh region terminal is "
                             f"{type(terminal).__name__}, not a mesh "
                             "collective")
        joins = []
        for m in node._members:
            if isinstance(m, self.c["BackendSwitchExec"]):
                self._fail(
                    node, "host transition (BackendSwitchExec) captured "
                    "inside a mesh region: the per-device program would "
                    "sync to host per shard inside one jitted body")
            mname = type(m).__name__
            if mname == "MeshJoinExec":
                joins.append(m)
            elif not (self.c["fusible"](m)
                      or isinstance(m, self.c["FusedStageExec"])
                      or mname == "MeshWindowExec"):
                self._fail(
                    node, f"mesh region member {type(m).__name__} is not "
                    "absorbable (fusible filter/project, FusedStageExec, "
                    "MeshJoinExec, or MeshWindowExec)")
            if isinstance(m, self.c["FusedStageExec"]) and \
                    getattr(m, "donate_ok", False):
                self._fail(
                    node, "fused member inside a mesh region still has "
                    "donate_ok: the slice-lost fallback replays the "
                    "member chain per batch, which a donated (deleted) "
                    "input cannot survive")
            if mname in ("MeshJoinExec", "MeshWindowExec") and \
                    (getattr(m, "mesh_size", None) != node.mesh_size
                     or getattr(m, "axis_name", None) != node.axis_name):
                self._fail(
                    node, f"collective member {mname} runs on mesh "
                    f"{getattr(m, 'mesh_size', None)}/"
                    f"{getattr(m, 'axis_name', None)!r} but the region "
                    f"program is compiled for {node.mesh_size}/"
                    f"{node.axis_name!r}")
        # region closure over the new edges: children must stay exactly
        # [pipeline leaf] + one build subtree per join member, matching
        # the members' OWN links — a rewrite that swapped either side
        # without the other would drain the wrong subtree
        if len(node.children) != 1 + len(joins):
            self._fail(
                node, f"mesh region carries {len(node.children)} children "
                f"for {len(joins)} join member(s); expected the pipeline "
                "leaf plus one build subtree per join")
        if node._members and node._members[0].children[0] \
                is not node.children[0]:
            self._fail(
                node, "mesh region leaf edge diverged: members[0] no "
                "longer consumes the region's child 0 — the program "
                "would shard a different subtree than lineage replays")
        for i, j in enumerate(joins):
            if j.children[1] is not node.children[1 + i]:
                self._fail(
                    node, f"mesh region build edge {i} diverged: the "
                    "absorbed join's build child is not the region's "
                    f"child {1 + i} — the stacked build input would not "
                    "match the join's lineage")
        # chained-region edge: an upstream mesh exchange (bare or a
        # region's exchange terminal) feeding this region must serve
        # the SAME mesh, or the committed shards cannot be consumed
        # in place
        leaf = node.children[0]
        lname = type(leaf).__name__
        up = leaf if lname == "MeshExchangeExec" else \
            (leaf._terminal if lname == "MeshRegionExec"
             and type(leaf._terminal).__name__ == "MeshExchangeExec"
             else None)
        if up is not None and \
                (up.mesh_size != node.mesh_size
                 or up.axis_name != node.axis_name):
            self._fail(
                node, f"chained region edge crosses meshes: upstream "
                f"exchange is mesh {up.mesh_size}/{up.axis_name!r}, "
                f"this region {node.mesh_size}/{node.axis_name!r} — "
                "per-device shards cannot stay committed across the "
                "chain")


def verify_plan(root, conf=None, pass_name: str = "mesh_regions") -> None:
    """Walk the exec tree under ``root`` and raise
    :class:`PlanInvariantError` on the first broken invariant.

    ``pass_name`` is the rewrite pass that just ran (see
    :data:`PASS_ORDER`): checks whose invariant a later pass establishes
    stay disarmed, and the name is carried on the error so a violation
    points at the pass that introduced it.  ``conf`` is optional and
    only consulted by conf-dependent checks."""
    v = _POOL.pop() if _POOL else _Verifier()
    v.reset(conf, pass_name)
    try:
        v.run(root)
    finally:
        # drop plan refs before pooling (error paths included: the
        # failure path string is rendered before the raise)
        v._parent_counts.clear()
        v._parents.clear()
        v._sig_memo.clear()
        if len(_POOL) < 4:
            _POOL.append(v)


def verify_governor_ledger(gov) -> None:
    """Runtime sibling of :func:`verify_plan` for the cross-query memory
    governor (memory/governor.py): check the invariants the arbitration
    logic only promises.  Called by the governor test suite and the
    premerge governor gate after ``shutdown(drain=True)``; raises
    :class:`PlanInvariantError` (node path ``<governor>``, pass
    ``governor_ledger``) on the first violation:

    * no negative ledger entries — a double-release or mis-attributed
      free would drive ``device_bytes``/``pinned_bytes`` below zero;
    * ``pinned_bytes <= device_bytes`` per query — pinned is a subset
      of the live working set, never more than what is resident;
    * ``peak_bytes >= device_bytes`` — the high-water mark is monotone;
    * zero outstanding reservations once no grant wait is in flight —
      a leaked reservation permanently shrinks every peer's headroom.
    """
    if gov is None:
        return

    def _fail(msg: str):
        raise PlanInvariantError("<governor>", "governor_ledger", msg)

    stats = gov.query_stats()
    for qid, s in stats.items():
        if s["device_bytes"] < 0 or s["pinned_bytes"] < 0:
            _fail(f"query {qid}: negative ledger "
                  f"(device={s['device_bytes']} pinned={s['pinned_bytes']})")
        if s["pinned_bytes"] > s["device_bytes"]:
            _fail(f"query {qid}: pinned_bytes {s['pinned_bytes']} exceeds "
                  f"device_bytes {s['device_bytes']}")
        if s["peak_bytes"] < s["device_bytes"]:
            _fail(f"query {qid}: peak_bytes {s['peak_bytes']} below live "
                  f"device_bytes {s['device_bytes']}")
    reserved = gov.reserved_bytes()
    if reserved:
        _fail(f"leaked grant reservation: {reserved} bytes still "
              "reserved with no waiter in flight")


#: small reuse pool: one walk per prepare means the same dicts serve
#: every verification instead of reallocating four maps per call
_POOL: list = []

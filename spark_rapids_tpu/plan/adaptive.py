"""Adaptive re-optimizer: rewrite the remainder of a running plan from
materialized stage statistics.

The reference plugin rides Spark AQE: at each query-stage boundary the
re-optimized plan is re-walked by `GpuTransitionOverrides` and
`GpuCustomShuffleReaderExec` regroups reduce partitions from actual
map-output sizes (PAPER.md §L3, §2.10).  This module is the re-planning
half for this engine: `exec/stage_boundary.py` marks the stage barrier
above an AQE-inserted join exchange, and when that barrier is first
pulled, :func:`replan_stage` materializes the build side (the map
stage), reads its ACTUAL bytes/rows from the shuffle transport
(`shuffle/local.py` partition_sizes/partition_rows), and rewrites the
not-yet-started join stage:

* **shuffle-join -> broadcast-join** when the built side landed under
  ``spark.sql.adaptive.autoBroadcastJoinThreshold``: the build-side
  ``ShuffleExchangeExec`` is wrapped in a ``BroadcastExchangeExec`` (the
  broadcast drains the already-materialized map output, so lineage
  recovery still covers it) and the ``JoinExec`` is re-strategized to
  ``BroadcastHashJoinExec`` — dropping the probe-side shuffle entirely,
  since a broadcast build no longer needs the probe co-partitioned.
* **dynamic filter pushdown** (the DPP analog): a small build side's
  distinct join-key values become an IN-set (or min-max range) filter
  installed on the probe-side file scan, so the probe stage never
  decodes rows the join would drop.

Reader-side coalescing/skew-splitting from the same statistics lives in
``exec/exchange.py`` ``AdaptiveShuffleReaderExec``; overrides lifts its
split-only restriction for the exchanges this module inserts.

Every decision is recorded under an ``aqe.replan`` span and counted in
the metrics registry (``aqe_broadcast_switches`` /
``aqe_partitions_coalesced`` / ``aqe_skew_splits`` /
``aqe_dynamic_filters``), so EXPLAIN ANALYZE shows both the re-planned
tree and the counters that produced it.
"""
from __future__ import annotations

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.conf import bool_conf, bytes_conf, int_conf

__all__ = ["AUTO_BROADCAST_THRESHOLD", "AQE_SHUFFLED_JOIN",
           "AQE_DYNAMIC_FILTER", "AQE_DYNAMIC_FILTER_MAX_KEYS",
           "unwrap_exchange", "dynamic_filter_targets", "replan_stage"]

AUTO_BROADCAST_THRESHOLD = bytes_conf(
    "spark.sql.adaptive.autoBroadcastJoinThreshold", 10 << 20,
    "A join build side whose MATERIALIZED map-output bytes land under "
    "this threshold is switched from a shuffled join to a broadcast "
    "join at the stage boundary (Spark AQE's "
    "DemoteBroadcastHashJoin/OptimizeLocalShuffleReader counterpart, "
    "decided from actual sizes instead of estimates).")
AQE_SHUFFLED_JOIN = bool_conf(
    "spark.sql.adaptive.shuffledHashJoin.enabled", False,
    "Plan equi-joins as shuffled hash joins (hash-partition both sides) "
    "with a stage boundary above the build exchange, letting the "
    "adaptive re-optimizer pick the final strategy from materialized "
    "sizes. Off by default: the engine's static join already streams "
    "the probe side against a whole-build table, which single-process "
    "benchmarks favor; enable where the build side is too large to "
    "materialize unpartitioned, or to let AQE prove it small.")
AQE_DYNAMIC_FILTER = bool_conf(
    "spark.sql.adaptive.dynamicFilter.enabled", True,
    "When a materialized join build side is small, push an IN-set / "
    "min-max filter over the join keys into the probe-side file scan "
    "(dynamic partition pruning analog). Only ever removes rows the "
    "join would drop; applies to inner/semi joins on integer keys over "
    "non-shared scans.")
AQE_DYNAMIC_FILTER_MAX_KEYS = int_conf(
    "spark.sql.adaptive.dynamicFilter.maxInSetSize", 4096,
    "Max distinct build-side keys for an IN-set dynamic filter; above "
    "this the filter degrades to a min-max range.")

#: key dtypes a dynamic filter may be derived for: plain integers whose
#: host values compare exactly against the arrow column (dates/
#: timestamps/strings/floats are excluded — their arrow-level scalar
#: comparison semantics differ from the raw stored representation)
_FILTERABLE = (T.ByteType, T.ShortType, T.IntegerType, T.LongType)


def unwrap_exchange(node):
    """The ShuffleExchangeExec under a chain of adaptive readers /
    batch coalescers, or None when the subtree is not exchange-rooted."""
    from spark_rapids_tpu.exec.exchange import (AdaptiveShuffleReaderExec,
                                                ShuffleExchangeExec)
    from spark_rapids_tpu.exec.sortexec import CoalesceBatchesExec
    while isinstance(node, (AdaptiveShuffleReaderExec, CoalesceBatchesExec)):
        node = node.children[0]
    return node if isinstance(node, ShuffleExchangeExec) else None


def dynamic_filter_targets(join) -> list[tuple]:
    """``(key_idx, scan, column)`` triples: probe-side join keys that
    resolve, through column-preserving operators, to a column of a file
    scan this join consumes EXCLUSIVELY (``share_output`` scans serve
    other plan branches, which a join-derived filter must never narrow).
    Computed at plan-prepare time — before stage fusion hides the scan —
    and carried on the stage boundary for the replanner."""
    from spark_rapids_tpu.exec.basic import FilterExec, ProjectExec
    from spark_rapids_tpu.exec.exchange import (AdaptiveShuffleReaderExec,
                                                ShuffleExchangeExec)
    from spark_rapids_tpu.exec.sortexec import CoalesceBatchesExec
    from spark_rapids_tpu.exec.transitions import BackendSwitchExec
    from spark_rapids_tpu.expr.core import BoundReference
    from spark_rapids_tpu.io.scan import FileScanExec

    out: list[tuple] = []
    for ki, k in enumerate(join._lkeys_b):
        if not isinstance(k, BoundReference) or \
                not isinstance(k.dtype, _FILTERABLE):
            continue
        node, idx = join.children[0], k.index
        while True:
            if isinstance(node, (FilterExec, CoalesceBatchesExec,
                                 AdaptiveShuffleReaderExec,
                                 ShuffleExchangeExec, BackendSwitchExec)):
                node = node.children[0]
                continue
            if isinstance(node, ProjectExec):
                b = node._bound[idx]
                if not isinstance(b, BoundReference):
                    break
                idx = b.index
                node = node.children[0]
                continue
            break
        if isinstance(node, FileScanExec) and not node.share_output and \
                idx < len(node.output_schema.fields):
            out.append((ki, node, node.output_schema.fields[idx].name))
    return out


def replan_stage(ctx, boundary):
    """Materialize the stage under ``boundary``'s join build exchange
    and re-plan the join from its actual statistics.  Returns the node
    to execute in place of the static join (possibly the join itself).
    Runs once per execution, on the device backend, at first pull of the
    boundary — before any probe-side work starts."""
    from spark_rapids_tpu.obs.registry import get_registry

    join = boundary.children[0]
    exchange = unwrap_exchange(join.children[1])
    if exchange is None or not getattr(exchange, "_aqe_inserted", False):
        return join
    ctx.check_cancel()   # a cancelled query must not launch the map stage
    new_join = join
    with ctx.trace_span("aqe.replan", "aqe", node=join.node_desc()):
        transport = exchange._shuffled(ctx)  # <- the stage barrier
        has_stats = hasattr(transport, "partition_sizes")
        sizes = transport.partition_sizes(exchange.shuffle_id) \
            if has_stats else {}
        rows = transport.partition_rows(exchange.shuffle_id) \
            if hasattr(transport, "partition_rows") else {}
        total = sum(sizes.values())
        threshold = ctx.conf.get(AUTO_BROADCAST_THRESHOLD)
        small = has_stats and total <= threshold
        decisions = []
        if small:
            new_join = _broadcast_switch(join, exchange)
            get_registry().inc("aqe_broadcast_switches")
            decisions.append("broadcast")
        if small and join.join_type in ("inner", "semi") and \
                ctx.conf.get(AQE_DYNAMIC_FILTER):
            decisions += _push_dynamic_filters(ctx, boundary, join, exchange)
        ctx.trace_event("aqe.decision", "aqe", build_bytes=total,
                        build_rows=sum(rows.values()), threshold=threshold,
                        decisions=",".join(decisions) or "none")
    return new_join


def _broadcast_switch(join, exchange):
    """Rewrite (probe-shuffle) JOIN (build-shuffle) into
    probe BROADCAST-JOIN broadcast(build-map-output).  The broadcast
    drains the exchange's already-written map partitions (through the
    recovering fetch, so lineage recovery still applies), and the
    probe's own AQE-inserted exchange — whose only purpose was
    co-partitioning — is dropped."""
    from spark_rapids_tpu.exec.exchange import BroadcastExchangeExec
    from spark_rapids_tpu.exec.joins import BroadcastHashJoinExec
    bcast = BroadcastExchangeExec(exchange)
    probe = join.children[0]
    pex = unwrap_exchange(probe)
    if pex is not None and getattr(pex, "_aqe_inserted", False):
        # AQE-inserted exchanges have exactly one consumer (this join),
        # so no other operator depends on the probe's partitioning
        probe = pex.children[0]
    return BroadcastHashJoinExec.from_shuffled(join, probe, bcast)


def _collect_build_key_values(ctx, exchange, key):
    """All non-null build-side join-key values from the materialized map
    output, as one numpy array (None when the dtype is not filterable).
    Host-side evaluation over mirrored batches: zero device compilation,
    so a dynamic filter never perturbs the compile cache."""
    from spark_rapids_tpu.exec.core import device_to_host
    from spark_rapids_tpu.expr.core import eval_host
    if not isinstance(key.dtype, _FILTERABLE):
        return None
    npdt = key.dtype.np_dtype
    out = []
    for pid in range(exchange.num_partitions(ctx)):
        for b in exchange.partition_iter(ctx, pid):
            hb = device_to_host(b)
            c = eval_host(key, hb)
            data = np.asarray(c.data)
            valid = np.asarray(c.validity, dtype=bool)
            out.append(data[valid])
    if not out:
        return np.empty(0, npdt)
    return np.concatenate(out)


def _push_dynamic_filters(ctx, boundary, join, exchange) -> list[str]:
    """Derive and install per-key filters on the probe-side scans listed
    in ``boundary.df_targets``.  Returns decision strings for the replan
    trace."""
    from spark_rapids_tpu.obs.registry import get_registry
    decisions: list[str] = []
    max_keys = ctx.conf.get(AQE_DYNAMIC_FILTER_MAX_KEYS)
    for ki, scan, col_name in boundary.df_targets:
        vals = _collect_build_key_values(ctx, exchange, join._rkeys_b[ki])
        if vals is None:
            continue
        distinct = np.unique(vals)
        if distinct.size == 0:
            # empty build side: an inner/semi join emits nothing — an
            # impossible range skips the probe decode entirely
            scan.add_runtime_filter(col_name, lo=1, hi=0)
            kind = "empty"
        elif distinct.size <= max_keys:
            scan.add_runtime_filter(
                col_name, values=[v.item() for v in distinct])
            kind = f"in[{distinct.size}]"
        else:
            scan.add_runtime_filter(col_name, lo=distinct[0].item(),
                                    hi=distinct[-1].item())
            kind = "minmax"
        get_registry().inc("aqe_dynamic_filters")
        ctx.trace_event("aqe.dynamic_filter", "aqe", column=col_name,
                        kind=kind, keys=int(distinct.size),
                        scan=scan.node_desc())
        decisions.append(f"filter:{col_name}")
    return decisions

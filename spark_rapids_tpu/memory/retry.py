"""Operator-level OOM retry with split-and-retry.

Reference mapping (SURVEY §2.2): the plugin grows the alloc-failure
spill hook (DeviceMemoryEventHandler.onAllocFailure) into a full retry
framework — RmmRapidsRetryIterator.scala's ``withRetry`` /
``withRetryNoSplit`` / ``splitAndRetry``: an operator step runs inside
a retry scope; on RetryOOM it is re-attempted after spilling, and on
SplitAndRetryOOM its input is split in half by rows and each half is
retried, emitting partial outputs in order.  Operator state is
checkpoint/restored around each attempt (Retryable.scala) so a failed
attempt leaves no half-updated accumulators.

The TPU port has no RMM alloc callback — OOM is a caught XLA
``RESOURCE_EXHAUSTED`` around dispatch (or around the *sync point* on
async backends, where the error surfaces at the first
``block_until_ready``/``device_get`` after the poisoned dispatch).
Three scopes cover both shapes:

* :func:`with_retry` — run ``fn(batch)`` over one input (ColumnBatch or
  SpillableColumnarBatch).  On OOM: spill; when spill frees nothing,
  unpin the input, split it in half by rows, and retry each half
  recursively — partial outputs are returned in row order — down to
  ``spark.rapids.memory.tpu.oomRetry.minSplitRows``.
* :func:`with_retry_no_split` — same, splitting disabled (the reference
  uses withRetryNoSplit where partial outputs would break semantics,
  e.g. GpuSortExec's total sort).
* :func:`retry_sync` — guard a blocking sync of asynchronously
  dispatched work (the chunk-flush ``device_get`` in aggregate/join).
  On OOM: spill, then call ``redo()`` to re-dispatch the poisoned
  values (re-deriving them from retained inputs, which may split), and
  sync again.  This closes the ``_sync_dispatch`` gap where async
  backends surfaced OOMs at sync points outside any retry loop.
"""
from __future__ import annotations

from functools import partial

import jax

from spark_rapids_tpu.columnar.batch import ColumnBatch, round_capacity
from spark_rapids_tpu.conf import bool_conf, int_conf
from spark_rapids_tpu.memory.catalog import (SpillableColumnarBatch,
                                             _sync_dispatch)
from spark_rapids_tpu.ops import kernels as dk

__all__ = ["with_retry", "with_retry_no_split", "retry_sync", "split_half",
           "is_oom", "SplitAndRetryOOM"]


OOM_RETRY_ENABLED = bool_conf(
    "spark.rapids.memory.tpu.oomRetry.enabled", True,
    "Operator-level OOM retry: on RESOURCE_EXHAUSTED the failed step is "
    "re-attempted after spilling from the buffer catalog, and when spill "
    "frees nothing the input batch is split in half by rows and each "
    "half retried (reference RmmRapidsRetryIterator withRetry / "
    "split-and-retry).  Disabled: only the plain spill-and-retry "
    "dispatch hook runs.")
OOM_RETRY_MAX = int_conf(
    "spark.rapids.memory.tpu.oomRetry.maxRetries", 8,
    "Attempts per input piece before the OOM propagates (a split "
    "produces fresh pieces with a fresh budget).")
OOM_RETRY_MIN_ROWS = int_conf(
    "spark.rapids.memory.tpu.oomRetry.minSplitRows", 32,
    "Row floor for split-and-retry: a batch is not split below this "
    "many rows per half; at the floor the OOM propagates (reference "
    "splitSpillableInHalfByRows' single-row stop).")


class SplitAndRetryOOM(RuntimeError):
    """OOM that survived spilling with splitting unavailable or
    exhausted (reference com.nvidia.spark.rapids.jni.SplitAndRetryOOM)."""


_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory")


def is_oom(ex: BaseException) -> bool:
    """True when ``ex`` is an HBM exhaustion (real XLA or injected).
    Terminal errors (QueryCancelled / QueryDeadlineExceeded /
    MapOutputLostError carry ``terminal = True``) are never OOMs, no
    matter what their message says — a cancelled query must not be
    split-and-retried back to life."""
    if getattr(ex, "terminal", False):
        return False
    msg = str(ex)
    return any(m in msg for m in _OOM_MARKERS)


@partial(jax.jit, static_argnames=("out_cap",))
def _slice_rows_jit(batch: ColumnBatch, start, count, out_cap: int):
    import jax.numpy as jnp
    idx = jnp.asarray(start, jnp.int32) + jnp.arange(out_cap,
                                                     dtype=jnp.int32)
    return dk.take(batch, idx, jnp.asarray(count, jnp.int32))


_SHARED_SLICE: dict = {}


def _shared_slice():
    """Split compiles a new executable per (shape, out_cap) right in the
    middle of an OOM storm, concurrently with other drain threads'
    compiles; route it through the shared-jit wrapper (which serializes
    CPU compiles).  Bound lazily — memory/ sits below exec/."""
    w = _SHARED_SLICE.get("slice")
    if w is None:
        from spark_rapids_tpu.exec.compile_cache import instrument
        w = _SHARED_SLICE.setdefault("slice", instrument(_slice_rows_jit))
    return w


def split_half(batch: ColumnBatch) -> list[ColumnBatch]:
    """Split a front-packed batch into two row-contiguous halves, each
    at its own right-sized pow2 capacity (reference
    splitSpillableInHalfByRows, RmmRapidsRetryIterator.scala)."""
    n = batch.host_num_rows()
    if n <= 1:
        raise SplitAndRetryOOM(f"cannot split a {n}-row batch further")
    h = (n + 1) // 2
    slice_rows = _shared_slice()
    lo = slice_rows(batch, dk.device_scalar(0), dk.device_scalar(h),
                    round_capacity(h))
    hi = slice_rows(batch, dk.device_scalar(h),
                    dk.device_scalar(n - h),
                    round_capacity(max(n - h, 1)))
    # the jit boundary strips known_rows; the halves' counts are host
    # facts here, so restore them (metrics then never double-count a
    # split: each half reports its own exact rows)
    lo.known_rows = h
    hi.known_rows = n - h
    return [lo, hi]


def _check_oom_fault(faults, op: str, rows: int | None = None) -> None:
    """Fire memory.oom / memory.oom.until_rows injection points.  The
    ``rows`` context enables until_rows rules: OOM persists while the
    dispatched batch is above the threshold, so split-and-retry is
    deterministically provable without a real device."""
    ctx = {"op": op}
    if rows is not None:
        ctx["rows"] = rows
    act = faults.check("memory.oom", **ctx)
    if act is None:
        act = faults.check("memory.oom.until_rows", **ctx)
    if act is not None:
        raise RuntimeError(
            "RESOURCE_EXHAUSTED: injected fault: simulated HBM OOM "
            f"(spark.rapids.test.faults {act.point})")


def _bump(catalog, key: str) -> None:
    catalog.metrics[key] = catalog.metrics.get(key, 0) + 1


def _reclaim(catalog, need_bytes: int) -> int:
    """Free device memory for a failed allocation, sized to the actual
    need (the dispatched batch's device bytes; the governor applies a
    conf'd floor, spark.rapids.memory.governor.minSpillBytes) instead
    of the historical blind ``device_limit // 4`` sweep.  Governed
    catalogs arbitrate cross-query (own lowest-priority buffers first,
    then younger peers', wound-wait ordered — memory/governor.py);
    ungoverned catalogs keep the legacy sweep byte-identical to the
    pre-governor engine."""
    gov = getattr(catalog, "governor", None)
    if gov is not None:
        return gov.reclaim(catalog, need_bytes)
    return catalog.spill_device(catalog.device_limit // 4)


def with_retry(fn, catalog, inp, *, split=split_half, op: str | None = None,
               settings=None, checkpoint=None, restore=None,
               pairs: bool = False, max_retries: int | None = None,
               min_split_rows: int | None = None, sync: bool | None = None):
    """Run ``fn(batch)`` under the OOM retry scope.

    ``inp`` is a ColumnBatch or a SpillableColumnarBatch (materialized
    per attempt, pinned through the spill pass — evicting our own input
    is no progress — and closed when replaced by split halves).
    Returns the list of outputs
    — one per final input piece, in row order; with ``pairs=True`` each
    element is ``(piece, output)`` so callers can retain the processed
    piece for a later :func:`retry_sync` redo.

    ``checkpoint()``/``restore(state)`` bracket each attempt: whatever
    external state ``fn`` mutates must be restorable so a failed attempt
    leaves no half-applied update (reference Retryable.scala contract).
    """
    settings = settings if settings is not None else {}
    if not OOM_RETRY_ENABLED.get(settings):
        from spark_rapids_tpu.memory.catalog import run_with_spill_retry
        if isinstance(inp, SpillableColumnarBatch):
            b = inp.get()
            try:
                r = run_with_spill_retry(fn, catalog, b)
            finally:
                inp.unpin()
        else:
            r = run_with_spill_retry(fn, catalog, inp)
        return [(inp, r)] if pairs else [r]
    if max_retries is None:
        max_retries = OOM_RETRY_MAX.get(settings)
    if min_split_rows is None:
        min_split_rows = OOM_RETRY_MIN_ROWS.get(settings)
    faults = getattr(catalog, "faults", None)
    do_sync = _sync_dispatch() if sync is None else sync
    name = op or getattr(fn, "__name__", str(fn))

    out = []
    pending: list = [inp]
    while pending:
        piece = pending.pop(0)
        spillable = isinstance(piece, SpillableColumnarBatch)
        attempts = 0
        while True:
            saved = checkpoint() if checkpoint is not None else None
            b = piece.get() if spillable else piece
            try:
                if faults is not None:
                    _check_oom_fault(faults, name, b.host_num_rows())
                r = fn(b)
                if do_sync:
                    jax.block_until_ready(jax.tree_util.tree_leaves(r))
            except (RuntimeError, jax.errors.JaxRuntimeError) as ex:
                if not is_oom(ex):
                    if spillable:
                        piece.unpin()
                    raise
                if restore is not None:
                    restore(saved)
                _bump(catalog, "oom_retries")
                attempts += 1
                if attempts > max_retries:
                    if spillable:
                        piece.unpin()
                    raise
                # spill with the piece still PINNED: evicting our own
                # input is not progress — it would round-trip back on
                # the next attempt and the budget would exhaust without
                # ever splitting.  Sized to the failed work (input
                # bytes), not a blind quarter of the budget
                try:
                    need = int(b.device_size_bytes())
                except Exception:  # enginelint: disable=RL001 (sizing is best-effort; the governor floor covers it)
                    need = 0
                freed = _reclaim(catalog, need)
                if spillable:
                    piece.unpin()
                if freed > 0:
                    continue  # room was made: retry the piece whole
                # spill freed nothing — every unpinned buffer is already
                # out of HBM: halve the working set instead
                n = b.host_num_rows()
                if split is None:
                    raise SplitAndRetryOOM(
                        f"{name}: OOM with nothing left to spill and "
                        "splitting disabled") from ex
                if n <= 1 or (n + 1) // 2 < min_split_rows:
                    raise SplitAndRetryOOM(
                        f"{name}: OOM at the {min_split_rows}-row split "
                        f"floor ({n} rows)") from ex
                halves = split(b)
                if spillable:
                    piece.close()  # replaced by the halves
                _bump(catalog, "oom_splits")
                pending[0:0] = list(halves)
                break
            else:
                out.append((piece, r) if pairs else r)
                if spillable:
                    piece.unpin()
                break
    return out


def with_retry_no_split(fn, catalog, inp, **kw):
    """`with_retry` with split-and-retry disabled — for steps whose
    partial outputs would break semantics (reference withRetryNoSplit:
    total sort, final-merge concat)."""
    kw["split"] = None
    return with_retry(fn, catalog, inp, **kw)


def retry_sync(sync_fn, catalog, *, redo=None, op: str = "sync",
               settings=None, max_retries: int | None = None):
    """Guard a blocking sync point of asynchronously dispatched work.

    On ``tpu``/``axon`` backends dispatches don't block
    (``_sync_dispatch()`` is False), so an OOM raised by XLA for an
    earlier dispatch surfaces HERE — previously outside every retry
    loop (ADVICE round-5, memory/catalog.py).  On OOM: spill from the
    catalog, call ``redo()`` to re-dispatch the poisoned device values
    from retained inputs (a redo may itself run :func:`with_retry` and
    split), then run ``sync_fn()`` again."""
    settings = settings if settings is not None else {}
    if not OOM_RETRY_ENABLED.get(settings):
        return sync_fn()
    if max_retries is None:
        max_retries = OOM_RETRY_MAX.get(settings)
    faults = getattr(catalog, "faults", None)
    attempts = 0
    while True:
        try:
            if faults is not None:
                _check_oom_fault(faults, op)
            return sync_fn()
        except (RuntimeError, jax.errors.JaxRuntimeError) as ex:
            if not is_oom(ex):
                raise
            _bump(catalog, "oom_retries")
            attempts += 1
            if attempts > max_retries:
                raise
            # a sync point reports no allocation size; the governor's
            # minSpillBytes floor sizes the request (ungoverned: legacy
            # quarter-budget sweep)
            _reclaim(catalog, 0)
            if redo is not None:
                redo()

"""Memory runtime: 3-tier spill catalog + device occupancy control.

Reference layer L1 (SURVEY.md §2.2): RapidsBufferCatalog wiring
device->host->disk spill stores (RapidsBufferCatalog.scala:128-142),
SpillPriorities, SpillableColumnarBatch, GpuSemaphore, and the RMM
alloc-failure hook (DeviceMemoryEventHandler.scala:42-69).
"""
from spark_rapids_tpu.memory.catalog import (BufferCatalog, DeviceSemaphore,
                                             SpillPriority,
                                             SpillableColumnarBatch,
                                             run_with_spill_retry)
from spark_rapids_tpu.memory.retry import (SplitAndRetryOOM, is_oom,
                                           retry_sync, split_half,
                                           with_retry, with_retry_no_split)

__all__ = ["BufferCatalog", "DeviceSemaphore", "SpillPriority",
           "SpillableColumnarBatch", "run_with_spill_retry",
           "SplitAndRetryOOM", "is_oom", "retry_sync", "split_half",
           "with_retry", "with_retry_no_split"]

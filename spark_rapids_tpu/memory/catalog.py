"""3-tier buffer catalog: HBM -> host arena (C++) -> disk.

Reference mapping (SURVEY.md §2.2, §3.5):
  * `BufferCatalog` = RapidsBufferCatalog (RapidsBufferCatalog.scala:34)
    + the three RapidsBufferStore tiers wired device->host->disk
    (:136-137), with acquire/release refcounts and priority-ordered
    synchronous spill (RapidsBufferStore.synchronousSpill:147-200).
  * `SpillPriority` = SpillPriorities.scala:26-60 bands.
  * `SpillableColumnarBatch` = SpillableColumnarBatch.scala:28 — hold
    data across iterator steps without pinning HBM.
  * `run_with_spill_retry` = DeviceMemoryEventHandler.onAllocFailure:
    PJRT exposes no RMM-style alloc callback, so the hook is a catch of
    XLA RESOURCE_EXHAUSTED around dispatch -> spill -> retry.
  * `DeviceSemaphore` = GpuSemaphore.scala (concurrent tasks per chip).

TPU-first storage design: a spilled batch's leaves are packed into ONE
contiguous slice of the native host arena (native/arena.cpp) so the
host tier has real pooling and the disk tier writes one file per
buffer; restore rebuilds the ColumnBatch pytree from zero-copy numpy
views of the slice.
"""
from __future__ import annotations

import errno
import functools
import os
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Any

import jax
import numpy as np

from spark_rapids_tpu.columnar.batch import ColumnBatch
from spark_rapids_tpu.conf import ConfEntry, register, _bool
from spark_rapids_tpu.shuffle.compression import get_codec

__all__ = ["BufferCatalog", "SpillPriority", "SpillableColumnarBatch",
           "SpillCorruptionError", "DeviceSemaphore", "run_with_spill_retry"]

#: spill-file integrity checksum: CRC32C when the C binding is present,
#: zlib's CRC32 otherwise (same ladder as the TCP frame checksum in
#: shuffle/tcp.py — the disk tier must carry its own integrity just
#: like the DCN plane does)
try:
    import google_crc32c as _gcrc32c

    _SPILL_CRC_NAME, _spill_crc = "crc32c", _gcrc32c.value
except ImportError:  # pragma: no cover - env without the binding
    _SPILL_CRC_NAME, _spill_crc = "crc32", zlib.crc32


class SpillCorruptionError(RuntimeError):
    """A spilled buffer's disk read-back failed its checksum (or its
    storage was invalidated): the DATA is lost, not the operation.
    Consumers that can recompute the buffer from lineage (the shuffle
    store -> exec/recovery.py) translate this into MapOutputLostError;
    everything else fails with a diagnosable error instead of silently
    consuming flipped bytes."""


def _sidecar(path: str) -> str:
    return path + ".crc"


def _timed_spill(fn):
    """Record each spill/unspill movement's wall time in the
    ``spill.io_seconds`` histogram (failures included: a slow corrupt
    read-back is still I/O the query waited on)."""
    @functools.wraps(fn)
    def inner(self, *args, **kwargs):
        t0 = time.perf_counter()
        try:
            return fn(self, *args, **kwargs)
        finally:
            from spark_rapids_tpu.obs.registry import get_registry
            get_registry().observe("spill.io_seconds",
                                   time.perf_counter() - t0)
    return inner


def _write_sidecar(path: str, value: int, nbytes: int) -> None:
    with open(_sidecar(path), "w") as f:
        f.write(f"{_SPILL_CRC_NAME}:{value & 0xFFFFFFFF:08x}:{nbytes}")


def _verify_sidecar(path: str, data) -> None:
    """Check ``data`` (bytes-like) against the spill file's sidecar;
    raises SpillCorruptionError on mismatch or a missing/garbled
    sidecar — an unverifiable spill file is treated as lost, never
    trusted."""
    try:
        with open(_sidecar(path)) as f:
            algo, want_hex, want_len = f.read().strip().split(":")
    except (OSError, ValueError) as e:
        raise SpillCorruptionError(
            f"spill file {path} has no readable checksum sidecar: "
            f"{type(e).__name__}: {e}") from e
    if algo != _SPILL_CRC_NAME:
        raise SpillCorruptionError(
            f"spill file {path} was checksummed with {algo!r} but this "
            f"process verifies {_SPILL_CRC_NAME!r}")
    got = _spill_crc(bytes(data)) & 0xFFFFFFFF
    if int(want_len) != len(data) or got != int(want_hex, 16):
        raise SpillCorruptionError(
            f"spill file {path} failed its {algo} read-back check "
            f"(wrote {want_hex}/{want_len}B, read {got:08x}/"
            f"{len(data)}B): corrupted on disk")


def _is_enospc(e: OSError) -> bool:
    return e.errno in (errno.ENOSPC, errno.EDQUOT)


class _SpillDiskFull(RuntimeError):
    """Internal: the disk tier is full; the buffer stays where it is and
    the spill pass returns what it already freed, letting the OOM
    split-and-retry scope (memory/retry.py) absorb the pressure."""


DEVICE_SPILL_LIMIT = register(ConfEntry(
    "spark.rapids.memory.tpu.spillStoreSize", 2 << 30,
    "Soft HBM budget for catalog-registered batches; adding past it "
    "spills lowest-priority buffers to host (reference "
    "spark.rapids.memory.gpu pool fraction, RapidsConf.scala:269+)."))
HOST_SPILL_LIMIT = register(ConfEntry(
    "spark.rapids.memory.host.spillStorageSize", 1 << 30,
    "Host arena size for spilled buffers (reference "
    "RapidsConf.scala:330)."))
MEMORY_DEBUG = register(ConfEntry(
    "spark.rapids.memory.debug", False,
    "Leak tracking: warn with per-buffer detail when catalog buffers "
    "are still registered at close (reference "
    "spark.rapids.memory.gpu.debug -> cudf MemoryCleaner, "
    "RapidsConf.scala:288).", conv=_bool))
SPILL_DIR = register(ConfEntry(
    "spark.rapids.memory.spill.dir", "",
    "Directory for disk-tier spill files (one file per buffer plus a "
    ".crc checksum sidecar). Empty = $TMPDIR/srt_spill_<pid>. Files "
    "are fsynced before the catalog entry flips to tier=disk and "
    "deleted on restore, invalidation, and catalog close (reference "
    "spark.local.dir placement of RapidsDiskStore block files)."))
SPILL_COMPRESSION_CODEC = register(ConfEntry(
    "spark.rapids.memory.spill.compression.codec", "none",
    "Codec for disk-tier spill files: none, lz4 (native C++ block codec, "
    "native/lz4.cpp) or zstd — the shuffle codec ladder "
    "(shuffle/compression.py) applied to the RapidsDiskStore analog. "
    "The .crc sidecar is computed over the COMPRESSED bytes, so "
    "read-back verifies exactly what the disk stored; a corrupt or "
    "truncated compressed spill degrades into the existing lost-tier "
    "path (SpillCorruptionError -> lineage recompute where available), "
    "never a decompressor crash. (ref RapidsConf.scala:729)",
    check=lambda v: v in ("none", "lz4", "zstd"),
    check_doc="must be none|lz4|zstd"))


class SpillPriority:
    """Lower spills first (reference SpillPriorities.scala:26-60)."""
    SHUFFLE_OUTPUT = 0
    READ_SHUFFLE = 100
    ACTIVE_BATCH = 1 << 30


@dataclass
class _Entry:
    buffer_id: int
    priority: int
    size: int
    refcount: int = 0
    tier: str = "device"            # device | host | disk | lost
    batch: ColumnBatch | None = None
    # host/disk tier state
    treedef: Any = None
    leaf_meta: list | None = None   # (dtype, shape, nbytes, offset_in_slice)
    arena_offset: int | None = None
    disk_path: str | None = None
    disk_codec: str | None = None   # codec the disk file was written with


class BufferCatalog:
    """id -> buffer map with acquire/refcount + tiered spill."""

    def __init__(self, device_limit: int | None = None,
                 host_limit: int | None = None,
                 spill_dir: str | None = None, conf=None):
        settings = getattr(conf, "settings", {}) if conf is not None else {}
        self._lock = threading.RLock()
        self._entries: dict[int, _Entry] = {}
        self._next_id = 0
        self._debug = MEMORY_DEBUG.get(settings)
        if device_limit:
            self.device_limit = device_limit
        elif DEVICE_SPILL_LIMIT.key in settings:
            self.device_limit = DEVICE_SPILL_LIMIT.get(settings)
        else:
            # no explicit budget: size from the initialized device's HBM
            # via allocFraction/reserve (reference computeRmmInitSizes,
            # GpuDeviceManager.scala:159-194); conf default otherwise
            from spark_rapids_tpu.device import device_pool_limit
            self.device_limit = (device_pool_limit()
                                 or DEVICE_SPILL_LIMIT.get(settings))
        self.device_used = 0
        # the C++ arena maps its full capacity up front (~0.3s for 1GB),
        # so it is created on FIRST SPILL, not per catalog/query — unless
        # spark.rapids.memory.pinnedPool.size asks for an eager staging
        # pool, which is a PROCESS-level singleton (reference
        # allocatePinnedMemory: once per executor, GpuDeviceManager.scala:
        # 264-270)
        self._host_limit = host_limit or HOST_SPILL_LIMIT.get(settings)
        self._arena_obj = None
        self._arena_shared = False
        from spark_rapids_tpu.conf import PINNED_POOL_SIZE
        pinned = PINNED_POOL_SIZE.get(settings)
        if pinned and pinned > 0:
            from spark_rapids_tpu.runtime import get_pinned_arena
            # borrower=self: this catalog holds numpy views into the
            # arena, so a later larger request must park (not destroy)
            # this mapping until the catalog is collected
            self._arena_obj = get_pinned_arena(
                max(self._host_limit, pinned), borrower=self)
            self._arena_shared = True
        self._spill_dir_base = spill_dir or SPILL_DIR.get(settings) or None
        self._spill_dir_made: str | None = None
        self._spill_codec = get_codec(SPILL_COMPRESSION_CODEC.get(settings))
        # deterministic fault plan (spark.rapids.test.faults): the
        # memory.oom point drives run_with_spill_retry exactly like a
        # real XLA RESOURCE_EXHAUSTED; None when unset (inert)
        from spark_rapids_tpu.faults import FaultRegistry
        self.faults = FaultRegistry.from_conf(settings)
        # query lifecycle handle (exec/lifecycle.py), bound by ExecCtx:
        # spill I/O checks it so a cancelled query stops pushing bytes
        # between tiers instead of finishing a multi-buffer spill sweep
        self.lifecycle = None
        # cross-query memory governor (memory/governor.py), bound by
        # ExecCtx via maybe_register when the governor conf is on: the
        # catalog mirrors every device-byte move into the per-query
        # ledger so arbitration and admission shedding see who holds
        # HBM.  None (the default) keeps the catalog query-blind —
        # byte-identical to the pre-governor engine
        self.governor = None
        self.query_id = None
        self.metrics = {"device_spills": 0, "host_spills": 0,
                        "bytes_spilled_to_host": 0,
                        "bytes_spilled_to_disk": 0,
                        # OOM retry framework (memory/retry.py):
                        # attempts re-run after an exhaustion, inputs
                        # halved when spill freed nothing, and the HBM
                        # pressure high-watermark of registered batches
                        "oom_retries": 0, "oom_splits": 0,
                        "device_bytes_peak": 0,
                        # disk-tier integrity + stage recovery
                        # (exec/recovery.py bumps the recovery counters;
                        # they live here because the catalog is the one
                        # metrics sink the bench runner already exports)
                        "spill_crc_failures": 0, "spill_enospc": 0,
                        # disk-tier compression: bytes before/after the
                        # spill codec (zero deltas when codec=none)
                        "spill_raw_bytes": 0, "spill_compressed_bytes": 0,
                        "stage_recomputes": 0, "map_outputs_recomputed": 0,
                        "recovery_wall_s": 0.0}
        # surface catalog counters in the process metrics registry as
        # pull gauges (weakref-bound; dropped again in close())
        from spark_rapids_tpu.obs.registry import get_registry
        self._reg_source = get_registry().register_object_source(
            f"catalog.{id(self):x}", self)

    def occupancy(self) -> dict:
        """Device-tier occupancy alone (no per-entry walk): the cheap
        high-rate probe the HBM occupancy sampler (obs/profile.py)
        reads when no governor ledger is available."""
        with self._lock:
            return {"device_used": self.device_used,
                    "device_limit": self.device_limit}

    def tier_occupancy(self) -> dict:
        """Buffers/bytes currently registered per spill tier — the
        at-a-glance memory picture diagnostics bundles carry."""
        occ: dict[str, dict] = {}
        with self._lock:
            for e in self._entries.values():
                t = occ.setdefault(e.tier, {"buffers": 0, "bytes": 0})
                t["buffers"] += 1
                t["bytes"] += e.size
            occ["_totals"] = {"device_used": self.device_used,
                              "device_limit": self.device_limit}
        return occ

    @property
    def _arena(self):
        if self._arena_obj is None:
            from spark_rapids_tpu.native import HostArena
            self._arena_obj = HostArena(self._host_limit)
        return self._arena_obj

    @property
    def _spill_dir(self) -> str:
        if self._spill_dir_made is None:
            d = self._spill_dir_base or os.path.join(
                os.environ.get("TMPDIR", "/tmp"), f"srt_spill_{os.getpid()}")
            os.makedirs(d, exist_ok=True)
            self._spill_dir_made = d
        return self._spill_dir_made

    def _gov_account(self, delta: int) -> None:
        """Mirror a device_used move into the governor's per-query
        ledger (no-op when ungoverned).  Called at every site that
        mutates ``device_used`` so the ledger can never drift from
        catalog occupancy."""
        gov = self.governor
        if gov is not None:
            gov.account(self, delta)

    def _gov_pinned(self, delta: int) -> None:
        gov = self.governor
        if gov is not None:
            gov.account_pinned(self, delta)

    # -- registration --------------------------------------------------
    def add_batch(self, batch: ColumnBatch, priority: int) -> int:
        """Register a device batch; may synchronously spill others."""
        size = batch.device_size_bytes()
        with self._lock:
            bid = self._next_id
            self._next_id += 1
            self._entries[bid] = _Entry(bid, priority, size, batch=batch)
            self.device_used += size
            self._gov_account(size)
            if self.device_used > self.metrics["device_bytes_peak"]:
                self.metrics["device_bytes_peak"] = self.device_used
            if self.device_used > self.device_limit:
                self._spill_device_locked(self.device_used
                                          - self.device_limit)
            return bid

    def acquire(self, buffer_id: int) -> ColumnBatch:
        """Materialize on device (unspilling if needed) and pin."""
        with self._lock:
            e = self._entries[buffer_id]
            e.refcount += 1   # pin BEFORE unspill so the over-budget pass
            try:              # cannot immediately re-spill this buffer
                if e.tier != "device":
                    self._unspill_locked(e)
            except Exception:
                e.refcount -= 1
                raise
            if e.refcount == 1:
                self._gov_pinned(e.size)
            return e.batch

    def release(self, buffer_id: int) -> None:
        with self._lock:
            e = self._entries[buffer_id]
            assert e.refcount > 0, f"release without acquire: {buffer_id}"
            e.refcount -= 1
            if e.refcount == 0:
                self._gov_pinned(-e.size)

    def remove(self, buffer_id: int) -> None:
        with self._lock:
            e = self._entries.pop(buffer_id)
            if e.refcount > 0:
                self._gov_pinned(-e.size)
            self._drop_storage_locked(e)

    # -- spill ----------------------------------------------------------
    def spill_device(self, target_bytes: int) -> int:
        with self._lock:
            return self._spill_device_locked(target_bytes)

    def _spillable_locked(self):
        return sorted((e for e in self._entries.values()
                       if e.tier == "device" and e.refcount == 0),
                      key=lambda e: e.priority)

    def _spill_device_locked(self, target: int) -> int:
        freed = 0
        for e in self._spillable_locked():
            if freed >= target:
                break
            try:
                self._spill_one_to_host_locked(e)
            except _SpillDiskFull:
                # disk tier is full: stop spilling and report what was
                # freed so far (possibly 0) — the OOM retry scope then
                # splits its input instead of the operator crashing on a
                # write error (ENOSPC degrades into PR 2's retry path)
                break
            freed += e.size
        return freed

    def _check_cancel(self) -> None:
        """Cooperative cancellation point at spill-I/O entry: checked
        BEFORE any tier state mutates, so an abort here leaves the
        entry where it was (still consistent) and the query unwinds
        without half-moved buffers."""
        lc = self.lifecycle
        if lc is not None:
            lc.check()

    def _compress_spill(self, raw: bytes) -> "tuple[bytes, str | None]":
        """Apply the spill codec to one disk payload; identity when
        codec=none.  Counters track the before/after byte volumes so
        the compression ratio is observable per catalog."""
        codec = self._spill_codec
        if codec is None:
            return raw, None
        data = codec.compress(raw)
        self.metrics["spill_raw_bytes"] += len(raw)
        self.metrics["spill_compressed_bytes"] += len(data)
        return data, codec.name

    def _decompress_spill_locked(self, e: _Entry, data: bytes,
                                 out_size: int) -> bytes:
        """Inverse of ``_compress_spill`` at read-back (the sidecar CRC
        over the compressed bytes already passed).  Any decode failure
        — truncation racing the sidecar, a codec the process can no
        longer construct — marks the entry LOST like a CRC failure
        does: data loss the lineage layer can recompute, not a
        decompressor crash."""
        if not e.disk_codec:
            return data
        try:
            codec = self._spill_codec \
                if self._spill_codec is not None \
                and self._spill_codec.name == e.disk_codec \
                else get_codec(e.disk_codec)
            out = codec.decompress(data, out_size)
            if len(out) != out_size:
                raise ValueError(f"decompressed {len(out)}B, "
                                 f"want {out_size}B")
            return out
        except Exception as ex:
            self._mark_lost_locked(e)
            raise SpillCorruptionError(
                f"buffer {e.buffer_id}: {e.disk_codec} spill "
                f"decompression failed ({type(ex).__name__}: {ex}); "
                "storage dropped") from ex

    @_timed_spill
    def _spill_one_to_host_locked(self, e: _Entry) -> None:
        self._check_cancel()
        leaves, treedef = jax.tree_util.tree_flatten(e.batch)
        host = jax.device_get(leaves)
        metas, total = [], 0
        host = [np.asarray(a) for a in host]
        for a in host:
            nb = a.nbytes
            # record the ORIGINAL shape: ascontiguousarray would promote
            # 0-d scalars (num_rows) to 1-d and corrupt the restore
            metas.append([a.dtype, a.shape, nb, total])
            total = _align(total + nb)
        off = None
        if total <= self._arena.capacity:
            off = self._arena.alloc(max(total, 1))
            while off is None and self._spill_host_one_locked():
                off = self._arena.alloc(max(total, 1))
        e.treedef = treedef
        e.leaf_meta = metas
        if off is not None:
            for a, m in zip(host, metas):
                flat = np.ascontiguousarray(a).reshape(-1).view(np.uint8)
                self._arena.view(off + m[3], m[2])[:] = flat
            e.arena_offset = off
            e.tier = "host"
            self.metrics["bytes_spilled_to_host"] += total
        else:
            # buffer cannot fit in the host arena (too large, or arena
            # fragmented with nothing spillable): fall through device->disk
            # (reference RapidsHostMemoryStore spill-through)
            packed = np.zeros(max(total, 1), np.uint8)
            for a, m in zip(host, metas):
                flat = np.ascontiguousarray(a).reshape(-1).view(np.uint8)
                packed[m[3]:m[3] + m[2]] = flat
            path = os.path.join(self._spill_dir, f"buf_{e.buffer_id}.bin")
            data, disk_codec = self._compress_spill(packed.tobytes())
            try:
                self._check_enospc_fault(e)
                with open(path, "wb") as f:
                    f.write(data)
                    f.flush()
                    # durable BEFORE the entry flips to tier=disk: a
                    # torn page-cache write must not become the only
                    # copy of the buffer
                    os.fsync(f.fileno())
                _write_sidecar(path, _spill_crc(data), len(data))
            except OSError as ex:
                if not _is_enospc(ex):
                    raise
                self.metrics["spill_enospc"] += 1
                _unlink_quiet(path)
                _unlink_quiet(_sidecar(path))
                e.treedef = None
                e.leaf_meta = None
                raise _SpillDiskFull(str(ex)) from ex
            e.disk_path = path
            e.disk_codec = disk_codec
            e.tier = "disk"
            self.metrics["bytes_spilled_to_disk"] += total
        e.batch = None
        self.device_used -= e.size
        self._gov_account(-e.size)
        self.metrics["device_spills"] += 1

    @_timed_spill
    def _spill_host_one_locked(self) -> bool:
        """Move one host-tier buffer to disk; False if none exist."""
        self._check_cancel()
        cands = sorted((e for e in self._entries.values()
                        if e.tier == "host" and e.refcount == 0),
                       key=lambda e: e.priority)
        if not cands:
            return False
        e = cands[0]
        total = _align_total(e.leaf_meta)
        path = os.path.join(self._spill_dir, f"buf_{e.buffer_id}.bin")
        disk_codec = None
        if self._spill_codec is not None:
            # compressed spill cannot stream straight from the arena:
            # materialize the slice, compress, write + fsync; the
            # sidecar covers the COMPRESSED bytes (what the disk holds)
            raw = bytes(self._arena.view(e.arena_offset, total))
            data, disk_codec = self._compress_spill(raw)
            try:
                self._check_enospc_fault(e)
                with open(path, "wb") as f:
                    f.write(data)
                    f.flush()
                    os.fsync(f.fileno())
                _write_sidecar(path, _spill_crc(data), len(data))
            except OSError as ex:
                if not _is_enospc(ex):
                    raise
                self.metrics["spill_enospc"] += 1
                _unlink_quiet(path)
                _unlink_quiet(_sidecar(path))
                return False
        else:
            # checksum the arena slice (the source of truth) before it
            # is freed; verified against the file on read-back
            crc = _spill_crc(bytes(self._arena.view(e.arena_offset, total)))
            try:
                self._check_enospc_fault(e)
                self._arena.write_to_disk(e.arena_offset, total, path)
                fd = os.open(path, os.O_RDONLY)
                try:
                    os.fsync(fd)
                finally:
                    os.close(fd)
                _write_sidecar(path, crc, total)
            except OSError as ex:
                if not _is_enospc(ex):
                    raise
                # full disk: the buffer stays on the host tier; callers
                # see False ("nothing moved") and stop pushing
                self.metrics["spill_enospc"] += 1
                _unlink_quiet(path)
                _unlink_quiet(_sidecar(path))
                return False
        self._arena.free(e.arena_offset)
        e.arena_offset = None
        e.disk_path = path
        e.disk_codec = disk_codec
        e.tier = "disk"
        self.metrics["host_spills"] += 1
        self.metrics["bytes_spilled_to_disk"] += total
        return True

    # -- unspill ---------------------------------------------------------
    @_timed_spill
    def _unspill_locked(self, e: _Entry) -> None:
        import jax.numpy as jnp
        self._check_cancel()
        if e.tier == "lost":
            raise SpillCorruptionError(
                f"buffer {e.buffer_id}: storage was lost to disk "
                "corruption; only lineage recomputation can restore it")
        total = _align_total(e.leaf_meta)
        if e.tier == "disk" and e.arena_offset is None:
            self._check_corrupt_fault(e)
            # oversized direct-to-disk buffers restore without the arena
            if total > self._arena.capacity:
                with open(e.disk_path, "rb") as f:
                    raw = f.read()
                try:
                    _verify_sidecar(e.disk_path, raw)
                except SpillCorruptionError:
                    self._mark_lost_locked(e)
                    raise
                raw = self._decompress_spill_locked(e, raw, max(total, 1))
                packed = np.frombuffer(raw, np.uint8)
                leaves = [jnp.asarray(np.frombuffer(
                    packed[rel:rel + nb].tobytes(), dtype=dtype
                ).reshape(shape)) for dtype, shape, nb, rel in e.leaf_meta]
                os.unlink(e.disk_path)
                _unlink_quiet(_sidecar(e.disk_path))
                e.disk_path = None
                e.disk_codec = None
                self._finish_unspill_locked(e, leaves)
                return
            off = self._arena.alloc(max(total, 1))
            while off is None:
                if not self._spill_host_one_locked():
                    raise MemoryError("host arena exhausted during unspill")
                off = self._arena.alloc(max(total, 1))
            if e.disk_codec:
                # compressed file is smaller than the arena slice: read,
                # verify the sidecar over the compressed bytes, inflate,
                # then copy into the slice
                try:
                    with open(e.disk_path, "rb") as f:
                        raw = f.read()
                    _verify_sidecar(e.disk_path, raw)
                    raw = self._decompress_spill_locked(e, raw, total)
                    self._arena.view(off, total)[:] = np.frombuffer(
                        raw, np.uint8)
                except SpillCorruptionError:
                    self._arena.free(off)
                    if e.tier != "lost":
                        self._mark_lost_locked(e)
                    raise
                except Exception:
                    self._arena.free(off)
                    raise
            else:
                try:
                    self._arena.read_from_disk(off, total, e.disk_path)
                    _verify_sidecar(e.disk_path,
                                    bytes(self._arena.view(off, total)))
                except SpillCorruptionError:
                    self._arena.free(off)
                    self._mark_lost_locked(e)
                    raise
                except Exception:
                    self._arena.free(off)
                    raise
            os.unlink(e.disk_path)
            _unlink_quiet(_sidecar(e.disk_path))
            e.disk_path = None
            e.disk_codec = None
            e.arena_offset = off
            e.tier = "host"
        leaves = []
        for dtype, shape, nb, rel in e.leaf_meta:
            raw = self._arena.view(e.arena_offset + rel, nb)
            leaves.append(jnp.asarray(
                np.frombuffer(raw.tobytes(), dtype=dtype).reshape(shape)))
        self._arena.free(e.arena_offset)
        e.arena_offset = None
        self._finish_unspill_locked(e, leaves)

    def _finish_unspill_locked(self, e: _Entry, leaves) -> None:
        e.batch = jax.tree_util.tree_unflatten(e.treedef, leaves)
        e.leaf_meta = None
        e.treedef = None
        e.tier = "device"
        self.device_used += e.size
        self._gov_account(e.size)
        if self.device_used > self.metrics["device_bytes_peak"]:
            self.metrics["device_bytes_peak"] = self.device_used
        if self.device_used > self.device_limit:
            self._spill_device_locked(self.device_used - self.device_limit)

    def _check_enospc_fault(self, e: _Entry) -> None:
        """spill.disk.enospc injection point: make a spill-to-disk write
        fail exactly like a full disk would."""
        if self.faults is not None:
            act = self.faults.check("spill.disk.enospc",
                                    buffer_id=e.buffer_id,
                                    priority=e.priority, size=e.size)
            if act is not None:
                raise OSError(errno.ENOSPC,
                              "injected fault: no space left on device")

    def _check_corrupt_fault(self, e: _Entry) -> None:
        """spill.disk.corrupt injection point: flip one seeded byte of
        the on-disk payload so the read-back checksum catches it — real
        bit rot as the verifier sees it."""
        if self.faults is not None and e.disk_path:
            act = self.faults.check("spill.disk.corrupt",
                                    buffer_id=e.buffer_id,
                                    priority=e.priority, size=e.size)
            if act is not None:
                with open(e.disk_path, "r+b") as f:
                    data = f.read()
                    if data:
                        i = act.rng.randrange(len(data))
                        f.seek(i)
                        f.write(bytes([data[i] ^ 0xFF]))

    def _mark_lost_locked(self, e: _Entry) -> None:
        """Corrupt read-back: drop the unverifiable storage and mark the
        entry lost so every later acquire fails fast with
        SpillCorruptionError instead of re-reading flipped bytes."""
        self.metrics["spill_crc_failures"] += 1
        if e.disk_path:
            _unlink_quiet(e.disk_path)
            _unlink_quiet(_sidecar(e.disk_path))
        e.disk_path = None
        e.disk_codec = None
        e.arena_offset = None
        e.batch = None
        e.treedef = None
        e.leaf_meta = None
        e.tier = "lost"

    def _drop_storage_locked(self, e: _Entry) -> None:
        if e.tier == "device":
            self.device_used -= e.size
            self._gov_account(-e.size)
        elif e.tier == "host" and e.arena_offset is not None:
            self._arena.free(e.arena_offset)
        elif e.tier == "disk" and e.disk_path:
            _unlink_quiet(e.disk_path)
            _unlink_quiet(_sidecar(e.disk_path))
        e.batch = None

    # -- introspection ---------------------------------------------------
    def tier_of(self, buffer_id: int) -> str:
        with self._lock:
            return self._entries[buffer_id].tier

    def close(self) -> None:
        """Free everything.  With spark.rapids.memory.debug, buffers
        still registered (or pinned) at close are reported — the leak
        tracker analog of cudf's MemoryCleaner behind
        spark.rapids.memory.gpu.debug (RapidsConf.scala:288): a buffer
        alive at executor teardown means some operator failed to
        release it."""
        from spark_rapids_tpu.obs.registry import get_registry
        get_registry().unregister_source(self._reg_source)
        with self._lock:
            if self._debug and self._entries:
                leaks = [f"id={i} tier={e.tier} size={e.size} "
                         f"refcount={e.refcount} priority={e.priority}"
                         for i, e in sorted(self._entries.items())]
                import warnings
                # UserWarning, not ResourceWarning: the default filters
                # silently drop ResourceWarning, which would make the
                # debug flag a no-op in normal runs
                warnings.warn(
                    f"BufferCatalog leak check: {len(leaks)} buffer(s) "
                    "still registered at close:\n  " + "\n  ".join(leaks),
                    UserWarning)
            for e in list(self._entries.values()):
                self._drop_storage_locked(e)
            self._entries.clear()
            if self._arena_obj is not None and not self._arena_shared:
                self._arena_obj.close()
            self._arena_obj = None
        gov = self.governor
        if gov is not None:
            # after the entries drained (each drop mirrored its ledger
            # move): a finished query stops counting against the shed
            # watermark the moment its catalog closes
            gov.unregister(self)


def _unlink_quiet(path: str) -> None:
    try:
        os.unlink(path)
    except OSError:
        pass


def _align(n: int) -> int:
    return (n + 63) & ~63


def _align_total(metas) -> int:
    if not metas:
        return 1
    last = metas[-1]
    return max(_align(last[3] + last[2]), 1)


class SpillableColumnarBatch:
    """Hold a batch across iterator steps without pinning HBM
    (reference SpillableColumnarBatch.scala:28-47)."""

    def __init__(self, batch: ColumnBatch, catalog: BufferCatalog,
                 priority: int = SpillPriority.ACTIVE_BATCH):
        self._catalog = catalog
        self._id = catalog.add_batch(batch, priority)
        self._closed = False
        self._pins = 0
        # pin accounting is lock-protected: plan branches sharing one
        # parked list (scan reuse) and concurrent partition workers
        # get/unpin the same handle from different threads; an unlocked
        # read-modify-write loses pins and lets the catalog spill HBM
        # still in use
        self._lock = threading.Lock()

    def get(self) -> ColumnBatch:
        """Materialize AND pin; pair every get() with an unpin() once the
        batch is no longer referenced (reference incRefCount/close
        contract) so the catalog cannot spill HBM still in use."""
        with self._lock:
            if self._closed:
                # a stage recovery invalidated this map output while a
                # concurrent pull still held the handle: that pull's
                # data is gone, which is loss, not a usage bug
                raise SpillCorruptionError(
                    f"buffer {self._id}: handle closed by a concurrent "
                    "invalidation")
            b = self._catalog.acquire(self._id)
            self._pins += 1
            return b

    def unpin(self) -> None:
        with self._lock:
            if self._closed:
                return  # close() already released every pin
            assert self._pins > 0
            self._catalog.release(self._id)
            self._pins -= 1

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            while self._pins:
                self._catalog.release(self._id)
                self._pins -= 1
            self._catalog.remove(self._id)
            self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class DeviceSemaphore:
    """Bound concurrent tasks touching the chip (reference
    GpuSemaphore.scala: spark.rapids.sql.concurrentGpuTasks)."""

    def __init__(self, concurrency: int):
        self._sem = threading.BoundedSemaphore(concurrency)
        self.concurrency = concurrency

    def __enter__(self):
        self._sem.acquire()
        return self

    def __exit__(self, *exc):
        self._sem.release()


_SYNC_DISPATCH: bool | None = None


def _sync_dispatch() -> bool:
    """Whether dispatches block for synchronous OOM capture.

    On a tunneled PJRT backend each ``block_until_ready`` costs a host
    round trip (~60ms; 94 dispatches = 4.8s of a 10s TPC-DS q6 SF1
    iteration) while completing no useful work — there the engine
    dispatches asynchronously and the spill-retry loop catches only
    errors that surface at dispatch/sync points (best-effort, like the
    reference with the retry iterator disabled).  Local backends keep
    the reference's synchronous DeviceMemoryEventHandler semantics.
    SRT_SYNC_DISPATCH=0/1 forces either mode."""
    global _SYNC_DISPATCH
    if _SYNC_DISPATCH is None:
        import os
        force = os.environ.get("SRT_SYNC_DISPATCH")
        if force is not None:
            _SYNC_DISPATCH = force != "0"
        else:
            import jax
            _SYNC_DISPATCH = jax.default_backend() not in ("tpu", "axon")
    return _SYNC_DISPATCH


def _need_estimate(args, kwargs) -> int:
    """Estimate the failed allocation from the dispatched inputs: the
    device bytes of every batch argument (a program's output is on the
    order of its inputs).  0 when nothing measurable was passed — the
    governor then applies its conf'd floor."""
    need = 0
    for a in list(args) + list(kwargs.values()):
        sz = getattr(a, "device_size_bytes", None)
        if callable(sz):
            try:
                need += int(sz())
            except Exception:  # enginelint: disable=RL001 (sizing is best-effort; the floor covers a batch that cannot report)
                pass
    return need


def run_with_spill_retry(fn, catalog: BufferCatalog, *args,
                         max_retries: int = 3, spill_bytes: int | None = None,
                         **kwargs):
    """Dispatch ``fn(*args, **kwargs)``; on XLA OOM spill from the catalog
    and retry (the DeviceMemoryEventHandler.onAllocFailure loop).

    Spill sizing: governed catalogs ask the memory governor for a
    need-sized reclaim (own buffers first, then younger peers' —
    memory/governor.py); ungoverned catalogs keep the legacy blind
    quarter-budget sweep, byte-identical to the pre-governor engine."""
    faults = getattr(catalog, "faults", None)
    attempt = 0
    while True:
        try:
            if faults is not None:
                act = faults.check("memory.oom",
                                   op=getattr(fn, "__name__", str(fn)))
                if act is not None:
                    # same shape as a real XLA HBM exhaustion so the
                    # handler below spills and retries, proving the
                    # recovery path without a real device
                    raise RuntimeError(
                        "RESOURCE_EXHAUSTED: injected fault: simulated "
                        "HBM OOM (spark.rapids.test.faults memory.oom)")
            out = fn(*args, **kwargs)
            if _sync_dispatch():
                jax.block_until_ready(jax.tree_util.tree_leaves(out))
            return out
        except (RuntimeError, jax.errors.JaxRuntimeError) as ex:
            msg = str(ex)
            if "RESOURCE_EXHAUSTED" not in msg and "Out of memory" not in msg:
                raise
            catalog.metrics["oom_retries"] = \
                catalog.metrics.get("oom_retries", 0) + 1
            attempt += 1
            if attempt > max_retries:
                raise
            gov = getattr(catalog, "governor", None)
            if gov is not None:
                freed = gov.reclaim(
                    catalog, spill_bytes or _need_estimate(args, kwargs))
            else:
                freed = catalog.spill_device(
                    spill_bytes or catalog.device_limit // 4)
            if freed == 0:
                raise

"""Cross-query HBM memory governor: accounting, arbitration, shedding.

PR 5 made the engine admit N concurrent queries; each one owns a
private :class:`~spark_rapids_tpu.memory.catalog.BufferCatalog`, so the
memory plane was query-blind: query A's OOM retry spilled a blind
quarter of A's budget while B did the same, each evicting what the
other was about to unspill — the thrash/livelock shape ROADMAP item 4
names as the serving-tier failure mode.  The reference arbitrates this
with GpuSemaphore task gating, per-buffer SpillPriorities, and the
DeviceMemoryEventHandler alloc-failure callback (PAPER.md §L1-L2);
PJRT exposes none of those hooks, so the TPU-native analog is this
process-wide governor layered over the per-query catalogs:

* **Per-query accounting** — every catalog registers under its
  ``ExecCtx`` query_id; every ``add_batch``/pin/release/spill/unspill
  moves the owner's device-byte ledger, so the MetricsRegistry (pull
  source ``governor``), EXPLAIN ANALYZE footers, and diagnostic
  bundles show who holds HBM, not just that it is held.

* **Need-sized, ownership-aware arbitration** — :meth:`reclaim`
  replaces the blind ``device_limit // 4`` sweep: the requester spills
  its OWN lowest-priority buffers first, sized to the failed
  allocation (with a conf'd floor), then — only for the shortfall —
  idle peers' unpinned buffers, youngest owner first.  Pinned working
  sets are never touched (the catalog only ever spills refcount==0
  entries), and **wound-wait** ordering (older query wins) breaks the
  two-mid-retry-queries livelock: an older requester may evict a
  younger peer's spillables, a younger requester must wait for the
  older to release instead of evicting it.

* **Watermarks + background spill** — aggregate occupancy above the
  high watermark wakes a daemon that pushes idle queries' buffers to
  host until the low watermark, off the query hot path.

* **Bounded, lifecycle-integrated grant waits** — a younger loser
  parks in :meth:`reclaim` with a reservation on the wanted bytes,
  re-checking its ``QueryLifecycle`` every wakeup so cancellation and
  deadlines abort the wait (terminal errors are never swallowed), and
  gives up after ``grantTimeoutSeconds`` so a wedged peer cannot hold
  it forever.

* **Pressure-shed admission** — sustained aggregate occupancy above
  the shed watermark makes :meth:`admission_pressure` (wired into
  ``AdmissionController.pressure_hook`` by the session) reject NEW
  queries with ``QueryRejected`` instead of admitting them into an
  OOM-retry storm.

Gate-off reversibility: with ``spark.rapids.memory.governor.enabled=
false`` nothing registers, catalogs keep ``governor=None``, and every
retry path falls back to the pre-governor quarter-budget sweep —
plans and single-query behavior are byte-identical to the ungoverned
engine (tests/test_memory_governor.py proves it).

Dependency discipline: stdlib + conf + obs.registry only (like
exec/lifecycle.py), so the catalog and retry modules import this at
module level without dragging jax into light paths.
"""
from __future__ import annotations

import threading
import time
import weakref

from spark_rapids_tpu.conf import (ConfEntry, bool_conf, float_conf,
                                   int_conf, register)
from spark_rapids_tpu.obs.registry import get_registry

__all__ = ["MemoryGovernor", "get_governor", "maybe_register"]


GOVERNOR_ENABLED = bool_conf(
    "spark.rapids.memory.governor.enabled", True,
    "Cross-query HBM memory governor: per-query device-byte "
    "accounting, need-sized ownership-aware spill arbitration with "
    "wound-wait ordering (older query wins), watermark-driven "
    "background spill, and pressure-shed admission.  Disabled: "
    "catalogs stay query-blind and OOM retries fall back to the "
    "legacy quarter-budget spill sweep — byte-identical to the "
    "pre-governor engine.")
GOVERNOR_MIN_SPILL = register(ConfEntry(
    "spark.rapids.memory.governor.minSpillBytes", 16 << 20,
    "Floor for a need-sized spill request: an OOM retry asks the "
    "governor for max(failed allocation estimate, this floor) instead "
    "of the legacy blind quarter of the device budget, so tiny "
    "allocations stop evicting whole working sets.", conv=int))
GOVERNOR_HIGH_WM = float_conf(
    "spark.rapids.memory.governor.highWatermark", 0.85,
    "Aggregate device occupancy fraction above which the governor's "
    "background thread starts spilling idle queries' lowest-priority "
    "buffers to host (proactive, off the query hot path).")
GOVERNOR_LOW_WM = float_conf(
    "spark.rapids.memory.governor.lowWatermark", 0.65,
    "Background spill stops once aggregate occupancy is back under "
    "this fraction (hysteresis partner of highWatermark).")
GOVERNOR_SHED_WM = float_conf(
    "spark.rapids.memory.governor.shedWatermark", 0.95,
    "Aggregate occupancy fraction above which — once sustained for "
    "shedHoldSeconds — NEW queries are load-shed at admission with "
    "QueryRejected instead of joining an OOM-retry storm.  Admitted "
    "queries are never shed, only throttled by arbitration.")
GOVERNOR_SHED_HOLD = float_conf(
    "spark.rapids.memory.governor.shedHoldSeconds", 1.0,
    "How long aggregate occupancy must stay above shedWatermark "
    "before admission sheds — a single transient spike between two "
    "batches must not reject a query.")
GOVERNOR_GRANT_TIMEOUT = float_conf(
    "spark.rapids.memory.governor.grantTimeoutSeconds", 10.0,
    "Longest a wound-wait loser blocks for a memory grant before the "
    "OOM propagates to its split-and-retry ladder.  Cancellation and "
    "deadlines abort the wait early at every wakeup (the wait is a "
    "cooperative cancellation point); 0 disables waiting entirely.")
GOVERNOR_POLL_MS = int_conf(
    "spark.rapids.memory.governor.pollIntervalMs", 50,
    "Background watermark-spill thread poll interval.  The thread "
    "exists only while governed catalogs are registered and parks on "
    "an event otherwise.")


class _QueryState:
    """Ledger for one registered query (one catalog)."""

    __slots__ = ("query_id", "seq", "cat_ref", "lifecycle",
                 "device_bytes", "pinned_bytes", "peak_bytes",
                 "reserved_bytes")

    def __init__(self, query_id: str, seq: int, catalog, lifecycle):
        self.query_id = query_id
        self.seq = seq                      # admission order: lower = older
        self.cat_ref = weakref.ref(catalog)
        self.lifecycle = lifecycle
        self.device_bytes = 0
        self.pinned_bytes = 0
        self.peak_bytes = 0
        self.reserved_bytes = 0


class MemoryGovernor:
    """Process-wide arbiter over every registered per-query catalog.

    All public entry points are thread-safe; ``_cond`` guards the
    ledgers AND doubles as the grant-wait channel (released bytes
    notify parked waiters)."""

    def __init__(self):
        self._cond = threading.Condition()
        self._states: dict[int, _QueryState] = {}   # id(catalog) -> state
        self._seq = 0
        self._budget = 0          # max of registered catalogs' device limits
        self._over_since: float | None = None
        self._bg_thread: threading.Thread | None = None
        self._bg_wake = threading.Event()
        self._bg_stop = threading.Event()
        # conf snapshot, refreshed at each register() from that query's
        # conf — one session's settings win for process-wide knobs,
        # matching how the shared pinned arena is sized today
        self._min_spill = GOVERNOR_MIN_SPILL.default
        self._high_wm = GOVERNOR_HIGH_WM.default
        self._low_wm = GOVERNOR_LOW_WM.default
        self._shed_wm = GOVERNOR_SHED_WM.default
        self._shed_hold = GOVERNOR_SHED_HOLD.default
        self._grant_timeout = GOVERNOR_GRANT_TIMEOUT.default
        self._poll_s = GOVERNOR_POLL_MS.default / 1000.0
        # the process result cache (exec/result_cache.py), weakly held:
        # its entries are the governor's LOWEST-priority occupants —
        # unpinned, rebuildable — evicted before any query is wounded
        # or load-shed
        self._cache_ref = None
        # control-plane watermark overrides (None = static conf): the
        # register() conf refresh below would silently clobber an
        # adapted watermark on the next query, so overrides are
        # re-applied after every refresh
        self._wm_override: "tuple[float, float] | None" = None
        get_registry().register_source("governor", self._source)

    def set_watermark_overrides(self, high: "float | None",
                                low: "float | None") -> None:
        """Control-plane actuation: pin the high/low spill watermarks
        to adapted values that survive the per-query conf refresh in
        :meth:`register`.  ``(None, None)`` clears the override — the
        next register() restores the static conf values (the
        controller calls that on stop(), so a stopped control plane
        leaves no residue).  Waiters are woken: a lowered watermark
        may make spilling (and therefore grants) possible right now."""
        with self._cond:
            if high is None or low is None:
                self._wm_override = None
            else:
                self._wm_override = (float(high), float(low))
                self._high_wm, self._low_wm = self._wm_override
            self._bg_wake.set()
            self._cond.notify_all()

    def watermarks(self) -> dict:
        """Current effective watermarks (+ whether the control plane
        has them overridden) for the /control endpoint."""
        with self._cond:
            return {"high": self._high_wm, "low": self._low_wm,
                    "shed": self._shed_wm,
                    "overridden": self._wm_override is not None}

    def register_cache(self, cache) -> None:
        """Bind the process-wide result/fragment cache as the first
        eviction victim under memory pressure (weakref: the governor
        must never keep the cache alive)."""
        self._cache_ref = weakref.ref(cache)

    def _evict_cache(self, need_bytes, kind=None) -> int:
        """Drop idle cache entries; returns DEVICE bytes freed (host
        result blobs relieve RAM, not HBM, so only fragment bytes
        count toward device pressure)."""
        ref = self._cache_ref
        cache = ref() if ref is not None else None
        if cache is None:
            return 0
        dev_before = cache.device_bytes()
        freed = cache.evict(need_bytes, kind=kind)
        if freed:
            get_registry().inc("governor_cache_evict_bytes", freed)
        return dev_before - cache.device_bytes()

    # -- registration ------------------------------------------------------

    def register(self, catalog, query_id: str, lifecycle, settings) -> None:
        """Bind a per-query catalog to the governor.  Called by
        ``ExecCtx.catalog`` right after construction; the catalog
        mirrors every device-byte move here until ``unregister``."""
        self._min_spill = GOVERNOR_MIN_SPILL.get(settings)
        self._high_wm = GOVERNOR_HIGH_WM.get(settings)
        self._low_wm = GOVERNOR_LOW_WM.get(settings)
        self._shed_wm = GOVERNOR_SHED_WM.get(settings)
        self._shed_hold = GOVERNOR_SHED_HOLD.get(settings)
        self._grant_timeout = GOVERNOR_GRANT_TIMEOUT.get(settings)
        self._poll_s = max(GOVERNOR_POLL_MS.get(settings), 1) / 1000.0
        ov = self._wm_override
        if ov is not None:
            self._high_wm, self._low_wm = ov
        with self._cond:
            st = _QueryState(query_id, self._seq, catalog, lifecycle)
            # a catalog garbage-collected without close() (leaked by
            # its owner) must not pin its ledger forever: stale bytes
            # would inflate aggregate occupancy for every later query,
            # turning headroom permanently negative
            key = id(catalog)
            st.cat_ref = weakref.ref(
                catalog, lambda _r, _s=self, _k=key: _s._drop_dead(_k))
            self._seq += 1
            self._states[key] = st
            self._budget = max((s.cat_ref().device_limit
                                for s in self._states.values()
                                if s.cat_ref() is not None), default=0)
            catalog.governor = self
            catalog.query_id = query_id
            self._ensure_bg_locked()

    def unregister(self, catalog) -> None:
        """Drop a catalog's ledger (catalog.close()).  Its bytes are
        already zero by then — close() dropped every entry — but the
        ledger is cleared defensively and waiters are woken since a
        whole query's worth of HBM just went away."""
        with self._cond:
            self._states.pop(id(catalog), None)
            catalog.governor = None
            if not self._states:
                self._stop_bg_locked()
            self._cond.notify_all()

    def _drop_dead(self, key: int) -> None:
        """Weakref callback: a governed catalog died without close().
        Drop its ledger so leaked bytes cannot masquerade as occupancy
        (``_cond`` is an RLock underneath, so firing on a thread that
        already holds it is safe)."""
        with self._cond:
            st = self._states.get(key)
            if st is not None and st.cat_ref() is None:
                del self._states[key]
                if not self._states:
                    self._stop_bg_locked()
                self._cond.notify_all()

    # -- accounting --------------------------------------------------------

    def account(self, catalog, delta: int) -> None:
        """Mirror a device_used move (+add/unspill, -spill/remove) into
        the owner's ledger.  Called under the catalog lock from the
        sites that mutate ``device_used`` — cheap: one dict hit."""
        with self._cond:
            st = self._states.get(id(catalog))
            if st is None:
                return
            st.device_bytes += delta
            if st.device_bytes > st.peak_bytes:
                st.peak_bytes = st.device_bytes
            if delta < 0:
                # memory came free: wake grant waiters
                self._cond.notify_all()
            else:
                self._update_pressure_locked()

    def account_pinned(self, catalog, delta: int) -> None:
        """Mirror a pin/unpin transition (refcount 0->1 / 1->0) so
        arbitration can see how much of a query's footprint is
        working set vs spillable."""
        with self._cond:
            st = self._states.get(id(catalog))
            if st is not None:
                st.pinned_bytes += delta

    # -- arbitration -------------------------------------------------------

    def reclaim(self, catalog, need_bytes: int) -> int:
        """Free at least ``need_bytes`` of device memory for ``catalog``
        (best effort; returns bytes actually freed, possibly 0).

        Order: the requester's own lowest-priority unpinned buffers,
        then — for the shortfall — peers' unpinned buffers, youngest
        owner first, skipping owners OLDER than the requester
        (wound-wait: the older query wins; the younger parks in a
        bounded, cancellable grant wait for the older to release).
        Pinned buffers are never candidates at any step."""
        need = max(int(need_bytes), self._min_spill)
        st = None
        with self._cond:
            st = self._states.get(id(catalog))
        faults = getattr(catalog, "faults", None)
        if faults is not None:
            act = faults.check("memory.governor.oom_storm",
                               query_id=getattr(st, "query_id", "?"),
                               need=need)
            if act is not None:
                # storm mode: arbitration "cannot keep up" — report
                # nothing freed so the caller's split ladder absorbs
                # the pressure (deterministic livelock-shape chaos)
                get_registry().inc("governor_storm_denials")
                return 0
        reg = get_registry()
        reg.inc("governor_reclaims")
        # lowest priority first: idle shared-scan fragments in the
        # result cache are rebuildable — drop them before spilling the
        # requester's own working set, let alone wounding a peer
        freed = self._evict_cache(need, kind="fragment")
        if freed >= need:
            return freed
        own = catalog.spill_device(need - freed)
        freed += own
        reg.inc("governor_spill_bytes_own", own)
        if freed >= need or st is None:
            return freed
        freed += self._reclaim_from_peers(st, need - freed)
        if freed > 0:
            return freed
        # nothing anywhere the requester may touch: park for a grant
        # (older peers may be about to release), then report whatever
        # the wait yielded — 0 lets the caller split
        return self._wait_for_grant(catalog, st, need)

    def _reclaim_from_peers(self, st: _QueryState, shortfall: int) -> int:
        """Spill unpinned buffers from YOUNGER peers, youngest first.
        Peers older than the requester are off limits (wound-wait)."""
        reg = get_registry()
        with self._cond:
            peers = sorted((s for s in self._states.values()
                            if s is not st and s.seq > st.seq),
                           key=lambda s: -s.seq)
            victims = [(s, s.cat_ref()) for s in peers]
        freed = 0
        for vs, vcat in victims:
            if freed >= shortfall:
                break
            if vcat is None:
                continue
            try:
                got = vcat.spill_device(shortfall - freed)
            # enginelint: disable=RL001 (a victim's failure — terminal lifecycle or spill I/O — is the VICTIM's state; it must never kill the requester)
            except Exception:
                reg.inc("governor_victim_errors")
                continue
            if got:
                freed += got
                reg.inc("governor_spills_peer")
                reg.inc("governor_spill_bytes_peer", got)
        return freed

    def _wait_for_grant(self, catalog, st: _QueryState, need: int) -> int:
        """Park until peers release at least ``need`` bytes (observed as
        aggregate occupancy dropping enough to plausibly fit), the
        grant times out, or the query's lifecycle turns terminal.
        The reservation is visible in the ``governor.reserved_bytes``
        gauge and ALWAYS released on exit — success, timeout,
        cancellation, or deadline."""
        timeout = self._grant_timeout
        if timeout <= 0:
            return 0
        with self._cond:
            # only park when a wait can plausibly be granted:
            # * headroom already >= need: the OOM is outside the
            #   ledger's model (fragmentation, injected storm) and no
            #   peer release changes anything — split instead
            # * no LIVE peer registered: nobody exists to release the
            #   shortfall — a solo query waiting on itself is pure stall
            # * need unreachable: even every peer byte released leaves
            #   less than need under the requester's budget
            if self._headroom_locked(st) >= need:
                return 0
            if not any(s is not st and s.cat_ref() is not None
                       for s in self._states.values()):
                return 0
            cat = st.cat_ref()
            limit = cat.device_limit if cat is not None else self._budget
            if need > limit - st.device_bytes:
                return 0
        reg = get_registry()
        reg.inc("governor_grant_waits")
        lc = st.lifecycle
        faults = getattr(catalog, "faults", None)
        if faults is not None:
            act = faults.check("memory.grant.stall",
                               query_id=st.query_id, need=need)
            if act is not None:
                # injected stall: hold the waiter the full configured
                # seconds before the normal wait loop, cancellation
                # still honored (chaos proves mid-wait cancel unwinds)
                stall = act.param("seconds", 0.05)
                if lc is not None:
                    lc.wait(stall)
                else:
                    time.sleep(stall)
        deadline = time.monotonic() + timeout
        with self._cond:
            st.reserved_bytes = need
            try:
                while True:
                    if lc is not None:
                        lc.check()  # terminal -> raises, finally releases
                    if self._headroom_locked(st) >= need:
                        reg.inc("governor_grants")
                        return need
                    rem = deadline - time.monotonic()
                    if rem <= 0:
                        reg.inc("governor_grant_timeouts")
                        return 0
                    self._cond.wait(min(rem, 0.05))
            finally:
                st.reserved_bytes = 0
                self._cond.notify_all()

    def _headroom_locked(self, st: _QueryState) -> int:
        """Device bytes the requester could allocate right now: its
        catalog budget minus everything currently registered across
        queries (catalogs share one physical HBM)."""
        cat = st.cat_ref()
        limit = cat.device_limit if cat is not None else self._budget
        return limit - self._total_locked()

    def _total_locked(self) -> int:
        return sum(s.device_bytes for s in self._states.values())

    # -- admission pressure ------------------------------------------------

    def _update_pressure_locked(self) -> None:
        if self._budget <= 0:
            self._over_since = None
            return
        frac = self._total_locked() / self._budget
        now = time.monotonic()
        if frac >= self._shed_wm:
            if self._over_since is None:
                self._over_since = now
        else:
            self._over_since = None
        if frac >= self._high_wm:
            self._bg_wake.set()

    def admission_pressure(self, tenant: "str | None" = None
                           ) -> str | None:
        """AdmissionController pressure hook: a reason string when new
        admissions should be shed (aggregate occupancy has sat above
        shedWatermark for shedHoldSeconds), else None.  Reading is
        cheap — admission already takes a lock of its own.  Memory
        pressure is tenant-blind (``tenant`` is accepted for the hook
        signature; per-tenant targeting lives in the control plane's
        composed hook) — the controller's over-share gate decides who
        absorbs the shed."""
        with self._cond:
            self._update_pressure_locked()
            over = self._over_since
            if over is None or self._budget <= 0:
                return None
            held = time.monotonic() - over
            if held < self._shed_hold:
                return None
            frac = self._total_locked() / self._budget
        # lowest-priority occupant goes first: if dropping idle cached
        # scan fragments actually freed device bytes, this pressure
        # event is absorbed by the cache and no query is shed (result
        # blobs are host memory and cannot relieve HBM — they don't
        # spare a shed)
        if self._evict_cache(None, kind="fragment") > 0:
            return None
        get_registry().inc("governor_pressure_sheds")
        return (f"memory pressure: device occupancy {frac:.0%} above "
                f"shedWatermark={self._shed_wm:g} for {held:.1f}s "
                "(spark.rapids.memory.governor.*)")

    # -- background watermark spill ----------------------------------------

    def _ensure_bg_locked(self) -> None:
        if self._bg_thread is not None and self._bg_thread.is_alive():
            return
        self._bg_stop.clear()
        t = threading.Thread(target=self._bg_loop, daemon=True,
                             name="tpu-mem-governor")
        self._bg_thread = t
        t.start()

    def _stop_bg_locked(self) -> None:
        self._bg_stop.set()
        self._bg_wake.set()
        self._bg_thread = None

    def _bg_loop(self) -> None:
        """Proactive spill off the hot path: when aggregate occupancy
        crosses the high watermark, push idle (youngest-first) queries'
        unpinned buffers to host until the low watermark.  The loop
        parks on an event between checks and exits when the last
        catalog unregisters."""
        reg = get_registry()
        # enginelint: disable=RL004 (daemon loop; bounded by _bg_stop, set when the last catalog unregisters)
        while not self._bg_stop.is_set():
            self._bg_wake.wait(self._poll_s)
            self._bg_wake.clear()
            if self._bg_stop.is_set():
                return
            with self._cond:
                budget = self._budget
                total = self._total_locked()
                if budget <= 0 or total < self._high_wm * budget:
                    continue
                target = total - int(self._low_wm * budget)
                victims = [s.cat_ref() for s in
                           sorted(self._states.values(),
                                  key=lambda s: -s.seq)]
            moved = 0
            for vcat in victims:
                if moved >= target or vcat is None:
                    break
                try:
                    got = vcat.spill_device(target - moved)
                # enginelint: disable=RL001 (one victim's failure must not kill the watermark daemon; the per-query retry paths surface real errors)
                except Exception:
                    reg.inc("governor_victim_errors")
                    continue
                if got:
                    moved += got
            if moved:
                reg.inc("governor_background_spills")
                reg.inc("governor_spill_bytes_background", moved)

    # -- introspection -----------------------------------------------------

    def reserved_bytes(self) -> int:
        """Outstanding grant reservations (must be 0 when no query is
        mid-wait — the premerge gate's leak check)."""
        with self._cond:
            return sum(s.reserved_bytes for s in self._states.values())

    def query_stats(self, query_id: str | None = None) -> dict:
        """Per-query ledgers: {query_id: {device_bytes, pinned_bytes,
        peak_bytes, reserved_bytes, seq}} (one entry when filtered)."""
        with self._cond:
            out = {}
            for s in self._states.values():
                if query_id is not None and s.query_id != query_id:
                    continue
                out[s.query_id] = {
                    "device_bytes": s.device_bytes,
                    "pinned_bytes": s.pinned_bytes,
                    "peak_bytes": s.peak_bytes,
                    "reserved_bytes": s.reserved_bytes,
                    "seq": s.seq,
                }
            return out

    def occupancy_sample(self) -> dict:
        """One compact occupancy snapshot in a SINGLE lock acquisition:
        total device bytes, per-query ledger bytes, and the effective
        watermark position.  The cost-attribution plane's HBM sampler
        (obs/profile.py) polls this at tens of Hz, so it must not take
        the condition lock four separate times the way composing
        ``query_stats``+``watermarks``+``reserved_bytes`` would."""
        with self._cond:
            return {
                "device_bytes_total": self._total_locked(),
                "reserved_bytes": sum(s.reserved_bytes
                                      for s in self._states.values()),
                "budget_bytes": self._budget,
                "per_query": {s.query_id: s.device_bytes
                              for s in self._states.values()},
                "watermarks": {"high": self._high_wm, "low": self._low_wm,
                               "shed": self._shed_wm,
                               "overridden":
                                   self._wm_override is not None},
            }

    def _source(self) -> dict:
        """MetricsRegistry pull source: aggregate + per-query gauges
        (bounded — entries exist only while their query runs)."""
        with self._cond:
            vals = {
                "device_bytes_total": self._total_locked(),
                "reserved_bytes": sum(s.reserved_bytes
                                      for s in self._states.values()),
                "queries_registered": len(self._states),
                "budget_bytes": self._budget,
            }
            for s in self._states.values():
                q = s.query_id
                vals[f"q.{q}.device_bytes"] = s.device_bytes
                vals[f"q.{q}.pinned_bytes"] = s.pinned_bytes
                vals[f"q.{q}.peak_bytes"] = s.peak_bytes
            return vals


_GOVERNOR: MemoryGovernor | None = None
_GOV_LOCK = threading.Lock()


def get_governor() -> MemoryGovernor:
    """The process-wide governor singleton (created on first use)."""
    global _GOVERNOR
    with _GOV_LOCK:
        if _GOVERNOR is None:
            _GOVERNOR = MemoryGovernor()
        return _GOVERNOR


def maybe_register(catalog, query_id: str, lifecycle, conf) -> None:
    """Register ``catalog`` with the governor when the conf enables it;
    a strict no-op otherwise (the catalog keeps ``governor=None`` and
    every retry path stays on the legacy quarter-budget sweep)."""
    settings = getattr(conf, "settings", None) or {}
    if not GOVERNOR_ENABLED.get(settings):
        return
    get_governor().register(catalog, query_id, lifecycle, settings)

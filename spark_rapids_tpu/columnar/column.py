"""Device-resident column: the TPU analog of cuDF ColumnVector.

Reference: GpuColumnVector.java:40 wraps a cuDF device ColumnVector inside a
Spark ColumnarBatch column. Here a column is a pytree of jax arrays:

* fixed-width types: ``data``  — jax array ``[capacity]`` (numpy dtype from
  :mod:`spark_rapids_tpu.types`), ``validity`` — bool ``[capacity]``.
* strings:           ``data``  — uint8 ``[capacity, max_len]`` padded UTF-8
  bytes, ``lengths`` — int32 ``[capacity]``, ``validity`` as above.

TPU-first design notes (why this is not cuDF's offsets+chars layout): XLA
requires static shapes, so variable-width character buffers whose total size
depends on the data would force a recompile per batch.  A padded byte matrix
keeps every string op a dense vectorized kernel on the VPU (compare, slice,
case-map) at the cost of padding; ``max_len`` is bucketed to powers of two to
bound the number of compiled variants.

Rows at index >= the owning batch's ``num_rows`` are *padding*: their
validity is False and data is zeroed.  Invalid (null) rows also carry zeroed
data so reductions can run unmasked and be corrected via validity.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T

__all__ = ["DeviceColumn", "round_string_width"]


def round_string_width(n: int) -> int:
    """Bucket a max string byte-length to a power of two (min 4)."""
    c = 4
    while c < n:
        c <<= 1
    return c


@jax.tree_util.register_pytree_node_class
class DeviceColumn:
    """One column of a device batch. Immutable."""

    __slots__ = ("data", "validity", "lengths", "dtype")

    def __init__(self, data: jax.Array, validity: jax.Array,
                 dtype: T.DataType, lengths: Optional[jax.Array] = None):
        self.data = data
        self.validity = validity
        self.lengths = lengths
        self.dtype = dtype

    # -- pytree protocol ----------------------------------------------------
    def tree_flatten(self):
        if self.lengths is None:
            return (self.data, self.validity), (self.dtype, False)
        return (self.data, self.validity, self.lengths), (self.dtype, True)

    @classmethod
    def tree_unflatten(cls, aux, children):
        dtype, has_len = aux
        if has_len:
            data, validity, lengths = children
            return cls(data, validity, dtype, lengths)
        data, validity = children
        return cls(data, validity, dtype)

    # -- properties ---------------------------------------------------------
    @property
    def capacity(self) -> int:
        return self.data.shape[0]

    @property
    def is_string(self) -> bool:
        return isinstance(self.dtype, T.StringType)

    @property
    def is_array(self) -> bool:
        return isinstance(self.dtype, T.ArrayType)

    @property
    def is_var_width(self) -> bool:
        """Matrix-layout column (strings, arrays): data[capacity,
        max_len] + lengths[capacity]."""
        return self.lengths is not None

    @property
    def max_len(self) -> int:
        assert self.is_var_width
        return self.data.shape[1]

    def with_validity(self, validity: jax.Array) -> "DeviceColumn":
        return DeviceColumn(self.data, validity, self.dtype, self.lengths)

    # -- construction helpers ----------------------------------------------
    @staticmethod
    def stage_fixed(data: np.ndarray, validity: np.ndarray | None,
                    capacity: int) -> tuple:
        """Pad host numpy data to ``capacity``; returns (data, validity)
        host leaves (no device move — see batch._PackBuilder)."""
        n = data.shape[0]
        assert n <= capacity, (n, capacity)
        if validity is None:
            validity = np.ones(n, dtype=np.bool_)
        vfull = np.zeros(capacity, dtype=np.bool_)
        vfull[:n] = validity
        dfull = np.zeros((capacity,) + data.shape[1:], dtype=data.dtype)
        dfull[:n] = data
        # zero out null slots for deterministic padding semantics
        dfull[:n][~validity] = 0
        return dfull, vfull

    @staticmethod
    def from_numpy(data: np.ndarray, validity: np.ndarray | None,
                   dtype: T.DataType, capacity: int) -> "DeviceColumn":
        """Pad host numpy data to ``capacity`` and move to device."""
        dfull, vfull = DeviceColumn.stage_fixed(data, validity, capacity)
        return DeviceColumn(jnp.asarray(dfull), jnp.asarray(vfull), dtype)

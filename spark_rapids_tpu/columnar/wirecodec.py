"""Wire codec: encoded host->device transfers, decoded on device.

The reference ships compressed tables over its transports and
decompresses ON the GPU (nvcomp seam, TableCompressionCodec.scala:41,
GpuCompressedColumnVector.java) because PCIe/IB bandwidth — not kernel
time — bounds scan-heavy queries.  The TPU analog has the same shape:
the (tunneled) PJRT link moves ~15 MB/s, so every column is encoded
host-side into compact integer streams and decoded INSIDE the single
jitted unpack program that already materializes a packed batch
(columnar/batch.py _PackBuilder) — the decode fuses with the
slice/reshape pass and costs no extra dispatch or host round trip.

Encodings (chosen per column per batch, host-side, O(n) numpy passes):

* ints / dates / timestamps / bools — frame-of-reference + bit-packing:
  ship ``ceil(n*b/32)`` uint32 words where ``b = bit_length(max-min)``,
  decode ``(bits + min) * div``; an optional integral divisor (1e3/1e6)
  catches second-aligned timestamps.
* float64 — when exactly representable as scaled integers (money is
  cents: ``rint(v/s)*s == v`` bitwise for s in {1, 0.01}), ship the
  FOR/bit-packed integers and decode ``(bits + base) * s``.
* strings — pyarrow dictionary encoding when it pays: ship the (small)
  dictionary byte-matrix plus bit-packed indices; decode is one gather.
* validity — all-valid columns ship NOTHING (decode compares against
  num_rows); others ship 1 bit/row.

Bit widths are arbitrary (1..32, values may straddle word boundaries),
not power-of-two buckets: a 17-bit key column ships 17 bits, not 32.
"""
from __future__ import annotations

import numpy as np

__all__ = ["encode_fixed", "encode_lengths", "maybe_dict_arrow",
           "pack_bits_host", "decode_data", "decode_validity",
           "bits_needed"]

_FAST_BITS = {8: np.uint8, 16: np.uint16, 32: np.uint32}

#: integral divisors probed for int64 columns (timestamp micros that are
#: second- or milli-aligned shrink below the 32-bit FOR window)
_INT_DIVISORS = (1_000_000, 1_000)
#: scales probed for float64 columns (money = cents first, then whole)
_FLOAT_SCALES = (0.01, 1.0)


#: bit widths are BUCKETED: the unpack program's structure (and the
#: encoded leaf sizes feeding every later leaf's offset) depend on the
#: width, so free widths would compile a fresh program whenever a
#: batch's value range crosses a bit boundary — these rungs keep the
#: variant count bounded while staying within ~15% of minimal bits
_BIT_BUCKETS = (1, 2, 4, 8, 12, 16, 20, 24, 28, 32)


def bits_needed(rng: int) -> int:
    """Bucketed bits to hold values in [0, rng]."""
    raw = max(1, int(rng).bit_length())
    for b in _BIT_BUCKETS:
        if raw <= b:
            return b
    return raw


def pack_bits_host(vals: np.ndarray, bits: int, cap: int) -> np.ndarray:
    """Pack ``vals`` (non-negative, < 2**bits, any int dtype) into a
    little-endian bit stream of ``cap`` slots, returned as uint32 words.
    Slots beyond ``len(vals)`` are zero bits."""
    n = vals.shape[0]
    nwords = (cap * bits + 31) // 32
    if bits in _FAST_BITS:
        per = 32 // bits
        buf = np.zeros(nwords * per, dtype=_FAST_BITS[bits])
        buf[:n] = vals.astype(_FAST_BITS[bits])
        return buf.view(np.uint32)
    # Word-level shift/or accumulation.  The previous formulation built
    # an n x bits uint8 bit-matrix plus a 32-aligned bit stream (~n*bits
    # bytes each — ~120 MB of host staging per 4M-row 24-bit column
    # before the arrays even reached packbits).  Values are laid out in
    # BLOCKS of lcm(bits, 32): g = lcm/bits values fill exactly
    # wpb = lcm/32 words, value j of a block starting at bit j*bits —
    # and because g*bits == wpb*32, no value ever spills across a block
    # boundary, so each of the g column passes is a pure vectorized
    # shift/or over the block rows with no scatter and no carries.
    # Peak temporaries are O(n) bytes (padded input + one uint64 column
    # + the uint64 accumulator), independent of the bit width.
    from math import gcd
    lcm = bits * 32 // gcd(bits, 32)
    g = lcm // bits             # values per block
    wpb = lcm // 32             # words per block
    nblocks = (nwords + wpb - 1) // wpb
    padded = np.zeros(nblocks * g, dtype=vals.dtype)
    padded[:n] = vals
    blocks = padded.reshape(nblocks, g)
    acc = np.zeros((nblocks, wpb + 1), np.uint64)
    for j in range(g):
        off = j * bits
        wi, sh = off // 32, np.uint64(off % 32)
        contrib = blocks[:, j].astype(np.uint64) << sh
        acc[:, wi] |= contrib & np.uint64(0xFFFFFFFF)
        acc[:, wi + 1] |= contrib >> np.uint64(32)
    return acc[:, :wpb].reshape(-1)[:nwords].astype(np.uint32)


def _unpack_bits_device(words, cap: int, bits: int):
    """uint32[cap] of ``bits``-bit values from the packed word stream
    (traced; runs inside the batch unpack program)."""
    import jax.numpy as jnp
    mask = jnp.uint32((1 << bits) - 1) if bits < 32 else jnp.uint32(0xFFFFFFFF)
    i = jnp.arange(cap, dtype=jnp.uint32)
    if bits in _FAST_BITS:
        per = 32 // bits
        w = words[(i // per).astype(jnp.int32)]
        sh = (i % per) * jnp.uint32(bits)
        return (w >> sh) & mask
    nwords = words.shape[0]
    o = i * jnp.uint32(bits)
    wi = (o >> 5).astype(jnp.int32)
    sh = o & jnp.uint32(31)
    lo = words[wi] >> sh
    hi = words[jnp.minimum(wi + 1, nwords - 1)]
    # (32 - sh) & 31 keeps the shift defined when sh == 0; the where
    # discards that lane anyway
    spill = jnp.where(sh > 0, hi << ((jnp.uint32(32) - sh) & jnp.uint32(31)),
                      jnp.uint32(0))
    return (lo | spill) & mask


# ---------------------------------------------------------------------------
# Host-side encoding decisions
# ---------------------------------------------------------------------------

def _valid_minmax(data: np.ndarray, validity: np.ndarray | None):
    """(vmin, vmax) over valid slots; None when no valid values."""
    if validity is not None and not validity.all():
        if not validity.any():
            return None
        data = data[validity]
    if data.size == 0:
        return None
    return data.min(), data.max()


def encode_fixed(data: np.ndarray, validity: np.ndarray | None, cap: int,
                 add_leaf, add_i64):
    """Encode one fixed-width column's data leaf.

    ``data`` is the UNPADDED host array (null slots already zeroed).
    ``add_leaf(arr)`` registers a host buffer and returns its leaf index;
    ``add_i64`` registers a dynamic decode param (the FOR base) and
    returns its param index.  Divisors/scales come from tiny fixed menus
    so they ride the spec as STATIC program constants.  Returns the
    data_desc spec tuple.
    """
    dt = data.dtype
    out_dtype = dt.str

    def raw():
        full = np.zeros((cap,) + data.shape[1:], dtype=dt)
        full[:data.shape[0]] = data
        return ("raw", add_leaf(full))

    if dt.kind == "b":
        return ("bits", add_leaf(pack_bits_host(
            data.astype(np.uint8), 1, cap)), 1, out_dtype, add_i64(0), 1)
    if dt.kind in "iu":
        mm = _valid_minmax(data.astype(np.int64, copy=False), validity)
        if mm is None:
            return ("bits", add_leaf(pack_bits_host(
                np.zeros(0, np.uint32), 1, cap)), 1, out_dtype,
                add_i64(0), 1)
        vmin, vmax = int(mm[0]), int(mm[1])
        div = 1
        if dt.itemsize == 8 and vmax - vmin >= (1 << 32):
            for d in _INT_DIVISORS:
                q, r = np.divmod(data.astype(np.int64, copy=False), d)
                if not r.any() and (vmax - vmin) // d < (1 << 32):
                    data, vmin, vmax, div = q, vmin // d, vmax // d, d
                    break
            else:
                return raw()
        rng = vmax - vmin
        if rng >= (1 << 32):
            return raw()
        bits = bits_needed(rng)
        if bits >= dt.itemsize * 8 and div == 1:
            return raw()
        enc = (data.astype(np.int64, copy=False) - vmin).astype(np.uint32)
        if validity is not None and not validity.all():
            enc = np.where(validity, enc, 0)
        return ("bits", add_leaf(pack_bits_host(enc, bits, cap)), bits,
                out_dtype, add_i64(vmin), div)
    if dt.kind == "f" and dt.itemsize == 8:
        v = data
        # -0.0 round-trips to +0.0 through the integer path; the values
        # compare equal but format differently ("-0" vs "0") in the
        # differential harness — ship raw when any negative zero exists
        zeros = v == 0
        if zeros.any() and np.signbit(v[zeros]).any():
            return raw()
        for scale in _FLOAT_SCALES:
            with np.errstate(invalid="ignore", over="ignore"):
                ints = np.rint(v / scale)
            if not np.isfinite(ints).all():
                break  # NaN/inf present: ship raw
            if not (ints * scale == v).all():
                continue  # not exactly representable at this scale
            mm = _valid_minmax(ints, validity)
            vmin = 0 if mm is None else int(mm[0])
            vmax = 0 if mm is None else int(mm[1])
            rng = vmax - vmin
            if rng >= (1 << 32):
                continue
            bits = bits_needed(rng)
            if bits > 32:
                continue
            enc = (ints.astype(np.int64) - vmin).astype(np.uint32)
            if validity is not None and not validity.all():
                enc = np.where(validity, enc, 0)
            return ("fbits", add_leaf(pack_bits_host(enc, bits, cap)),
                    bits, out_dtype, add_i64(vmin), scale)
        return raw()
    return raw()


def encode_lengths(lengths: np.ndarray, cap: int, max_len: int,
                   add_leaf, add_i64):
    """Length vectors are in [0, max_len]: always bit-packable."""
    bits = bits_needed(max(int(max_len), 1))
    return ("bits", add_leaf(pack_bits_host(
        lengths.astype(np.uint32), bits, cap)), bits, "<i4",
        add_i64(0), 1)


def maybe_dict_arrow(arr, n: int):
    """Try pyarrow dictionary encoding for a string array; returns
    (indices int32[n] with nulls->0, dictionary pa.Array) when the
    encoded form is materially smaller, else None."""
    if n < 4096:
        return None
    import pyarrow.compute as pc
    try:
        enc = arr.dictionary_encode()
    # enginelint: disable=RL001 (dictionary codec is best-effort; un-encodable arrays ship raw)
    except Exception:  # noqa: BLE001 - codec is best-effort
        return None
    k = len(enc.dictionary)
    if k == 0 or k > max(256, n // 8):
        return None
    idx = enc.indices
    if idx.null_count:
        idx = pc.fill_null(idx, 0)
    return np.asarray(idx, dtype=np.int64).astype(np.int32), enc.dictionary


# ---------------------------------------------------------------------------
# Device-side decode (traced helpers called from the unpack program)
# ---------------------------------------------------------------------------

def decode_validity(desc, leaf, cap: int, nr):
    """bool[cap] from a validity desc — ("av",) derives the mask from
    the row count, ("vbits", i) unpacks 1 bit/row; ``leaf`` resolves
    leaf indices to traced arrays, ``nr`` is the traced row count."""
    import jax.numpy as jnp
    if desc[0] == "av":
        return jnp.arange(cap, dtype=jnp.int32) < nr
    return _unpack_bits_device(leaf(desc[1]), cap, 1) != 0


def decode_data(desc, leaf, i64p, cap: int):
    """Traced decode of a data/lengths desc to its full-capacity array
    (padding/null slots NOT yet zeroed — the caller masks by validity).
    Divisors/scales are static program constants; only the FOR base is
    dynamic (read from the i64 params vector)."""
    import jax.numpy as jnp
    kind = desc[0]
    if kind == "raw":
        return leaf(desc[1])
    _, li, bits, out_dtype, pbase, factor = desc
    raw = _unpack_bits_device(leaf(li), cap, bits)
    dt = np.dtype(out_dtype)
    if kind == "fbits":
        return ((raw.astype(jnp.float64) + i64p[pbase].astype(jnp.float64))
                * factor).astype(dt.str)
    if dt.kind == "b":
        return raw != 0
    val = (raw.astype(jnp.int64) + i64p[pbase]) * factor
    return val.astype(dt.str)

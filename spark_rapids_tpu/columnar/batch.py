"""Device columnar batch + Arrow host interop.

The TPU analog of the reference's ``ColumnarBatch`` of ``GpuColumnVector``
(GpuColumnVector.java:251,283 ``from(Table)``/``from(ColumnarBatch)``) plus
the host<->device transfer paths (HostColumnarToGpu.scala,
GpuColumnarToRowExec.scala).  Host-side canonical format is Arrow
(pyarrow.RecordBatch) instead of Spark InternalRow — TPU-first choice: Arrow
is the host decode format for Parquet/ORC/CSV and transfers to HBM without
per-row conversion.

Static-shape discipline: a batch has a power-of-two ``capacity`` (static,
part of the jit cache key) and a *device* scalar ``num_rows`` (traced), so
data-dependent operators (filter, join) stay inside one compiled program
without host round-trips; the true row count is only materialized at batch
boundaries (coalesce, collect).
"""
from __future__ import annotations

import functools as _functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import wirecodec as wc
from spark_rapids_tpu.columnar.column import DeviceColumn, round_string_width

__all__ = ["ColumnBatch", "round_capacity"]

#: wire-codec default (overridable per call; SRT_WIRE_CODEC=0 disables
#: globally for debugging)
_CODEC_DEFAULT = __import__("os").environ.get("SRT_WIRE_CODEC", "1") != "0"
#: below this capacity transfers are latency-bound, not bandwidth-bound:
#: the codec would only multiply compiled unpack variants (a 6x
#: test-suite slowdown when engaged for every tiny batch)
_CODEC_MIN_CAPACITY = 2048


def _codec_auto(cap: int, codec: bool | None) -> bool:
    if codec is not None:
        return codec
    return _CODEC_DEFAULT and cap >= _CODEC_MIN_CAPACITY

_MIN_CAPACITY = 8

# ---------------------------------------------------------------------------
# Packed host->device transfer
#
# The tunneled PJRT backend pays a large per-(shape, dtype) setup cost on
# the FIRST transfer of each distinct buffer shape (60ms-6s measured) and
# a fixed per-call overhead after that; per-column transfers made the q6
# scan ~97 small device_puts per iteration.  Packing every column leaf of
# a batch into ONE contiguous host buffer PER DTYPE collapses that to
# ~3-5 large puts, and a single jitted unpack program (cached per schema
# spec) slices the columns back out on device — one dispatch instead of
# dozens.  Reference analog: JCudfSerialization packs a whole table into
# one contiguous host buffer for the D2H/H2D path (SURVEY §2.2).
# ---------------------------------------------------------------------------


class _PackBuilder:
    """Accumulates per-column host leaves — raw or wire-codec encoded
    (columnar/wirecodec.py) — and materializes them on device with one
    transfer per dtype group + one unpack/decode program."""

    def __init__(self, capacity: int, codec: bool = True):
        self.capacity = capacity
        self.codec = codec
        self.groups: dict[str, list] = {}   # dtype key -> host 1-D chunks
        self.offsets: dict[str, int] = {}   # dtype key -> elements so far
        self.leaves: list[tuple] = []       # ("g"|"w", ...) — see _add_leaf
        self.i64_params: list[int] = []
        self.col_specs: list[tuple] = []

    def _add_leaf(self, arr: np.ndarray) -> int:
        """Register one host buffer.

        Every dtype of width <= 4 bytes rides ONE shared uint32 word
        buffer (little-endian byte view; decode is a 32-bit bitcast,
        which lowers on TPU — only 64-bit bitcasts don't): a tunneled
        device_put costs ~75ms of per-call overhead, so a batch ships
        as one u32 transfer plus (rare) i64/f64 raw leaves instead of
        one transfer per dtype.  Leaf records:
          ("g", gkey, elem_off, elem_size, shape)     — plain group
          ("w", word_off, word_size, dtype, shape, n) — u32-view leaf
        """
        dt = arr.dtype
        flat = np.ravel(arr)
        if dt.itemsize <= 4 and dt.kind in "uifb":
            by = flat.view(np.uint8)
            pad = (-by.size) % 4
            if pad:
                by = np.concatenate([by, np.zeros(pad, np.uint8)])
            words = by.view(np.uint32)
            woff = self.offsets.get("<u4", 0)
            self.groups.setdefault("<u4", []).append(words)
            self.offsets["<u4"] = woff + words.size
            self.leaves.append(("w", woff, words.size, dt.str, arr.shape,
                                flat.size))
            return len(self.leaves) - 1
        gkey = dt.str
        off = self.offsets.get(gkey, 0)
        self.groups.setdefault(gkey, []).append(flat)
        self.offsets[gkey] = off + flat.size
        self.leaves.append(("g", gkey, off, flat.size, arr.shape))
        return len(self.leaves) - 1

    def _add_i64(self, v: int) -> int:
        self.i64_params.append(int(v))
        return len(self.i64_params) - 1

    # -- column registration ------------------------------------------------
    def _val_desc(self, validity: np.ndarray | None) -> tuple:
        """Validity spec: all-valid columns ship nothing (decode derives
        the mask from num_rows); others ship 1 bit/row."""
        if validity is None or bool(validity.all()):
            return ("av",)
        return ("vbits", self._add_leaf(
            wc.pack_bits_host(validity.astype(np.uint8), 1, self.capacity)))

    def add_fixed(self, data: np.ndarray, validity: np.ndarray | None):
        """Fixed-width column from UNPADDED host data (+ validity)."""
        n = data.shape[0]
        if validity is not None and not validity.all():
            data = np.where(validity, data, data.dtype.type(0))
        if self.codec:
            desc = wc.encode_fixed(data, validity, self.capacity,
                                   self._add_leaf, self._add_i64)
        else:
            full = np.zeros((self.capacity,) + data.shape[1:],
                            dtype=data.dtype)
            full[:n] = data
            desc = ("raw", self._add_leaf(full))
        self.col_specs.append(("fixed", desc, self._val_desc(validity)))

    def add_var(self, matrix: np.ndarray, lengths: np.ndarray,
                validity: np.ndarray | None, width: int):
        """Var-width (string/array) column from an UNPADDED [n, w]
        matrix + lengths."""
        n = matrix.shape[0]
        cap = self.capacity
        if validity is not None and not validity.all():
            matrix = np.where(validity[:, None], matrix,
                              matrix.dtype.type(0))
            lengths = np.where(validity, lengths, 0)
        mfull = np.zeros((cap, width), dtype=matrix.dtype)
        mfull[:n] = matrix
        mdesc = ("raw", self._add_leaf(mfull))
        if self.codec:
            ldesc = wc.encode_lengths(lengths, cap, width, self._add_leaf,
                                      self._add_i64)
        else:
            lfull = np.zeros(cap, dtype=np.int32)
            lfull[:n] = lengths
            ldesc = ("raw", self._add_leaf(lfull))
        self.col_specs.append(("var", mdesc, self._val_desc(validity),
                               ldesc))

    def add_dict_string(self, indices: np.ndarray,
                        dict_matrix: np.ndarray, dict_lengths: np.ndarray,
                        validity: np.ndarray | None):
        """Dictionary-encoded string column: bit-packed int32 indices +
        a pow2-row-padded dictionary byte matrix; decode is one gather."""
        cap = self.capacity
        k, w = dict_matrix.shape
        kp = round_capacity(max(k, 1))
        mfull = np.zeros((kp, w), dtype=np.uint8)
        mfull[:k] = dict_matrix
        lfull = np.zeros(kp, dtype=np.int32)
        lfull[:k] = dict_lengths
        if validity is not None and not validity.all():
            indices = np.where(validity, indices, 0)
        idesc = wc.encode_fixed(indices, validity, cap, self._add_leaf,
                                self._add_i64)
        self.col_specs.append(("dict", idesc,
                               self._val_desc(validity),
                               self._add_leaf(mfull),
                               self._add_leaf(lfull)))

    # -- materialization ----------------------------------------------------
    def build(self, num_rows: int, schema: T.Schema) -> "ColumnBatch":
        """One device_put per dtype group — with the u32 word routing in
        :meth:`_add_leaf`, typically ONE transfer total — plus one jitted
        unpack+decode.  The i64 decode params (FOR bases) ship as u32
        word pairs and are rebuilt arithmetically on device (64-bit
        bitcasts don't lower on TPU; shifts do)."""
        nr = self._add_leaf(np.asarray([num_rows], dtype=np.int32))
        ip = -1
        if self.i64_params:
            p = np.asarray(self.i64_params, np.int64)
            pairs = np.empty(2 * p.size, np.uint32)
            pairs[0::2] = (p & 0xFFFFFFFF).astype(np.uint32)
            pairs[1::2] = ((p >> 32) & 0xFFFFFFFF).astype(np.uint32)
            ip = self._add_leaf(pairs)
        gkeys = tuple(sorted(self.groups))
        host_bufs = tuple(
            self.groups[k][0] if len(self.groups[k]) == 1
            else np.concatenate(self.groups[k]) for k in gkeys)
        dev_bufs = tuple(jax.device_put(b) for b in host_bufs)
        spec = (self.capacity, gkeys, tuple(self.leaves),
                tuple(self.col_specs), nr, ip)
        arrays = _packed_unpack_cached(spec)(dev_bufs)
        cols = [DeviceColumn(d, v, f.data_type, ln)
                for f, (d, v, ln) in zip(schema, arrays[0])]
        return ColumnBatch(cols, arrays[1], schema,
                           known_rows=int(num_rows))


@_functools.lru_cache(maxsize=1024)
def _packed_unpack_cached(spec):
    cap, gkeys, leaves, col_specs, nr_idx, ip_idx = spec

    def unpack(bufs):
        import jax.numpy as jnp
        by_key = dict(zip(gkeys, bufs))

        def leaf(i):
            rec = leaves[i]
            if rec[0] == "g":
                _, gkey, off, size, shape = rec
                piece = jax.lax.slice(by_key[gkey], (off,), (off + size,))
                return piece.reshape(shape)
            _, woff, wsize, dtype_str, shape, nelem = rec
            words = jax.lax.slice(by_key["<u4"], (woff,), (woff + wsize,))
            dt = np.dtype(dtype_str)
            if dt.str == "<u4":
                arr = words
            elif dt.kind == "b":
                arr = jax.lax.bitcast_convert_type(
                    words, jnp.uint8).reshape(-1)[:nelem] != 0
                return arr.reshape(shape)
            elif dt.itemsize == 4:
                arr = jax.lax.bitcast_convert_type(words, dt)
            else:
                arr = jax.lax.bitcast_convert_type(
                    words, dt).reshape(-1)[:nelem]
            return arr.reshape(shape)

        nr = leaf(nr_idx)[0]
        i64p = None
        if ip_idx >= 0:
            pw = leaf(ip_idx)
            i64p = ((pw[1::2].astype(jnp.int64) << 32)
                    | pw[0::2].astype(jnp.int64))
        out_cols = []
        for cspec in col_specs:
            kind = cspec[0]
            validity = wc.decode_validity(cspec[2], leaf, cap, nr)
            if kind == "fixed":
                data = wc.decode_data(cspec[1], leaf, i64p, cap)
                zero = jnp.zeros((), data.dtype)
                data = jnp.where(validity, data, zero)
                out_cols.append((data, validity, None))
            elif kind == "var":
                data = wc.decode_data(cspec[1], leaf, i64p, cap)
                lens = wc.decode_data(cspec[3], leaf, i64p, cap)
                data = jnp.where(validity[:, None], data,
                                 jnp.zeros((), data.dtype))
                lens = jnp.where(validity, lens, 0)
                out_cols.append((data, validity, lens))
            else:  # dict string
                idx = wc.decode_data(cspec[1], leaf, i64p, cap)
                mat, dlens = leaf(cspec[3]), leaf(cspec[4])
                data = jnp.where(validity[:, None], mat[idx],
                                 jnp.zeros((), mat.dtype))
                lens = jnp.where(validity, dlens[idx], 0)
                out_cols.append((data, validity, lens))
        return tuple(out_cols), nr

    # through the shared-jit wrapper: the io scan worker compiles NEW
    # unpack programs mid-query, which must serialize against every
    # other engine compile/dispatch on CPU (compile_cache guard); bound
    # lazily — columnar/ sits below exec/
    from spark_rapids_tpu.exec.compile_cache import instrument
    return instrument(jax.jit(unpack))

# Arrow<->device conversions are serialized AND pyarrow's internal pool
# is pinned to one thread (runtime.pin_arrow_threads): pyarrow compute
# kernels running on their multi-threaded pool concurrently with jax CPU
# execution segfault intermittently.  The lock costs little —
# conversions are host-side staging; device programs still overlap.
_ARROW_LOCK = __import__("threading").Lock()


def _arrow_guard():
    from spark_rapids_tpu.runtime import pin_arrow_threads
    pin_arrow_threads()
    return _ARROW_LOCK


def round_capacity(n: int) -> int:
    """Round a row count up to the compilation capacity bucket (pow2)."""
    c = _MIN_CAPACITY
    while c < n:
        c <<= 1
    return c


@jax.tree_util.register_pytree_node_class
class ColumnBatch:
    """An immutable device batch: tuple of DeviceColumn + device num_rows.

    ``known_rows`` is an OPTIONAL host-side int mirror of ``num_rows``,
    set where the count is already on host (the pack builder, shuffle
    map-writers, OOM split halves) — metrics/tracing read it without a
    D2H sync.  It is metadata only: deliberately excluded from both the
    pytree leaves (it must not be traced) and the aux treedef (a static
    per-count treedef would retrigger jit compilation per row count), so
    batches that cross a jit boundary correctly come back with
    known_rows=None (their count is whatever the program computed).
    """

    __slots__ = ("columns", "num_rows", "schema", "known_rows")

    def __init__(self, columns: Sequence[DeviceColumn], num_rows: jax.Array,
                 schema: T.Schema, known_rows: int | None = None):
        self.columns = tuple(columns)
        self.num_rows = num_rows
        self.schema = schema
        self.known_rows = known_rows

    def tree_flatten(self):
        return (self.columns, self.num_rows), (self.schema,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        columns, num_rows = children
        return cls(columns, num_rows, aux[0])

    # ------------------------------------------------------------------
    @property
    def capacity(self) -> int:
        if self.columns:
            return self.columns[0].capacity
        return _MIN_CAPACITY

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    def column(self, i: int) -> DeviceColumn:
        return self.columns[i]

    def row_mask(self) -> jax.Array:
        """bool[capacity]: True for real (non-padding) rows."""
        return jnp.arange(self.capacity, dtype=jnp.int32) < self.num_rows

    def with_columns(self, columns: Sequence[DeviceColumn],
                     schema: T.Schema) -> "ColumnBatch":
        return ColumnBatch(columns, self.num_rows, schema,
                           known_rows=self.known_rows)

    def host_num_rows(self) -> int:
        """Materialize the row count on host (sync point); cached into
        ``known_rows`` so a later metrics read is free."""
        if self.known_rows is None:
            self.known_rows = int(jax.device_get(self.num_rows))
        return self.known_rows

    # ------------------------------------------------------------------
    # Arrow interop
    # ------------------------------------------------------------------
    @staticmethod
    def from_arrow(rb, capacity: int | None = None,
                   string_widths: dict[str, int] | None = None,
                   codec: bool | None = None) -> "ColumnBatch":
        """Build a device batch from a pyarrow.RecordBatch (H2D transfer)."""
        with _arrow_guard():
            return ColumnBatch._from_arrow_locked(rb, capacity,
                                                  string_widths, codec)

    @staticmethod
    def _from_arrow_locked(rb, capacity=None, string_widths=None,
                           codec=None):
        import pyarrow as pa
        n = rb.num_rows
        cap = capacity or round_capacity(max(n, 1))
        schema = T.Schema.from_arrow(rb.schema)
        pack = _PackBuilder(cap, _codec_auto(cap, codec))
        for i, field in enumerate(schema):
            arr = rb.column(i)
            if isinstance(arr, pa.ChunkedArray):
                arr = arr.combine_chunks()
            validity = T.arrow_validity_numpy(arr)
            if isinstance(field.data_type, T.StringType):
                w = (string_widths or {}).get(field.name)
                dic = wc.maybe_dict_arrow(arr, n) if pack.codec else None
                if dic is not None:
                    idx, dictionary = dic
                    # honor the scan's width hint so batches across
                    # files keep one compiled width bucket
                    dm, dlens = _strings_to_matrix(dictionary, w)
                    pack.add_dict_string(idx, dm, dlens, validity)
                else:
                    bm, lens = _strings_to_matrix(arr, w)
                    pack.add_var(bm, lens, validity,
                                 bm.shape[1] if bm.ndim == 2 else 4)
            elif isinstance(field.data_type, T.ArrayType):
                m, lens = _lists_to_matrix(arr, field.data_type)
                pack.add_var(m, lens, validity,
                             m.shape[1] if m.ndim == 2 else 1)
            else:
                data = T.arrow_fixed_to_numpy(arr, field.data_type)
                pack.add_fixed(data, validity)
        return pack.build(n, schema)

    def to_arrow(self):
        """Copy the batch back to host as a pyarrow.RecordBatch (D2H).

        Leaves are materialized as OWNED numpy copies: pyarrow keeps
        references to the buffers it is handed, and zero-copy views into
        jax device buffers can dangle once the runtime reclaims them
        (observed as a segfault under the virtual multi-device CPU mesh).
        """
        import pyarrow as pa
        # one device_get for num_rows + leaves (one round trip, not two)
        n, host_cols = jax.device_get(
            (self.num_rows,
             [(c.data, c.validity, c.lengths) for c in self.columns]))
        n = int(n)
        with _arrow_guard():
            return self._to_arrow_locked(n, host_cols)

    def _to_arrow_locked(self, n, host_cols):
        import pyarrow as pa
        # slice to the real rows BEFORE the ownership copy: copying the
        # full pow2-capacity buffers wastes D2H-path memory traffic
        host_cols = [tuple(None if a is None else np.array(a[:n], copy=True)
                           for a in t) for t in host_cols]
        arrays = []
        for field, (data, validity, lengths) in zip(self.schema, host_cols):
            v = np.asarray(validity[:n], dtype=np.bool_)
            mask = ~v  # arrow mask: True = null
            if isinstance(field.data_type, T.StringType):
                bm = np.asarray(data[:n])
                lens = np.asarray(lengths[:n])
                py = [None if not v[i] else bytes(bm[i, :lens[i]]).decode("utf-8", "replace")
                      for i in range(n)]
                arrays.append(pa.array(py, type=pa.string()))
            elif isinstance(field.data_type, T.ArrayType):
                m = np.asarray(data[:n])
                lens = np.asarray(lengths[:n])
                py = [None if not v[i] else m[i, :lens[i]].tolist()
                      for i in range(n)]
                arrays.append(pa.array(py, type=T.to_arrow(field.data_type)))
            else:
                d = np.asarray(data[:n])
                at = T.to_arrow(field.data_type)
                if isinstance(field.data_type, T.TimestampType):
                    arrays.append(pa.Array.from_buffers(
                        at, n, pa.array(d.astype("int64"), mask=mask).buffers()))
                elif isinstance(field.data_type, T.DateType):
                    arrays.append(pa.Array.from_buffers(
                        at, n, pa.array(d.astype("int32"), mask=mask).buffers()))
                else:
                    arrays.append(pa.array(d, type=at, mask=mask))
        return pa.RecordBatch.from_arrays(arrays, schema=self.schema.to_arrow())

    def device_size_bytes(self) -> int:
        """Approximate HBM footprint of this batch."""
        total = 0
        for c in self.columns:
            total += c.data.size * c.data.dtype.itemsize
            total += c.validity.size
            if c.lengths is not None:
                total += c.lengths.size * 4
        return total


def _lists_to_matrix(arr, dtype):
    """Arrow list array -> (elem[n, w] padded matrix, int32[n] lengths).
    Same static-shape layout as strings; element nulls are rejected
    (they have no device representation — such columns stay on host)."""
    import pyarrow as pa
    arr = arr.cast(pa.large_list(T.to_arrow(dtype.element_type)))
    n = len(arr)
    offsets = np.frombuffer(arr.buffers()[1], dtype=np.int64, count=n + 1,
                            offset=arr.offset * 8)
    # trim values to THIS slice's offset window — .values spans the
    # whole child buffer and would reject element nulls outside the
    # slice; slicing (not flatten) keeps offset alignment even if a
    # null list row had a nonzero offset span
    values = arr.values.slice(int(offsets[0]),
                              int(offsets[-1] - offsets[0]))
    if values.null_count:
        raise ValueError("arrays with null elements have no device "
                         "representation")
    offsets = offsets - offsets[0]
    flat = T.arrow_fixed_to_numpy(values, dtype.element_type)
    lens = (offsets[1:] - offsets[:-1]).astype(np.int32)
    if arr.null_count:
        valid = np.asarray(arr.is_valid(), dtype=np.bool_)
        lens = np.where(valid, lens, 0)
    maxw = int(lens.max()) if n else 0
    w = round_string_width(max(maxw, 1))
    out = np.zeros((n, w), dtype=dtype.np_dtype)
    if n and flat.size:
        pos = offsets[:-1, None] + np.arange(w, dtype=np.int64)[None, :]
        mask = np.arange(w, dtype=np.int32)[None, :] < lens[:, None]
        out[mask] = flat[np.minimum(pos[mask], flat.size - 1)]
    return out, lens


def _strings_to_matrix(arr, width: int | None = None):
    """Arrow string array -> (uint8[n, w] padded bytes, int32[n] lengths)."""
    import pyarrow as pa
    arr = arr.cast(pa.large_string())
    n = len(arr)
    buffers = arr.buffers()
    # large_string: [validity, offsets(int64), data]
    offsets = np.frombuffer(buffers[1], dtype=np.int64, count=n + 1,
                            offset=arr.offset * 8)
    databuf = np.frombuffer(buffers[2], dtype=np.uint8) if buffers[2] is not None \
        else np.zeros(0, np.uint8)
    lens = (offsets[1:] - offsets[:-1]).astype(np.int32)
    # nulls contribute zero-length
    if arr.null_count:
        valid = np.asarray(arr.is_valid(), dtype=np.bool_)
        lens = np.where(valid, lens, 0)
    maxw = int(lens.max()) if n else 0
    w = width or round_string_width(max(maxw, 1))
    if maxw > w:
        raise ValueError(f"string width {maxw} exceeds bucket {w}")
    out = np.zeros((n, w), dtype=np.uint8)
    if n and databuf.size:
        # vectorized gather: out[i, j] = databuf[offsets[i] + j] for j < lens[i]
        pos = offsets[:-1, None] + np.arange(w, dtype=np.int64)[None, :]
        mask = np.arange(w, dtype=np.int32)[None, :] < lens[:, None]
        out[mask] = databuf[pos[mask]]
    return out, lens

from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.columnar.batch import ColumnBatch, round_capacity

__all__ = ["DeviceColumn", "ColumnBatch", "round_capacity"]

"""Deterministic, conf-driven fault injection for robustness testing.

Reference motivation (SURVEY §2.6): the UCX shuffle plane survives
transport failures by surfacing them to Spark's stage-retry machinery
(RapidsShuffleIterator), and the reference proves that behavior with
mocked transports (RapidsShuffleTestHelper.scala:26-95).  Here the REAL
server/client/store/spill code runs under seeded faults instead: the
engine carries injection points that are inert (a single ``is None``
check) unless ``spark.rapids.test.faults`` names a plan, so robustness
behavior is testable in-process on CPU with no cluster and no mocks.

Spec grammar (``spark.rapids.test.faults``)::

    spec  := rule (';' rule)*
    rule  := point ':' action (',' key '=' value)*

Injection points wired today (site -> actions it interprets):

    tcp.server.frame    per outgoing data frame (ctx: shuffle, part,
                        frame).  Actions: ``reset`` (abrupt connection
                        close mid-stream), ``stall`` (sleep ``seconds``
                        before sending, to trip the client timeout),
                        ``corrupt`` (flip one seeded byte of the wire
                        payload AFTER the checksum was computed —
                        in-transit corruption), ``error`` (send a
                        server error frame instead of data).
    tcp.client.connect  before dialing a peer (ctx: host, port).
                        Action ``reset`` fails the dial.
    store.fetch         local shuffle store reads (ctx: shuffle, part).
                        Action ``error`` raises from the store — over
                        TCP it reaches the client as an error frame.
    shuffle.peer.hang   accepted-then-stalled peer: checked at the TOP
                        of the server's fetch handling (ctx: shuffle,
                        part).  Any action name works (use ``hang``);
                        the server holds the connection open sending
                        nothing — no header, no error frame — for
                        ``seconds`` (default 3600, interrupted by
                        server close), so the CLIENT's
                        spark.rapids.shuffle.socketTimeout is what
                        breaks the wedge as a retryable
                        ShuffleFetchError.  Default ``times=1``: the
                        retry's reconnect succeeds.
    shuffle.peer.dead   terminal peer death, checked on every store /
                        remote fetch (ctx: shuffle, part).  Any action
                        name works (use ``dead``); once triggered the
                        fetch raises MapOutputLostError naming every
                        map output in the requested slice, driving the
                        stage-recovery layer instead of the transient
                        retry ladder.  Points ending in ``.dead``
                        default to ``times=0`` (a dead peer stays
                        dead); give an explicit ``times=N`` to model a
                        peer that is replaced after N failed pulls.
    spill.disk.corrupt  before a disk spill file is read back (ctx:
                        buffer_id, priority, size).  Action ``corrupt``
                        flips one seeded byte of the on-disk payload so
                        the CRC32C read-back check fails and the
                        catalog surfaces SpillCorruptionError — data
                        loss, not a crash.
    spill.disk.enospc   on each spill-to-disk write (ctx: buffer_id,
                        priority, size).  Action ``enospc`` makes the
                        write fail like a full disk; the catalog treats
                        the buffer as unspillable and lets the PR 2
                        OOM split-and-retry scope absorb the pressure.
    mesh.slice.lost     around a mesh program launch (ctx: op, devices).
                        Action ``lost`` simulates losing a device slice
                        mid-execution; mesh execs fall back to the
                        single-device recompute path and count a stage
                        recompute.
    memory.oom          run_with_spill_retry dispatch (ctx: op) and the
                        operator retry scopes in memory/retry.py (ctx:
                        op, and rows at with_retry sites).  Action
                        ``oom`` raises a simulated XLA
                        RESOURCE_EXHAUSTED, driving the spill-retry
                        loop exactly like a real HBM exhaustion.
    memory.oom.until_rows
                        with_retry dispatch sites only (ctx: op, rows).
                        Action ``oom`` with ``until_rows=N`` keeps
                        raising the simulated OOM while the dispatched
                        batch holds MORE than N rows — the exhaustion
                        "persists" until split-and-retry shrinks the
                        working set below the threshold, making the
                        split path deterministically provable without a
                        real device.
    memory.grant.stall  governor grant-wait entry (ctx: query_id,
                        need; memory/governor.py).  Action ``stall``
                        holds the waiter ``seconds`` (default 0.05)
                        before the normal bounded wait loop runs — a
                        deterministic mid-grant-wait window for chaos
                        tests to land cancellations in, proving the
                        reservation is released on terminal unwind.
    memory.governor.oom_storm
                        governor reclaim entry (ctx: query_id, need;
                        memory/governor.py).  Action ``oom`` makes the
                        arbitration report ZERO bytes freed — an OOM
                        storm spilling cannot keep up with — so the
                        requester's split-and-retry ladder absorbs the
                        pressure; chaos tests use it to prove bounded
                        wall time (no eviction livelock) under
                        concurrent queries.
    cache.result.corrupt
                        result-cache hit verification (ctx: kind;
                        exec/result_cache.py).  Action ``corrupt``
                        flips one seeded byte of the cached blob so the
                        per-hit CRC32 verify fails: the entry is
                        dropped, ``result_cache_corrupt`` counts it,
                        and the query recomputes — corruption is a
                        cache miss, never stale rows or a crash.
    cluster.worker.dead checked in the driver-side map-output tracker
                        on each reduce fetch (ctx: shuffle, part,
                        worker).  Any action name works (use ``dead``);
                        the driver SIGKILLs the worker owning the first
                        requested map output — a real process death,
                        driving heartbeat-loss detection plus lineage
                        reassignment onto surviving workers.  Never
                        fires when only one worker remains alive (a
                        0-worker cluster cannot recover anything).
                        ``.dead`` default times=0 applies; chaos plans
                        should pass ``times=1`` to kill exactly one.
    cluster.worker.hang checked in the driver's heartbeat handler (ctx:
                        worker).  Any action name works (use ``hang``);
                        once fired the driver IGNORES that worker's
                        subsequent heartbeats — the process lives but
                        goes silent, so the heartbeat monitor declares
                        it dead after cluster.heartbeat.timeoutSeconds
                        and recovery reassigns its partitions.
    cluster.worker.slow checked driver-side before each fragment RPC is
                        sent (ctx: worker, shuffle; cluster/exec.py).
                        Any action name works (use ``slow``); the
                        dispatch thread sleeps ``seconds`` (default 2)
                        before calling the worker, modelling a
                        straggling executor so speculation
                        (spark.rapids.cluster.speculation.enabled) can
                        be driven deterministically.
    cluster.worker.flaky
                        checked driver-side before each fragment RPC is
                        sent (ctx: worker, shuffle; cluster/exec.py).
                        Any action name works (use ``flaky``); the
                        dispatch fails with an RpcError as if the
                        worker's control plane dropped the call —
                        consecutive firings drive the quarantine
                        machinery (quarantine.maxFailures) without
                        killing the process, so its map outputs stay
                        servable.
    cluster.migrate.drop
                        checked driver-side per slot while planning a
                        graceful drain's map-output migration (ctx:
                        shuffle, part, map; cluster/driver.py).  Any
                        action name works (use ``drop``); the slot is
                        excluded from migration and left on the
                        retiring worker, so removal marks it lost and
                        the reader's MapOutputLostError -> lineage
                        fallback is exercised for real.
    cluster.rpc.drop    before each control-plane RPC send (ctx: op).
                        Any action name works (use ``drop``); the dial
                        fails with a ConnectionError the RPC retry
                        ladder absorbs — a dropped/blackholed control
                        message, distinct from a dead worker.
    admission.tenant.storm
                        weighted-fair admission entry (ctx: tenant,
                        query_id; exec/lifecycle.py).  Action ``storm``
                        (any name works) rejects the arrival with
                        QueryRejected before it takes a queue slot —
                        a deterministic per-tenant admission storm for
                        chaos tests to prove other tenants' queries
                        still flow (no cross-tenant starvation).
    io.write.partial    after each file a write task attempt finishes
                        (ctx: task, attempt, worker, file;
                        io/writer.py write_task_attempt).  Action
                        ``crash`` raises InjectedFault so the attempt
                        dies mid-write leaving a partial private
                        staging dir; action ``truncate`` first shears
                        the just-written file to half its bytes —
                        garbage that must never become visible and that
                        a later attempt must not be confused by.
    io.write.commit.drop
                        on manifest registration at the driver's write
                        commit coordinator (ctx: task, attempt, worker;
                        io/writer.py WriteCommitCoordinator.register).
                        Any action name works (use ``drop``); the
                        attempt's commit message is treated as lost in
                        flight — no winner is recorded, the task is
                        re-attempted, and the orphaned attempt's files
                        stay in staging for GC.
    io.write.rename.fail
                        per staging->final rename during job commit
                        (ctx: file; io/writer.py
                        WriteCommitCoordinator._rename).  Any action
                        name works (use ``fail``); the rename raises
                        OSError, exercising the commit retry ladder
                        and — once retries are exhausted — the
                        roll-back path that un-renames every already
                        published file.
    control.signal.stale
                        per control-loop tick (ctx: tick;
                        control/loop.py ControlLoop.tick).  Any action
                        name works (use ``stale``); the tick reads a
                        FROZEN copy of the previous registry snapshot
                        instead of a fresh one — an empty delta, as if
                        the metrics pipeline wedged.  Chaos tests
                        assert the rules decay to no-ops on frozen
                        signals instead of oscillating.
    control.actuate.drop
                        per derived control decision, before actuation
                        (ctx: rule, action; control/loop.py
                        ControlLoop.tick).  Any action name works (use
                        ``drop``); the decision is lost in flight —
                        never applied, recorded with dropped=true.
                        Safe by design: decisions are idempotent and
                        re-derived from fresh signals next tick, so a
                        dropped actuation only delays convergence by
                        one interval.
    cluster.driver.crash
                        named driver-death points, all routed through
                        ``faults.crash_point`` (ctx: point, plus
                        site-specific keys like round or job).  Any
                        action name works (use ``kill``); the DRIVER
                        process SIGKILLs itself on the spot — no
                        cleanup, no atexit, exactly an OOM-killed or
                        power-cut driver.  Filter on ``point=`` to pick
                        the death site: ``dispatch`` (top of a fragment
                        dispatch round, cluster/exec.py), ``shuffle_read``
                        (first reduce-side fetch, cluster/exec.py),
                        ``write.commit`` (mid-rename during job commit,
                        io/writer.py), ``drain`` (mid graceful drain,
                        cluster/driver.py).  Recovery tests pair it
                        with reattachGraceSeconds + journal.dir and
                        rebuild via ClusterDriver.recover().
    cluster.journal.torn
                        after a journal group-commit writes its batch
                        (cluster/journal.py).  Any action name works
                        (use ``torn``); the freshly appended tail is
                        sheared mid-record, as if the process died
                        inside the write syscall — replay must heal the
                        torn tail back to the last intact record.
    cluster.journal.fsync.fail
                        on the journal's group-commit fsync
                        (cluster/journal.py).  Any action name works
                        (use ``fail``); the fsync raises OSError.  The
                        journal ABSORBS the failure — counts
                        journal_fsync_failures and degrades to
                        flush-only durability — rather than failing
                        the query.

Trigger keys (all optional):

    nth=N      first eligible hit that fires (1-based, default 1) —
               "reset after 2 frames" is ``nth=3`` on a frame point
    times=N    how many hits fire once triggered (default 1 so a retry
               can succeed; 0 = every hit forever).  Rules carrying
               ``until_rows`` default to 0: the row threshold is the
               natural stop condition
    p=F        per-hit probability, drawn from the rule's seeded PRNG
    seconds=F  action parameter (stall duration)
    until_rows=N  fire only when the site reports a ``rows`` context
               above N (sites that report no row count never match)

Any other ``key=value`` is a FILTER compared (as strings) against the
call-site context, e.g. ``shuffle=9,part=0`` scopes a rule to one
partition stream and ``frame=2`` fires on the third frame regardless of
how many eligible hits preceded it.

Determinism: every rule owns a ``random.Random`` seeded from
``spark.rapids.test.faults.seed`` plus the rule's index and text, so a
fault plan replays identically run to run and process to process.
Counters live on the registry instance — components build ONE registry
at construction (transport, catalog), so a ``times=1`` rule fires once
per component lifetime, not once per fetch attempt.
"""
from __future__ import annotations

import random
import threading

from spark_rapids_tpu.conf import TEST_FAULTS, TEST_FAULTS_SEED

__all__ = ["FaultRegistry", "FaultRule", "FaultAction", "InjectedFault",
           "KNOWN_POINTS", "crash_point"]

#: every injection point wired into the engine (the module docstring
#: documents each).  enginelint RL005 cross-checks this registry against
#: the live ``.check("point", ...)`` call sites in both directions, so a
#: renamed site or a stale entry fails premerge instead of silently
#: turning a fault plan into a no-op.
KNOWN_POINTS = frozenset({
    "tcp.server.frame",
    "tcp.client.connect",
    "store.fetch",
    "shuffle.peer.hang",
    "shuffle.peer.dead",
    "spill.disk.corrupt",
    "spill.disk.enospc",
    "mesh.slice.lost",
    "memory.oom",
    "memory.oom.until_rows",
    "memory.grant.stall",
    "memory.governor.oom_storm",
    "cache.result.corrupt",
    "admission.tenant.storm",
    "cluster.worker.dead",
    "cluster.worker.hang",
    "cluster.worker.slow",
    "cluster.worker.flaky",
    "cluster.migrate.drop",
    "cluster.rpc.drop",
    "io.write.partial",
    "io.write.commit.drop",
    "io.write.rename.fail",
    "control.signal.stale",
    "control.actuate.drop",
    "cluster.driver.crash",
    "cluster.journal.torn",
    "cluster.journal.fsync.fail",
})

#: keys with registry-level meaning; everything else in a rule is a
#: context filter
_RESERVED = ("nth", "times", "p", "seconds", "until_rows")


class InjectedFault(RuntimeError):
    """Raised by injection sites whose action surfaces as an error."""


class FaultRule:
    def __init__(self, index: int, text: str, seed: int):
        self.text = text
        point, _, rest = text.partition(":")
        self.point = point.strip()
        if not self.point or not rest.strip():
            raise ValueError(f"fault rule {text!r}: want 'point:action"
                             "[,k=v...]'")
        parts = [p.strip() for p in rest.split(",")]
        self.action = parts[0]
        self.params: dict[str, str] = {}
        for kv in parts[1:]:
            k, sep, v = kv.partition("=")
            if not sep:
                raise ValueError(f"fault rule {text!r}: bad param {kv!r}")
            self.params[k.strip()] = v.strip()
        self.nth = int(self.params.get("nth", 1))
        self.until_rows = (int(self.params["until_rows"])
                           if "until_rows" in self.params else None)
        # until_rows rules fire forever by default: the row threshold,
        # not a hit budget, is what stops them.  ``.dead`` points also
        # default to forever — a dead peer stays dead unless the plan
        # explicitly revives it with times=N
        default_times = (0 if self.until_rows is not None
                         or self.point.endswith(".dead") else 1)
        self.times = int(self.params.get("times", default_times))
        self.p = float(self.params.get("p", 1.0))
        self.filters = {k: v for k, v in self.params.items()
                        if k not in _RESERVED}
        self.rng = random.Random(f"{seed}:{index}:{text}")
        self.hits = 0
        self.fired = 0

    def _try_fire(self, ctx: dict) -> bool:
        if self.until_rows is not None:
            rows = ctx.get("rows")
            if rows is None or int(rows) <= self.until_rows:
                return False
        for k, v in self.filters.items():
            if k not in ctx or str(ctx[k]) != v:
                return False
        self.hits += 1
        if self.hits < self.nth:
            return False
        if self.times > 0 and self.fired >= self.times:
            return False
        if self.p < 1.0 and self.rng.random() >= self.p:
            return False
        self.fired += 1
        return True


class FaultAction:
    """What an injection site got back: the action name, its params,
    and the rule's seeded PRNG (for e.g. picking the corrupted byte)."""

    __slots__ = ("point", "action", "params", "rng")

    def __init__(self, rule: FaultRule):
        self.point = rule.point
        self.action = rule.action
        self.params = rule.params
        self.rng = rule.rng

    def param(self, key: str, default: float) -> float:
        return float(self.params.get(key, default))


def crash_point(faults, point: str, **ctx) -> None:
    """Driver-death injection site: when a ``cluster.driver.crash``
    rule matches (``point=`` filters pick the site), SIGKILL the
    CURRENT process — no cleanup, no atexit, the same instant death as
    an OOM-killed driver.  One shared helper so enginelint sees exactly
    one call site for the point."""
    if faults is None:
        return
    if faults.check("cluster.driver.crash", point=point, **ctx) is not None:
        import os
        import signal
        os.kill(os.getpid(), signal.SIGKILL)


class FaultRegistry:
    """Parsed fault plan + firing state.  Thread-safe: the TCP server
    checks points from its per-connection threads."""

    def __init__(self, spec: str, seed: int = 0):
        self.spec = spec
        self.seed = seed
        self.rules = [FaultRule(i, r.strip(), seed)
                      for i, r in enumerate(spec.split(";")) if r.strip()]
        self._lock = threading.Lock()
        #: audit log of fired injections: (point, action, ctx)
        self.log: list[tuple[str, str, dict]] = []

    @classmethod
    def from_conf(cls, conf) -> "FaultRegistry | None":
        """None (inert) unless spark.rapids.test.faults is set.  Accepts
        a TpuConf or a raw settings dict."""
        if conf is None:
            return None
        settings = conf.settings if hasattr(conf, "settings") else dict(conf)
        spec = TEST_FAULTS.get(settings)
        if not spec:
            return None
        return cls(spec, TEST_FAULTS_SEED.get(settings))

    def check(self, point: str, /, **ctx) -> FaultAction | None:
        """Called by an injection site; returns the action to perform
        when a rule on this point matches and its trigger fires."""
        with self._lock:
            for rule in self.rules:
                if rule.point != point:
                    continue
                if rule._try_fire(ctx):
                    self.log.append((point, rule.action, dict(ctx)))
                    # chaos runs assert injection actually fired via the
                    # process metrics registry (obs.registry is stdlib-
                    # only; this class only exists when faults are on)
                    from spark_rapids_tpu.obs.registry import get_registry
                    reg = get_registry()
                    reg.inc("faults.injected")
                    reg.inc(f"faults.injected.{point}")
                    return FaultAction(rule)
        return None

    def fired_count(self, point: str | None = None) -> int:
        with self._lock:
            return len([1 for p, _, _ in self.log
                        if point is None or p == point])

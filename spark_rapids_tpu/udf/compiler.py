"""CPython-bytecode symbolic execution -> Expression IR.

The compile strategy mirrors the reference's Instruction/State design
(udf-compiler Instruction.scala symbolic stack machine, State.scala):
walk the instruction stream with a symbolic operand stack whose entries
are Expression nodes.  Conditional jumps FORK the symbolic state (the
CPython analog of CFG.scala's basic blocks + State.scala's per-block
condition): one successor per branch edge, each carrying the
accumulated path condition, and every RETURN contributes a
(condition, value) pair merged into a nested If tree the way
CatalystExpressionBuilder.compile folds blocks into CaseWhen.  Scope:
branches (if/else, ternary, short-circuit and/or), arithmetic
(+ - * / // % **), unary minus/not, comparisons, and calls to a small
builtin allowlist (abs).  Backward jumps (loops) and unknown opcodes
raise internally and the caller falls back to the row-at-a-time host
UDF — the reference's silent-fallback contract
(LogicalPlanRules.apply :79-94).
"""
from __future__ import annotations

import dis
from typing import Callable, Sequence

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import (EvalCtx, Expression, Literal, Val,
                                        lit)

__all__ = ["PythonUDF", "compile_udf", "maybe_compile_udfs", "udf"]


class _Unsupported(Exception):
    pass


# python 3.11+ BINARY_OP argument -> builder
def _binary_builders():
    from spark_rapids_tpu.expr import arithmetic as A

    def div(a, b):
        return A.Divide(a, b)

    return {
        "+": lambda a, b: A.Add(a, b),
        "-": lambda a, b: A.Subtract(a, b),
        "*": lambda a, b: A.Multiply(a, b),
        "/": div,
        "//": lambda a, b: A.IntegralDivide(a, b)
        if hasattr(A, "IntegralDivide") else _unsup(),
        "%": lambda a, b: A.Remainder(a, b),
        "**": _pow,
    }


def _pow(a, b):
    from spark_rapids_tpu.expr.math_ops import Pow
    return Pow(a, b)


def _unsup():
    raise _Unsupported("operator")


def _compare_builders():
    from spark_rapids_tpu.expr import predicates as P
    return {
        "==": P.EqualTo, "!=": lambda a, b: P.Not(P.EqualTo(a, b)),
        "<": P.LessThan, "<=": P.LessThanOrEqual,
        ">": P.GreaterThan, ">=": P.GreaterThanOrEqual,
    }


def compile_udf(fn: Callable, args: Sequence[Expression]) -> Expression | None:
    """Compile ``fn``'s bytecode against symbolic ``args``; None when any
    construct is outside the supported subset (silent fallback)."""
    try:
        return _compile(fn, list(args))
    # enginelint: disable=RL001 (unsupported bytecode falls back to the interpreted UDF)
    except Exception:
        return None


#: path-explosion bound for branchy lambdas (the reference's CFG fold is
#: linear in blocks; path enumeration is exponential in nesting, so cap)
_MAX_PATHS = 64


def _as_bool(e: Expression) -> Expression:
    """Coerce a popped jump operand to a boolean condition."""
    from spark_rapids_tpu.expr import predicates as P
    if isinstance(e, Literal) and not isinstance(e.value, bool):
        return lit(bool(e.value))
    try:
        is_bool = isinstance(e.dtype, T.BooleanType)
    # enginelint: disable=RL001 (unbound dtype at compile time; numeric truthiness assumed)
    except Exception:
        # unbound attribute: dtype unknown at compile time — assume
        # numeric truthiness (comparisons/logic produce Boolean nodes
        # whose dtype IS known, so they take the branch above)
        is_bool = False
    if is_bool:
        return e
    # python truthiness of a numeric: x != 0
    return P.Not(P.EqualTo(e, lit(0)))


def _compile(fn: Callable, args: list[Expression]) -> Expression:
    code = fn.__code__
    if code.co_argcount != len(args):
        raise _Unsupported("arity")
    binops = _binary_builders()
    cmps = _compare_builders()
    from spark_rapids_tpu.expr import predicates as P
    from spark_rapids_tpu.expr.arithmetic import Abs, UnaryMinus
    from spark_rapids_tpu.expr.conditional import If as IfExpr
    allowed_globals = {"abs": lambda a: Abs(a)}

    instructions = list(dis.get_instructions(fn))
    by_offset = {ins.offset: i for i, ins in enumerate(instructions)}

    init_locals: dict[str, Expression] = {
        name: args[i] for i, name in
        enumerate(code.co_varnames[:code.co_argcount])}

    # worklist of symbolic paths: (instr index, stack, locals, pathcond)
    # — the CPython analog of the reference's per-basic-block State with
    # a condition (State.scala); conditional jumps fork the path
    paths: list[tuple[int, list, dict, Expression | None]] = [
        (0, [], init_locals, None)]
    returns: list[tuple[Expression | None, Expression]] = []
    steps = 0

    while paths:
        if len(paths) + len(returns) > _MAX_PATHS:
            raise _Unsupported("too many paths")
        i, stack, locals_map, cond = paths.pop()
        while True:
            steps += 1
            if steps > 100_000 or i >= len(instructions):
                raise _Unsupported("no return / runaway")
            ins = instructions[i]
            op = ins.opname

            def jump_index() -> int:
                tgt = ins.argval  # byte offset of the jump target
                if tgt not in by_offset:
                    raise _Unsupported("jump target")
                j = by_offset[tgt]
                if j <= i:
                    raise _Unsupported("backward jump (loop)")
                return j

            if op in ("RESUME", "NOP", "PRECALL", "CACHE", "PUSH_NULL",
                      "COPY_FREE_VARS", "NOT_TAKEN"):
                i += 1
            elif op in ("LOAD_FAST", "LOAD_FAST_CHECK",
                        "LOAD_FAST_BORROW"):
                if ins.argval not in locals_map:
                    raise _Unsupported(f"unbound local {ins.argval}")
                stack.append(locals_map[ins.argval])
                i += 1
            elif op in ("LOAD_FAST_LOAD_FAST",
                        "LOAD_FAST_BORROW_LOAD_FAST_BORROW"):
                for name in ins.argval:
                    if name not in locals_map:
                        raise _Unsupported(f"unbound local {name}")
                    stack.append(locals_map[name])
                i += 1
            elif op == "LOAD_CONST":
                stack.append(lit(ins.argval))
                i += 1
            elif op == "LOAD_GLOBAL":
                name = ins.argval
                if name not in allowed_globals:
                    raise _Unsupported(f"global {name}")
                stack.append(allowed_globals[name])
                i += 1
            elif op == "BINARY_OP":
                sym = ins.argrepr.rstrip("=")
                if "=" in ins.argrepr and not ins.argrepr.endswith("="):
                    raise _Unsupported(ins.argrepr)
                if sym not in binops:
                    raise _Unsupported(f"binary {ins.argrepr}")
                b, a = stack.pop(), stack.pop()
                stack.append(binops[sym](a, b))
                i += 1
            elif op == "UNARY_NEGATIVE":
                stack.append(UnaryMinus(stack.pop()))
                i += 1
            elif op == "UNARY_NOT":
                stack.append(P.Not(_as_bool(stack.pop())))
                i += 1
            elif op == "TO_BOOL":
                stack.append(_as_bool(stack.pop()))
                i += 1
            elif op == "COMPARE_OP":
                # 3.13+ sets a bool-coercion bit rendered as
                # "bool(>)"; the coercion is the TO_BOOL this machine
                # already models, so strip the wrapper
                sym = ins.argrepr.split()[0]
                if sym.startswith("bool(") and sym.endswith(")"):
                    sym = sym[5:-1]
                if sym not in cmps:
                    raise _Unsupported(f"compare {ins.argrepr}")
                b, a = stack.pop(), stack.pop()
                stack.append(cmps[sym](a, b))
                i += 1
            elif op == "CALL":
                argc = ins.arg
                call_args = [stack.pop() for _ in range(argc)][::-1]
                target = stack.pop()
                if stack and stack[-1] is None:
                    stack.pop()
                if not callable(target):
                    raise _Unsupported("call target")
                stack.append(target(*call_args))
                i += 1
            elif op == "STORE_FAST":
                locals_map = dict(locals_map)
                locals_map[ins.argval] = stack.pop()
                i += 1
            elif op == "POP_TOP":
                stack.pop()
                i += 1
            elif op == "COPY":
                stack.append(stack[-ins.arg])
                i += 1
            elif op == "SWAP":
                stack[-1], stack[-ins.arg] = stack[-ins.arg], stack[-1]
                i += 1
            elif op in ("JUMP_FORWARD",):
                i = jump_index()
            elif op in ("POP_JUMP_IF_FALSE", "POP_JUMP_IF_TRUE"):
                c = _as_bool(stack.pop())
                taken = c if op.endswith("TRUE") else P.Not(c)
                fall = P.Not(c) if op.endswith("TRUE") else c
                j = jump_index()
                paths.append((j, list(stack), locals_map,
                              taken if cond is None else P.And(cond,
                                                               taken)))
                cond = fall if cond is None else P.And(cond, fall)
                i += 1
            elif op in ("POP_JUMP_IF_NONE", "POP_JUMP_IF_NOT_NONE"):
                v = stack.pop()
                c = P.IsNull(v)
                taken = c if op.endswith("IF_NONE") else P.Not(c)
                fall = P.Not(c) if op.endswith("IF_NONE") else c
                j = jump_index()
                paths.append((j, list(stack), locals_map,
                              taken if cond is None else P.And(cond,
                                                               taken)))
                cond = fall if cond is None else P.And(cond, fall)
                i += 1
            elif op in ("JUMP_IF_FALSE_OR_POP", "JUMP_IF_TRUE_OR_POP"):
                # short-circuit and/or (<=3.11): on the jump edge the
                # operand STAYS on the stack as the expression value
                v = stack[-1]
                c = _as_bool(v)
                is_true = op.startswith("JUMP_IF_TRUE")
                taken = c if is_true else P.Not(c)
                fall = P.Not(c) if is_true else c
                j = jump_index()
                paths.append((j, list(stack), locals_map,
                              taken if cond is None else P.And(cond,
                                                               taken)))
                stack.pop()
                cond = fall if cond is None else P.And(cond, fall)
                i += 1
            elif op == "RETURN_VALUE":
                if len(stack) != 1:
                    raise _Unsupported("stack depth at return")
                returns.append((cond, stack[0]))
                break
            elif op == "RETURN_CONST":
                returns.append((cond, lit(ins.argval)))
                break
            else:
                raise _Unsupported(op)

    if not returns:
        raise _Unsupported("no return")
    # merge return paths into a nested If (CatalystExpressionBuilder's
    # block fold); the LAST explored path (first pushed) is the default.
    # The default branch fires whenever every guarded condition is
    # false OR NULL — but a NULL condition means an intermediate went
    # SQL-null where Python would have RAISED (x/0 -> ZeroDivisionError
    # vs Divide -> null), so a null-capable condition would make the
    # result depend on whether compilation succeeded.  Refuse those.
    for c, _ in returns:
        if c is not None and _cond_may_null(c):
            raise _Unsupported("null-producing op in branch condition")
    out = returns[0][1]
    for c, v in returns[1:]:
        out = IfExpr(c, v, out) if c is not None else v
    return out


def _cond_may_null(e: Expression) -> bool:
    """True when the subtree contains an op that maps NON-null inputs
    to SQL NULL (division family): under such a condition the compiled
    If-tree silently takes the default branch while the uncompiled
    Python would raise (advisor r4; ref CatalystExpressionBuilder
    restricts conditions to null-safe predicates the same way)."""
    from spark_rapids_tpu.expr import arithmetic as A
    if isinstance(e, A._DivModLike):
        return True
    return any(_cond_may_null(c) for c in getattr(e, "children", ()))


# ---------------------------------------------------------------------------
# Row-at-a-time fallback expression (reference GpuScalaUDF / the
# uncompiled ScalaUDF path — host-only, tags the exec off device)
# ---------------------------------------------------------------------------

class PythonUDF(Expression):
    sql_name = "PythonUDF"

    def __init__(self, fn: Callable, children: Sequence[Expression],
                 return_type: T.DataType):
        self.fn = fn
        self.children = tuple(children)
        self.return_type = return_type

    def with_new_children(self, children):
        return PythonUDF(self.fn, children, self.return_type)

    @property
    def dtype(self):
        return self.return_type

    @property
    def nullable(self):
        return True

    @property
    def device_supported(self):
        return False

    def _eval(self, vals: list[Val], ctx: EvalCtx):
        n = ctx.capacity
        is_str = isinstance(self.return_type, T.StringType)
        out = np.empty(n, dtype=object) if is_str else \
            np.zeros(n, dtype=self.return_type.np_dtype)
        validity = np.zeros(n, dtype=np.bool_)
        for i in range(n):
            # null-propagating call semantics, matching what the COMPILED
            # expression tree produces (null in -> null out) so results
            # do not depend on whether compilation succeeded
            if not all(v.validity[i] for v in vals):
                if is_str:
                    out[i] = None
                continue
            r = self.fn(*[v.data[i] for v in vals])
            if r is not None:
                out[i] = r
                validity[i] = True
            elif is_str:
                out[i] = None
        return Val(out, validity, None, self.return_type)

    def __repr__(self):
        name = getattr(self.fn, "__name__", "<lambda>")
        return f"PythonUDF({name}, {', '.join(map(repr, self.children))})"


def udf(fn: Callable, return_type: T.DataType | None = None):
    """Wrap a python function as a column UDF:
    ``df.select(udf(lambda x: x * 2 + 1)(col("a")))``."""

    def apply(*cols):
        rt = return_type or T.DoubleType()
        return PythonUDF(fn, [c for c in cols], rt)

    return apply


def maybe_compile_udfs(exprs: Sequence[Expression], conf) -> list[Expression]:
    """Planner hook: replace PythonUDF nodes with compiled native trees
    when the compiler conf is on (reference LogicalPlanRules.apply)."""
    from spark_rapids_tpu.conf import UDF_COMPILER_ENABLED
    if not conf.get(UDF_COMPILER_ENABLED):
        return list(exprs)

    def rewrite(node):
        if isinstance(node, PythonUDF):
            compiled = compile_udf(node.fn, node.children)
            if compiled is not None:
                # honor the declared return type either way, so the output
                # schema is identical whether or not compilation succeeds
                from spark_rapids_tpu.expr.cast import Cast
                from spark_rapids_tpu.expr.conditional import If
                from spark_rapids_tpu.expr.predicates import IsNull, Or
                # null-in -> null-out guard: a branch taken on a NULL
                # condition can yield a literal, but the interpreter
                # fallback never calls the python fn on null inputs —
                # results must not depend on whether compilation
                # succeeded
                null_any = None
                for child in node.children:
                    # the rewrite runs on UNBOUND expressions, where
                    # nullable is not yet known — guard everything that
                    # is not a provably non-null literal
                    if isinstance(child, Literal) and child.value is not None:
                        continue
                    t = IsNull(child)
                    null_any = t if null_any is None else Or(null_any, t)
                out = Cast(compiled, node.return_type)
                if null_any is not None:
                    out = If(null_any,
                             Cast(lit(None), node.return_type), out)
                return out
        return node

    return [e.transform_up(rewrite) if isinstance(e, Expression) else e
            for e in exprs]

"""CPython-bytecode symbolic execution -> Expression IR.

The compile strategy mirrors the reference's Instruction/State design
(udf-compiler Instruction.scala symbolic stack machine, State.scala):
walk the instruction stream with a symbolic operand stack whose entries
are Expression nodes; a RETURN yields the compiled tree.  v0 scope:
straight-line code (no jumps/loops/short-circuit), arithmetic
(+ - * / // % **), unary minus, comparisons, and calls to a small
builtin allowlist (abs).  Unsupported constructs raise internally and
the caller falls back to the row-at-a-time host UDF — the reference's
silent-fallback contract (LogicalPlanRules.apply :79-94).
"""
from __future__ import annotations

import dis
from typing import Callable, Sequence

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import (EvalCtx, Expression, Literal, Val,
                                        lit)

__all__ = ["PythonUDF", "compile_udf", "maybe_compile_udfs", "udf"]


class _Unsupported(Exception):
    pass


# python 3.11+ BINARY_OP argument -> builder
def _binary_builders():
    from spark_rapids_tpu.expr import arithmetic as A

    def div(a, b):
        return A.Divide(a, b)

    return {
        "+": lambda a, b: A.Add(a, b),
        "-": lambda a, b: A.Subtract(a, b),
        "*": lambda a, b: A.Multiply(a, b),
        "/": div,
        "//": lambda a, b: A.IntegralDivide(a, b)
        if hasattr(A, "IntegralDivide") else _unsup(),
        "%": lambda a, b: A.Remainder(a, b),
        "**": _pow,
    }


def _pow(a, b):
    from spark_rapids_tpu.expr.math_ops import Pow
    return Pow(a, b)


def _unsup():
    raise _Unsupported("operator")


def _compare_builders():
    from spark_rapids_tpu.expr import predicates as P
    return {
        "==": P.EqualTo, "!=": lambda a, b: P.Not(P.EqualTo(a, b)),
        "<": P.LessThan, "<=": P.LessThanOrEqual,
        ">": P.GreaterThan, ">=": P.GreaterThanOrEqual,
    }


def compile_udf(fn: Callable, args: Sequence[Expression]) -> Expression | None:
    """Compile ``fn``'s bytecode against symbolic ``args``; None when any
    construct is outside the supported subset (silent fallback)."""
    try:
        return _compile(fn, list(args))
    except Exception:
        return None


def _compile(fn: Callable, args: list[Expression]) -> Expression:
    code = fn.__code__
    if code.co_argcount != len(args):
        raise _Unsupported("arity")
    locals_map: dict[str, Expression] = {
        name: args[i] for i, name in
        enumerate(code.co_varnames[:code.co_argcount])}
    binops = _binary_builders()
    cmps = _compare_builders()
    from spark_rapids_tpu.expr.arithmetic import Abs, UnaryMinus
    allowed_globals = {"abs": lambda a: Abs(a)}

    stack: list = []
    for ins in dis.get_instructions(fn):
        op = ins.opname
        if op in ("RESUME", "NOP", "PRECALL", "CACHE", "PUSH_NULL",
                  "COPY_FREE_VARS"):
            continue
        if op in ("LOAD_FAST", "LOAD_FAST_CHECK", "LOAD_FAST_BORROW"):
            if ins.argval not in locals_map:
                raise _Unsupported(f"unbound local {ins.argval}")
            stack.append(locals_map[ins.argval])
        elif op in ("LOAD_FAST_LOAD_FAST", "LOAD_FAST_BORROW_LOAD_FAST_BORROW"):
            for name in ins.argval:
                if name not in locals_map:
                    raise _Unsupported(f"unbound local {name}")
                stack.append(locals_map[name])
        elif op == "LOAD_CONST":
            stack.append(lit(ins.argval))
        elif op in ("LOAD_GLOBAL",):
            name = ins.argval
            if name not in allowed_globals:
                raise _Unsupported(f"global {name}")
            stack.append(allowed_globals[name])
        elif op == "BINARY_OP":
            sym = ins.argrepr.rstrip("=")
            if "=" in ins.argrepr and not ins.argrepr.endswith("="):
                raise _Unsupported(ins.argrepr)
            if sym not in binops:
                raise _Unsupported(f"binary {ins.argrepr}")
            b, a = stack.pop(), stack.pop()
            stack.append(binops[sym](a, b))
        elif op == "UNARY_NEGATIVE":
            stack.append(UnaryMinus(stack.pop()))
        elif op == "COMPARE_OP":
            sym = ins.argrepr.split()[0]
            if sym not in cmps:
                raise _Unsupported(f"compare {ins.argrepr}")
            b, a = stack.pop(), stack.pop()
            stack.append(cmps[sym](a, b))
        elif op == "CALL":
            argc = ins.arg
            call_args = [stack.pop() for _ in range(argc)][::-1]
            target = stack.pop()
            if stack and stack[-1] is None:
                stack.pop()
            if not callable(target):
                raise _Unsupported("call target")
            stack.append(target(*call_args))
        elif op in ("RETURN_VALUE",):
            if len(stack) != 1:
                raise _Unsupported("stack depth at return")
            return stack[0]
        elif op == "RETURN_CONST":
            return lit(ins.argval)
        elif op == "STORE_FAST":
            locals_map[ins.argval] = stack.pop()
        else:
            raise _Unsupported(op)
    raise _Unsupported("no return")


# ---------------------------------------------------------------------------
# Row-at-a-time fallback expression (reference GpuScalaUDF / the
# uncompiled ScalaUDF path — host-only, tags the exec off device)
# ---------------------------------------------------------------------------

class PythonUDF(Expression):
    sql_name = "PythonUDF"

    def __init__(self, fn: Callable, children: Sequence[Expression],
                 return_type: T.DataType):
        self.fn = fn
        self.children = tuple(children)
        self.return_type = return_type

    def with_new_children(self, children):
        return PythonUDF(self.fn, children, self.return_type)

    @property
    def dtype(self):
        return self.return_type

    @property
    def nullable(self):
        return True

    @property
    def device_supported(self):
        return False

    def _eval(self, vals: list[Val], ctx: EvalCtx):
        n = ctx.capacity
        is_str = isinstance(self.return_type, T.StringType)
        out = np.empty(n, dtype=object) if is_str else \
            np.zeros(n, dtype=self.return_type.np_dtype)
        validity = np.zeros(n, dtype=np.bool_)
        for i in range(n):
            # null-propagating call semantics, matching what the COMPILED
            # expression tree produces (null in -> null out) so results
            # do not depend on whether compilation succeeded
            if not all(v.validity[i] for v in vals):
                if is_str:
                    out[i] = None
                continue
            r = self.fn(*[v.data[i] for v in vals])
            if r is not None:
                out[i] = r
                validity[i] = True
            elif is_str:
                out[i] = None
        return Val(out, validity, None, self.return_type)

    def __repr__(self):
        name = getattr(self.fn, "__name__", "<lambda>")
        return f"PythonUDF({name}, {', '.join(map(repr, self.children))})"


def udf(fn: Callable, return_type: T.DataType | None = None):
    """Wrap a python function as a column UDF:
    ``df.select(udf(lambda x: x * 2 + 1)(col("a")))``."""

    def apply(*cols):
        rt = return_type or T.DoubleType()
        return PythonUDF(fn, [c for c in cols], rt)

    return apply


def maybe_compile_udfs(exprs: Sequence[Expression], conf) -> list[Expression]:
    """Planner hook: replace PythonUDF nodes with compiled native trees
    when the compiler conf is on (reference LogicalPlanRules.apply)."""
    from spark_rapids_tpu.conf import UDF_COMPILER_ENABLED
    if not conf.get(UDF_COMPILER_ENABLED):
        return list(exprs)

    def rewrite(node):
        if isinstance(node, PythonUDF):
            compiled = compile_udf(node.fn, node.children)
            if compiled is not None:
                # honor the declared return type either way, so the output
                # schema is identical whether or not compilation succeeds
                from spark_rapids_tpu.expr.cast import Cast
                return Cast(compiled, node.return_type)
        return node

    return [e.transform_up(rewrite) if isinstance(e, Expression) else e
            for e in exprs]

"""Python-UDF compiler: bytecode -> native expression trees.

Reference: the udf-compiler module (udf-compiler/, 4.3k LoC) symbolically
executes JVM bytecode of Scala lambdas into Catalyst expressions
(LambdaReflection + CFG + Instruction.scala + CatalystExpressionBuilder.
scala:45-80, `compile` :66), falling back silently when a construct is
unsupported.  The TPU analog symbolically executes CPython bytecode:
straight-line lambdas over arithmetic/comparison ops compile to the
engine's Expression IR and run on device; anything else stays a
host-evaluated row-at-a-time PythonUDF (the planner tags the enclosing
exec off-device, explain shows `!`).

Enabled by ``spark.rapids.sql.udfCompiler.enabled``.
"""
from spark_rapids_tpu.udf.compiler import (PythonUDF, compile_udf,
                                           maybe_compile_udfs, udf)

__all__ = ["udf", "PythonUDF", "compile_udf", "maybe_compile_udfs"]

"""Shuffle/spill buffer compression codecs.

Reference: `TableCompressionCodec` SPI + nvcomp LZ4
(TableCompressionCodec.scala:41,137, NvcompLZ4CompressionCodec.scala:25).
Here LZ4 is the native C++ block codec (native/lz4.cpp — the nvcomp
analog on host staging buffers) and zstd rides the bundled python
binding.  Selected by ``spark.rapids.shuffle.compression.codec``.
"""
from __future__ import annotations

__all__ = ["Codec", "get_codec"]


class Codec:
    name = "none"

    def compress(self, data: bytes) -> bytes:
        raise NotImplementedError

    def decompress(self, data: bytes, out_size: int) -> bytes:
        raise NotImplementedError


class Lz4Codec(Codec):
    name = "lz4"

    def compress(self, data: bytes) -> bytes:
        from spark_rapids_tpu.native import lz4_compress
        return lz4_compress(data)

    def decompress(self, data: bytes, out_size: int) -> bytes:
        from spark_rapids_tpu.native import lz4_decompress
        return lz4_decompress(data, out_size)


class ZstdCodec(Codec):
    name = "zstd"

    def __init__(self):
        try:
            import zstandard
        except ImportError as e:
            raise RuntimeError(
                "spark.rapids.shuffle.compression.codec=zstd requires the "
                "'zstandard' package, which is not installed in this "
                "environment; install zstandard or pick codec 'lz4' or "
                "'none'") from e
        self._c = zstandard.ZstdCompressor()
        self._d = zstandard.ZstdDecompressor()

    def compress(self, data: bytes) -> bytes:
        return self._c.compress(data)

    def decompress(self, data: bytes, out_size: int) -> bytes:
        out = self._d.decompress(data, max_output_size=out_size)
        if len(out) != out_size:
            raise ValueError(
                f"zstd decompression size mismatch ({len(out)} != "
                f"{out_size})")
        return out


def get_codec(name: str) -> Codec | None:
    """None for "none"; raises on unknown codec names."""
    if name in (None, "", "none"):
        return None
    if name == "lz4":
        return Lz4Codec()
    if name == "zstd":
        return ZstdCodec()
    raise ValueError(f"unknown compression codec {name!r}")

"""TcpShuffleTransport: the cross-process / cross-host shuffle plane.

Reference mapping (SURVEY §2.6, §5.8): the UCX transport module — a TCP
management/metadata plane plus a tagged data plane moving partition
buffers peer-to-peer, with an inflight-bytes throttle
(UCX.scala:192-328, UCXShuffleTransport.scala:365-391) — behind the
`RapidsShuffleTransport` SPI.  The TPU engine's cross-slice analog is a
host TCP plane (DCN-style): map output stays spillable in the local
store (the `LocalShuffleTransport` it wraps), a server thread serves
partition ranges on demand, and peers fetch with a length-prefixed,
type-tagged frame protocol:

    request  (JSON frame): {"op": "fetch", "shuffle_id": .., "part_id":
              .., "lo": .., "hi": .., "window": <client ack window>,
              "crc": [<checksum algos the client can verify>]}
              | {"op": "meta", "shuffle_id": ..}
    response: [8-byte big-endian length][1-byte tag][payload] frames:
              tag 0x03 = JSON header/metadata (fetch headers carry the
              server's codec and its checksum pick, so compression AND
              integrity are negotiated, not assumed), 0x00 = batch data
              (Arrow IPC bytes, codec-compressed with a 4-byte raw-size
              prefix when the header says so, prefixed with a 4-byte
              CRC32C/CRC32 when a checksum was negotiated), 0x01 = end
              of stream, 0x02 = server-side error (payload is the
              message — a store failure reaches the client as a
              diagnosable ShuffleFetchError, not a connection reset).

The server throttles at the CLIENT-declared ``window`` (carried in the
request), so both endpoints count the same bytes and a conf mismatch
cannot deadlock the ack exchange.  Request/ack frames are capped at 64
KiB (``_MAX_CTRL_FRAME``): a desynced peer lying in a control frame's
length prefix cannot make the server attempt a multi-GiB allocation.
Transport failures (reset, stall past the deadline, checksum mismatch)
raise the retryable ``ShuffleTransportError``; shuffle/retry.py wraps
the client in a resumable backoff ladder with a per-peer circuit
breaker, and spark_rapids_tpu/faults.py can inject failures
deterministically at every seam.

Within a slice the mesh collective path (parallel/mesh_shuffle.py) is
the ICI plane; this module is the inter-process/DCN plane.  The
listener binds ``spark.rapids.shuffle.tcp.bindAddress`` (loopback by
default; set 0.0.0.0 — plus advertiseAddress — for real multi-host).
"""
from __future__ import annotations

import json
import socket
import struct
import threading
import time
import zlib
from collections import deque
from typing import Iterable

from spark_rapids_tpu.conf import ConfEntry, register, parse_bytes, _bool
# obs.registry is dependency-free (stdlib only) — safe at module level
from spark_rapids_tpu.obs.registry import get_registry
from spark_rapids_tpu.shuffle.compression import get_codec
# re-exported for backward compatibility: these historically lived here
from spark_rapids_tpu.shuffle.errors import (MapOutputLostError,
                                             ShuffleFetchError,
                                             ShuffleTransportError)
from spark_rapids_tpu.shuffle.local import LocalShuffleTransport
from spark_rapids_tpu.shuffle.serializer import deserialize_batch

__all__ = ["TcpShuffleTransport", "TcpShuffleServer", "ShuffleFetchError",
           "ShuffleTransportError", "MapOutputLostError", "fetch_remote",
           "remote_partition_sizes"]

TCP_PORT = register(ConfEntry(
    "spark.rapids.shuffle.tcp.port", 0,
    "Listen port for the TCP shuffle server (0 = ephemeral). The bound "
    "address is exposed as transport.address, the analog of the UCX "
    "management port carried in MapStatus "
    "(RapidsShuffleInternalManager.scala:173-186).", conv=int))
TCP_BIND_ADDRESS = register(ConfEntry(
    "spark.rapids.shuffle.tcp.bindAddress", "127.0.0.1",
    "Interface the TCP shuffle server binds. Loopback by default "
    "(single-host); set 0.0.0.0 (with advertiseAddress) so peers on "
    "other hosts can fetch over DCN."))
TCP_ADVERTISE_ADDRESS = register(ConfEntry(
    "spark.rapids.shuffle.tcp.advertiseAddress", "",
    "Host peers should dial (when binding 0.0.0.0 the bound address is "
    "not routable). Empty = the bind address."))
TCP_INFLIGHT_LIMIT = register(ConfEntry(
    "spark.rapids.shuffle.tcp.maxBytesInFlight", 64 << 20,
    "Client fetch window: the server sends at most this many payload "
    "bytes ahead of the client's acks. Carried in each fetch request, "
    "so both endpoints always use the same window (reference "
    "inflight-bytes throttle, UCXShuffleTransport.scala:365-391).",
    conv=parse_bytes))
TCP_TIMEOUT = register(ConfEntry(
    "spark.rapids.shuffle.tcp.timeoutSeconds", 120,
    "Socket timeout for shuffle fetches: a wedged peer raises "
    "ShuffleFetchError instead of hanging the reduce task forever "
    "(reference: fetch timeout via spark.network.timeout, "
    "GpuShuffleEnv.scala:60-62, propagated through "
    "RapidsShuffleIterator).", conv=float))
SOCKET_TIMEOUT = register(ConfEntry(
    "spark.rapids.shuffle.socketTimeout", 0.0,
    "Per-read/write timeout in seconds on established shuffle data "
    "connections, applied on BOTH ends: the client's fetch socket and "
    "the server's accepted connections. A peer that accepts and then "
    "stalls mid-stream surfaces as a retryable ShuffleFetchError after "
    "this long instead of holding the connection (and a serve thread) "
    "until tcp.timeoutSeconds. 0 inherits tcp.timeoutSeconds. Set it "
    "well below the backoff ladder's total budget so a hung peer "
    "converts into retries the circuit breaker can count.", conv=float))
TCP_CHECKSUM = register(ConfEntry(
    "spark.rapids.shuffle.tcp.checksumEnabled", True,
    "Per-data-frame integrity checksum (CRC32C when the C binding is "
    "available, CRC32 otherwise), negotiated through the fetch header "
    "so old/new peers interoperate: the client advertises the "
    "algorithms it knows, the server echoes its pick and prefixes each "
    "frame with the 4-byte checksum. Corruption surfaces as a "
    "retryable ShuffleFetchError at the frame boundary instead of a "
    "poisoned Arrow deserialize. (reference: UCX delegates integrity "
    "to the fabric; a DCN-style TCP plane must carry its own)",
    conv=_bool))

_LEN = struct.Struct(">Q")
_TAG_DATA, _TAG_END, _TAG_ERROR, _TAG_JSON = b"\x00", b"\x01", b"\x02", b"\x03"
#: frame sanity floor: a frame is one batch's bytes; the effective cap
#: is max(this, 2x spark.rapids.sql.batchSizeBytes) so oversized-batch
#: configs stay fetchable while a desynced/non-protocol peer still gets
#: a clean error instead of a garbage-length allocation
_MAX_FRAME_MIN = 2 << 30
#: request/ack frames are small JSON — a desynced or malicious peer
#: must not be able to make the server attempt a multi-GiB allocation
#: by lying in a control frame's length prefix
_MAX_CTRL_FRAME = 64 << 10

#: frame checksum algorithms this endpoint can verify, in preference
#: order; negotiation picks the first name both peers know, so a build
#: without the C crc32c binding still interoperates via zlib's crc32
_CRC_ALGOS: dict = {}
try:
    import google_crc32c as _gcrc32c

    _CRC_ALGOS["crc32c"] = _gcrc32c.value
except ImportError:  # pragma: no cover - env without the binding
    pass
_CRC_ALGOS["crc32"] = zlib.crc32
_CRC = struct.Struct(">I")

#: codec names this endpoint can DECODE, advertised in fetch requests
#: (resolved once; zstd only when its binding imports)
_CLIENT_CODECS: "list[str] | None" = None


def _client_codecs() -> "list[str]":
    """Codecs the client side can inflate, carried in the fetch request
    as ``codecs`` so a server whose store compresses with something the
    client lacks refuses the stream with a diagnosable error frame
    instead of letting the client die inside get_codec/decompress.
    Old peers send/understand no ``codecs`` key — same interop pattern
    as the ``crc`` negotiation."""
    global _CLIENT_CODECS
    if _CLIENT_CODECS is None:
        names = ["none", "lz4"]
        try:
            import zstandard  # noqa: F401

            names.append("zstd")
        except ImportError:  # pragma: no cover - env without zstd
            pass
        _CLIENT_CODECS = names
    return _CLIENT_CODECS


def _max_frame(conf=None) -> int:
    if conf is None:
        return _MAX_FRAME_MIN
    return max(_MAX_FRAME_MIN, 2 * conf.batch_size_bytes)


#: error-frame prefix carrying a structured terminal-loss payload: the
#: server's store lost map outputs, and the client must surface WHICH
#: ones so stage recovery can recompute exactly those (not retry)
_LOST_MARKER = "MAP_OUTPUT_LOST "


def _raise_error_frame(body: bytes, shuffle_id, part_id: int) -> None:
    """Decode a _TAG_ERROR payload into the right exception class: a
    MAP_OUTPUT_LOST marker means terminal data loss at the peer (raise
    MapOutputLostError with the lost map ids), anything else is a plain
    server-side ShuffleFetchError."""
    text = body.decode()
    if text.startswith(_LOST_MARKER):
        try:
            payload = json.loads(text[len(_LOST_MARKER):])
        except ValueError:
            raise ShuffleFetchError(text) from None
        raise MapOutputLostError.parse(shuffle_id, part_id, payload)
    raise ShuffleFetchError(text)


def _send_frame(sock: socket.socket, tag: bytes, payload: bytes = b"") -> None:
    sock.sendall(_LEN.pack(len(payload) + 1) + tag + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket,
                max_frame: int = _MAX_FRAME_MIN) -> tuple[bytes, bytes]:
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if n < 1 or n > max_frame:
        raise ConnectionError(f"bad frame length {n} (desynced or "
                              "non-protocol peer)")
    body = _recv_exact(sock, n)
    return body[:1], body[1:]


class TcpShuffleServer:
    """Serves a LocalShuffleTransport's map output over TCP (reference
    RapidsShuffleServer.scala:67: serve metadata + buffer-send requests
    from the catalog-backed store)."""

    def __init__(self, store: LocalShuffleTransport, bind: str = "127.0.0.1",
                 port: int = 0, advertise: str = ""):
        self._store = store
        # deterministic fault plan (spark.rapids.test.faults), owned by
        # the store so counters span this server's whole lifetime
        self._faults = getattr(store, "faults", None)
        self.metrics = {"meta_requests": 0, "fetch_requests": 0,
                        "data_frames_sent": 0, "bytes_sent": 0,
                        "faults_injected": 0, "traced_fetches": 0}
        # propagated trace headers from peers' fetch requests (bounded):
        # the serving side's record that remote work belonged to a given
        # originating query_id/trace_id
        self.trace_log: deque = deque(maxlen=256)
        self._reg_source = get_registry().register_object_source(
            f"shuffle.server.{id(self):x}", self)
        # read/write timeout for accepted connections: a client that
        # connects and then wedges must not pin a serve thread forever
        settings = getattr(getattr(store, "conf", None), "settings", {})
        st = SOCKET_TIMEOUT.get(settings)
        if not st or st <= 0:
            st = TCP_TIMEOUT.get(settings)
        self._sock_timeout = st if st and st > 0 else None
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((bind, port))
        self._sock.listen(16)
        host, bound_port = self._sock.getsockname()
        self.address = (advertise or host, bound_port)
        self._closed = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True, name="tpu-shuffle-srv")
        self._thread.start()

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            # a timed-out read raises TimeoutError (an OSError), which
            # the _serve handlers already treat as "drop the connection"
            conn.settimeout(self._sock_timeout)
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            with conn:
                # enginelint: disable=RL004 (per-connection serve loop; peer close raises ConnectionError and server shutdown closes the socket)
                while True:
                    try:
                        _, body = _recv_frame(conn, _MAX_CTRL_FRAME)
                        req = json.loads(body.decode())
                    except (ConnectionError, ValueError):
                        return
                    try:
                        self._serve_one(conn, req)
                    except (ConnectionError, OSError):
                        return
                    except MapOutputLostError as e:
                        # terminal loss: ship the structured payload so
                        # the reader's stage-recovery layer learns WHICH
                        # map outputs died, not just that the fetch failed
                        _send_frame(conn, _TAG_ERROR, (
                            _LOST_MARKER + json.dumps(
                                {"shuffle_id": e.shuffle_id,
                                 "part_id": e.part_id,
                                 "lost": {str(k): v
                                          for k, v in e.lost.items()},
                                 "detail": "reported by peer",
                                 "observed_empty":
                                     e.observed_empty})).encode())
                    # enginelint: disable=RL001 (failure is surfaced to the peer as an error frame, not swallowed)
                    except Exception as e:  # noqa: BLE001 - sent to peer
                        # store/codec failures must reach the client as a
                        # diagnosable error frame, not a connection reset
                        _send_frame(conn, _TAG_ERROR,
                                    f"{type(e).__name__}: {e}".encode())
        except (ConnectionError, OSError):
            pass

    def _serve_one(self, conn: socket.socket, req: dict) -> None:
        if req.get("op") == "meta":
            self.metrics["meta_requests"] += 1
            sizes = self._store.partition_sizes(req["shuffle_id"])
            batches = {str(p): self._store.batch_sizes(req["shuffle_id"], p)
                       for p in sizes}
            _send_frame(conn, _TAG_JSON, json.dumps(
                {"sizes": {str(k): v for k, v in sizes.items()},
                 "batch_sizes": batches,
                 "codec": self._store.codec_name}).encode())
            return
        if req.get("op") != "fetch":
            _send_frame(conn, _TAG_ERROR,
                        f"unknown op {req.get('op')!r}".encode())
            return
        self.metrics["fetch_requests"] += 1
        if self._faults is not None:
            act = self._faults.check("shuffle.peer.hang",
                                     shuffle=req["shuffle_id"],
                                     part=req["part_id"])
            if act is not None:
                # accepted-then-stalled peer: hold the connection open
                # sending NOTHING (no header, no error frame) until the
                # client's socketTimeout trips or this server closes —
                # the exact wedge satellite 1's data-socket timeout
                # exists to convert into a retryable ShuffleFetchError
                self.metrics["faults_injected"] += 1
                self._closed.wait(act.param("seconds", 3600.0))
                return
        # trace propagation: a new peer carries its query's ids in the
        # request; record them, emit a serve event re-parented onto the
        # propagated span when this process has a live tracer, and echo
        # the header back.  An old peer sends no "trace" key and is
        # served exactly as before.
        tr = req.get("trace") or None
        if isinstance(tr, dict):
            self.metrics["traced_fetches"] += 1
            self.trace_log.append({
                "query_id": tr.get("query_id"),
                "trace_id": tr.get("trace_id"),
                "span_id": tr.get("span_id"),
                "shuffle_id": req["shuffle_id"], "part_id": req["part_id"],
                "lo": req.get("lo", 0), "hi": req.get("hi")})
            try:
                ctx = getattr(self._store, "ctx", None)
                tracer = ctx.tracer if ctx is not None else None
            # enginelint: disable=RL001 (tracing is best-effort; serving proceeds without a span)
            except Exception:
                tracer = None
            if tracer is not None:
                tracer.event("shuffle.serve", "shuffle",
                             parent_id=tr.get("span_id"),
                             origin_query_id=tr.get("query_id"),
                             origin_trace_id=tr.get("trace_id"),
                             shuffle=str(req["shuffle_id"]),
                             part=req["part_id"],
                             lo=req.get("lo", 0), hi=req.get("hi"))
        window = int(req.get("window") or TCP_INFLIGHT_LIMIT.default)
        # codec negotiation: a new client lists the codecs it can
        # decode; when this store's codec is not among them the stream
        # is refused with a diagnosable error frame — the client would
        # otherwise die inside decompress on the first data frame.  An
        # old peer sends no "codecs" key and is served as before.
        accepts = req.get("codecs")
        if accepts is not None and self._store.codec_name not in accepts:
            self.metrics["codec_rejects"] = \
                self.metrics.get("codec_rejects", 0) + 1
            _send_frame(conn, _TAG_ERROR, (
                f"shuffle codec {self._store.codec_name!r} not accepted "
                f"by client (client accepts {list(accepts)}); align "
                "spark.rapids.shuffle.compression.codec across peers"
            ).encode())
            return
        # checksum negotiation: the client advertises the algorithms it
        # can verify; pick the first this server also knows and echo it
        # in the header.  An old peer sends/understands no "crc" key and
        # gets the unprefixed frames it expects.
        offered = req.get("crc") or []
        if isinstance(offered, str):
            offered = [offered]
        crc_name = next((n for n in offered if n in _CRC_ALGOS), None)
        header = {"codec": self._store.codec_name}
        if crc_name is not None:
            header["crc"] = crc_name
        if isinstance(tr, dict):
            header["trace"] = tr
        crc_fn = _CRC_ALGOS.get(crc_name)
        _send_frame(conn, _TAG_JSON, json.dumps(header).encode())
        sent_window = 0
        for i, raw in enumerate(self._store.fetch_partition_serialized(
                req["shuffle_id"], req["part_id"],
                req.get("lo", 0), req.get("hi"))):
            payload = raw if crc_fn is None else \
                _CRC.pack(crc_fn(raw) & 0xFFFFFFFF) + raw
            if self._faults is not None:
                act = self._faults.check(
                    "tcp.server.frame", shuffle=req["shuffle_id"],
                    part=req["part_id"], frame=i)
                if act is not None:
                    self.metrics["faults_injected"] += 1
                    if act.action == "reset":
                        # abrupt mid-stream close: the client sees a
                        # peer reset, never an END or error frame
                        raise ConnectionError("injected fault: reset")
                    if act.action == "error":
                        _send_frame(conn, _TAG_ERROR,
                                    b"injected fault: server error frame")
                        return
                    if act.action == "stall":
                        time.sleep(act.param("seconds", 5.0))
                    elif act.action == "corrupt":
                        # flip one seeded byte AFTER the checksum was
                        # computed: in-transit corruption as the client
                        # verifier sees it
                        flipped = bytearray(payload)
                        flipped[act.rng.randrange(len(flipped))] ^= 0xFF
                        payload = bytes(flipped)
            _send_frame(conn, _TAG_DATA, payload)
            self.metrics["data_frames_sent"] += 1
            self.metrics["bytes_sent"] += len(payload)
            sent_window += len(payload)
            if sent_window >= window:
                # wait for the client before sending further frames
                # (inflight throttle at the client-declared window)
                tag, _ = _recv_frame(conn, _MAX_CTRL_FRAME)
                if tag != _TAG_JSON:
                    return
                sent_window = 0
        _send_frame(conn, _TAG_END)

    def close(self) -> None:
        self._closed.set()
        get_registry().unregister_source(self._reg_source)
        try:
            self._sock.close()
        except OSError:
            pass


class TcpShuffleTransport(LocalShuffleTransport):
    """SPI transport = local spillable store + TCP server for peers.

    In-process consumers read straight from the store (the reference's
    local-block path, RapidsCachingReader.scala:49); remote consumers
    connect to ``transport.address`` and stream frames (`fetch_remote`).
    """

    def __init__(self, conf, ctx=None):
        super().__init__(conf, ctx)
        self._server = TcpShuffleServer(
            self, bind=conf.get(TCP_BIND_ADDRESS),
            port=conf.get(TCP_PORT),
            advertise=conf.get(TCP_ADVERTISE_ADDRESS))
        self.address = self._server.address

    @property
    def server_metrics(self) -> dict:
        return self._server.metrics

    def fetch_from(self, address, shuffle_id: "int | str", part_id: int,
                   lo: int = 0, hi: int | None = None,
                   device: bool = True) -> Iterable:
        """Client entry honoring this transport's conf: window, timeout,
        checksum, and the retry/backoff/circuit-breaker ladder all come
        from the conf (reference: the transport owns its inflight
        throttle and its failure policy, not the call site)."""
        from spark_rapids_tpu.shuffle.retry import fetch_remote_with_retry
        ctx = getattr(self, "ctx", None)
        tracer = ctx.tracer if ctx is not None else None
        trace = tracer.trace_header() if tracer is not None else None
        lifecycle = ctx.lifecycle if ctx is not None else None
        return fetch_remote_with_retry(address, shuffle_id, part_id,
                                       lo=lo, hi=hi, device=device,
                                       conf=self.conf, faults=self.faults,
                                       tracer=tracer, trace=trace,
                                       lifecycle=lifecycle)

    def close(self) -> None:
        self._server.close()
        super().close()


def _resolve_timeout(timeout: float | None) -> float | None:
    """None -> conf default; 0 -> no timeout (blocking), the usual
    convention for disabling it."""
    t = TCP_TIMEOUT.default if timeout is None else float(timeout)
    return t if t > 0 else None


def _check_connect_fault(faults, address) -> None:
    if faults is not None:
        act = faults.check("tcp.client.connect", host=address[0],
                           port=address[1])
        if act is not None:
            raise ConnectionError("injected fault: connect reset")


def remote_partition_sizes(address, shuffle_id: "int | str",
                           timeout: float | None = None,
                           sock_timeout: float | None = None,
                           faults=None) -> tuple[dict, dict]:
    """Metadata plane: (partition_sizes, batch_sizes) from a peer
    (reference MetadataRequest/Response flatbuffer RPC).  A wedged peer
    raises ShuffleFetchError after ``timeout`` seconds (``sock_timeout``
    tightens the per-read deadline once connected — the socketTimeout
    conf); a reset or mid-frame close is wrapped with the same context
    instead of leaking a raw ConnectionError to the reduce task."""
    tmo = _resolve_timeout(timeout)
    try:
        _check_connect_fault(faults, tuple(address))
        with socket.create_connection(tuple(address), timeout=tmo) as sock:
            if sock_timeout is not None and sock_timeout > 0:
                sock.settimeout(sock_timeout)
            _send_frame(sock, _TAG_JSON, json.dumps(
                {"op": "meta", "shuffle_id": shuffle_id}).encode())
            tag, body = _recv_frame(sock)
    except TimeoutError as e:
        raise ShuffleTransportError(
            f"metadata fetch of shuffle {shuffle_id} from {address} "
            f"stalled past its read deadline") from e
    except (ConnectionError, OSError) as e:
        raise ShuffleTransportError(
            f"metadata fetch of shuffle {shuffle_id} from {address} "
            f"failed: {type(e).__name__}: {e}") from e
    if tag == _TAG_ERROR:
        raise ShuffleFetchError(body.decode())
    meta = json.loads(body.decode())
    return ({int(k): v for k, v in meta["sizes"].items()},
            {int(k): v for k, v in meta["batch_sizes"].items()})


def fetch_remote(address, shuffle_id: "int | str", part_id: int, lo: int = 0,
                 hi: int | None = None, device: bool = True,
                 inflight_limit: int | None = None,
                 max_frame: int = _MAX_FRAME_MIN,
                 timeout: float | None = None,
                 sock_timeout: float | None = None,
                 checksum: bool = True, faults=None,
                 trace: dict | None = None, raw: bool = False) -> Iterable:
    """Data plane: stream one reduce partition's batches from a peer
    (reference RapidsShuffleClient.scala: TransferRequest -> bounce
    buffers -> reassembled device buffers).  The wire codec and frame
    checksum come from the server's response header — never assumed by
    the client.  Every transport failure — a stall past ``timeout``
    (connect, send, or receive; 0 disables the deadline), a reset or
    mid-frame close, a frame failing its negotiated checksum — raises
    ShuffleTransportError (retryable; see shuffle/retry.py) instead of
    wedging or poisoning the reduce task.

    ``raw=True`` yields the decompressed Arrow IPC bytes of each slot
    instead of deserialized batches — the graceful-drain migration path
    relays a retiring worker's slots into a survivor's store without a
    decode/re-encode round trip (cluster/worker.py)."""
    window = int(inflight_limit or TCP_INFLIGHT_LIMIT.default)
    tmo = _resolve_timeout(timeout)
    peer_label = ":".join(str(x) for x in tuple(address))
    bytes_fetched = 0
    try:
        _check_connect_fault(faults, tuple(address))
        with socket.create_connection(tuple(address), timeout=tmo) as sock:
            if sock_timeout is not None and sock_timeout > 0:
                # tighter per-read deadline on the established data
                # connection (spark.rapids.shuffle.socketTimeout): an
                # accepted-then-stalled peer fails fast and retryably
                sock.settimeout(sock_timeout)
            req = {"op": "fetch", "shuffle_id": shuffle_id,
                   "part_id": part_id, "lo": lo, "hi": hi,
                   "window": window, "codecs": _client_codecs()}
            if checksum:
                req["crc"] = list(_CRC_ALGOS)
            if trace:
                # propagation header: the serving side attributes this
                # stream to the originating query_id/trace_id (absent
                # for old callers — same interop pattern as "crc")
                req["trace"] = trace
            _send_frame(sock, _TAG_JSON, json.dumps(req).encode())
            tag, body = _recv_frame(sock)
            if tag == _TAG_ERROR:
                _raise_error_frame(body, shuffle_id, part_id)
            if tag != _TAG_JSON:
                raise ShuffleTransportError(f"bad fetch header tag {tag!r}")
            header = json.loads(body.decode())
            codec_name = header.get("codec", "none")
            try:
                codec = get_codec(codec_name)
            except (ValueError, RuntimeError) as e:
                # negotiation should have caught this server-side; a
                # header naming a codec this build cannot construct is
                # a config/version mismatch, not a transient — surface
                # it terminally with the fix in the message
                err = ShuffleFetchError(
                    f"peer {address} serves shuffle {shuffle_id} with "
                    f"codec {codec_name!r} this client cannot decode "
                    f"(supports {_client_codecs()}): {e}")
                err.terminal = True
                raise err from e
            # handshake record: which codec each fetch stream actually
            # negotiated (tests + diag bundles read this)
            get_registry().inc(f"shuffle.fetch.codec.{codec_name}")
            crc_name = header.get("crc")
            crc_fn = _CRC_ALGOS.get(crc_name)
            if crc_name is not None and crc_fn is None:
                raise ShuffleFetchError(
                    f"peer {address} negotiated unknown frame checksum "
                    f"{crc_name!r} (offered {list(_CRC_ALGOS)})")
            recv_window = 0
            index = lo
            # enginelint: disable=RL004 (frame pump bounded by the socket timeout; END/ERROR frames or ConnectionError exit)
            while True:
                tag, frame = _recv_frame(sock, max_frame)
                if tag == _TAG_END:
                    return
                if tag == _TAG_ERROR:
                    _raise_error_frame(frame, shuffle_id, part_id)
                bytes_fetched += len(frame)
                recv_window += len(frame)
                if recv_window >= window:
                    _send_frame(sock, _TAG_JSON, b"{}")
                    recv_window = 0
                if crc_fn is not None:
                    if len(frame) <= _CRC.size:
                        raise ShuffleTransportError(
                            f"malformed frame: {len(frame)} bytes with a "
                            f"{crc_name} prefix negotiated")
                    (want,) = _CRC.unpack(frame[:_CRC.size])
                    frame = frame[_CRC.size:]
                    got = crc_fn(frame) & 0xFFFFFFFF
                    if got != want:
                        get_registry().inc("shuffle.fetch.checksum_failures")
                        raise ShuffleTransportError(
                            f"frame {index} of shuffle {shuffle_id} part "
                            f"{part_id} from {address} failed its "
                            f"{crc_name} check (sent {want:#010x}, "
                            f"computed {got:#010x}): corrupted in transit")
                if codec is not None:
                    if len(frame) < 4:
                        raise ShuffleFetchError(
                            f"malformed compressed frame: {len(frame)} "
                            "bytes, need >= 4 for the raw-size prefix")
                    (raw_size,) = struct.unpack(">I", frame[:4])
                    if raw_size > max_frame:
                        raise ShuffleFetchError(
                            f"compressed frame claims raw size {raw_size} "
                            f"> max frame {max_frame}")
                    frame = codec.decompress(frame[4:], raw_size)
                yield frame if raw else deserialize_batch(frame,
                                                          device=device)
                index += 1
    except TimeoutError as e:
        raise ShuffleTransportError(
            f"fetch of shuffle {shuffle_id} part {part_id} from "
            f"{address} stalled past its read deadline") from e
    except (ConnectionError, OSError) as e:
        raise ShuffleTransportError(
            f"fetch of shuffle {shuffle_id} part {part_id} from "
            f"{address} failed: {type(e).__name__}: {e}") from e
    finally:
        # flushed once per stream (attempt), whatever way it ends, so
        # per-peer byte movement is visible even for failed attempts
        if bytes_fetched:
            get_registry().inc(f"shuffle.peer.{peer_label}.bytes_fetched",
                               bytes_fetched)
            get_registry().inc("shuffle.fetch.bytes", bytes_fetched)

"""LocalShuffleTransport: the single-process shuffle data plane.

Reference mapping (SURVEY §2.6): RapidsCachingWriter stores map-output
tables spillable in the device store (RapidsShuffleInternalManager.scala:
90-155) and RapidsCachingReader serves local blocks straight from the
catalog.  Here:

* codec == none  -> partition batches stay device-resident, registered in
  the execution's BufferCatalog as SpillableColumnarBatch with
  SHUFFLE_OUTPUT priority (spilled first under pressure);
* codec != none  -> batches are serialized (Arrow IPC, shuffle/
  serializer.py) and compressed into host bytes — the
  GpuColumnarBatchSerializer + nvcomp path — and restored on fetch.

Stage recovery (exec/recovery.py) makes the store LOSS-AWARE: every
stored batch lives in a ``_Slot`` tagged with its producing map id and
an output EPOCH.  ``invalidate_map_outputs`` marks a map task's slots
lost IN PLACE — positions never shift, so the adaptive reader's
``(part_id, lo, hi)`` slices and a resumed pull's batch index stay
valid across a recovery — and bumps the map's epoch so a straggling
write from the previous attempt is discarded instead of mixed in
(the epoch-tagging analog of Spark's stage attempt ids on map status).
Fetching a lost slot raises ``MapOutputLostError`` naming exactly the
dead ``(shuffle_id, map_id)`` outputs; a spill file that fails its
read-back checksum (memory/catalog.py SpillCorruptionError) is
reclassified the same way — data loss drives recomputation, not a
query abort.

Multi-host planes (ICI collectives / DCN) implement the same SPI; the
planner's mesh path (exec/mesh_exec.py) is the ICI plane.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Iterable

from spark_rapids_tpu.conf import (SHUFFLE_COMPRESSION_CODEC,
                                   SHUFFLE_MAX_METADATA_SIZE, TpuConf)
from spark_rapids_tpu.shuffle.compression import get_codec
from spark_rapids_tpu.shuffle.errors import MapOutputLostError
from spark_rapids_tpu.shuffle.serializer import (deserialize_batch,
                                                 serialize_batch)

__all__ = ["LocalShuffleTransport"]


@dataclass
class _Slot:
    """One map-output batch's position in a reduce partition's fetch
    order.  ``item is None`` means the output was invalidated and not
    yet recomputed; the slot keeps its position so resumed pulls and
    AQE skew-split ranges stay aligned across recoveries."""
    map_id: int
    epoch: int
    item: Any          # ("spillable", scb) | ("bytes", data, raw) | None
    size: int
    rows: int = 0


class LocalShuffleTransport:
    """In-process ShuffleTransport (see shuffle/__init__.py SPI)."""

    def __init__(self, conf: TpuConf, ctx=None):
        from spark_rapids_tpu.faults import FaultRegistry
        self.conf = conf
        self.ctx = ctx
        self.codec_name = conf.get(SHUFFLE_COMPRESSION_CODEC)
        self.codec = get_codec(self.codec_name)
        self.max_metadata = conf.get(SHUFFLE_MAX_METADATA_SIZE)
        # deterministic fault plan (spark.rapids.test.faults; None when
        # unset = every injection site is one is-None check).  One
        # registry per transport so nth/times counters span its lifetime.
        self.faults = FaultRegistry.from_conf(conf)
        self._lock = threading.Lock()
        # (shuffle_id, part_id) -> list of _Slot in map-batch order
        self._store: dict[tuple, list[_Slot]] = {}
        self._sizes: dict[tuple, int] = {}
        self._rows: dict[tuple, int] = {}
        self._batch_sizes: dict[tuple, list[int]] = {}
        # (shuffle_id, map_id) -> current output epoch; a write tagged
        # with an older epoch raced a recovery and is discarded
        self._epochs: dict[tuple, int] = {}
        self.metrics = {"bytes_written": 0, "bytes_compressed": 0,
                        "batches_written": 0, "stale_writes_discarded": 0,
                        "map_outputs_invalidated": 0}
        # surface transport counters in the process metrics registry as
        # pull gauges (weakref-bound; dropped again in close())
        from spark_rapids_tpu.obs.registry import get_registry
        self._reg_source = get_registry().register_object_source(
            f"shuffle.transport.{id(self):x}", self)

    # -- SPI ------------------------------------------------------------
    def write_partition(self, shuffle_id: "int | str", map_id: int,
                        part_id: int, batch, epoch: int | None = None) -> None:
        if self.codec is None and self.ctx is not None:
            from spark_rapids_tpu.memory.catalog import (
                SpillableColumnarBatch, SpillPriority)
            item = ("spillable", SpillableColumnarBatch(
                batch, self.ctx.catalog, SpillPriority.SHUFFLE_OUTPUT))
            size = batch.device_size_bytes()
        else:
            raw = serialize_batch(batch, self.max_metadata)
            self.metrics["bytes_written"] += len(raw)
            if self.codec is not None:
                comp = self.codec.compress(raw)
                self.metrics["bytes_compressed"] += len(comp)
                item = ("bytes", comp, len(raw))
            else:
                item = ("bytes", raw, len(raw))
            size = len(item[1])
        rows = int(getattr(batch, "known_rows", 0) or 0)
        stale = None
        with self._lock:
            current = self._epochs.get((shuffle_id, map_id), 0)
            eff = current if epoch is None else epoch
            if eff < current:
                # a prior attempt's straggler landed after recovery
                # already invalidated this map output: discard, never mix
                # epochs within one partition stream
                self.metrics["stale_writes_discarded"] += 1
                stale = item
            else:
                slots = self._store.setdefault((shuffle_id, part_id), [])
                refill = next((s for s in slots
                               if s.map_id == map_id and s.item is None),
                              None)
                if refill is not None:
                    refill.item = item
                    refill.epoch = eff
                    refill.size = size
                    refill.rows = rows
                    idx = slots.index(refill)
                    self._batch_sizes[(shuffle_id, part_id)][idx] = size
                else:
                    slots.append(_Slot(map_id, eff, item, size, rows))
                    self._batch_sizes.setdefault((shuffle_id, part_id),
                                                 []).append(size)
                self._sizes[(shuffle_id, part_id)] = \
                    self._sizes.get((shuffle_id, part_id), 0) + size
                self._rows[(shuffle_id, part_id)] = \
                    self._rows.get((shuffle_id, part_id), 0) + rows
        if stale is not None:
            if stale[0] == "spillable":
                stale[1].close()
            return
        self.metrics["batches_written"] += 1

    def import_serialized(self, shuffle_id: "int | str", map_id: int,
                          part_id: int, raw: bytes, rows: int = 0,
                          epoch: int | None = None) -> None:
        """Store one already-serialized map-output batch (Arrow IPC
        bytes) under an explicit epoch — the graceful-drain migration
        path (cluster/worker.py _h_migrate_slots): a survivor pulls a
        retiring peer's slots as wire bytes and adopts them without a
        device round-trip.  The local epoch advances to the imported
        one so a straggling write from the retiring attempt is
        discarded, mirroring write_partition's stale-epoch rule."""
        self.metrics["bytes_written"] += len(raw)
        if self.codec is not None:
            comp = self.codec.compress(raw)
            self.metrics["bytes_compressed"] += len(comp)
            item = ("bytes", comp, len(raw))
        else:
            item = ("bytes", raw, len(raw))
        size = len(item[1])
        with self._lock:
            current = self._epochs.get((shuffle_id, map_id), 0)
            eff = current if epoch is None else int(epoch)
            if eff < current:
                self.metrics["stale_writes_discarded"] += 1
                return
            self._epochs[(shuffle_id, map_id)] = eff
            slots = self._store.setdefault((shuffle_id, part_id), [])
            refill = next((s for s in slots
                           if s.map_id == map_id and s.item is None),
                          None)
            if refill is not None:
                refill.item = item
                refill.epoch = eff
                refill.size = size
                refill.rows = rows
                idx = slots.index(refill)
                self._batch_sizes[(shuffle_id, part_id)][idx] = size
            else:
                slots.append(_Slot(map_id, eff, item, size, rows))
                self._batch_sizes.setdefault((shuffle_id, part_id),
                                             []).append(size)
            self._sizes[(shuffle_id, part_id)] = \
                self._sizes.get((shuffle_id, part_id), 0) + size
            self._rows[(shuffle_id, part_id)] = \
                self._rows.get((shuffle_id, part_id), 0) + rows
        self.metrics["batches_written"] += 1

    def map_epoch(self, shuffle_id: "int | str", map_id: int) -> int:
        with self._lock:
            return self._epochs.get((shuffle_id, map_id), 0)

    def map_output_present(self, shuffle_id: "int | str", part_id: int,
                           map_id: int) -> bool:
        """True when this reduce partition currently holds a live output
        of the given map task.  Recovery re-checks this for empty-slot
        observations: a reader can catch a slot between invalidation and
        the recovering thread's rewrite — at the very epoch the rewrite
        will carry, so epoch ordering alone cannot tell "mid-recompute"
        from "still lost"."""
        with self._lock:
            return any(s.map_id == map_id and s.item is not None
                       for s in self._store.get((shuffle_id, part_id), ()))

    def invalidate_map_outputs(self, shuffle_id: "int | str",
                               map_ids: Iterable[int]) -> dict[int, int]:
        """Mark every stored output of the given map tasks lost, bump
        their epochs, and free their storage (including spill files, via
        the catalog entry's close).  Returns map_id -> new epoch; writes
        tagged with an older epoch are discarded from now on.  Slots
        keep their positions so in-flight pulls and AQE ranges survive
        the recovery."""
        wanted = set(map_ids)
        to_close = []
        new_epochs: dict[int, int] = {}
        with self._lock:
            for m in wanted:
                new_epochs[m] = self._epochs.get((shuffle_id, m), 0) + 1
                self._epochs[(shuffle_id, m)] = new_epochs[m]
            for (sid, pid), slots in self._store.items():
                if sid != shuffle_id:
                    continue
                for s in slots:
                    if s.map_id in wanted and s.item is not None:
                        to_close.append(s.item)
                        s.item = None
                        # advance to the post-invalidation epoch: a pull
                        # that later observes this still-empty slot must
                        # report the CURRENT epoch, or recovery would
                        # judge it already-recovered and never retry
                        s.epoch = new_epochs[s.map_id]
                        self._sizes[(sid, pid)] -= s.size
                        self._rows[(sid, pid)] = \
                            self._rows.get((sid, pid), 0) - s.rows
                        self.metrics["map_outputs_invalidated"] += 1
        # close OUTSIDE the transport lock: spillable close takes the
        # catalog lock (and may unlink disk files); nesting the two
        # orders would deadlock against spill paths fetching from us
        for item in to_close:
            if item[0] == "spillable":
                item[1].close()
        return new_epochs

    def partition_sizes(self, shuffle_id: "int | str") -> dict[int, int]:
        """Map-output statistics per reduce partition (reference
        MapStatus sizes feeding AQE's coalescing decisions)."""
        with self._lock:
            return {pid: sz for (sid, pid), sz in self._sizes.items()
                    if sid == shuffle_id}

    def partition_rows(self, shuffle_id: "int | str") -> dict[int, int]:
        """Exact row counts per reduce partition, from the batch mirror's
        ``known_rows`` stamped at map-write time — the second statistic
        (after bytes) the adaptive re-optimizer feeds on."""
        with self._lock:
            return {pid: n for (sid, pid), n in self._rows.items()
                    if sid == shuffle_id}

    def batch_sizes(self, shuffle_id: "int | str", part_id: int) -> list[int]:
        """Per-map-batch sizes of one reduce partition, in fetch order —
        the granularity the adaptive reader splits skewed partitions at."""
        with self._lock:
            return list(self._batch_sizes.get((shuffle_id, part_id), ()))

    def slots_for(self, shuffle_id: "int | str",
                  part_id: int) -> list[tuple[int, int, int, int]]:
        """Per-slot ``(map_id, size, rows, epoch)`` of one reduce
        partition in fetch order — the map-output registration record a
        cluster worker rolls back to the driver so its tracker can
        address individual slots for locality-aware reduce fetches
        (cluster/exec.py; reference MapStatus -> MapOutputTracker)."""
        with self._lock:
            return [(s.map_id, s.size, s.rows, s.epoch)
                    for s in self._store.get((shuffle_id, part_id), ())]

    def shuffle_inventory(self) -> dict:
        """Everything this store still holds, slot-indexed:
        ``{shuffle_id: {part_id: [(slot_idx, map_id, size, rows,
        epoch), ...]}}`` for LIVE slots only (invalidated holes keep
        their index so slot addressing matches the registrations the
        dead driver journaled).  This is the RECONNECT handshake's
        payload: a recovered driver reconciles it against the journal
        and re-seeds its map-output tracker from what actually
        survived."""
        with self._lock:
            out: dict = {}
            for (sid, pid), slots in self._store.items():
                rows = [(idx, s.map_id, s.size, s.rows, s.epoch)
                        for idx, s in enumerate(slots)
                        if s.item is not None]
                if rows:
                    out.setdefault(sid, {})[pid] = rows
            return out

    def alias_shuffle(self, old_sid, new_sid) -> int:
        """Re-key every slot of ``old_sid`` under ``new_sid`` (a
        recovered driver's replanned query carries a fresh per-process
        shuffle id for the same exchange; claiming the journaled map
        outputs renames them in place — no copy, no device traffic).
        Returns the number of partitions moved."""
        moved = 0
        with self._lock:
            for (sid, pid) in [k for k in self._store if k[0] == old_sid]:
                self._store[(new_sid, pid)] = self._store.pop((sid, pid))
                self._sizes[(new_sid, pid)] = self._sizes.pop(
                    (sid, pid), 0)
                self._rows[(new_sid, pid)] = self._rows.pop((sid, pid), 0)
                self._batch_sizes[(new_sid, pid)] = \
                    self._batch_sizes.pop((sid, pid), [])
                moved += 1
            for (sid, mid) in [k for k in self._epochs
                               if k[0] == old_sid]:
                self._epochs[(new_sid, mid)] = self._epochs.pop((sid, mid))
        return moved

    def _slice_or_lost(self, shuffle_id, part_id, lo, hi) -> list[_Slot]:
        """Snapshot the requested slot slice, raising MapOutputLostError
        naming EVERY lost map output in it (recovery recomputes them all
        in one stage attempt, not one per failed fetch)."""
        self._check_fetch_fault(shuffle_id, part_id)
        with self._lock:
            slots = list(self._store.get((shuffle_id, part_id), ()))[lo:hi]
            lost = {s.map_id: s.epoch for s in slots if s.item is None}
        if self.faults is not None and slots:
            act = self.faults.check("shuffle.peer.dead", shuffle=shuffle_id,
                                    part=part_id)
            if act is not None:
                raise MapOutputLostError(
                    shuffle_id, part_id,
                    {s.map_id: s.epoch for s in slots},
                    "injected fault: shuffle.peer.dead")
        if lost:
            raise MapOutputLostError(shuffle_id, part_id, lost,
                                     "slot invalidated and not recomputed",
                                     observed_empty=True)
        return slots

    def _get_spillable(self, scb, slot: _Slot, shuffle_id, part_id):
        """Materialize a spillable slot, reclassifying a corrupt spill
        read-back (or a handle closed by a concurrent invalidation) as
        terminal loss of that map output: the data is gone no matter how
        often the fetch retries."""
        from spark_rapids_tpu.memory.catalog import SpillCorruptionError
        try:
            return scb.get()
        except SpillCorruptionError as e:
            raise MapOutputLostError(
                shuffle_id, part_id, {slot.map_id: slot.epoch},
                f"spill read-back failed its checksum: {e}") from e

    def fetch_partition(self, shuffle_id: "int | str", part_id: int,
                        lo: int = 0, hi: int | None = None) -> Iterable:
        """Stream one reduce partition's batches, optionally only the
        map-batch slice [lo, hi) — the adaptive reader's skew-split
        groups fetch their own range without materializing the rest."""
        for slot in self._slice_or_lost(shuffle_id, part_id, lo, hi):
            # snapshot: a concurrent invalidation nulls slot.item in
            # place, and we must not flip representations mid-iteration
            item = slot.item
            if item is None:
                raise MapOutputLostError(
                    shuffle_id, part_id, {slot.map_id: slot.epoch},
                    "invalidated while the pull was in flight",
                    observed_empty=True)
            if item[0] == "spillable":
                b = self._get_spillable(item[1], slot, shuffle_id, part_id)
                try:
                    yield b
                finally:
                    # unpin on GeneratorExit too: a consumer breaking out
                    # mid-iteration must not leave the batch pinned
                    # (unspillable) for the rest of the execution
                    item[1].unpin()
            else:
                _, data, raw_size = item
                raw = self.codec.decompress(data, raw_size) \
                    if self.codec is not None else data
                yield deserialize_batch(raw, device=True)

    def fetch_partition_serialized(self, shuffle_id: "int | str", part_id: int,
                                   lo: int = 0,
                                   hi: int | None = None) -> Iterable[bytes]:
        """Wire frames for one reduce partition's map-batch slice: Arrow
        IPC bytes, codec-compressed with a 4-byte raw-size prefix when a
        codec is configured.  Spillable (device-resident) items serialize
        on demand — the TCP server's send path (reference
        RapidsShuffleServer: acquire from catalog -> copy to bounce
        buffer -> send)."""
        import struct
        for slot in self._slice_or_lost(shuffle_id, part_id, lo, hi):
            item = slot.item
            if item is None:
                raise MapOutputLostError(
                    shuffle_id, part_id, {slot.map_id: slot.epoch},
                    "invalidated while the pull was in flight",
                    observed_empty=True)
            if item[0] == "spillable":
                b = self._get_spillable(item[1], slot, shuffle_id, part_id)
                try:
                    raw = serialize_batch(b, self.max_metadata)
                finally:
                    item[1].unpin()
                if self.codec is not None:
                    yield struct.pack(">I", len(raw)) + \
                        self.codec.compress(raw)
                else:
                    yield raw
            else:
                _, data, raw_size = item
                if self.codec is not None:
                    yield struct.pack(">I", raw_size) + data
                else:
                    yield data

    def _check_fetch_fault(self, shuffle_id, part_id) -> None:
        """store.fetch injection point: a simulated store failure — over
        the TCP plane it reaches the client as an error frame, exactly
        like a real catalog/codec failure would."""
        if self.faults is not None:
            from spark_rapids_tpu.faults import InjectedFault
            act = self.faults.check("store.fetch", shuffle=shuffle_id,
                                    part=part_id)
            if act is not None:
                raise InjectedFault(
                    f"injected fault: store.fetch {act.action} "
                    f"(shuffle={shuffle_id} part={part_id})")

    def release_shuffle(self, shuffle_id) -> int:
        """Drop every slot of ONE shuffle (map outputs, sizes, epochs)
        and return the byte count released.  The cluster plane calls
        this from the driver once a query's tracker closes, so a
        long-lived worker store does not accumulate dead shuffles
        across queries (the in-process engine instead closes the whole
        transport with its ExecCtx)."""
        with self._lock:
            keys = [k for k in self._store if k[0] == shuffle_id]
            freed = 0
            items = []
            for k in keys:
                for s in self._store.pop(k, ()):
                    if s.item is not None:
                        items.append(s.item)
                    freed += s.size
                self._sizes.pop(k, None)
                self._rows.pop(k, None)
                self._batch_sizes.pop(k, None)
            for mk in [k for k in self._epochs if k[0] == shuffle_id]:
                self._epochs.pop(mk, None)
        for item in items:
            if item[0] == "spillable":
                item[1].close()
        return freed

    def close(self) -> None:
        from spark_rapids_tpu.obs.registry import get_registry
        get_registry().unregister_source(self._reg_source)
        with self._lock:
            items = [s.item for lst in self._store.values() for s in lst
                     if s.item is not None]
            self._store.clear()
        for item in items:
            if item[0] == "spillable":
                item[1].close()

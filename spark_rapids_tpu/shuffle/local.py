"""LocalShuffleTransport: the single-process shuffle data plane.

Reference mapping (SURVEY §2.6): RapidsCachingWriter stores map-output
tables spillable in the device store (RapidsShuffleInternalManager.scala:
90-155) and RapidsCachingReader serves local blocks straight from the
catalog.  Here:

* codec == none  -> partition batches stay device-resident, registered in
  the execution's BufferCatalog as SpillableColumnarBatch with
  SHUFFLE_OUTPUT priority (spilled first under pressure);
* codec != none  -> batches are serialized (Arrow IPC, shuffle/
  serializer.py) and compressed into host bytes — the
  GpuColumnarBatchSerializer + nvcomp path — and restored on fetch.

Multi-host planes (ICI collectives / DCN) implement the same SPI; the
planner's mesh path (exec/mesh_exec.py) is the ICI plane.
"""
from __future__ import annotations

import threading
from typing import Iterable

from spark_rapids_tpu.conf import (SHUFFLE_COMPRESSION_CODEC,
                                   SHUFFLE_MAX_METADATA_SIZE, TpuConf)
from spark_rapids_tpu.shuffle.compression import get_codec
from spark_rapids_tpu.shuffle.serializer import (deserialize_batch,
                                                 serialize_batch)

__all__ = ["LocalShuffleTransport"]


class LocalShuffleTransport:
    """In-process ShuffleTransport (see shuffle/__init__.py SPI)."""

    def __init__(self, conf: TpuConf, ctx=None):
        from spark_rapids_tpu.faults import FaultRegistry
        self.conf = conf
        self.ctx = ctx
        self.codec_name = conf.get(SHUFFLE_COMPRESSION_CODEC)
        self.codec = get_codec(self.codec_name)
        self.max_metadata = conf.get(SHUFFLE_MAX_METADATA_SIZE)
        # deterministic fault plan (spark.rapids.test.faults; None when
        # unset = every injection site is one is-None check).  One
        # registry per transport so nth/times counters span its lifetime.
        self.faults = FaultRegistry.from_conf(conf)
        self._lock = threading.Lock()
        # (shuffle_id, part_id) -> list of stored items in map order
        self._store: dict[tuple, list] = {}
        self._sizes: dict[tuple, int] = {}
        self._batch_sizes: dict[tuple, list[int]] = {}
        self.metrics = {"bytes_written": 0, "bytes_compressed": 0,
                        "batches_written": 0}

    # -- SPI ------------------------------------------------------------
    def write_partition(self, shuffle_id: "int | str", map_id: int, part_id: int,
                        batch) -> None:
        if self.codec is None and self.ctx is not None:
            from spark_rapids_tpu.memory.catalog import (
                SpillableColumnarBatch, SpillPriority)
            item = ("spillable", SpillableColumnarBatch(
                batch, self.ctx.catalog, SpillPriority.SHUFFLE_OUTPUT))
        else:
            raw = serialize_batch(batch, self.max_metadata)
            self.metrics["bytes_written"] += len(raw)
            if self.codec is not None:
                comp = self.codec.compress(raw)
                self.metrics["bytes_compressed"] += len(comp)
                item = ("bytes", comp, len(raw))
            else:
                item = ("bytes", raw, len(raw))
        if item[0] == "spillable":
            size = batch.device_size_bytes()
        else:
            size = len(item[1])
        with self._lock:
            self._store.setdefault((shuffle_id, part_id), []).append(item)
            self._sizes[(shuffle_id, part_id)] = \
                self._sizes.get((shuffle_id, part_id), 0) + size
            self._batch_sizes.setdefault((shuffle_id, part_id),
                                         []).append(size)
        self.metrics["batches_written"] += 1

    def partition_sizes(self, shuffle_id: "int | str") -> dict[int, int]:
        """Map-output statistics per reduce partition (reference
        MapStatus sizes feeding AQE's coalescing decisions)."""
        with self._lock:
            return {pid: sz for (sid, pid), sz in self._sizes.items()
                    if sid == shuffle_id}

    def batch_sizes(self, shuffle_id: "int | str", part_id: int) -> list[int]:
        """Per-map-batch sizes of one reduce partition, in fetch order —
        the granularity the adaptive reader splits skewed partitions at."""
        with self._lock:
            return list(self._batch_sizes.get((shuffle_id, part_id), ()))

    def fetch_partition(self, shuffle_id: "int | str", part_id: int,
                        lo: int = 0, hi: int | None = None) -> Iterable:
        """Stream one reduce partition's batches, optionally only the
        map-batch slice [lo, hi) — the adaptive reader's skew-split
        groups fetch their own range without materializing the rest."""
        self._check_fetch_fault(shuffle_id, part_id)
        with self._lock:
            items = list(self._store.get((shuffle_id, part_id), ()))
        for item in items[lo:hi]:
            if item[0] == "spillable":
                b = item[1].get()
                try:
                    yield b
                finally:
                    # unpin on GeneratorExit too: a consumer breaking out
                    # mid-iteration must not leave the batch pinned
                    # (unspillable) for the rest of the execution
                    item[1].unpin()
            else:
                _, data, raw_size = item
                raw = self.codec.decompress(data, raw_size) \
                    if self.codec is not None else data
                yield deserialize_batch(raw, device=True)

    def fetch_partition_serialized(self, shuffle_id: "int | str", part_id: int,
                                   lo: int = 0,
                                   hi: int | None = None) -> Iterable[bytes]:
        """Wire frames for one reduce partition's map-batch slice: Arrow
        IPC bytes, codec-compressed with a 4-byte raw-size prefix when a
        codec is configured.  Spillable (device-resident) items serialize
        on demand — the TCP server's send path (reference
        RapidsShuffleServer: acquire from catalog -> copy to bounce
        buffer -> send)."""
        import struct
        self._check_fetch_fault(shuffle_id, part_id)
        with self._lock:
            items = list(self._store.get((shuffle_id, part_id), ()))
        for item in items[lo:hi]:
            if item[0] == "spillable":
                b = item[1].get()
                try:
                    raw = serialize_batch(b, self.max_metadata)
                finally:
                    item[1].unpin()
                if self.codec is not None:
                    yield struct.pack(">I", len(raw)) + \
                        self.codec.compress(raw)
                else:
                    yield raw
            else:
                _, data, raw_size = item
                if self.codec is not None:
                    yield struct.pack(">I", raw_size) + data
                else:
                    yield data

    def _check_fetch_fault(self, shuffle_id, part_id) -> None:
        """store.fetch injection point: a simulated store failure — over
        the TCP plane it reaches the client as an error frame, exactly
        like a real catalog/codec failure would."""
        if self.faults is not None:
            from spark_rapids_tpu.faults import InjectedFault
            act = self.faults.check("store.fetch", shuffle=shuffle_id,
                                    part=part_id)
            if act is not None:
                raise InjectedFault(
                    f"injected fault: store.fetch {act.action} "
                    f"(shuffle={shuffle_id} part={part_id})")

    def close(self) -> None:
        with self._lock:
            items = [i for lst in self._store.values() for i in lst]
            self._store.clear()
        for item in items:
            if item[0] == "spillable":
                item[1].close()

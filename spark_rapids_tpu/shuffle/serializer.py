"""Columnar batch <-> bytes serializer (Arrow IPC stream).

Reference: `GpuColumnarBatchSerializer` — the JVM-shuffle fallback path
serializes batches with JCudfSerialization to host streams
(GpuColumnarBatchSerializer.scala:38,85-89).  The TPU engine's canonical
host format is Arrow, so the serializer is Arrow IPC: one stream per
batch, schema header + record batch.  ``max_metadata_size`` bounds the
schema header (``spark.rapids.shuffle.maxMetadataSize`` analog of the
flatbuffer metadata-message cap).
"""
from __future__ import annotations

import io

__all__ = ["serialize_batch", "deserialize_batch"]


def serialize_batch(batch, max_metadata_size: int | None = None) -> bytes:
    """Device (or host) batch -> Arrow IPC stream bytes (D2H copy)."""
    import pyarrow as pa
    from spark_rapids_tpu.columnar.batch import ColumnBatch
    rb = batch.to_arrow()
    if max_metadata_size is not None:
        header = rb.schema.serialize().size
        if header > max_metadata_size:
            raise ValueError(
                f"shuffle metadata {header}B exceeds "
                f"spark.rapids.shuffle.maxMetadataSize={max_metadata_size}")
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, rb.schema) as w:
        w.write_batch(rb)
    return sink.getvalue()


def deserialize_batch(data: bytes, device: bool = True,
                      string_widths=None):
    """Arrow IPC stream bytes -> ColumnBatch (H2D) or host RecordBatch."""
    import pyarrow as pa
    from spark_rapids_tpu.columnar.batch import ColumnBatch
    # consume the batch while the reader is still open: batch buffers may
    # reference reader-owned memory, so converting after close is a
    # use-after-free (observed as delayed heap-corruption segfaults)
    with pa.ipc.open_stream(pa.BufferReader(data)) as r:
        rb = r.read_next_batch()
        if not device:
            return rb
        return ColumnBatch.from_arrow(rb, string_widths=string_widths)

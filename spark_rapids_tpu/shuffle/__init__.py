"""Accelerated-shuffle subsystem: transport SPI + codec + serializer.

Reference (SURVEY §2.6, §5.8): `RapidsShuffleTransport` is a pluggable
SPI loaded by reflection from ``spark.rapids.shuffle.transport.class``
(RapidsShuffleTransport.scala:378-460, makeTransport :638-658), with the
UCX implementation as its one transport; shuffle data lives spillable in
the catalog and is served on demand.  The TPU analog keeps the SPI shape:
a transport owns (a) map-output storage and (b) the data plane that moves
partition bytes to consumers.  `LocalShuffleTransport` (shuffle/local.py)
is the single-process plane; the mesh collective path (parallel/
mesh_shuffle.py) is the ICI plane the planner picks for mesh-sharded
plans.

Fault tolerance: cross-process pulls go through shuffle/retry.py —
resumable retrying fetches (exponential backoff + jitter, per-peer
circuit breaker) over tcp.py's checksummed frame protocol; TERMINAL
data loss (shuffle/errors.py MapOutputLostError) bypasses the retry
ladder and drives lineage recomputation of exactly the lost map
outputs (exec/recovery.py), with epoch-tagged writes so a straggler
from a dead attempt is discarded.  The deterministic fault-injection
plan (spark.rapids.test.faults, spark_rapids_tpu/faults.py) exercises
every failure path in-process.
"""
from __future__ import annotations

import importlib
from typing import Iterable, Protocol, runtime_checkable

from spark_rapids_tpu.conf import TpuConf

__all__ = ["ShuffleTransport", "make_transport"]


@runtime_checkable
class ShuffleTransport(Protocol):
    """Transport SPI (reference RapidsShuffleTransport.scala:378-460).

    A transport instance is scoped to one execution: the exchange writes
    every map task's partition batches, then consumers fetch per reduce
    partition.  Implementations own storage (spillable or serialized) and
    the movement plane.
    """

    def write_partition(self, shuffle_id: "int | str", map_id: int, part_id: int,
                        batch, epoch: int | None = None) -> None:
        """Store one map-output batch for (shuffle, map, partition).

        ``epoch`` tags the write with a map-output attempt: None means
        "the map task's current epoch" (the common case); stage
        recovery passes the post-invalidation epoch so a straggling
        write from the superseded attempt is discarded instead of
        mixed into the recovered stream."""
        ...

    def fetch_partition(self, shuffle_id: "int | str", part_id: int,
                        lo: int = 0, hi: int | None = None) -> Iterable:
        """Batches of one reduce partition in a stable map order,
        restricted to the batch slice [lo, hi) (hi=None -> end).  The
        adaptive reader uses sub-ranges to split skewed partitions."""
        ...

    def close(self) -> None:
        ...


def make_transport(conf: TpuConf, ctx=None) -> ShuffleTransport:
    """Reflection-load the transport class from
    ``spark.rapids.shuffle.transport.class`` (reference makeTransport,
    RapidsShuffleTransport.scala:638-658)."""
    from spark_rapids_tpu.conf import SHUFFLE_TRANSPORT_CLASS
    path = conf.get(SHUFFLE_TRANSPORT_CLASS)
    mod_name, _, cls_name = path.rpartition(".")
    try:
        mod = importlib.import_module(mod_name)
        cls = getattr(mod, cls_name)
    except (ImportError, AttributeError) as e:
        raise ValueError(
            f"cannot load shuffle transport {path!r}: {e}") from e
    return cls(conf, ctx)

"""Shuffle failure taxonomy: transient vs. terminal loss.

The three-layer fault-tolerance model (docs/tuning-guide.md "Fault
tolerance") needs every layer to agree on WHAT failed before deciding
WHO handles it:

* ``ShuffleTransportError`` — the *connection* died (reset, stall past
  the deadline, frame checksum mismatch).  The map output is still
  intact at the peer; shuffle/retry.py reconnects and resumes
  (layer 1, transient).
* ``MapOutputLostError`` — the *data* died: a spilled map-output file
  came back corrupt, a peer is terminally dead, or a store slot was
  invalidated mid-fetch (stale epoch).  Retrying the fetch cannot
  help; the exchange's stage-recovery layer (exec/recovery.py)
  invalidates exactly the named ``(shuffle_id, map_id)`` outputs and
  recomputes them from lineage (layer 3, terminal).  ``lost`` maps
  each dead map id to the output EPOCH the reader observed, so a
  concurrent recovery that already advanced the epoch is not redone.
* ``StageRecoveryExhausted`` — recovery itself gave up: the per-stage
  attempt budget (``spark.rapids.shuffle.recovery.maxStageAttempts``)
  ran out while the same map outputs kept dying.

The query lifecycle plane (exec/lifecycle.py) extends the same
``terminal`` convention: ``QueryCancelled`` / ``QueryDeadlineExceeded``
carry ``terminal = True`` as a class attribute, so every ladder here —
and the OOM retry scopes in memory/retry.py — refuses to swallow them
with the one ``getattr(ex, "terminal", False)`` check it already does,
no lifecycle import required.

Reference mapping (SURVEY §2.6): FetchFailedException carries
(shuffleId, mapId) up to Spark's DAGScheduler, which resubmits the
lost map stage — the lineage-recomputation model of RDDs (Zaharia et
al., NSDI 2012).  This standalone engine has no DAGScheduler above it,
so the classification lives here and the resubmission in
exec/recovery.py.
"""
from __future__ import annotations

__all__ = ["ShuffleFetchError", "ShuffleTransportError",
           "MapOutputLostError", "StageRecoveryExhausted"]


class ShuffleFetchError(RuntimeError):
    """A peer reported a server-side failure while serving a fetch."""

    #: True when retrying the same fetch cannot succeed (the data is
    #: gone, not just this connection) — the retry ladder re-raises
    #: instead of burning backoff attempts.
    terminal: bool = False


class ShuffleTransportError(ShuffleFetchError):
    """The transport itself failed (reset, stall past the timeout,
    desynced or corrupted frame) — always retryable: the map output is
    still intact at the peer, only this connection's stream died."""


class MapOutputLostError(ShuffleFetchError):
    """Terminal loss of specific map outputs of one shuffle.

    ``lost`` maps each dead ``map_id`` to the output epoch the reader
    observed when the loss surfaced; stage recovery skips any map id
    whose store epoch has already advanced past the observed one
    (a concurrent pull recovered it first).

    ``observed_empty`` distinguishes the two ways a loss is observed:
    True means the reader found an invalidated slot with no data (the
    output may already be mid-recompute by another thread — recovery
    re-checks presence before re-invalidating); False means data was
    present but is terminally gone (dead peer, corrupt spill read-back)
    and must be recomputed regardless of what the store holds now.
    """

    terminal = True
    observed_empty = False

    def __init__(self, shuffle_id, part_id: int, lost: dict,
                 detail: str = "", observed_empty: bool = False):
        self.shuffle_id = shuffle_id
        self.part_id = part_id
        self.lost = dict(lost)
        self.observed_empty = observed_empty
        ids = ", ".join(f"map {m} (epoch {e})"
                        for m, e in sorted(self.lost.items()))
        msg = (f"map output lost: shuffle {shuffle_id} part {part_id} "
               f"[{ids}]")
        if detail:
            msg += f": {detail}"
        super().__init__(msg)

    @classmethod
    def parse(cls, shuffle_id, part_id: int,
              payload: dict) -> "MapOutputLostError":
        """Rebuild from a wire payload (tcp.py MAP_OUTPUT_LOST error
        frame): map ids arrive as JSON object keys, i.e. strings."""
        lost = {int(k): int(v)
                for k, v in (payload.get("lost") or {}).items()}
        return cls(payload.get("shuffle_id", shuffle_id),
                   int(payload.get("part_id", part_id)), lost,
                   payload.get("detail", "reported by peer"),
                   observed_empty=bool(payload.get("observed_empty",
                                                   False)))


class StageRecoveryExhausted(RuntimeError):
    """The per-stage recovery attempt budget ran out: the same shuffle
    kept losing map outputs after ``maxStageAttempts`` recomputations."""

    def __init__(self, shuffle_id, attempts: int, lost: dict):
        self.shuffle_id = shuffle_id
        self.attempts = attempts
        self.lost = dict(lost)
        super().__init__(
            f"stage recovery exhausted for shuffle {shuffle_id}: map "
            f"outputs {sorted(self.lost)} still lost after {attempts} "
            f"recovery attempts "
            f"(spark.rapids.shuffle.recovery.maxStageAttempts)")

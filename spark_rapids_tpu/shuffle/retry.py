"""Resumable retrying shuffle fetches + per-peer circuit breaker.

Reference mapping (SURVEY §2.6): the UCX client surfaces every
transport failure to Spark's stage-retry machinery instead of wedging
the reduce task (RapidsShuffleIterator; fetch deadline via
spark.network.timeout), and Spark's own block transfer layer retries at
the transport level first (RetryingBlockTransferor behind
spark.shuffle.io.maxRetries/retryWait).  The TPU engine has no Spark
scheduler above it, so the transport-level ladder lives HERE:

* ``fetch_remote_with_retry`` wraps the raw ``fetch_remote`` stream in
  an exponential-backoff + jitter loop.  On reconnect it RESUMES the
  partition stream at ``lo + delivered`` using the protocol's existing
  lo/hi map-batch range fields — a batch is counted delivered only
  after it was fully received, checksum-verified, and yielded, so a
  retry never duplicates or drops a batch.  Progress resets the
  ladder: a reconnect that delivered at least one new batch starts
  again from zero failed attempts, so a long stream cannot exhaust its
  retries across many independent hiccups.
* ``remote_partition_sizes_with_retry`` gives the metadata plane the
  same ladder.
* A per-peer circuit breaker counts CONSECUTIVE failed attempts across
  all fetches to that peer; past the threshold, further fetches fail
  fast with a diagnosable error (peer, failure count, last cause)
  instead of burning the full backoff ladder per partition against a
  dead host.  After ``circuitBreaker.resetSeconds`` one probe attempt
  is allowed through (half-open); success closes the breaker.
* TERMINAL errors bypass the ladder entirely: a ``MapOutputLostError``
  (the peer's data is gone, not its connection) re-raises immediately
  so stage recovery (exec/recovery.py) can recompute the lost outputs
  — retrying a fetch of destroyed data only delays that.  Conversely,
  ladder exhaustion and an open breaker mark THEIR errors terminal
  (``.terminal = True``): the transient machinery has given up, and
  whatever sits above must not spin on them either.

With no faults and a healthy peer the success path is exactly ONE
``fetch_remote`` call — the retry layer adds no round trips.
"""
from __future__ import annotations

import random
import threading
import time
from typing import Iterator

from spark_rapids_tpu.conf import ConfEntry, register
# obs.registry is dependency-free (stdlib only) — safe at module level
from spark_rapids_tpu.obs.registry import get_registry
from spark_rapids_tpu.shuffle.tcp import (TCP_CHECKSUM, TCP_INFLIGHT_LIMIT,
                                          TCP_TIMEOUT, ShuffleFetchError,
                                          _max_frame, fetch_remote,
                                          remote_partition_sizes)

__all__ = ["fetch_remote_with_retry", "remote_partition_sizes_with_retry",
           "PeerCircuitBreaker", "reset_circuit_breakers"]

TCP_MAX_RETRIES = register(ConfEntry(
    "spark.rapids.shuffle.tcp.maxRetries", 3,
    "Transport-level retries per shuffle fetch before the failure "
    "propagates to the caller. Each retry reconnects and RESUMES the "
    "partition stream from the last fully-delivered batch (the "
    "protocol's lo/hi range fields), so no batch is duplicated or "
    "dropped; an attempt that delivers at least one new batch resets "
    "the ladder. (reference: spark.shuffle.io.maxRetries, "
    "RetryingBlockTransferor)", conv=int))
TCP_RETRY_WAIT = register(ConfEntry(
    "spark.rapids.shuffle.tcp.retryWaitSeconds", 0.5,
    "Base wait before the first shuffle-fetch retry; each further "
    "retry multiplies it by retryBackoffMultiplier, with +-50% "
    "deterministic jitter so a burst of reduce tasks does not "
    "reconnect in lockstep. (reference: spark.shuffle.io.retryWait)",
    conv=float))
TCP_RETRY_BACKOFF = register(ConfEntry(
    "spark.rapids.shuffle.tcp.retryBackoffMultiplier", 2.0,
    "Multiplier applied to retryWaitSeconds per consecutive failed "
    "shuffle-fetch attempt (exponential backoff).", conv=float))
TCP_BREAKER_FAILURES = register(ConfEntry(
    "spark.rapids.shuffle.tcp.circuitBreaker.maxFailures", 8,
    "Consecutive failed fetch attempts against one peer (across all "
    "partitions) that trip its circuit breaker: further fetches fail "
    "fast with a diagnosable error instead of burning the full backoff "
    "ladder per partition against a dead peer. Any success resets the "
    "count.", conv=int))
TCP_BREAKER_RESET = register(ConfEntry(
    "spark.rapids.shuffle.tcp.circuitBreaker.resetSeconds", 30.0,
    "Cooldown after a peer's circuit breaker opens before ONE probe "
    "attempt is allowed through (half-open); a successful probe closes "
    "the breaker, a failed one re-opens it for another cooldown.",
    conv=float))


class PeerCircuitBreaker:
    """Consecutive-failure counter for one peer address."""

    def __init__(self, peer):
        self.peer = peer
        self._lock = threading.Lock()
        self.failures = 0
        self.last_error: str | None = None
        self._opened_at: float | None = None

    def before_attempt(self, reset_seconds: float) -> None:
        """Fail fast while open; allow one probe after the cooldown."""
        with self._lock:
            if self._opened_at is None:
                return
            age = time.monotonic() - self._opened_at
            if age < reset_seconds:
                err = ShuffleFetchError(
                    f"circuit breaker open for shuffle peer {self.peer}: "
                    f"{self.failures} consecutive fetch failures "
                    f"(last: {self.last_error}); next probe in "
                    f"{reset_seconds - age:.1f}s")
                # the transient ladder has given up on this peer: callers
                # above must recover (or fail), not re-enter the ladder
                err.terminal = True
                raise err
            # half-open: let this attempt probe the peer

    def record_failure(self, err: BaseException, threshold: int) -> None:
        with self._lock:
            self.failures += 1
            self.last_error = f"{type(err).__name__}: {err}"
            if self.failures >= threshold:
                if self._opened_at is None:
                    # closed -> open transition only (a failed half-open
                    # probe re-arms the cooldown without recounting)
                    get_registry().inc("shuffle.breaker.opens")
                self._opened_at = time.monotonic()

    @property
    def is_open(self) -> bool:
        with self._lock:
            return self._opened_at is not None

    def record_success(self) -> None:
        with self._lock:
            self.failures = 0
            self.last_error = None
            self._opened_at = None


_BREAKERS: dict = {}
_BREAKERS_LOCK = threading.Lock()


def _breaker(peer) -> PeerCircuitBreaker:
    with _BREAKERS_LOCK:
        b = _BREAKERS.get(peer)
        if b is None:
            b = _BREAKERS[peer] = PeerCircuitBreaker(peer)
        return b


def reset_circuit_breakers() -> None:
    """Forget all peer state (tests; a deliberate cluster-topology
    change where old addresses are known stale)."""
    with _BREAKERS_LOCK:
        _BREAKERS.clear()


def _peer_label(peer) -> str:
    return ":".join(str(x) for x in peer) if isinstance(peer, tuple) \
        else str(peer)


def _is_conn_refused(err: BaseException) -> bool:
    """True when a ConnectionRefusedError sits anywhere on the error's
    cause/context chain.  A refused dial means no process is listening
    YET — the normal state of a ``local[N]`` worker that is still
    binding its shuffle server — not a sick peer.  Counting it toward
    the per-peer circuit breaker lets N concurrent reduce fetches trip
    the breaker (maxFailures=8) during a startup race and turn a
    would-succeed-in-50ms query into a terminal failure, so the ladder
    retries these WITHOUT charging the breaker (the attempt budget
    still bounds them)."""
    seen: set[int] = set()
    e: BaseException | None = err
    while e is not None and id(e) not in seen:
        if isinstance(e, ConnectionRefusedError):
            return True
        seen.add(id(e))
        e = e.__cause__ if e.__cause__ is not None else e.__context__
    return False


def _breaker_gauges() -> dict:
    """Registry source: per-peer breaker state, visible to snapshots as
    shuffle.breaker.<host:port>.{failures,open} gauges."""
    with _BREAKERS_LOCK:
        breakers = list(_BREAKERS.values())
    out = {}
    for b in breakers:
        p = _peer_label(b.peer)
        out[f"{p}.failures"] = b.failures
        out[f"{p}.open"] = int(b.is_open)
    return out


get_registry().register_source("shuffle.breaker", _breaker_gauges)


def _settings(conf) -> dict:
    return conf.settings if conf is not None else {}


def remote_partition_sizes_with_retry(address, shuffle_id: "int | str",
                                      conf=None, timeout: float | None = None,
                                      max_retries: int | None = None,
                                      retry_wait: float | None = None,
                                      backoff: float | None = None,
                                      faults=None,
                                      lifecycle=None) -> tuple[dict, dict]:
    """Metadata plane with the same retry ladder + circuit breaker as
    the data plane."""
    s = _settings(conf)
    sock_timeout = _sock_timeout(s)
    max_retries = TCP_MAX_RETRIES.get(s) if max_retries is None \
        else int(max_retries)
    retry_wait = TCP_RETRY_WAIT.get(s) if retry_wait is None \
        else float(retry_wait)
    backoff = TCP_RETRY_BACKOFF.get(s) if backoff is None else float(backoff)
    if timeout is None:
        timeout = TCP_TIMEOUT.get(s)
    threshold = TCP_BREAKER_FAILURES.get(s)
    reset_s = TCP_BREAKER_RESET.get(s)
    peer = tuple(address)
    breaker = _breaker(peer)
    rng = random.Random(f"meta:{peer}:{shuffle_id}")
    attempt = 0
    while True:
        if lifecycle is not None:
            lifecycle.check()
        breaker.before_attempt(reset_s)
        try:
            out = remote_partition_sizes(peer, shuffle_id, timeout=timeout,
                                         sock_timeout=sock_timeout,
                                         faults=faults)
            breaker.record_success()
            return out
        except ShuffleFetchError as e:
            if _is_conn_refused(e):
                get_registry().inc("shuffle.fetch.conn_refused")
            else:
                breaker.record_failure(e, threshold)
            attempt += 1
            if attempt > max_retries:
                raise ShuffleFetchError(
                    f"metadata fetch of shuffle {shuffle_id} from {peer}: "
                    f"giving up after {attempt} attempts: {e}") from e
            _backoff_sleep(retry_wait, backoff, attempt, rng, lifecycle)


def fetch_remote_with_retry(address, shuffle_id: "int | str", part_id: int,
                            lo: int = 0, hi: int | None = None,
                            device: bool = True, conf=None, faults=None,
                            inflight_limit: int | None = None,
                            max_frame: int | None = None,
                            timeout: float | None = None,
                            checksum: bool | None = None,
                            max_retries: int | None = None,
                            retry_wait: float | None = None,
                            backoff: float | None = None,
                            tracer=None, trace: dict | None = None,
                            lifecycle=None, raw: bool = False) -> Iterator:
    """Stream one reduce partition's batches, surviving transport
    failures: on a retryable error, reconnect with exponential backoff
    + jitter and resume at the last fully-delivered batch offset.

    ``trace`` is an optional propagation header (query_id/trace_id/
    span_id) carried in the fetch request so the SERVING side attributes
    its work to the originating query; ``tracer`` records retry events
    locally. Attempt/retry counts land in the process metrics registry
    either way.

    ``lifecycle`` (exec/lifecycle.py QueryLifecycle) makes the ladder
    cancellable: checked before every attempt, and backoff pauses wait
    on the cancel event instead of sleeping — a cancel or deadline
    aborts the ladder mid-pause with the terminal lifecycle error."""
    s = _settings(conf)
    sock_timeout = _sock_timeout(s)
    max_retries = TCP_MAX_RETRIES.get(s) if max_retries is None \
        else int(max_retries)
    retry_wait = TCP_RETRY_WAIT.get(s) if retry_wait is None \
        else float(retry_wait)
    backoff = TCP_RETRY_BACKOFF.get(s) if backoff is None else float(backoff)
    if inflight_limit is None:
        inflight_limit = TCP_INFLIGHT_LIMIT.get(s)
    if max_frame is None:
        max_frame = _max_frame(conf)
    if timeout is None:
        timeout = TCP_TIMEOUT.get(s)
    if checksum is None:
        checksum = TCP_CHECKSUM.get(s)
    threshold = TCP_BREAKER_FAILURES.get(s)
    reset_s = TCP_BREAKER_RESET.get(s)
    peer = tuple(address)
    breaker = _breaker(peer)
    reg = get_registry()
    plabel = _peer_label(peer)
    rng = random.Random(f"fetch:{peer}:{shuffle_id}:{part_id}")
    delivered = 0     # batches fully yielded downstream, across attempts
    failures = 0      # consecutive failed attempts with NO new batches
    t_fetch = time.perf_counter()
    while True:
        if lifecycle is not None:
            lifecycle.check()
        breaker.before_attempt(reset_s)
        reg.inc("shuffle.fetch.attempts")
        reg.inc(f"shuffle.peer.{plabel}.fetch_attempts")
        before = delivered
        try:
            for batch in fetch_remote(peer, shuffle_id, part_id,
                                      lo=lo + delivered, hi=hi,
                                      device=device,
                                      inflight_limit=inflight_limit,
                                      max_frame=max_frame, timeout=timeout,
                                      sock_timeout=sock_timeout,
                                      checksum=checksum, faults=faults,
                                      trace=trace, raw=raw):
                yield batch
                delivered += 1
            breaker.record_success()
            # round-trip covers the whole ladder (retries + backoff
            # included): the latency the CONSUMER saw, not one socket
            reg.observe("shuffle.fetch.round_trip_seconds",
                        time.perf_counter() - t_fetch)
            return
        except ShuffleFetchError as e:
            if getattr(e, "terminal", False):
                # the DATA is gone (MapOutputLostError names which map
                # outputs), not the connection: reconnecting cannot help
                # and must not count against this peer's breaker —
                # surface straight to stage recovery
                if tracer is not None:
                    tracer.event("shuffle.fetch.terminal", "shuffle",
                                 peer=plabel, part=part_id,
                                 delivered=delivered, error=str(e)[:256])
                raise
            if _is_conn_refused(e):
                # startup race (nothing listening yet), not peer illness:
                # retry with backoff inside the attempt budget but do NOT
                # charge the breaker
                reg.inc("shuffle.fetch.conn_refused")
            else:
                breaker.record_failure(e, threshold)
            reg.inc("shuffle.fetch.retries")
            reg.inc(f"shuffle.peer.{plabel}.fetch_failures")
            failures = 1 if delivered > before else failures + 1
            if tracer is not None:
                tracer.event("shuffle.fetch.retry", "shuffle",
                             peer=plabel, part=part_id, attempt=failures,
                             delivered=delivered,
                             resume_at=lo + delivered,
                             error=str(e)[:256])
            if failures > max_retries:
                err = ShuffleFetchError(
                    f"fetch of shuffle {shuffle_id} part {part_id} from "
                    f"{peer}: giving up after {failures} consecutive "
                    f"failed attempts ({delivered} batches delivered, "
                    f"resume offset {lo + delivered}): {e}")
                err.terminal = True
                raise err from e
            _backoff_sleep(retry_wait, backoff, failures, rng, lifecycle)


def _sock_timeout(settings: dict) -> "float | None":
    """Resolve the per-read data-socket timeout for this fetch: the
    dedicated socketTimeout conf, falling back to the overall
    tcp.timeoutSeconds when unset (0)."""
    from spark_rapids_tpu.shuffle.tcp import SOCKET_TIMEOUT
    st = SOCKET_TIMEOUT.get(settings)
    return st if st and st > 0 else None


def _backoff_sleep(base: float, mult: float, attempt: int,
                   rng: random.Random, lifecycle=None) -> None:
    """attempt-th (1-based) backoff: base * mult^(attempt-1), jittered
    to [0.5x, 1.5x) from the caller's deterministically-seeded PRNG.
    With a ``lifecycle``, the pause waits on the cancel event instead
    of sleeping, so cancel/deadline interrupts it immediately."""
    pause = base * (mult ** (attempt - 1)) * (0.5 + rng.random())
    if pause <= 0:
        return
    if lifecycle is not None:
        lifecycle.wait(pause)
    else:
        time.sleep(pause)

"""spark_rapids_tpu: a TPU-native columnar SQL/ETL acceleration framework.

A from-scratch, TPU-first re-design of the capabilities of the RAPIDS
Accelerator for Apache Spark (reference: /root/reference, spark-rapids ~v0.3).
The reference is a Spark plugin that rewrites SQL physical plans so supported
operators run on GPU over cuDF columnar batches (reference
sql-plugin/src/main/scala/com/nvidia/spark/rapids/GpuOverrides.scala).

This framework is standalone: it provides
  * a DataFrame API and logical planner (mini-Catalyst),
  * a CPU columnar engine (Arrow/numpy) that doubles as the differential-test
    oracle (mirrors the reference's CPU-Spark-as-oracle strategy,
    tests/SparkQueryCompareTestSuite.scala:153-167),
  * a plan-rewrite engine (`TpuOverrides`) that tags and replaces CPU physical
    operators with TPU columnar operators, with per-op config keys, explain
    output and automatic host<->device transitions (reference
    GpuOverrides.scala:1991-2010, GpuTransitionOverrides.scala),
  * TPU columnar kernels built on jax/XLA/Pallas over static-shape padded
    batches with validity masks,
  * a spill-tiered buffer catalog (HBM -> host -> disk; reference
    RapidsBufferCatalog.scala) and device-occupancy semaphore,
  * distributed exchange: hash/range/round-robin/single partitioning and a
    mesh-collective shuffle over jax.sharding meshes (ICI all-to-all), plus a
    local transport (reference shuffle-plugin/ UCX transport),
  * Parquet/ORC/CSV scans and writers (Arrow host decode -> HBM),
  * a Python-UDF bytecode compiler to expressions (reference udf-compiler/).
"""

import jax as _jax

# SQL long/double semantics require 64-bit types (Spark LongType/DoubleType);
# must be set before any jax computation.
_jax.config.update("jax_enable_x64", True)

# Pin pyarrow's internal pools to one thread BEFORE any pool use: pyarrow
# compute/alloc on its multi-threaded pool concurrently with jax CPU
# execution segfaults intermittently in this runtime (see
# runtime.pin_arrow_threads).  Import-time is the only point guaranteed
# single-threaded and before first use.
try:
    import pyarrow as _pa
    _pa.set_cpu_count(1)
    _pa.set_io_thread_count(1)
# enginelint: disable=RL001 (pyarrow optional at import time; no query can be running yet)
except Exception:  # pyarrow optional at import time
    pass

from spark_rapids_tpu.version import __version__


def __getattr__(name):
    # lazy: session pulls in the exec/plan layers; keep bare import cheap
    if name in ("TpuSession", "DataFrame"):
        from spark_rapids_tpu import session as _s
        return getattr(_s, name)
    raise AttributeError(name)


__all__ = ["__version__", "TpuSession", "DataFrame"]

"""Cluster driver: worker pool lifecycle, heartbeat liveness, and the
control-plane endpoint workers report into.

The driver owns planning, admission, AQE and broadcast builds exactly
as in single-process mode; this module only adds the pool: N
``local[N]`` worker subprocesses (cluster/worker.py) spawned over
stdin/stdout handshake, an :class:`RpcServer` accepting their
heartbeats (liveness + a metrics-registry snapshot that feeds
per-worker gauges and the bench observability block), and a monitor
thread whose dead-worker verdict — heartbeat silence past
``cluster.heartbeat.timeoutSeconds`` or an exited process — marks the
handle lost so the map-output trackers (cluster/exec.py) route the
worker's slots into lineage recovery.

Fault point ``cluster.worker.hang`` fires in the heartbeat HANDLER:
the worker keeps running but the driver ignores its heartbeats, so the
timeout path is exercised for real rather than simulated.
"""
from __future__ import annotations

import atexit
import json
import os
import signal
import subprocess
import sys
import threading
import time
import weakref
from collections import deque

#: per-query bound on heartbeat-shipped span events buffered while the
#: query is still running, and on how many distinct queries buffer at
#: once (oldest evicted) — a chatty cluster cannot grow the driver
_MAX_BUFFERED_SPANS = 8192
_MAX_SPAN_QUERIES = 16

from spark_rapids_tpu.cluster import (DEATH_PROBE_TIMEOUT, DRAIN_TIMEOUT,
                                      HEARTBEAT_INTERVAL,
                                      HEARTBEAT_TIMEOUT, JOURNAL_DIR,
                                      JOURNAL_ENABLED, JOURNAL_MAX_BYTES,
                                      MAX_WORKERS, MIN_WORKERS,
                                      QUARANTINE_MAX_FAILURES,
                                      QUARANTINE_PROBATION,
                                      RPC_COMPRESSION_CODEC,
                                      WORKER_STARTUP_TIMEOUT,
                                      parse_cluster_mode)
from spark_rapids_tpu.cluster.rpc import (RpcError, RpcServer, rpc_call,
                                          set_caller_epoch)
from spark_rapids_tpu.cluster.worker import MAP_ID_STRIDE, READY_PREFIX
from spark_rapids_tpu.faults import crash_point
from spark_rapids_tpu.obs.registry import get_registry


class WorkerHandle:
    """Driver-side view of one worker subprocess."""

    def __init__(self, worker_id: str, proc: subprocess.Popen):
        self.worker_id = worker_id
        self.proc = proc
        self.pid: int | None = None
        self.rpc_addr: tuple | None = None
        self.shuffle_addr: tuple | None = None
        self.ready = threading.Event()
        self.alive = False
        self.lost_reason: str | None = None
        self.last_heartbeat = 0.0
        #: elastic membership state: a draining worker accepts no new
        #: fragments while its slots migrate; a quarantined worker sat
        #: out too many consecutive dispatch failures but still serves
        #: its map outputs; a retired worker exited via planned removal
        self.draining = False
        self.quarantined_until: float | None = None
        self.failures = 0
        self.retired = False
        self.io_thread: threading.Thread | None = None
        #: last heartbeat's registry snapshot and the first one seen —
        #: their counter diff is the worker's per-run registry delta
        self.metrics: dict = {}
        self.baseline: dict = {}

    @property
    def state(self) -> str:
        """One of retired/lost/draining/quarantined/alive — the
        /healthz and cluster_workers{state=...} vocabulary.  Only
        ``lost`` is an UNPLANNED condition."""
        if self.retired:
            return "retired"
        if not self.alive:
            return "lost"
        if self.draining:
            return "draining"
        if self.quarantined_until is not None:
            return "quarantined"
        return "alive"


class _ReattachedProc:
    """Popen-shaped shim over a worker this driver did NOT spawn (a
    lingering worker re-attached during recovery): liveness via signal
    0, kill via os.kill.  stdin/stdout are None — the recovered driver
    holds no pipe to the process, so driver-loss detection on the
    worker side runs over heartbeats instead of stdin EOF."""

    def __init__(self, pid: int):
        self.pid = int(pid)
        self.returncode: int | None = None
        self.stdin = None
        self.stdout = None

    def poll(self) -> int | None:
        if self.returncode is None:
            try:
                os.kill(self.pid, 0)
            except (ProcessLookupError, PermissionError):
                self.returncode = -9
        return self.returncode

    def wait(self, timeout: float | None = None) -> int:
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        while self.poll() is None:
            if deadline is not None and time.monotonic() >= deadline:
                raise subprocess.TimeoutExpired("reattached-worker",
                                                timeout)
            time.sleep(0.05)
        return self.returncode

    def send_signal(self, sig) -> None:
        try:
            os.kill(self.pid, sig)
        except OSError:
            pass

    def kill(self) -> None:
        self.send_signal(signal.SIGKILL)


class ClusterDriver:
    """Spawns and supervises the ``local[N]`` worker pool for one
    TpuSession (the scheduler/heartbeat half of the reference's driver
    process; map-output bookkeeping lives per-shuffle in
    ClusterMapOutputTracker)."""

    def __init__(self, conf):
        n = parse_cluster_mode(conf)
        if n <= 0:
            raise ValueError("ClusterDriver requires cluster.mode="
                             "local[N] with N >= 1")
        self._init_common(conf)
        self._next_worker = n
        if self.journal is not None:
            self.journal.append("driver_start", epoch=self.epoch)
        try:
            for i in range(n):
                self._spawn(f"w{i}")
            self._await_ready()
        except BaseException:
            self.shutdown()
            raise
        for h in self.workers():
            self._journal_worker_ready(h)
        self._finish_init()
        get_registry().inc("cluster.workers_spawned", n)

    def _init_common(self, conf) -> None:
        """State shared by a fresh __init__ and recover(): everything
        up to (but not including) worker membership."""
        from spark_rapids_tpu.faults import FaultRegistry
        self.conf = conf
        self._faults = FaultRegistry.from_conf(conf)
        s = conf.settings
        self._hb_timeout = HEARTBEAT_TIMEOUT.get(s)
        self._probe_timeout = DEATH_PROBE_TIMEOUT.get(s)
        self._drain_timeout = DRAIN_TIMEOUT.get(s)
        self._min_workers = MIN_WORKERS.get(s)
        self._max_workers = MAX_WORKERS.get(s)
        self._quar_max = QUARANTINE_MAX_FAILURES.get(s)
        self._quar_probation = QUARANTINE_PROBATION.get(s)
        self._lock = threading.Lock()
        self._handles: dict[str, WorkerHandle] = {}
        self._hang_ignored: set[str] = set()
        # live ClusterMapOutputTrackers (one per in-flight cluster
        # shuffle): a graceful drain walks them to migrate the retiring
        # worker's slots; weak so a finished query's tracker vanishes
        self._trackers: "weakref.WeakSet" = weakref.WeakSet()
        # live write-job commit coordinators (exec/write_exec.py): a
        # drain or quarantine fences the worker in each so a straggler
        # attempt finishing after removal cannot steal a task commit
        self._write_coordinators: "weakref.WeakSet" = weakref.WeakSet()
        # query_id -> worker span events shipped on heartbeats, held
        # until the dispatching stage drains them into ITS tracer
        self._span_lock = threading.Lock()
        self._pending_spans: "dict[str, deque]" = {}
        self._closed = threading.Event()
        self._io_threads: list[threading.Thread] = []
        #: cluster epoch: bumped on every recovery, folded into RPC
        #: caller identity and journaled so stale-attempt fencing stays
        #: correct across a restart
        self.epoch = 1
        #: reconciled-but-unclaimed shuffles from a recovery (sid ->
        #: claimable record); always empty on a fresh driver, so
        #: claim_resume() is an unconditional no-op there
        self._recovered: dict = {}
        #: /healthz driver-recovery block; None on a fresh driver
        self.recovery_info: dict | None = None
        self.journal = None
        self._journal_tmp: str | None = None
        self.rpc = RpcServer(
            {"heartbeat": self._h_heartbeat},
            codec_name=RPC_COMPRESSION_CODEC.get(conf.settings))
        self._open_journal()
        set_caller_epoch(self.epoch)

    def _open_journal(self) -> None:
        """Open the write-ahead cluster journal (lazy import: with the
        journal disabled — or in single-process mode, which never
        builds a driver — cluster/journal.py is never imported)."""
        if not JOURNAL_ENABLED.get(self.conf.settings):
            return
        d = JOURNAL_DIR.get(self.conf.settings)
        if not d:
            import tempfile
            d = tempfile.mkdtemp(prefix="tpu-cluster-journal-")
            # throwaway journal: removed on clean shutdown (recovery
            # across processes needs an explicit journal.dir)
            self._journal_tmp = d
        from spark_rapids_tpu.cluster.journal import ClusterJournal
        self.journal = ClusterJournal(
            d, JOURNAL_MAX_BYTES.get(self.conf.settings),
            faults=self._faults)

    def _finish_init(self) -> None:
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         daemon=True,
                                         name="tpu-cluster-monitor")
        self._monitor.start()
        get_registry().register_source("cluster", self._source)
        atexit.register(self.shutdown)

    def _journal_worker_ready(self, h: WorkerHandle) -> None:
        if self.journal is not None and h.rpc_addr is not None:
            self.journal.append(
                "worker_ready", wid=h.worker_id, pid=h.pid,
                rpc=list(h.rpc_addr), shuffle=list(h.shuffle_addr))

    # -- crash recovery --------------------------------------------------
    @classmethod
    def recover(cls, conf, journal_dir: str | None = None) \
            -> "ClusterDriver":
        """Rebuild a crashed driver from its journal: replay the
        journaled state, bump the cluster epoch, RECONNECT to every
        lingering worker (spawning replacements for the rest),
        reconcile what the workers actually hold against the journaled
        map-output tracker, and roll interrupted write commits forward
        or back.  Queries then resume via :meth:`claim_resume` instead
        of recomputing journaled-complete map outputs."""
        from spark_rapids_tpu.cluster.journal import ClusterJournal
        n = parse_cluster_mode(conf)
        if n <= 0:
            raise ValueError("ClusterDriver.recover requires "
                             "cluster.mode=local[N] with N >= 1")
        d = journal_dir or JOURNAL_DIR.get(conf.settings)
        if not d:
            raise ValueError(
                "ClusterDriver.recover needs a journal directory "
                "(spark.rapids.cluster.journal.dir or journal_dir=)")
        state = ClusterJournal.replay(d)
        self = cls.__new__(cls)
        if journal_dir and not JOURNAL_DIR.get(conf.settings):
            # _open_journal must land on the SAME directory we replayed
            conf = type(conf)({**conf.settings,
                               "spark.rapids.cluster.journal.dir": d})
        self._init_common(conf)
        if self.journal is None:
            self.rpc.close()
            raise ValueError("ClusterDriver.recover requires "
                             "spark.rapids.cluster.journal.enabled=true")
        self.epoch = state.epoch + 1
        set_caller_epoch(self.epoch)
        journaled_idx = [int(w[1:]) for w in state.workers
                        if w[1:].isdigit()]
        self._next_worker = max(journaled_idx + [n - 1]) + 1
        self.journal.append("driver_start", epoch=self.epoch)
        reattached = replaced = 0
        inventories: dict = {}
        try:
            for wid, w in state.workers.items():
                if w.get("status") != "alive" or not w.get("rpc"):
                    continue
                try:
                    reply, _ = rpc_call(
                        tuple(w["rpc"]), "reconnect",
                        {"driver": list(self.rpc.address),
                         "epoch": self.epoch},
                        conf=self.conf, retries=0, timeout=10.0)
                    ok = reply.get("worker_id") == wid
                except (RpcError, ConnectionError, OSError):
                    ok = False
                if not ok:
                    self.journal.append("worker_gone", wid=wid,
                                        reason="reconnect failed")
                    continue
                h = WorkerHandle(wid, _ReattachedProc(int(reply["pid"])))
                h.pid = int(reply["pid"])
                h.rpc_addr = tuple(reply["rpc"])
                h.shuffle_addr = tuple(reply["shuffle"])
                h.alive = True
                h.last_heartbeat = time.monotonic()
                h.ready.set()
                with self._lock:
                    self._handles[wid] = h
                inventories[wid] = reply.get("inventory") or {}
                self._journal_worker_ready(h)
                reattached += 1
                print(f"cluster: worker {wid} re-attached "
                      f"(pid {h.pid})", file=sys.stderr)
            # replacements restore the pool to local[N] strength; they
            # hold none of the journaled outputs, so reconciliation
            # drops anything the journal pinned to the workers they
            # replace
            while len(self._handles) < n:
                with self._lock:
                    wid = f"w{self._next_worker}"
                    self._next_worker += 1
                self._spawn(wid)
                replaced += 1
            self._await_ready()
        except BaseException:
            self.shutdown()
            raise
        for h in self.workers():
            if not isinstance(h.proc, _ReattachedProc):
                self._journal_worker_ready(h)
        dropped = self._reconcile(state, inventories)
        rollfwd, rollback = self._recover_write_jobs(state)
        self.recovery_info = {
            "recovered_at": time.time(), "epoch": self.epoch,
            "workers_reattached": reattached,
            "workers_replaced": replaced,
            "shuffles_recovered": len(self._recovered),
            "entries_dropped": dropped,
            "journal_truncated_records": state.truncated_records,
            "write_rollforward": rollfwd, "write_rollback": rollback}
        self._finish_init()
        reg = get_registry()
        reg.inc("cluster.drivers_recovered")
        reg.inc("cluster.workers_reattached", reattached)
        if replaced:
            reg.inc("cluster.workers_spawned", replaced)
        print(f"cluster: driver recovered at epoch {self.epoch} "
              f"(reattached={reattached} replaced={replaced} "
              f"shuffles={len(self._recovered)} dropped={dropped} "
              f"write_fwd={rollfwd} write_back={rollback})",
              file=sys.stderr)
        return self

    def _reconcile(self, state, inventories: dict) -> int:
        """Cross-check the journaled map-output tracker against what
        the re-attached workers actually hold.  A journaled entry is
        CONFIRMED iff its owner re-attached and still holds a live slot
        at the journaled index with the journaled map id at >= the
        journaled epoch; anything else is dropped with a targeted epoch
        bump — never a full recompute.  A journaled-done child
        partition survives only if every journaled entry of it
        survived.  Returns the dropped-entry count."""
        dropped = 0
        for sid, st in state.shuffles.items():
            entries: dict = {}
            epochs = dict(st["epochs"])
            surviving: set = set()
            invalidated: dict = {}
            for (pid, mid), v in st["entries"].items():
                wid, wslot, size, rows, epoch = v
                rowset = (inventories.get(wid, {}).get(sid, {})
                          .get(str(pid))) or ()
                hit = any(int(r[0]) == wslot and int(r[1]) == mid
                          and int(r[4]) >= epoch for r in rowset)
                if hit:
                    entries.setdefault(wid, []).append(
                        [mid, pid, wslot, size, rows, epoch])
                    surviving.add((pid, mid))
                else:
                    dropped += 1
                    # targeted invalidation: the epoch bump fences any
                    # pre-crash straggler of this map output
                    epochs[mid] = max(epochs.get(mid, 0), epoch) + 1
                    invalidated[mid] = epochs[mid]
            ent_by_cpid: dict = {}
            surv_by_cpid: dict = {}
            for (pid, mid) in st["entries"]:
                ent_by_cpid.setdefault(mid // MAP_ID_STRIDE,
                                       set()).add((pid, mid))
            for (pid, mid) in surviving:
                surv_by_cpid.setdefault(mid // MAP_ID_STRIDE,
                                        set()).add((pid, mid))
            done = {c for c in st["done"]
                    if ent_by_cpid.get(c, set())
                    <= surv_by_cpid.get(c, set())}
            if invalidated and self.journal is not None:
                self.journal.append("map_invalidate", sid=sid,
                                    epochs={str(m): e for m, e
                                            in invalidated.items()})
            self._recovered[sid] = {
                "fp": st["fp"], "num_parts": st["num_parts"],
                "ncpids": st["ncpids"], "conf_fp": st["conf_fp"],
                "entries": entries, "done": done, "epochs": epochs}
        if dropped:
            get_registry().inc("cluster.journal.entries_dropped", dropped)
        return dropped

    def _recover_write_jobs(self, state) -> tuple:
        """Resolve write jobs the crash interrupted: a job whose full
        rename plan was journaled (write_commit_begin) rolls FORWARD —
        each rename re-executed idempotently, manifest and _SUCCESS
        published, staging removed; a job without one rolls BACK to
        staging (nothing visible was renamed... the plan is journaled
        before the first rename runs).  Never double-commits: a
        journaled write_commit_done means everything already landed."""
        import shutil
        from spark_rapids_tpu.io.writer import MANIFEST_NAME, STAGING_DIR
        rollfwd = rollback = 0
        for job, j in state.write_jobs.items():
            if j["committed"] or j["aborted"]:
                continue
            path = j["path"]
            if not path:
                continue
            staging = os.path.join(path, STAGING_DIR, job)
            if j["commit"] is not None:
                for src, dst in j["commit"]["renames"]:
                    try:
                        if os.path.exists(dst):
                            continue  # this rename already ran pre-crash
                        if os.path.exists(src):
                            os.makedirs(os.path.dirname(dst),
                                        exist_ok=True)
                            os.replace(src, dst)
                    except OSError:
                        pass
                man = j["commit"].get("manifest")
                mpath = os.path.join(path, MANIFEST_NAME)
                if man and not os.path.exists(mpath):
                    tmp = mpath + ".tmp"
                    with open(tmp, "w") as f:
                        json.dump(man, f, indent=1, sort_keys=True)
                    os.replace(tmp, mpath)
                open(os.path.join(path, "_SUCCESS"), "w").close()
                shutil.rmtree(staging, ignore_errors=True)
                try:
                    os.rmdir(os.path.join(path, STAGING_DIR))
                except OSError:
                    pass
                self.journal.append("write_commit_done", job=job)
                get_registry().inc("write.jobs_rolled_forward")
                rollfwd += 1
            else:
                # no rename plan was journaled, so nothing is visible:
                # drop staging, the query re-runs the write cleanly
                shutil.rmtree(staging, ignore_errors=True)
                try:
                    os.rmdir(os.path.join(path, STAGING_DIR))
                except OSError:
                    pass
                self.journal.append("write_abort", job=job)
                get_registry().inc("write.jobs_rolled_back")
                rollback += 1
        return rollfwd, rollback

    def claim_resume(self, fp: str, new_sid, num_parts: int,
                     ncpids: int, conf_fp: str) -> dict | None:
        """Hand a recovered shuffle's surviving state to a resuming
        query: match on the restart-stable fragment fingerprint (+
        shape + conf fingerprint), re-key the held slots on every
        owning worker under the query's fresh shuffle id
        (``alias_shuffle``), and return ``{entries, addrs, done,
        epochs}`` for tracker seeding.  None when nothing matches — a
        fresh driver always returns None."""
        with self._lock:
            sid = next((s for s, r in self._recovered.items()
                        if r["fp"] == fp and r["num_parts"] == num_parts
                        and r["ncpids"] == ncpids
                        and r["conf_fp"] == conf_fp), None)
            if sid is None:
                return None
            rec = self._recovered.pop(sid)
        entries: dict = {}
        addrs: dict = {}
        done = set(rec["done"])
        for wid, ents in rec["entries"].items():
            h = self.worker_by_id(wid)
            ok = h is not None and h.alive
            if ok:
                try:
                    rpc_call(h.rpc_addr, "alias_shuffle",
                             {"old": sid, "new": new_sid},
                             conf=self.conf, retries=0, timeout=10.0)
                except (RpcError, ConnectionError, OSError):
                    ok = False
            if not ok:
                # the holder died between reconcile and claim: its
                # child partitions are no longer complete
                for e in ents:
                    done.discard(e[0] // MAP_ID_STRIDE)
                continue
            entries[wid] = ents
            addrs[wid] = list(h.shuffle_addr)
        get_registry().inc("cluster.shuffles_resumed")
        return {"entries": entries, "addrs": addrs,
                "done": sorted(done), "epochs": rec["epochs"]}

    # -- spawn ----------------------------------------------------------
    def _spawn(self, worker_id: str) -> None:
        proc = subprocess.Popen(
            [sys.executable, "-m", "spark_rapids_tpu.cluster.worker"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            text=True, env=dict(os.environ))
        h = WorkerHandle(worker_id, proc)
        with self._lock:
            self._handles[worker_id] = h
        cfg = {"worker_id": worker_id, "driver": list(self.rpc.address),
               "conf": dict(self.conf.settings)}
        proc.stdin.write(json.dumps(cfg) + "\n")
        proc.stdin.flush()
        t = threading.Thread(target=self._pump_stdout, args=(h,),
                             daemon=True,
                             name=f"tpu-cluster-io-{worker_id}")
        t.start()
        h.io_thread = t
        self._io_threads = [x for x in self._io_threads if x.is_alive()]
        self._io_threads.append(t)

    def _pump_stdout(self, h: WorkerHandle) -> None:
        """Scan for the READY line, then keep draining so the worker
        never blocks on a full pipe; its logging passes through to the
        driver's stderr."""
        try:
            for line in h.proc.stdout:
                if line.startswith(READY_PREFIX):
                    info = json.loads(line[len(READY_PREFIX):])
                    h.pid = info.get("pid")
                    h.rpc_addr = tuple(info["rpc"])
                    h.shuffle_addr = tuple(info["shuffle"])
                    h.alive = True
                    h.last_heartbeat = time.monotonic()
                    h.ready.set()
                else:
                    print(f"[{h.worker_id}] {line.rstrip()}",
                          file=sys.stderr)
        except (ValueError, OSError):
            # teardown closed the pipe out from under the blocking read
            pass

    def _await_ready(self) -> None:
        deadline = time.monotonic() + WORKER_STARTUP_TIMEOUT.get(
            self.conf.settings)
        for h in list(self._handles.values()):
            if not h.ready.wait(max(0.0, deadline - time.monotonic())):
                rc = h.proc.poll()
                raise RuntimeError(
                    f"cluster worker {h.worker_id} did not become ready "
                    f"within spark.rapids.cluster.worker."
                    f"startupTimeoutSeconds "
                    f"(process {'exited rc=%s' % rc if rc is not None else 'still starting'})")

    # -- heartbeats + liveness ------------------------------------------
    def _h_heartbeat(self, payload: dict, blob: bytes):
        wid = payload.get("worker_id", "")
        if self._faults is not None:
            act = self._faults.check("cluster.worker.hang", worker=wid)
            if act is not None:
                self._hang_ignored.add(wid)
        if wid in self._hang_ignored:
            # the worker is "hung" from the driver's point of view: its
            # heartbeats no longer count, and the timeout declares it dead
            return ({"ok": True, "ignored": True}, b"")
        h = self._handles.get(wid)
        if h is not None:
            h.last_heartbeat = time.monotonic()
            snap = payload.get("metrics") or {}
            if not h.baseline:
                h.baseline = snap
            h.metrics = snap
        spans = payload.get("spans")
        if spans:
            self.buffer_spans(spans.get("events") or [])
        self._fold_worker_costs(wid, payload)
        return ({"ok": True}, b"")

    def _fold_worker_costs(self, wid: str, payload: dict) -> None:
        """Fold heartbeat-shipped worker metering deltas / HBM samples
        into the driver's books (obs/profile.py, obs/metering.py).
        Raw-conf gated so a disabled driver never imports the profiler
        modules, whatever a worker ships."""
        metering = payload.get("metering")
        hbm = payload.get("profile_hbm")
        if not metering and not hbm:
            return
        raw = self.conf.settings.get("spark.rapids.obs.profile.enabled")
        if raw is None or str(raw).lower() not in ("true", "1", "yes"):
            return
        try:
            if metering:
                from spark_rapids_tpu.obs.metering import get_meter
                meter = get_meter()
                tenants = metering.get("tenants")
                if tenants:
                    meter.merge_delta({"tenants": tenants})
                # worker totals stay under the per-worker ledger, OUT
                # of this process's conservation cross-check: each
                # process conserves its own books
                totals = metering.get("totals")
                if totals:
                    meter.ingest_worker(wid, totals)
            if hbm:
                from spark_rapids_tpu.obs.profile import ingest_worker_hbm
                ingest_worker_hbm(wid, hbm)
        # enginelint: disable=RL001 (cost folding must never fail a heartbeat)
        except Exception:
            pass

    # -- trace aggregation ----------------------------------------------
    def buffer_spans(self, events: list) -> None:
        """Hold heartbeat-shipped worker span events per query until the
        dispatching stage (cluster/exec.py) drains them into the query's
        driver tracer.  Bounded both per query and across queries."""
        with self._span_lock:
            for ev in events:
                qid = str((ev.get("args") or {}).get("query_id") or "?")
                dq = self._pending_spans.get(qid)
                if dq is None:
                    while len(self._pending_spans) >= _MAX_SPAN_QUERIES:
                        # evict the oldest query's buffer wholesale
                        self._pending_spans.pop(
                            next(iter(self._pending_spans)))
                    dq = self._pending_spans[qid] = \
                        deque(maxlen=_MAX_BUFFERED_SPANS)
                dq.append(ev)

    def drain_query_spans(self, query_id: str) -> list:
        """Pop every buffered worker span for one query (exactly-once:
        the caller ingests them into the driver tracer)."""
        with self._span_lock:
            dq = self._pending_spans.pop(query_id, None)
        return list(dq) if dq else []

    def merged_worker_histograms(self) -> dict:
        """Cluster-wide latency distributions: each worker's histogram
        movement since its first heartbeat, merged across workers (dead
        workers included — their last shipped snapshot still counts)."""
        from spark_rapids_tpu.obs.registry import (
            delta_histogram_snapshot, merge_histogram_snapshots)
        out: dict = {}
        for h in self.workers():
            cur = (h.metrics or {}).get("histograms") or {}
            base = (h.baseline or {}).get("histograms") or {}
            for name, snap in cur.items():
                moved = delta_histogram_snapshot(snap, base.get(name))
                if moved is None:
                    continue
                out[name] = merge_histogram_snapshots(out.get(name), moved)
        return out

    def _monitor_loop(self) -> None:
        interval = min(0.5, HEARTBEAT_INTERVAL.get(self.conf.settings))
        while not self._closed.wait(interval):
            now = time.monotonic()
            for h in self.live_workers():
                if self._closed.is_set():
                    # shutdown started mid-sweep: stop issuing death
                    # verdicts against workers being retired on purpose
                    break
                if h.draining:
                    # planned removal in progress: remove_worker owns
                    # this handle's fate; the death verdict must not
                    # race its shutdown sequence
                    continue
                if h.quarantined_until is not None \
                        and now >= h.quarantined_until:
                    h.quarantined_until = None
                    h.failures = 0
                    get_registry().inc("cluster_workers_readmitted")
                    print(f"cluster: worker {h.worker_id} re-admitted "
                          "after probation", file=sys.stderr)
                if h.proc.poll() is not None:
                    self.mark_worker_lost(
                        h.worker_id,
                        f"process exited rc={h.proc.returncode}")
                elif now - h.last_heartbeat > self._hb_timeout:
                    silence = now - h.last_heartbeat
                    # one direct RPC probe before the verdict: stalled
                    # heartbeats (or a driver that stopped counting
                    # them) on a live control plane is not a death
                    if self._probe_worker(h):
                        h.last_heartbeat = time.monotonic()
                        self._hang_ignored.discard(h.worker_id)
                        continue
                    self.mark_worker_lost(
                        h.worker_id,
                        f"no heartbeat for {silence:.1f}s "
                        "(probe failed)")

    def mark_worker_lost(self, worker_id: str, reason: str) -> None:
        """Idempotently declare one worker dead: SIGKILL whatever is
        left of the process and count the loss.  Map-output trackers
        observe ``alive`` flipping and surface the worker's slots as
        MapOutputLostError on the next fetch."""
        with self._lock:
            if self._closed.is_set():
                # shutdown owns the pool now; a concurrent death
                # verdict here could start output migration against a
                # worker shutdown is already retiring
                return
            h = self._handles.get(worker_id)
            if h is None or not h.alive:
                return
            h.alive = False
            h.lost_reason = reason
        try:
            h.proc.kill()
        except OSError:
            pass
        get_registry().inc("cluster_workers_lost")
        if self.journal is not None:
            self.journal.append("worker_gone", wid=worker_id,
                                reason=reason)
        print(f"cluster: worker {worker_id} lost: {reason}",
              file=sys.stderr)

    def kill_worker(self, worker_id: str) -> None:
        """SIGKILL only — no bookkeeping.  Chaos injection uses this so
        the DETECTION machinery (failed fetch / heartbeat timeout) finds
        the death the same way a real crash surfaces."""
        h = self._handles.get(worker_id)
        if h is not None:
            try:
                h.proc.send_signal(signal.SIGKILL)
            except OSError:
                pass

    # -- elastic membership ----------------------------------------------
    def register_tracker(self, tracker) -> None:
        """Weakly track one live ClusterMapOutputTracker so a graceful
        drain can migrate the retiring worker's slots; finished queries'
        trackers vanish on their own."""
        self._trackers.add(tracker)

    def register_write_coordinator(self, coordinator) -> None:
        """Weakly track one write job's commit coordinator so planned
        drains and quarantine verdicts can fence the affected worker's
        future manifest registrations (abort-on-drain for in-flight
        write attempts); committed/aborted jobs vanish on their own."""
        self._write_coordinators.add(coordinator)

    def _fence_write_coordinators(self, worker_id: str) -> None:
        for coord in list(self._write_coordinators):
            coord.fence_worker(worker_id)

    def add_worker(self) -> str:
        """Spawn one new worker into the live pool and wait for its
        READY handshake.  The next dispatch round's worker snapshot —
        and therefore the next query — picks it up without a restart."""
        with self._lock:
            if self._closed.is_set():
                raise RuntimeError("cluster driver is shut down")
            live = [h for h in self._handles.values()
                    if h.alive and not h.draining]
            if self._max_workers and len(live) >= self._max_workers:
                raise RuntimeError(
                    f"cannot add a worker: spark.rapids.cluster."
                    f"maxWorkers={self._max_workers} already live")
            wid = f"w{self._next_worker}"
            self._next_worker += 1
        self._spawn(wid)
        h = self._handles[wid]
        if not h.ready.wait(WORKER_STARTUP_TIMEOUT.get(self.conf.settings)):
            rc = h.proc.poll()
            try:
                h.proc.kill()
            except OSError:
                pass
            with self._lock:
                self._handles.pop(wid, None)
            raise RuntimeError(
                f"added worker {wid} did not become ready "
                f"(process {'exited rc=%s' % rc if rc is not None else 'still starting'})")
        reg = get_registry()
        reg.inc("cluster_workers_added")
        reg.inc("cluster.workers_spawned")
        self._journal_worker_ready(h)
        print(f"cluster: worker {wid} added", file=sys.stderr)
        return wid

    def remove_worker(self, worker_id: str, drain: bool = True) -> dict:
        """Planned scale-down of one worker.  With ``drain=True`` the
        worker first stops accepting fragments, then its live map
        outputs stream to survivors over the shuffle plane (tracker
        entries rewritten under an epoch bump) — the removal costs a
        copy, not a recompute.  Whatever cannot migrate (drain=False,
        no survivor, or an injected ``cluster.migrate.drop``) is marked
        lost so readers fall into lineage recovery.  Returns
        ``{"migrated": n, "dropped": n}``."""
        with self._lock:
            if self._closed.is_set():
                raise RuntimeError("cluster driver is shut down")
            h = self._handles.get(worker_id)
            if h is None:
                raise KeyError(f"unknown worker {worker_id!r}")
            if h.retired:
                return {"migrated": 0, "dropped": 0}
            rest = [w for w in self._handles.values()
                    if w.alive and not w.draining
                    and w.worker_id != worker_id]
            if h.alive and len(rest) < self._min_workers:
                raise RuntimeError(
                    f"cannot remove {worker_id}: spark.rapids.cluster."
                    f"minWorkers={self._min_workers} would be violated")
            h.draining = True
        # a draining worker's in-flight write attempts must not win a
        # task commit after the worker is gone — fence it out of every
        # live commit coordinator before touching map outputs
        self._fence_write_coordinators(worker_id)
        crash_point(self._faults, "drain", worker=worker_id)
        stats = {"migrated": 0, "dropped": 0}
        if drain and h.alive:
            deadline = time.monotonic() + self._drain_timeout
            while time.monotonic() < deadline:
                try:
                    reply, _ = rpc_call(h.rpc_addr, "drain",
                                        conf=self.conf, retries=0,
                                        timeout=2.0)
                except (RpcError, ConnectionError, OSError):
                    break
                if not reply.get("active"):
                    break
                time.sleep(0.05)
            # the dispatching thread registers a fragment's slots just
            # AFTER the worker's RPC returns — give in-flight
            # registrations a beat to land before snapshotting what
            # must move (anything that still slips through is swept
            # into lineage below)
            time.sleep(0.2)
            for tracker in list(self._trackers):
                if getattr(tracker, "_closed", False):
                    continue
                m, d = self._migrate_worker_outputs(tracker, h)
                stats["migrated"] += m
                stats["dropped"] += d
        # leftover sweep: anything still registered on the retiring
        # worker (not drained, migration dropped/failed, or a race)
        # goes through the standard lineage recovery path
        for tracker in list(self._trackers):
            if not getattr(tracker, "_closed", False):
                tracker.mark_worker_lost(worker_id)
        if h.alive:
            try:
                rpc_call(h.rpc_addr, "shutdown", conf=self.conf,
                         retries=0, timeout=2.0)
            except (RpcError, ConnectionError, OSError):
                pass
        try:
            h.proc.wait(timeout=5.0)
        except subprocess.TimeoutExpired:
            h.proc.kill()
            try:
                h.proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                pass
        with self._lock:
            h.alive = False
            h.retired = True
            h.lost_reason = "drained" if drain else "removed"
        if h.io_thread is not None:
            h.io_thread.join(timeout=5.0)
        for stream in (h.proc.stdin, h.proc.stdout):
            if stream is not None:
                try:
                    stream.close()
                except OSError:
                    pass
        get_registry().inc("cluster_workers_drained" if drain
                           else "cluster_workers_removed")
        if self.journal is not None:
            self.journal.append(
                "worker_gone", wid=worker_id,
                reason="drained" if drain else "removed")
        print(f"cluster: worker {worker_id} "
              f"{'drained' if drain else 'removed'} "
              f"(migrated={stats['migrated']} dropped={stats['dropped']})",
              file=sys.stderr)
        return stats

    def _migrate_worker_outputs(self, tracker, h) -> tuple:
        """Move one tracker's slots off a draining worker: the tracker
        plans contiguous fetch runs under an epoch bump
        (begin_migration), survivors pull the raw frames over the
        shuffle plane (``migrate_slots`` RPC), and each successful copy
        re-registers at the new epoch — source stragglers and late
        duplicates are epoch-stale.  A run that fails stays owned by
        the retiring worker and the caller's sweep routes it into
        lineage."""
        runs, dropped = tracker.begin_migration(h.worker_id,
                                                faults=self._faults)
        if not runs:
            return 0, dropped
        targets = [w for w in self.schedulable_workers()
                   if w.worker_id != h.worker_id]
        if not targets:
            return 0, dropped
        migrated = 0
        for i, run in enumerate(runs):
            target = targets[i % len(targets)]
            try:
                reply, _ = rpc_call(target.rpc_addr, "migrate_slots",
                                    {"shuffle_id": tracker.shuffle_id,
                                     "source": list(h.shuffle_addr),
                                     "runs": [run]},
                                    conf=self.conf,
                                    timeout=self._drain_timeout)
            except (RpcError, ConnectionError, OSError):
                continue
            if reply.get("error_kind"):
                continue
            tracker.register(target.worker_id, reply["shuffle"],
                             reply["entries"])
            migrated += len(reply["entries"])
        if migrated:
            get_registry().inc("map_outputs_migrated", migrated)
        return migrated, dropped

    # -- failure verdicts -------------------------------------------------
    def _ping(self, h: WorkerHandle,
              timeout: float | None = None) -> bool:
        """One direct control-plane round-trip; True iff the worker's
        RPC server answered."""
        if h.rpc_addr is None:
            return False
        try:
            reply, _ = rpc_call(h.rpc_addr, "ping", conf=self.conf,
                                retries=0,
                                timeout=timeout or self._probe_timeout)
        except (RpcError, ConnectionError, OSError):
            return False
        return reply.get("worker_id") == h.worker_id

    def _probe_worker(self, h: WorkerHandle) -> bool:
        """Probe-before-death: one bounded RPC ping before a
        heartbeat-timeout verdict.  A worker whose heartbeats stalled
        (or were ignored) but whose RPC plane answers is NOT dead."""
        get_registry().inc("cluster_death_probes")
        if self._ping(h):
            get_registry().inc("cluster_death_probe_saves")
            return True
        return False

    def record_worker_failure(self, worker_id: str, reason: str) -> str:
        """Dispatch-failure verdict for one worker.  With quarantine
        disabled (the default) the worker is declared lost exactly as
        before.  With ``quarantine.maxFailures`` > 0 the worker
        accumulates strikes: a probe first separates a dead process
        (lost) from a flaky one, and past the threshold the worker is
        QUARANTINED — no new fragments, but its registered map outputs
        stay servable — until probation re-admits it.  Returns the
        verdict: ``lost`` | ``quarantined`` | ``tolerated``."""
        if self._closed.is_set():
            return "tolerated"
        h = self._handles.get(worker_id)
        if h is None:
            return "lost"
        if self._quar_max <= 0:
            self.mark_worker_lost(worker_id, reason)
            return "lost"
        if not self._ping(h):
            self.mark_worker_lost(worker_id, f"{reason} (probe failed)")
            return "lost"
        h.failures += 1
        if h.failures >= self._quar_max and h.quarantined_until is None:
            h.quarantined_until = time.monotonic() + self._quar_probation
            get_registry().inc("cluster_workers_quarantined")
            self._fence_write_coordinators(worker_id)
            print(f"cluster: worker {worker_id} quarantined after "
                  f"{h.failures} consecutive failures: {reason}",
                  file=sys.stderr)
            return "quarantined"
        return "tolerated"

    def note_worker_success(self, worker_id: str) -> None:
        """A fragment completed on the worker: reset its consecutive-
        failure strike count (quarantine counts CONSECUTIVE failures)."""
        h = self._handles.get(worker_id)
        if h is not None:
            h.failures = 0

    # -- views ----------------------------------------------------------
    def workers(self) -> list[WorkerHandle]:
        with self._lock:
            return list(self._handles.values())

    def live_workers(self) -> list[WorkerHandle]:
        with self._lock:
            return [h for h in self._handles.values() if h.alive]

    def schedulable_workers(self) -> list[WorkerHandle]:
        """Workers eligible for NEW fragments: alive, not draining, not
        quarantined.  If quarantine would empty the pool the
        quarantined workers stay schedulable — availability beats
        purity (matching speculative execution's blacklist override)."""
        with self._lock:
            live = [h for h in self._handles.values()
                    if h.alive and not h.draining]
        ok = [h for h in live if h.quarantined_until is None]
        return ok or live

    def drain_candidate(self) -> str | None:
        """The worker a scale-down should retire: the NEWEST
        schedulable one (last joined, so the least map output to
        migrate and the least warm compile cache to throw away), or
        None when retiring anyone would drop the pool below
        minWorkers.  The control plane's fleet rule calls this so
        scale-down policy lives with the membership ledger, not in the
        controller."""
        with self._lock:
            live = [h for h in self._handles.values()
                    if h.alive and not h.draining]
            if len(live) <= self._min_workers:
                return None

            def join_order(h):
                # worker ids are "w<N>" with N monotonically assigned
                wid = h.worker_id
                return int(wid[1:]) if wid[1:].isdigit() else -1

            return max(live, key=join_order).worker_id

    def worker_by_id(self, worker_id: str) -> WorkerHandle | None:
        return self._handles.get(worker_id)

    def worker_by_pid(self, pid: int) -> WorkerHandle | None:
        with self._lock:
            for h in self._handles.values():
                if h.pid == pid:
                    return h
        return None

    def worker_by_shuffle_addr(self, addr) -> WorkerHandle | None:
        addr = tuple(addr)
        with self._lock:
            for h in self._handles.values():
                if h.shuffle_addr == addr:
                    return h
        return None

    # -- observability ---------------------------------------------------
    @staticmethod
    def _flat(snap: dict) -> dict:
        # a worker snapshot is {"counters", "gauges"}; object sources
        # (WorkerRuntime.metrics among them) surface as gauges
        return {**(snap.get("counters") or {}),
                **(snap.get("gauges") or {})}

    def _source(self) -> dict:
        out = {"workers_live": float(len(self.live_workers()))}
        states: dict[str, int] = {}
        for h in self.workers():
            states[h.state] = states.get(h.state, 0) + 1
            for k, v in self._flat(h.metrics).items():
                if k.startswith(("cluster", "shuffle", "faults")):
                    out[f"worker.{h.worker_id}.{k}"] = float(v)
        # cluster_workers{state=...} gauge family (obs/registry.py
        # _LABELED rewrites cluster.workers.state.* into labels)
        for st in ("alive", "draining", "quarantined", "lost", "retired"):
            out[f"workers.state.{st}"] = float(states.get(st, 0))
        return out

    def worker_registry_deltas(self) -> dict:
        """Per-worker counter deltas since each worker's first
        heartbeat — the bench harness folds these into the
        tpch_cluster_scaling observability block."""
        out: dict = {}
        for h in self.workers():
            base = self._flat(h.baseline)
            cur = self._flat(h.metrics)
            d = {k: v - base.get(k, 0) for k, v in cur.items()
                 if v - base.get(k, 0)}
            out[h.worker_id] = {"alive": h.alive, "counters": d}
        return out

    # -- teardown --------------------------------------------------------
    def shutdown(self, timeout: float = 10.0) -> None:
        """Drain the pool: polite shutdown RPCs, a bounded wait, then
        SIGKILL stragglers.  Leaves zero orphan worker processes; safe
        to call more than once (atexit safety net)."""
        if self._closed.is_set():
            return
        self._closed.set()
        for h in self.live_workers():
            try:
                rpc_call(h.rpc_addr, "shutdown", conf=self.conf,
                         retries=0, timeout=2.0)
            except (ConnectionError, OSError):
                pass
        deadline = time.monotonic() + timeout
        for h in self.workers():
            left = max(0.1, deadline - time.monotonic())
            try:
                h.proc.wait(timeout=left)
            except subprocess.TimeoutExpired:
                h.proc.kill()
                try:
                    h.proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    pass
            h.alive = False
            for stream in (h.proc.stdin, h.proc.stdout):
                if stream is not None:
                    try:
                        stream.close()
                    except OSError:
                        pass
        self.rpc.close()
        if getattr(self, "journal", None) is not None:
            self.journal.close()
            self.journal = None
        if getattr(self, "_journal_tmp", None):
            # implicit (mkdtemp) journals die with a clean shutdown —
            # there is nothing to recover; explicit journal.dir stays
            import shutil
            shutil.rmtree(self._journal_tmp, ignore_errors=True)
            self._journal_tmp = None
        get_registry().unregister_source("cluster")
        atexit.unregister(self.shutdown)

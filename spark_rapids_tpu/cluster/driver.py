"""Cluster driver: worker pool lifecycle, heartbeat liveness, and the
control-plane endpoint workers report into.

The driver owns planning, admission, AQE and broadcast builds exactly
as in single-process mode; this module only adds the pool: N
``local[N]`` worker subprocesses (cluster/worker.py) spawned over
stdin/stdout handshake, an :class:`RpcServer` accepting their
heartbeats (liveness + a metrics-registry snapshot that feeds
per-worker gauges and the bench observability block), and a monitor
thread whose dead-worker verdict — heartbeat silence past
``cluster.heartbeat.timeoutSeconds`` or an exited process — marks the
handle lost so the map-output trackers (cluster/exec.py) route the
worker's slots into lineage recovery.

Fault point ``cluster.worker.hang`` fires in the heartbeat HANDLER:
the worker keeps running but the driver ignores its heartbeats, so the
timeout path is exercised for real rather than simulated.
"""
from __future__ import annotations

import atexit
import json
import os
import signal
import subprocess
import sys
import threading
import time
import weakref
from collections import deque

#: per-query bound on heartbeat-shipped span events buffered while the
#: query is still running, and on how many distinct queries buffer at
#: once (oldest evicted) — a chatty cluster cannot grow the driver
_MAX_BUFFERED_SPANS = 8192
_MAX_SPAN_QUERIES = 16

from spark_rapids_tpu.cluster import (DEATH_PROBE_TIMEOUT, DRAIN_TIMEOUT,
                                      HEARTBEAT_INTERVAL,
                                      HEARTBEAT_TIMEOUT, MAX_WORKERS,
                                      MIN_WORKERS,
                                      QUARANTINE_MAX_FAILURES,
                                      QUARANTINE_PROBATION,
                                      RPC_COMPRESSION_CODEC,
                                      WORKER_STARTUP_TIMEOUT,
                                      parse_cluster_mode)
from spark_rapids_tpu.cluster.rpc import RpcError, RpcServer, rpc_call
from spark_rapids_tpu.cluster.worker import READY_PREFIX
from spark_rapids_tpu.obs.registry import get_registry


class WorkerHandle:
    """Driver-side view of one worker subprocess."""

    def __init__(self, worker_id: str, proc: subprocess.Popen):
        self.worker_id = worker_id
        self.proc = proc
        self.pid: int | None = None
        self.rpc_addr: tuple | None = None
        self.shuffle_addr: tuple | None = None
        self.ready = threading.Event()
        self.alive = False
        self.lost_reason: str | None = None
        self.last_heartbeat = 0.0
        #: elastic membership state: a draining worker accepts no new
        #: fragments while its slots migrate; a quarantined worker sat
        #: out too many consecutive dispatch failures but still serves
        #: its map outputs; a retired worker exited via planned removal
        self.draining = False
        self.quarantined_until: float | None = None
        self.failures = 0
        self.retired = False
        self.io_thread: threading.Thread | None = None
        #: last heartbeat's registry snapshot and the first one seen —
        #: their counter diff is the worker's per-run registry delta
        self.metrics: dict = {}
        self.baseline: dict = {}

    @property
    def state(self) -> str:
        """One of retired/lost/draining/quarantined/alive — the
        /healthz and cluster_workers{state=...} vocabulary.  Only
        ``lost`` is an UNPLANNED condition."""
        if self.retired:
            return "retired"
        if not self.alive:
            return "lost"
        if self.draining:
            return "draining"
        if self.quarantined_until is not None:
            return "quarantined"
        return "alive"


class ClusterDriver:
    """Spawns and supervises the ``local[N]`` worker pool for one
    TpuSession (the scheduler/heartbeat half of the reference's driver
    process; map-output bookkeeping lives per-shuffle in
    ClusterMapOutputTracker)."""

    def __init__(self, conf):
        from spark_rapids_tpu.faults import FaultRegistry
        self.conf = conf
        n = parse_cluster_mode(conf)
        if n <= 0:
            raise ValueError("ClusterDriver requires cluster.mode="
                             "local[N] with N >= 1")
        self._faults = FaultRegistry.from_conf(conf)
        s = conf.settings
        self._hb_timeout = HEARTBEAT_TIMEOUT.get(s)
        self._probe_timeout = DEATH_PROBE_TIMEOUT.get(s)
        self._drain_timeout = DRAIN_TIMEOUT.get(s)
        self._min_workers = MIN_WORKERS.get(s)
        self._max_workers = MAX_WORKERS.get(s)
        self._quar_max = QUARANTINE_MAX_FAILURES.get(s)
        self._quar_probation = QUARANTINE_PROBATION.get(s)
        self._lock = threading.Lock()
        self._handles: dict[str, WorkerHandle] = {}
        self._hang_ignored: set[str] = set()
        self._next_worker = n
        # live ClusterMapOutputTrackers (one per in-flight cluster
        # shuffle): a graceful drain walks them to migrate the retiring
        # worker's slots; weak so a finished query's tracker vanishes
        self._trackers: "weakref.WeakSet" = weakref.WeakSet()
        # live write-job commit coordinators (exec/write_exec.py): a
        # drain or quarantine fences the worker in each so a straggler
        # attempt finishing after removal cannot steal a task commit
        self._write_coordinators: "weakref.WeakSet" = weakref.WeakSet()
        # query_id -> worker span events shipped on heartbeats, held
        # until the dispatching stage drains them into ITS tracer
        self._span_lock = threading.Lock()
        self._pending_spans: "dict[str, deque]" = {}
        self._closed = threading.Event()
        self._io_threads: list[threading.Thread] = []
        self.rpc = RpcServer(
            {"heartbeat": self._h_heartbeat},
            codec_name=RPC_COMPRESSION_CODEC.get(conf.settings))
        try:
            for i in range(n):
                self._spawn(f"w{i}")
            self._await_ready()
        except BaseException:
            self.shutdown()
            raise
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         daemon=True,
                                         name="tpu-cluster-monitor")
        self._monitor.start()
        get_registry().register_source("cluster", self._source)
        get_registry().inc("cluster.workers_spawned", n)
        atexit.register(self.shutdown)

    # -- spawn ----------------------------------------------------------
    def _spawn(self, worker_id: str) -> None:
        proc = subprocess.Popen(
            [sys.executable, "-m", "spark_rapids_tpu.cluster.worker"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            text=True, env=dict(os.environ))
        h = WorkerHandle(worker_id, proc)
        with self._lock:
            self._handles[worker_id] = h
        cfg = {"worker_id": worker_id, "driver": list(self.rpc.address),
               "conf": dict(self.conf.settings)}
        proc.stdin.write(json.dumps(cfg) + "\n")
        proc.stdin.flush()
        t = threading.Thread(target=self._pump_stdout, args=(h,),
                             daemon=True,
                             name=f"tpu-cluster-io-{worker_id}")
        t.start()
        h.io_thread = t
        self._io_threads = [x for x in self._io_threads if x.is_alive()]
        self._io_threads.append(t)

    def _pump_stdout(self, h: WorkerHandle) -> None:
        """Scan for the READY line, then keep draining so the worker
        never blocks on a full pipe; its logging passes through to the
        driver's stderr."""
        try:
            for line in h.proc.stdout:
                if line.startswith(READY_PREFIX):
                    info = json.loads(line[len(READY_PREFIX):])
                    h.pid = info.get("pid")
                    h.rpc_addr = tuple(info["rpc"])
                    h.shuffle_addr = tuple(info["shuffle"])
                    h.alive = True
                    h.last_heartbeat = time.monotonic()
                    h.ready.set()
                else:
                    print(f"[{h.worker_id}] {line.rstrip()}",
                          file=sys.stderr)
        except (ValueError, OSError):
            # teardown closed the pipe out from under the blocking read
            pass

    def _await_ready(self) -> None:
        deadline = time.monotonic() + WORKER_STARTUP_TIMEOUT.get(
            self.conf.settings)
        for h in list(self._handles.values()):
            if not h.ready.wait(max(0.0, deadline - time.monotonic())):
                rc = h.proc.poll()
                raise RuntimeError(
                    f"cluster worker {h.worker_id} did not become ready "
                    f"within spark.rapids.cluster.worker."
                    f"startupTimeoutSeconds "
                    f"(process {'exited rc=%s' % rc if rc is not None else 'still starting'})")

    # -- heartbeats + liveness ------------------------------------------
    def _h_heartbeat(self, payload: dict, blob: bytes):
        wid = payload.get("worker_id", "")
        if self._faults is not None:
            act = self._faults.check("cluster.worker.hang", worker=wid)
            if act is not None:
                self._hang_ignored.add(wid)
        if wid in self._hang_ignored:
            # the worker is "hung" from the driver's point of view: its
            # heartbeats no longer count, and the timeout declares it dead
            return ({"ok": True, "ignored": True}, b"")
        h = self._handles.get(wid)
        if h is not None:
            h.last_heartbeat = time.monotonic()
            snap = payload.get("metrics") or {}
            if not h.baseline:
                h.baseline = snap
            h.metrics = snap
        spans = payload.get("spans")
        if spans:
            self.buffer_spans(spans.get("events") or [])
        self._fold_worker_costs(wid, payload)
        return ({"ok": True}, b"")

    def _fold_worker_costs(self, wid: str, payload: dict) -> None:
        """Fold heartbeat-shipped worker metering deltas / HBM samples
        into the driver's books (obs/profile.py, obs/metering.py).
        Raw-conf gated so a disabled driver never imports the profiler
        modules, whatever a worker ships."""
        metering = payload.get("metering")
        hbm = payload.get("profile_hbm")
        if not metering and not hbm:
            return
        raw = self.conf.settings.get("spark.rapids.obs.profile.enabled")
        if raw is None or str(raw).lower() not in ("true", "1", "yes"):
            return
        try:
            if metering:
                from spark_rapids_tpu.obs.metering import get_meter
                meter = get_meter()
                tenants = metering.get("tenants")
                if tenants:
                    meter.merge_delta({"tenants": tenants})
                # worker totals stay under the per-worker ledger, OUT
                # of this process's conservation cross-check: each
                # process conserves its own books
                totals = metering.get("totals")
                if totals:
                    meter.ingest_worker(wid, totals)
            if hbm:
                from spark_rapids_tpu.obs.profile import ingest_worker_hbm
                ingest_worker_hbm(wid, hbm)
        # enginelint: disable=RL001 (cost folding must never fail a heartbeat)
        except Exception:
            pass

    # -- trace aggregation ----------------------------------------------
    def buffer_spans(self, events: list) -> None:
        """Hold heartbeat-shipped worker span events per query until the
        dispatching stage (cluster/exec.py) drains them into the query's
        driver tracer.  Bounded both per query and across queries."""
        with self._span_lock:
            for ev in events:
                qid = str((ev.get("args") or {}).get("query_id") or "?")
                dq = self._pending_spans.get(qid)
                if dq is None:
                    while len(self._pending_spans) >= _MAX_SPAN_QUERIES:
                        # evict the oldest query's buffer wholesale
                        self._pending_spans.pop(
                            next(iter(self._pending_spans)))
                    dq = self._pending_spans[qid] = \
                        deque(maxlen=_MAX_BUFFERED_SPANS)
                dq.append(ev)

    def drain_query_spans(self, query_id: str) -> list:
        """Pop every buffered worker span for one query (exactly-once:
        the caller ingests them into the driver tracer)."""
        with self._span_lock:
            dq = self._pending_spans.pop(query_id, None)
        return list(dq) if dq else []

    def merged_worker_histograms(self) -> dict:
        """Cluster-wide latency distributions: each worker's histogram
        movement since its first heartbeat, merged across workers (dead
        workers included — their last shipped snapshot still counts)."""
        from spark_rapids_tpu.obs.registry import (
            delta_histogram_snapshot, merge_histogram_snapshots)
        out: dict = {}
        for h in self.workers():
            cur = (h.metrics or {}).get("histograms") or {}
            base = (h.baseline or {}).get("histograms") or {}
            for name, snap in cur.items():
                moved = delta_histogram_snapshot(snap, base.get(name))
                if moved is None:
                    continue
                out[name] = merge_histogram_snapshots(out.get(name), moved)
        return out

    def _monitor_loop(self) -> None:
        interval = min(0.5, HEARTBEAT_INTERVAL.get(self.conf.settings))
        while not self._closed.wait(interval):
            now = time.monotonic()
            for h in self.live_workers():
                if h.draining:
                    # planned removal in progress: remove_worker owns
                    # this handle's fate; the death verdict must not
                    # race its shutdown sequence
                    continue
                if h.quarantined_until is not None \
                        and now >= h.quarantined_until:
                    h.quarantined_until = None
                    h.failures = 0
                    get_registry().inc("cluster_workers_readmitted")
                    print(f"cluster: worker {h.worker_id} re-admitted "
                          "after probation", file=sys.stderr)
                if h.proc.poll() is not None:
                    self.mark_worker_lost(
                        h.worker_id,
                        f"process exited rc={h.proc.returncode}")
                elif now - h.last_heartbeat > self._hb_timeout:
                    silence = now - h.last_heartbeat
                    # one direct RPC probe before the verdict: stalled
                    # heartbeats (or a driver that stopped counting
                    # them) on a live control plane is not a death
                    if self._probe_worker(h):
                        h.last_heartbeat = time.monotonic()
                        self._hang_ignored.discard(h.worker_id)
                        continue
                    self.mark_worker_lost(
                        h.worker_id,
                        f"no heartbeat for {silence:.1f}s "
                        "(probe failed)")

    def mark_worker_lost(self, worker_id: str, reason: str) -> None:
        """Idempotently declare one worker dead: SIGKILL whatever is
        left of the process and count the loss.  Map-output trackers
        observe ``alive`` flipping and surface the worker's slots as
        MapOutputLostError on the next fetch."""
        with self._lock:
            h = self._handles.get(worker_id)
            if h is None or not h.alive:
                return
            h.alive = False
            h.lost_reason = reason
        try:
            h.proc.kill()
        except OSError:
            pass
        get_registry().inc("cluster_workers_lost")
        print(f"cluster: worker {worker_id} lost: {reason}",
              file=sys.stderr)

    def kill_worker(self, worker_id: str) -> None:
        """SIGKILL only — no bookkeeping.  Chaos injection uses this so
        the DETECTION machinery (failed fetch / heartbeat timeout) finds
        the death the same way a real crash surfaces."""
        h = self._handles.get(worker_id)
        if h is not None:
            try:
                h.proc.send_signal(signal.SIGKILL)
            except OSError:
                pass

    # -- elastic membership ----------------------------------------------
    def register_tracker(self, tracker) -> None:
        """Weakly track one live ClusterMapOutputTracker so a graceful
        drain can migrate the retiring worker's slots; finished queries'
        trackers vanish on their own."""
        self._trackers.add(tracker)

    def register_write_coordinator(self, coordinator) -> None:
        """Weakly track one write job's commit coordinator so planned
        drains and quarantine verdicts can fence the affected worker's
        future manifest registrations (abort-on-drain for in-flight
        write attempts); committed/aborted jobs vanish on their own."""
        self._write_coordinators.add(coordinator)

    def _fence_write_coordinators(self, worker_id: str) -> None:
        for coord in list(self._write_coordinators):
            coord.fence_worker(worker_id)

    def add_worker(self) -> str:
        """Spawn one new worker into the live pool and wait for its
        READY handshake.  The next dispatch round's worker snapshot —
        and therefore the next query — picks it up without a restart."""
        with self._lock:
            if self._closed.is_set():
                raise RuntimeError("cluster driver is shut down")
            live = [h for h in self._handles.values()
                    if h.alive and not h.draining]
            if self._max_workers and len(live) >= self._max_workers:
                raise RuntimeError(
                    f"cannot add a worker: spark.rapids.cluster."
                    f"maxWorkers={self._max_workers} already live")
            wid = f"w{self._next_worker}"
            self._next_worker += 1
        self._spawn(wid)
        h = self._handles[wid]
        if not h.ready.wait(WORKER_STARTUP_TIMEOUT.get(self.conf.settings)):
            rc = h.proc.poll()
            try:
                h.proc.kill()
            except OSError:
                pass
            with self._lock:
                self._handles.pop(wid, None)
            raise RuntimeError(
                f"added worker {wid} did not become ready "
                f"(process {'exited rc=%s' % rc if rc is not None else 'still starting'})")
        reg = get_registry()
        reg.inc("cluster_workers_added")
        reg.inc("cluster.workers_spawned")
        print(f"cluster: worker {wid} added", file=sys.stderr)
        return wid

    def remove_worker(self, worker_id: str, drain: bool = True) -> dict:
        """Planned scale-down of one worker.  With ``drain=True`` the
        worker first stops accepting fragments, then its live map
        outputs stream to survivors over the shuffle plane (tracker
        entries rewritten under an epoch bump) — the removal costs a
        copy, not a recompute.  Whatever cannot migrate (drain=False,
        no survivor, or an injected ``cluster.migrate.drop``) is marked
        lost so readers fall into lineage recovery.  Returns
        ``{"migrated": n, "dropped": n}``."""
        with self._lock:
            h = self._handles.get(worker_id)
            if h is None:
                raise KeyError(f"unknown worker {worker_id!r}")
            if h.retired:
                return {"migrated": 0, "dropped": 0}
            rest = [w for w in self._handles.values()
                    if w.alive and not w.draining
                    and w.worker_id != worker_id]
            if h.alive and len(rest) < self._min_workers:
                raise RuntimeError(
                    f"cannot remove {worker_id}: spark.rapids.cluster."
                    f"minWorkers={self._min_workers} would be violated")
            h.draining = True
        # a draining worker's in-flight write attempts must not win a
        # task commit after the worker is gone — fence it out of every
        # live commit coordinator before touching map outputs
        self._fence_write_coordinators(worker_id)
        stats = {"migrated": 0, "dropped": 0}
        if drain and h.alive:
            deadline = time.monotonic() + self._drain_timeout
            while time.monotonic() < deadline:
                try:
                    reply, _ = rpc_call(h.rpc_addr, "drain",
                                        conf=self.conf, retries=0,
                                        timeout=2.0)
                except (RpcError, ConnectionError, OSError):
                    break
                if not reply.get("active"):
                    break
                time.sleep(0.05)
            # the dispatching thread registers a fragment's slots just
            # AFTER the worker's RPC returns — give in-flight
            # registrations a beat to land before snapshotting what
            # must move (anything that still slips through is swept
            # into lineage below)
            time.sleep(0.2)
            for tracker in list(self._trackers):
                if getattr(tracker, "_closed", False):
                    continue
                m, d = self._migrate_worker_outputs(tracker, h)
                stats["migrated"] += m
                stats["dropped"] += d
        # leftover sweep: anything still registered on the retiring
        # worker (not drained, migration dropped/failed, or a race)
        # goes through the standard lineage recovery path
        for tracker in list(self._trackers):
            if not getattr(tracker, "_closed", False):
                tracker.mark_worker_lost(worker_id)
        if h.alive:
            try:
                rpc_call(h.rpc_addr, "shutdown", conf=self.conf,
                         retries=0, timeout=2.0)
            except (RpcError, ConnectionError, OSError):
                pass
        try:
            h.proc.wait(timeout=5.0)
        except subprocess.TimeoutExpired:
            h.proc.kill()
            try:
                h.proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                pass
        with self._lock:
            h.alive = False
            h.retired = True
            h.lost_reason = "drained" if drain else "removed"
        if h.io_thread is not None:
            h.io_thread.join(timeout=5.0)
        for stream in (h.proc.stdin, h.proc.stdout):
            if stream is not None:
                try:
                    stream.close()
                except OSError:
                    pass
        get_registry().inc("cluster_workers_drained" if drain
                           else "cluster_workers_removed")
        print(f"cluster: worker {worker_id} "
              f"{'drained' if drain else 'removed'} "
              f"(migrated={stats['migrated']} dropped={stats['dropped']})",
              file=sys.stderr)
        return stats

    def _migrate_worker_outputs(self, tracker, h) -> tuple:
        """Move one tracker's slots off a draining worker: the tracker
        plans contiguous fetch runs under an epoch bump
        (begin_migration), survivors pull the raw frames over the
        shuffle plane (``migrate_slots`` RPC), and each successful copy
        re-registers at the new epoch — source stragglers and late
        duplicates are epoch-stale.  A run that fails stays owned by
        the retiring worker and the caller's sweep routes it into
        lineage."""
        runs, dropped = tracker.begin_migration(h.worker_id,
                                                faults=self._faults)
        if not runs:
            return 0, dropped
        targets = [w for w in self.schedulable_workers()
                   if w.worker_id != h.worker_id]
        if not targets:
            return 0, dropped
        migrated = 0
        for i, run in enumerate(runs):
            target = targets[i % len(targets)]
            try:
                reply, _ = rpc_call(target.rpc_addr, "migrate_slots",
                                    {"shuffle_id": tracker.shuffle_id,
                                     "source": list(h.shuffle_addr),
                                     "runs": [run]},
                                    conf=self.conf,
                                    timeout=self._drain_timeout)
            except (RpcError, ConnectionError, OSError):
                continue
            if reply.get("error_kind"):
                continue
            tracker.register(target.worker_id, reply["shuffle"],
                             reply["entries"])
            migrated += len(reply["entries"])
        if migrated:
            get_registry().inc("map_outputs_migrated", migrated)
        return migrated, dropped

    # -- failure verdicts -------------------------------------------------
    def _ping(self, h: WorkerHandle,
              timeout: float | None = None) -> bool:
        """One direct control-plane round-trip; True iff the worker's
        RPC server answered."""
        if h.rpc_addr is None:
            return False
        try:
            reply, _ = rpc_call(h.rpc_addr, "ping", conf=self.conf,
                                retries=0,
                                timeout=timeout or self._probe_timeout)
        except (RpcError, ConnectionError, OSError):
            return False
        return reply.get("worker_id") == h.worker_id

    def _probe_worker(self, h: WorkerHandle) -> bool:
        """Probe-before-death: one bounded RPC ping before a
        heartbeat-timeout verdict.  A worker whose heartbeats stalled
        (or were ignored) but whose RPC plane answers is NOT dead."""
        get_registry().inc("cluster_death_probes")
        if self._ping(h):
            get_registry().inc("cluster_death_probe_saves")
            return True
        return False

    def record_worker_failure(self, worker_id: str, reason: str) -> str:
        """Dispatch-failure verdict for one worker.  With quarantine
        disabled (the default) the worker is declared lost exactly as
        before.  With ``quarantine.maxFailures`` > 0 the worker
        accumulates strikes: a probe first separates a dead process
        (lost) from a flaky one, and past the threshold the worker is
        QUARANTINED — no new fragments, but its registered map outputs
        stay servable — until probation re-admits it.  Returns the
        verdict: ``lost`` | ``quarantined`` | ``tolerated``."""
        h = self._handles.get(worker_id)
        if h is None:
            return "lost"
        if self._quar_max <= 0:
            self.mark_worker_lost(worker_id, reason)
            return "lost"
        if not self._ping(h):
            self.mark_worker_lost(worker_id, f"{reason} (probe failed)")
            return "lost"
        h.failures += 1
        if h.failures >= self._quar_max and h.quarantined_until is None:
            h.quarantined_until = time.monotonic() + self._quar_probation
            get_registry().inc("cluster_workers_quarantined")
            self._fence_write_coordinators(worker_id)
            print(f"cluster: worker {worker_id} quarantined after "
                  f"{h.failures} consecutive failures: {reason}",
                  file=sys.stderr)
            return "quarantined"
        return "tolerated"

    def note_worker_success(self, worker_id: str) -> None:
        """A fragment completed on the worker: reset its consecutive-
        failure strike count (quarantine counts CONSECUTIVE failures)."""
        h = self._handles.get(worker_id)
        if h is not None:
            h.failures = 0

    # -- views ----------------------------------------------------------
    def workers(self) -> list[WorkerHandle]:
        with self._lock:
            return list(self._handles.values())

    def live_workers(self) -> list[WorkerHandle]:
        with self._lock:
            return [h for h in self._handles.values() if h.alive]

    def schedulable_workers(self) -> list[WorkerHandle]:
        """Workers eligible for NEW fragments: alive, not draining, not
        quarantined.  If quarantine would empty the pool the
        quarantined workers stay schedulable — availability beats
        purity (matching speculative execution's blacklist override)."""
        with self._lock:
            live = [h for h in self._handles.values()
                    if h.alive and not h.draining]
        ok = [h for h in live if h.quarantined_until is None]
        return ok or live

    def drain_candidate(self) -> str | None:
        """The worker a scale-down should retire: the NEWEST
        schedulable one (last joined, so the least map output to
        migrate and the least warm compile cache to throw away), or
        None when retiring anyone would drop the pool below
        minWorkers.  The control plane's fleet rule calls this so
        scale-down policy lives with the membership ledger, not in the
        controller."""
        with self._lock:
            live = [h for h in self._handles.values()
                    if h.alive and not h.draining]
            if len(live) <= self._min_workers:
                return None

            def join_order(h):
                # worker ids are "w<N>" with N monotonically assigned
                wid = h.worker_id
                return int(wid[1:]) if wid[1:].isdigit() else -1

            return max(live, key=join_order).worker_id

    def worker_by_id(self, worker_id: str) -> WorkerHandle | None:
        return self._handles.get(worker_id)

    def worker_by_pid(self, pid: int) -> WorkerHandle | None:
        with self._lock:
            for h in self._handles.values():
                if h.pid == pid:
                    return h
        return None

    def worker_by_shuffle_addr(self, addr) -> WorkerHandle | None:
        addr = tuple(addr)
        with self._lock:
            for h in self._handles.values():
                if h.shuffle_addr == addr:
                    return h
        return None

    # -- observability ---------------------------------------------------
    @staticmethod
    def _flat(snap: dict) -> dict:
        # a worker snapshot is {"counters", "gauges"}; object sources
        # (WorkerRuntime.metrics among them) surface as gauges
        return {**(snap.get("counters") or {}),
                **(snap.get("gauges") or {})}

    def _source(self) -> dict:
        out = {"workers_live": float(len(self.live_workers()))}
        states: dict[str, int] = {}
        for h in self.workers():
            states[h.state] = states.get(h.state, 0) + 1
            for k, v in self._flat(h.metrics).items():
                if k.startswith(("cluster", "shuffle", "faults")):
                    out[f"worker.{h.worker_id}.{k}"] = float(v)
        # cluster_workers{state=...} gauge family (obs/registry.py
        # _LABELED rewrites cluster.workers.state.* into labels)
        for st in ("alive", "draining", "quarantined", "lost", "retired"):
            out[f"workers.state.{st}"] = float(states.get(st, 0))
        return out

    def worker_registry_deltas(self) -> dict:
        """Per-worker counter deltas since each worker's first
        heartbeat — the bench harness folds these into the
        tpch_cluster_scaling observability block."""
        out: dict = {}
        for h in self.workers():
            base = self._flat(h.baseline)
            cur = self._flat(h.metrics)
            d = {k: v - base.get(k, 0) for k, v in cur.items()
                 if v - base.get(k, 0)}
            out[h.worker_id] = {"alive": h.alive, "counters": d}
        return out

    # -- teardown --------------------------------------------------------
    def shutdown(self, timeout: float = 10.0) -> None:
        """Drain the pool: polite shutdown RPCs, a bounded wait, then
        SIGKILL stragglers.  Leaves zero orphan worker processes; safe
        to call more than once (atexit safety net)."""
        if self._closed.is_set():
            return
        self._closed.set()
        for h in self.live_workers():
            try:
                rpc_call(h.rpc_addr, "shutdown", conf=self.conf,
                         retries=0, timeout=2.0)
            except (ConnectionError, OSError):
                pass
        deadline = time.monotonic() + timeout
        for h in self.workers():
            left = max(0.1, deadline - time.monotonic())
            try:
                h.proc.wait(timeout=left)
            except subprocess.TimeoutExpired:
                h.proc.kill()
                try:
                    h.proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    pass
            h.alive = False
            for stream in (h.proc.stdin, h.proc.stdout):
                if stream is not None:
                    try:
                        stream.close()
                    except OSError:
                        pass
        self.rpc.close()
        get_registry().unregister_source("cluster")
        atexit.unregister(self.shutdown)

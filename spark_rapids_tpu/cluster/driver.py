"""Cluster driver: worker pool lifecycle, heartbeat liveness, and the
control-plane endpoint workers report into.

The driver owns planning, admission, AQE and broadcast builds exactly
as in single-process mode; this module only adds the pool: N
``local[N]`` worker subprocesses (cluster/worker.py) spawned over
stdin/stdout handshake, an :class:`RpcServer` accepting their
heartbeats (liveness + a metrics-registry snapshot that feeds
per-worker gauges and the bench observability block), and a monitor
thread whose dead-worker verdict — heartbeat silence past
``cluster.heartbeat.timeoutSeconds`` or an exited process — marks the
handle lost so the map-output trackers (cluster/exec.py) route the
worker's slots into lineage recovery.

Fault point ``cluster.worker.hang`` fires in the heartbeat HANDLER:
the worker keeps running but the driver ignores its heartbeats, so the
timeout path is exercised for real rather than simulated.
"""
from __future__ import annotations

import atexit
import json
import os
import signal
import subprocess
import sys
import threading
import time
from collections import deque

#: per-query bound on heartbeat-shipped span events buffered while the
#: query is still running, and on how many distinct queries buffer at
#: once (oldest evicted) — a chatty cluster cannot grow the driver
_MAX_BUFFERED_SPANS = 8192
_MAX_SPAN_QUERIES = 16

from spark_rapids_tpu.cluster import (HEARTBEAT_INTERVAL,
                                      HEARTBEAT_TIMEOUT,
                                      RPC_COMPRESSION_CODEC,
                                      WORKER_STARTUP_TIMEOUT,
                                      parse_cluster_mode)
from spark_rapids_tpu.cluster.rpc import RpcServer, rpc_call
from spark_rapids_tpu.cluster.worker import READY_PREFIX
from spark_rapids_tpu.obs.registry import get_registry


class WorkerHandle:
    """Driver-side view of one worker subprocess."""

    def __init__(self, worker_id: str, proc: subprocess.Popen):
        self.worker_id = worker_id
        self.proc = proc
        self.pid: int | None = None
        self.rpc_addr: tuple | None = None
        self.shuffle_addr: tuple | None = None
        self.ready = threading.Event()
        self.alive = False
        self.lost_reason: str | None = None
        self.last_heartbeat = 0.0
        #: last heartbeat's registry snapshot and the first one seen —
        #: their counter diff is the worker's per-run registry delta
        self.metrics: dict = {}
        self.baseline: dict = {}


class ClusterDriver:
    """Spawns and supervises the ``local[N]`` worker pool for one
    TpuSession (the scheduler/heartbeat half of the reference's driver
    process; map-output bookkeeping lives per-shuffle in
    ClusterMapOutputTracker)."""

    def __init__(self, conf):
        from spark_rapids_tpu.faults import FaultRegistry
        self.conf = conf
        n = parse_cluster_mode(conf)
        if n <= 0:
            raise ValueError("ClusterDriver requires cluster.mode="
                             "local[N] with N >= 1")
        self._faults = FaultRegistry.from_conf(conf)
        self._hb_timeout = HEARTBEAT_TIMEOUT.get(conf.settings)
        self._lock = threading.Lock()
        self._handles: dict[str, WorkerHandle] = {}
        self._hang_ignored: set[str] = set()
        # query_id -> worker span events shipped on heartbeats, held
        # until the dispatching stage drains them into ITS tracer
        self._span_lock = threading.Lock()
        self._pending_spans: "dict[str, deque]" = {}
        self._closed = threading.Event()
        self._io_threads: list[threading.Thread] = []
        self.rpc = RpcServer(
            {"heartbeat": self._h_heartbeat},
            codec_name=RPC_COMPRESSION_CODEC.get(conf.settings))
        try:
            for i in range(n):
                self._spawn(f"w{i}")
            self._await_ready()
        except BaseException:
            self.shutdown()
            raise
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         daemon=True,
                                         name="tpu-cluster-monitor")
        self._monitor.start()
        get_registry().register_source("cluster", self._source)
        get_registry().inc("cluster.workers_spawned", n)
        atexit.register(self.shutdown)

    # -- spawn ----------------------------------------------------------
    def _spawn(self, worker_id: str) -> None:
        proc = subprocess.Popen(
            [sys.executable, "-m", "spark_rapids_tpu.cluster.worker"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            text=True, env=dict(os.environ))
        h = WorkerHandle(worker_id, proc)
        with self._lock:
            self._handles[worker_id] = h
        cfg = {"worker_id": worker_id, "driver": list(self.rpc.address),
               "conf": dict(self.conf.settings)}
        proc.stdin.write(json.dumps(cfg) + "\n")
        proc.stdin.flush()
        t = threading.Thread(target=self._pump_stdout, args=(h,),
                             daemon=True,
                             name=f"tpu-cluster-io-{worker_id}")
        t.start()
        self._io_threads.append(t)

    def _pump_stdout(self, h: WorkerHandle) -> None:
        """Scan for the READY line, then keep draining so the worker
        never blocks on a full pipe; its logging passes through to the
        driver's stderr."""
        for line in h.proc.stdout:
            if line.startswith(READY_PREFIX):
                info = json.loads(line[len(READY_PREFIX):])
                h.pid = info.get("pid")
                h.rpc_addr = tuple(info["rpc"])
                h.shuffle_addr = tuple(info["shuffle"])
                h.alive = True
                h.last_heartbeat = time.monotonic()
                h.ready.set()
            else:
                print(f"[{h.worker_id}] {line.rstrip()}",
                      file=sys.stderr)

    def _await_ready(self) -> None:
        deadline = time.monotonic() + WORKER_STARTUP_TIMEOUT.get(
            self.conf.settings)
        for h in list(self._handles.values()):
            if not h.ready.wait(max(0.0, deadline - time.monotonic())):
                rc = h.proc.poll()
                raise RuntimeError(
                    f"cluster worker {h.worker_id} did not become ready "
                    f"within spark.rapids.cluster.worker."
                    f"startupTimeoutSeconds "
                    f"(process {'exited rc=%s' % rc if rc is not None else 'still starting'})")

    # -- heartbeats + liveness ------------------------------------------
    def _h_heartbeat(self, payload: dict, blob: bytes):
        wid = payload.get("worker_id", "")
        if self._faults is not None:
            act = self._faults.check("cluster.worker.hang", worker=wid)
            if act is not None:
                self._hang_ignored.add(wid)
        if wid in self._hang_ignored:
            # the worker is "hung" from the driver's point of view: its
            # heartbeats no longer count, and the timeout declares it dead
            return ({"ok": True, "ignored": True}, b"")
        h = self._handles.get(wid)
        if h is not None:
            h.last_heartbeat = time.monotonic()
            snap = payload.get("metrics") or {}
            if not h.baseline:
                h.baseline = snap
            h.metrics = snap
        spans = payload.get("spans")
        if spans:
            self.buffer_spans(spans.get("events") or [])
        return ({"ok": True}, b"")

    # -- trace aggregation ----------------------------------------------
    def buffer_spans(self, events: list) -> None:
        """Hold heartbeat-shipped worker span events per query until the
        dispatching stage (cluster/exec.py) drains them into the query's
        driver tracer.  Bounded both per query and across queries."""
        with self._span_lock:
            for ev in events:
                qid = str((ev.get("args") or {}).get("query_id") or "?")
                dq = self._pending_spans.get(qid)
                if dq is None:
                    while len(self._pending_spans) >= _MAX_SPAN_QUERIES:
                        # evict the oldest query's buffer wholesale
                        self._pending_spans.pop(
                            next(iter(self._pending_spans)))
                    dq = self._pending_spans[qid] = \
                        deque(maxlen=_MAX_BUFFERED_SPANS)
                dq.append(ev)

    def drain_query_spans(self, query_id: str) -> list:
        """Pop every buffered worker span for one query (exactly-once:
        the caller ingests them into the driver tracer)."""
        with self._span_lock:
            dq = self._pending_spans.pop(query_id, None)
        return list(dq) if dq else []

    def merged_worker_histograms(self) -> dict:
        """Cluster-wide latency distributions: each worker's histogram
        movement since its first heartbeat, merged across workers (dead
        workers included — their last shipped snapshot still counts)."""
        from spark_rapids_tpu.obs.registry import (
            delta_histogram_snapshot, merge_histogram_snapshots)
        out: dict = {}
        for h in self.workers():
            cur = (h.metrics or {}).get("histograms") or {}
            base = (h.baseline or {}).get("histograms") or {}
            for name, snap in cur.items():
                moved = delta_histogram_snapshot(snap, base.get(name))
                if moved is None:
                    continue
                out[name] = merge_histogram_snapshots(out.get(name), moved)
        return out

    def _monitor_loop(self) -> None:
        interval = min(0.5, HEARTBEAT_INTERVAL.get(self.conf.settings))
        while not self._closed.wait(interval):
            now = time.monotonic()
            for h in self.live_workers():
                if h.proc.poll() is not None:
                    self.mark_worker_lost(
                        h.worker_id,
                        f"process exited rc={h.proc.returncode}")
                elif now - h.last_heartbeat > self._hb_timeout:
                    self.mark_worker_lost(
                        h.worker_id,
                        f"no heartbeat for {now - h.last_heartbeat:.1f}s")

    def mark_worker_lost(self, worker_id: str, reason: str) -> None:
        """Idempotently declare one worker dead: SIGKILL whatever is
        left of the process and count the loss.  Map-output trackers
        observe ``alive`` flipping and surface the worker's slots as
        MapOutputLostError on the next fetch."""
        with self._lock:
            h = self._handles.get(worker_id)
            if h is None or not h.alive:
                return
            h.alive = False
            h.lost_reason = reason
        try:
            h.proc.kill()
        except OSError:
            pass
        get_registry().inc("cluster_workers_lost")
        print(f"cluster: worker {worker_id} lost: {reason}",
              file=sys.stderr)

    def kill_worker(self, worker_id: str) -> None:
        """SIGKILL only — no bookkeeping.  Chaos injection uses this so
        the DETECTION machinery (failed fetch / heartbeat timeout) finds
        the death the same way a real crash surfaces."""
        h = self._handles.get(worker_id)
        if h is not None:
            try:
                h.proc.send_signal(signal.SIGKILL)
            except OSError:
                pass

    # -- views ----------------------------------------------------------
    def workers(self) -> list[WorkerHandle]:
        with self._lock:
            return list(self._handles.values())

    def live_workers(self) -> list[WorkerHandle]:
        with self._lock:
            return [h for h in self._handles.values() if h.alive]

    def worker_by_id(self, worker_id: str) -> WorkerHandle | None:
        return self._handles.get(worker_id)

    def worker_by_pid(self, pid: int) -> WorkerHandle | None:
        with self._lock:
            for h in self._handles.values():
                if h.pid == pid:
                    return h
        return None

    def worker_by_shuffle_addr(self, addr) -> WorkerHandle | None:
        addr = tuple(addr)
        with self._lock:
            for h in self._handles.values():
                if h.shuffle_addr == addr:
                    return h
        return None

    # -- observability ---------------------------------------------------
    @staticmethod
    def _flat(snap: dict) -> dict:
        # a worker snapshot is {"counters", "gauges"}; object sources
        # (WorkerRuntime.metrics among them) surface as gauges
        return {**(snap.get("counters") or {}),
                **(snap.get("gauges") or {})}

    def _source(self) -> dict:
        out = {"workers_live": float(len(self.live_workers()))}
        for h in self.workers():
            for k, v in self._flat(h.metrics).items():
                if k.startswith(("cluster", "shuffle", "faults")):
                    out[f"worker.{h.worker_id}.{k}"] = float(v)
        return out

    def worker_registry_deltas(self) -> dict:
        """Per-worker counter deltas since each worker's first
        heartbeat — the bench harness folds these into the
        tpch_cluster_scaling observability block."""
        out: dict = {}
        for h in self.workers():
            base = self._flat(h.baseline)
            cur = self._flat(h.metrics)
            d = {k: v - base.get(k, 0) for k, v in cur.items()
                 if v - base.get(k, 0)}
            out[h.worker_id] = {"alive": h.alive, "counters": d}
        return out

    # -- teardown --------------------------------------------------------
    def shutdown(self, timeout: float = 10.0) -> None:
        """Drain the pool: polite shutdown RPCs, a bounded wait, then
        SIGKILL stragglers.  Leaves zero orphan worker processes; safe
        to call more than once (atexit safety net)."""
        if self._closed.is_set():
            return
        self._closed.set()
        for h in self.live_workers():
            try:
                rpc_call(h.rpc_addr, "shutdown", conf=self.conf,
                         retries=0, timeout=2.0)
            except (ConnectionError, OSError):
                pass
        deadline = time.monotonic() + timeout
        for h in self.workers():
            left = max(0.1, deadline - time.monotonic())
            try:
                h.proc.wait(timeout=left)
            except subprocess.TimeoutExpired:
                h.proc.kill()
                try:
                    h.proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    pass
            h.alive = False
            if h.proc.stdin is not None:
                try:
                    h.proc.stdin.close()
                except OSError:
                    pass
        self.rpc.close()
        get_registry().unregister_source("cluster")
        atexit.unregister(self.shutdown)

"""Cluster worker process: ``python -m spark_rapids_tpu.cluster.worker``.

One worker = one long-lived process hosting

- a persistent :class:`LocalShuffleTransport` (``ctx=None`` so map
  outputs live as serialized bytes, never entangled with any query's
  spill catalog) — the worker-local shard of the DCN shuffle plane,
- the existing :class:`TcpShuffleServer` serving those outputs to the
  driver and to peer workers (shuffle/tcp.py — the same data plane,
  codec + checksum negotiation included, that single-process remote
  reads use),
- an :class:`RpcServer` control plane (cluster/rpc.py) accepting plan
  fragments from the driver.

Protocol with the driver (cluster/driver.py): the driver writes one
JSON config line on stdin ``{worker_id, driver: [host, port], conf}``;
the worker binds its servers and prints one READY line on stdout, then
heartbeats liveness + a metrics-registry snapshot to the driver until
told to shut down.  The reference splits these roles the same way:
Spark executors host RapidsShuffleServer for their locally-cached map
output and answer the driver's scheduler over the RPC env.

A ``run_fragment`` call carries a pickled clone of one
ShuffleExchangeExec whose child subtree reads upstream cluster
shuffles through WorkerShuffleReaderExec leaves (cluster/exec.py).
The worker executes the assigned child partitions and writes the
partitioned pieces into its local store under composite map ids
``cpid * MAP_ID_STRIDE + batch_index`` — integers, because
MapOutputLostError round-trips map ids through JSON as ints — then
returns per-slot registrations for the driver's map-output tracker.

The conf shipped to workers is scrubbed of ``cluster.mode`` (a worker
must never recursively spawn a cluster) and ``test.faults`` (fault
injection is driven from the driver so a plan fires exactly once per
cluster, not once per process).
"""
from __future__ import annotations

import json
import os
import pickle
import sys
import threading

#: composite map-id encoding: map_id = cpid * stride + map batch index.
#: One child partition producing >= a million batches would collide;
#: batch coalescing keeps real counts orders of magnitude below this.
MAP_ID_STRIDE = 1_000_000

#: stdout marker the driver scans for; everything else on the worker's
#: stdout/stderr is passthrough logging
READY_PREFIX = "CLUSTER_WORKER_READY "

_SCRUBBED_KEYS = ("spark.rapids.cluster.mode", "spark.rapids.test.faults",
                  # workers ship spans back over RPC instead of exporting
                  # their own files — the driver's single export IS the
                  # cluster trace (obs/trace.py stamp_for_shipping)
                  "spark.rapids.obs.trace.dir")

#: per-RPC-message span shipping bound (newest win): heartbeats and
#: fragment replies stay small even under span storms
_MAX_SHIP_EVENTS = 2000


def scrub_worker_conf(settings: dict) -> dict:
    out = dict(settings)
    for k in _SCRUBBED_KEYS:
        out.pop(k, None)
    return out


class WorkerRuntime:
    """Everything one worker process owns; also constructible in-process
    for tests (the premerge gate spot-checks fragment execution without
    paying subprocess startup)."""

    def __init__(self, worker_id: str, driver_addr=None,
                 settings: dict | None = None):
        from spark_rapids_tpu.cluster import (HEARTBEAT_INTERVAL,
                                              RPC_COMPRESSION_CODEC,
                                              RPC_TIMEOUT)
        from spark_rapids_tpu.cluster.rpc import RpcServer
        from spark_rapids_tpu.conf import TpuConf
        from spark_rapids_tpu.shuffle.local import LocalShuffleTransport
        from spark_rapids_tpu.shuffle.tcp import TcpShuffleServer
        self.worker_id = worker_id
        self.driver = tuple(driver_addr) if driver_addr else None
        self.conf = TpuConf(scrub_worker_conf(settings or {}))
        self._hb_interval = HEARTBEAT_INTERVAL.get(self.conf.settings)
        self.store = LocalShuffleTransport(self.conf, ctx=None)
        self.shuffle_server = TcpShuffleServer(self.store)
        self._stop = threading.Event()
        self._runtime_ready = False
        self._runtime_lock = threading.Lock()
        # graceful drain: once draining, new fragments are rejected with
        # a structured reply (the driver re-pools them on survivors) and
        # the driver polls _active down to zero before migrating slots
        self._draining = False
        self._active_lock = threading.Lock()
        self._active_fragments = 0
        # driver-loss linger: after stdin EOF (driver gone) the worker
        # can keep its RPC + shuffle servers alive for a grace window so
        # a recovered driver re-attaches; dispatch is paused meanwhile
        self._linger_lock = threading.Lock()
        self._lingering = False
        self._linger_timer: threading.Timer | None = None
        self._reattach_epoch = 0
        self.metrics = {"fragments_run": 0, "fragment_failures": 0,
                        "map_batches_written": 0,
                        "fragments_rejected_draining": 0,
                        "map_outputs_imported": 0,
                        "write_fragments_run": 0,
                        "write_tasks_staged": 0,
                        "write_fragment_failures": 0,
                        "linger_entered": 0, "linger_expired": 0,
                        "driver_reattached": 0, "shuffles_aliased": 0}
        # tracers of fragments currently executing: the heartbeat drains
        # them mid-run so a long map stage streams spans to the driver
        # instead of batching them all on completion
        self._tracer_lock = threading.Lock()
        self._live_tracers: list = []
        # heartbeat snapshots carry the process registry; folding this
        # runtime in gives the driver per-worker fragment counters
        from spark_rapids_tpu.obs.registry import get_registry
        get_registry().register_object_source("cluster.worker", self)
        self.rpc = RpcServer(
            {"ping": self._h_ping,
             "run_fragment": self._h_run_fragment,
             "run_write_fragment": self._h_run_write_fragment,
             "release_shuffle": self._h_release_shuffle,
             "drain": self._h_drain,
             "migrate_slots": self._h_migrate_slots,
             "reconnect": self._h_reconnect,
             "alias_shuffle": self._h_alias_shuffle,
             "shutdown": self._h_shutdown},
            timeout=RPC_TIMEOUT.get(self.conf.settings),
            codec_name=RPC_COMPRESSION_CODEC.get(self.conf.settings))
        self._hb_thread: threading.Thread | None = None

    # -- handlers -------------------------------------------------------
    def _h_ping(self, payload: dict, blob: bytes):
        return ({"worker_id": self.worker_id, "pid": os.getpid()}, b"")

    def _h_release_shuffle(self, payload: dict, blob: bytes):
        freed = self.store.release_shuffle(payload["shuffle_id"])
        return ({"freed": freed}, b"")

    def _h_shutdown(self, payload: dict, blob: bytes):
        self._stop.set()
        return ({"ok": True}, b"")

    def _h_drain(self, payload: dict, blob: bytes):
        """Enter (or poll) draining: stop accepting fragments and report
        how many are still executing.  Idempotent — the driver calls it
        repeatedly until ``active`` reaches zero."""
        self._draining = True
        with self._active_lock:
            active = self._active_fragments
        return ({"ok": True, "draining": True, "active": active}, b"")

    def _h_migrate_slots(self, payload: dict, blob: bytes):
        """Adopt a retiring peer's map-output slots: pull each run's
        serialized frames over the shuffle plane and import them into
        the local store under the driver-bumped epochs, then return the
        same per-slot registration records a fragment reply carries so
        the driver's tracker re-points atomically."""
        from spark_rapids_tpu.shuffle.errors import ShuffleFetchError
        from spark_rapids_tpu.shuffle.retry import fetch_remote_with_retry
        sid = payload["shuffle_id"]
        source = tuple(payload["source"])
        imported: set[int] = set()
        pids: set[int] = set()
        try:
            for run in payload["runs"]:
                pid = int(run["pid"])
                mids = [int(m) for m in run["map_ids"]]
                rows = [int(r) for r in run["rows"]]
                epochs = [int(e) for e in run["epochs"]]
                frames = list(fetch_remote_with_retry(
                    source, sid, pid, lo=int(run["lo"]),
                    hi=int(run["hi"]), device=False, conf=self.conf,
                    raw=True))
                if len(frames) != len(mids):
                    return ({"error_kind": "migrate_fetch",
                             "error": f"migration run for shuffle {sid} "
                                      f"part {pid} returned "
                                      f"{len(frames)} frames, expected "
                                      f"{len(mids)}"}, b"")
                for mid, r, ep, raw in zip(mids, rows, epochs, frames):
                    self.store.import_serialized(sid, mid, pid, raw,
                                                 rows=r, epoch=ep)
                    imported.add(mid)
                    pids.add(pid)
                    self.metrics["map_outputs_imported"] += 1
        except ShuffleFetchError as e:
            return ({"error_kind": "migrate_fetch", "error": str(e)}, b"")
        entries = []
        for pid in sorted(pids):
            for wslot, (mid, size, rows, ep) in enumerate(
                    self.store.slots_for(sid, pid)):
                if mid in imported:
                    entries.append([mid, pid, wslot, size, rows, ep])
        return ({"ok": True, "entries": entries,
                 "shuffle": list(self.shuffle_server.address),
                 "imported": len(imported)}, b"")

    # -- driver-loss linger / re-attach ---------------------------------
    def begin_linger(self, grace: float) -> None:
        """Driver gone (stdin EOF): pause dispatch but keep the RPC and
        shuffle servers up for ``grace`` seconds so a recovered driver
        can RECONNECT and resume against the surviving map outputs.
        Past the grace the worker self-terminates — the linger window,
        not process lifetime, bounds orphan risk."""
        with self._linger_lock:
            if self._lingering or self._stop.is_set():
                return
            self._lingering = True
            self.metrics["linger_entered"] += 1
            self._linger_timer = threading.Timer(grace, self._linger_expired)
            self._linger_timer.daemon = True
            self._linger_timer.start()

    def _linger_expired(self) -> None:
        with self._linger_lock:
            if not self._lingering:
                return  # a reconnect raced the timer and won
            self.metrics["linger_expired"] += 1
        self._stop.set()

    def _h_reconnect(self, payload: dict, blob: bytes):
        """RECONNECT handshake from a recovered driver: cancel the
        linger deadline, re-route heartbeats to the new driver address,
        adopt its journal epoch, and reply with a full inventory of the
        map-output slots this worker still holds so the driver can
        reconcile them against the journaled tracker."""
        with self._linger_lock:
            if self._linger_timer is not None:
                self._linger_timer.cancel()
                self._linger_timer = None
            self._lingering = False
            self.driver = tuple(payload["driver"])
            self._reattach_epoch = int(payload.get("epoch", 0))
            self.metrics["driver_reattached"] += 1
        return ({"worker_id": self.worker_id, "pid": os.getpid(),
                 "rpc": list(self.rpc.address),
                 "shuffle": list(self.shuffle_server.address),
                 "epoch": self._reattach_epoch,
                 "inventory": self.store.shuffle_inventory()}, b"")

    def _h_alias_shuffle(self, payload: dict, blob: bytes):
        """Re-key a held shuffle's slots under a new shuffle id: a
        recovered driver's replanned query carries a fresh (per-process)
        shuffle id for the same exchange, and claiming the journaled
        outputs means renaming them in every holder's store."""
        moved = self.store.alias_shuffle(payload["old"], payload["new"])
        self.metrics["shuffles_aliased"] += 1
        return ({"ok": True, "moved": moved}, b"")

    def _ensure_runtime(self) -> None:
        # first fragment pays JAX/runtime init, keeping READY fast
        with self._runtime_lock:
            if not self._runtime_ready:
                from spark_rapids_tpu.runtime import ensure_runtime
                ensure_runtime(self.conf)
                self._runtime_ready = True

    def _h_run_fragment(self, payload: dict, blob: bytes):
        """Execute one map-side fragment: drain the assigned child
        partitions of the shipped exchange clone and write the
        partitioned pieces into the local store.  Structured failure
        payloads (never error frames) let the driver distinguish a
        peer's data loss — which routes into lineage recovery — from
        this worker's own fault.  A draining worker rejects the call
        structurally so the driver re-pools the partitions on survivors
        without treating the rejection as data loss."""
        if self._draining or self._lingering:
            # a lingering worker rejects exactly like a draining one:
            # its map outputs stay servable but no new work lands until
            # a driver completes the RECONNECT handshake
            self.metrics["fragments_rejected_draining"] += 1
            return ({"error_kind": "draining",
                     "error": f"worker {self.worker_id} is "
                              f"{'lingering' if self._lingering else 'draining'}"},
                    b"")
        with self._active_lock:
            self._active_fragments += 1
        try:
            return self._run_fragment(payload, blob)
        finally:
            with self._active_lock:
                self._active_fragments -= 1

    def _run_fragment(self, payload: dict, blob: bytes):
        from spark_rapids_tpu.cluster.exec import WorkerFetchFailed
        from spark_rapids_tpu.conf import TpuConf
        from spark_rapids_tpu.exec.core import ExecCtx
        from spark_rapids_tpu.shuffle.errors import MapOutputLostError
        self._ensure_runtime()
        spec = pickle.loads(blob)
        exchange = spec["exchange"]
        n = int(spec["num_parts"])
        cpids = [int(c) for c in spec["cpids"]]
        epochs = {int(k): int(v)
                  for k, v in (spec.get("epochs") or {}).items()}
        sid = exchange.shuffle_id
        conf = TpuConf(scrub_worker_conf(spec.get("conf") or
                                         self.conf.settings))
        child = exchange.children[0]
        self.metrics["fragments_run"] += 1
        hdr = spec.get("trace") or None
        tracer = None
        try:
            with ExecCtx(backend="device", conf=conf) as ctx:
                if hdr:
                    # the driver's query/trace ids win: every span this
                    # fragment records lands under the ORIGINATING query
                    ctx.cache["query_id"] = hdr["query_id"]
                tracer = ctx.tracer
                if tracer is not None:
                    if hdr and hdr.get("trace_id"):
                        tracer.trace_id = hdr["trace_id"]
                    with self._tracer_lock:
                        self._live_tracers.append(tracer)
                with ctx.trace_span("worker.fragment", "cluster",
                                    worker_id=self.worker_id,
                                    shuffle_id=sid, cpids=list(cpids)):
                    for cpid in cpids:
                        for k, b in enumerate(
                                child.partition_iter(ctx, cpid)):
                            enc = cpid * MAP_ID_STRIDE + k
                            exchange._write_map_batch(
                                ctx, self.store, enc, b, False, n,
                                epoch=epochs.get(enc))
                            self.metrics["map_batches_written"] += 1
        except WorkerFetchFailed as e:
            self.metrics["fragment_failures"] += 1
            return ({"error": str(e), "error_kind": "peer_fetch",
                     "peer": list(e.address),
                     "lost_sid": e.shuffle_id,
                     **self._spans_field(tracer)}, b"")
        except MapOutputLostError as e:
            self.metrics["fragment_failures"] += 1
            return ({"error": str(e), "error_kind": "map_lost",
                     "lost_sid": e.shuffle_id, "part": e.part_id,
                     "lost": {str(k): v for k, v in e.lost.items()},
                     "observed_empty": e.observed_empty,
                     **self._spans_field(tracer)}, b"")
        finally:
            if tracer is not None:
                with self._tracer_lock:
                    try:
                        self._live_tracers.remove(tracer)
                    except ValueError:
                        pass
        wanted = set(cpids)
        entries = []
        for pid in range(n):
            for wslot, (mid, size, rows, ep) in enumerate(
                    self.store.slots_for(sid, pid)):
                if mid // MAP_ID_STRIDE in wanted:
                    entries.append([mid, pid, wslot, size, rows, ep])
        return ({"ok": True, "entries": entries,
                 "shuffle": list(self.shuffle_server.address),
                 "attempt": spec.get("attempt", 0),
                 **self._spans_field(tracer)}, b"")

    def _h_run_write_fragment(self, payload: dict, blob: bytes):
        """Execute one WRITE fragment: run the shipped plan subtree's
        assigned child partitions and stage each task's files into its
        private attempt directory under the job's ``_staging`` tree,
        replying with one manifest per task for the driver's commit
        coordinator to arbitrate.  Nothing here touches the final
        directory — a worker death mid-write leaves only staging
        garbage.  Draining workers reject structurally, like
        ``run_fragment``."""
        if self._draining or self._lingering:
            self.metrics["fragments_rejected_draining"] += 1
            return ({"error_kind": "draining",
                     "error": f"worker {self.worker_id} is "
                              f"{'lingering' if self._lingering else 'draining'}"},
                    b"")
        with self._active_lock:
            self._active_fragments += 1
        try:
            return self._run_write_fragment(payload, blob)
        finally:
            with self._active_lock:
                self._active_fragments -= 1

    def _run_write_fragment(self, payload: dict, blob: bytes):
        from spark_rapids_tpu.cluster.exec import WorkerFetchFailed
        from spark_rapids_tpu.conf import TpuConf
        from spark_rapids_tpu.exec.core import ExecCtx
        from spark_rapids_tpu.io.writer import (staging_attempt_dir,
                                                write_task_attempt)
        from spark_rapids_tpu.shuffle.errors import MapOutputLostError
        self._ensure_runtime()
        spec = pickle.loads(blob)
        plan = spec["plan"]
        w = spec["write"]
        cpids = [int(c) for c in spec["cpids"]]
        attempts = {int(k): int(v) for k, v in spec["attempts"].items()}
        conf = TpuConf(scrub_worker_conf(spec.get("conf") or
                                         self.conf.settings))
        self.metrics["write_fragments_run"] += 1
        hdr = spec.get("trace") or None
        tracer = None
        manifests: list[dict] = []
        try:
            with ExecCtx(backend="device", conf=conf) as ctx:
                if hdr:
                    ctx.cache["query_id"] = hdr["query_id"]
                tracer = ctx.tracer
                if tracer is not None:
                    if hdr and hdr.get("trace_id"):
                        tracer.trace_id = hdr["trace_id"]
                    with self._tracer_lock:
                        self._live_tracers.append(tracer)
                with ctx.trace_span("worker.write_fragment", "cluster",
                                    worker_id=self.worker_id,
                                    job=w["job_id"], cpids=list(cpids)):
                    for cpid in cpids:
                        attempt = attempts[cpid]
                        adir = staging_attempt_dir(
                            w["path"], w["job_id"], cpid, attempt)
                        # faults=None: fault plans are driver-side only
                        # (scrub_worker_conf strips them from the spec)
                        manifests.append(write_task_attempt(
                            plan, ctx, cpid, adir, w["fmt"],
                            w["partition_by"], w["options"],
                            job_id=w["job_id"], attempt=attempt,
                            worker=self.worker_id))
                        self.metrics["write_tasks_staged"] += 1
        except WorkerFetchFailed as e:
            self.metrics["write_fragment_failures"] += 1
            return ({"error": str(e), "error_kind": "peer_fetch",
                     "peer": list(e.address),
                     "lost_sid": e.shuffle_id,
                     **self._spans_field(tracer)}, b"")
        except MapOutputLostError as e:
            self.metrics["write_fragment_failures"] += 1
            return ({"error": str(e), "error_kind": "map_lost",
                     "lost_sid": e.shuffle_id, "part": e.part_id,
                     "lost": {str(k): v for k, v in e.lost.items()},
                     "observed_empty": e.observed_empty,
                     **self._spans_field(tracer)}, b"")
        except OSError as e:
            # the staging write itself failed (disk, quota): nothing
            # visible happened; the driver re-pools under a new attempt
            self.metrics["write_fragment_failures"] += 1
            return ({"error": str(e), "error_kind": "write_failed",
                     **self._spans_field(tracer)}, b"")
        finally:
            if tracer is not None:
                with self._tracer_lock:
                    try:
                        self._live_tracers.remove(tracer)
                    except ValueError:
                        pass
        return ({"ok": True, "manifests": manifests,
                 **self._spans_field(tracer)}, b"")

    def _spans_field(self, tracer) -> dict:
        """Drain one fragment tracer into a reply-payload field (empty
        dict when tracing is off — the obs package is untouched)."""
        if tracer is None:
            return {}
        from spark_rapids_tpu.obs.trace import stamp_for_shipping
        evs = stamp_for_shipping(tracer.drain_events(),
                                 tracer._wall_origin, os.getpid())
        if not evs:
            return {}
        return {"spans": {"pid": os.getpid(),
                          "events": evs[-_MAX_SHIP_EVENTS:]}}

    def _drain_live_spans(self) -> "dict | None":
        """Heartbeat payload: whatever the in-flight fragments have
        buffered since the last beat (exactly-once shipping — drain
        pops)."""
        with self._tracer_lock:
            tracers = list(self._live_tracers)
        if not tracers:
            return None
        from spark_rapids_tpu.obs.trace import stamp_for_shipping
        evs: list = []
        for t in tracers:
            evs.extend(stamp_for_shipping(t.drain_events(),
                                          t._wall_origin, os.getpid()))
        if not evs:
            return None
        return {"pid": os.getpid(), "events": evs[-_MAX_SHIP_EVENTS:]}

    # -- liveness -------------------------------------------------------
    def start_heartbeat(self) -> None:
        if self.driver is None:
            return
        self._hb_thread = threading.Thread(target=self._hb_loop,
                                           daemon=True,
                                           name="tpu-cluster-heartbeat")
        self._hb_thread.start()

    def _hb_loop(self) -> None:
        from spark_rapids_tpu.cluster import REATTACH_GRACE
        from spark_rapids_tpu.cluster.rpc import rpc_call
        from spark_rapids_tpu.obs.registry import get_registry
        # a RE-ATTACHED worker has no stdin pipe to the new driver, so a
        # second driver loss is detected by heartbeat silence instead:
        # grace seconds of consecutive failed beats re-enter linger
        grace = REATTACH_GRACE.get(self.conf.settings)
        misses = 0
        while not self._stop.wait(self._hb_interval):
            try:
                payload = {"worker_id": self.worker_id,
                           "pid": os.getpid(),
                           "metrics": get_registry().snapshot()}
                spans = self._drain_live_spans()
                if spans is not None:
                    payload["spans"] = spans
                # cost-attribution shipping: only when the profiler /
                # meter are already live in THIS process (sys.modules
                # gate — a disabled worker never imports them here)
                import sys as _sys
                if "spark_rapids_tpu.obs.metering" in _sys.modules:
                    from spark_rapids_tpu.obs.metering import get_meter
                    delta = get_meter().drain_delta()
                    if delta is not None:
                        payload["metering"] = delta
                if "spark_rapids_tpu.obs.profile" in _sys.modules:
                    from spark_rapids_tpu.obs.profile import \
                        drain_hbm_for_shipping
                    hbm = drain_hbm_for_shipping()
                    if hbm:
                        payload["profile_hbm"] = hbm
                rpc_call(self.driver, "heartbeat", payload,
                         conf=self.conf, retries=0, timeout=5.0)
                misses = 0
            except (ConnectionError, OSError):
                # driver unreachable: keep trying — the driver's timeout
                # is the authority on whether this worker is dead
                misses += 1
                if (grace > 0 and self._reattach_epoch > 0
                        and not self._lingering
                        and misses * self._hb_interval >= grace):
                    self.begin_linger(grace)

    def wait(self) -> None:
        self._stop.wait()

    def close(self) -> None:
        self._stop.set()
        self.rpc.close()
        self.shuffle_server.close()
        self.store.close()


def main() -> int:
    line = sys.stdin.readline()
    if not line:
        print("cluster worker: no config line on stdin", file=sys.stderr)
        return 2
    cfg = json.loads(line)
    rt = WorkerRuntime(cfg["worker_id"], cfg.get("driver"),
                       cfg.get("conf") or {})
    print(READY_PREFIX + json.dumps(
        {"worker_id": rt.worker_id, "pid": os.getpid(),
         "rpc": list(rt.rpc.address),
         "shuffle": list(rt.shuffle_server.address)}), flush=True)
    rt.start_heartbeat()
    # orphan reaper: the driver holds our stdin pipe open for its whole
    # life, so EOF here means the driver process is GONE (even SIGKILL,
    # which skips its shutdown RPCs).  With reattachGraceSeconds > 0 the
    # worker LINGERS instead of exiting — dispatch paused, shuffle
    # outputs servable — so a recovered driver can RECONNECT; past the
    # grace it self-terminates.  Grace 0 (default) is the pre-journal
    # behavior: exit immediately, never orphan.
    from spark_rapids_tpu.cluster import REATTACH_GRACE
    grace = REATTACH_GRACE.get(rt.conf.settings)

    def _watch_stdin() -> None:
        while sys.stdin.readline():
            pass
        if grace > 0:
            rt.begin_linger(grace)
        else:
            rt._stop.set()
    threading.Thread(target=_watch_stdin, daemon=True,
                     name="tpu-cluster-stdin").start()
    rt.wait()
    rt.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Driver-side cluster shuffle execution: fragment cloning, scheduling,
and the distributed map-output tracker.

``cluster_do_shuffle`` intercepts a cluster-tagged
ShuffleExchangeExec's device materialization (the hook sits at the top
of ``_do_shuffle_device``): instead of draining the child in-process,
it clones the exchange's subtree into a self-contained, picklable
FRAGMENT — upstream cluster shuffles become
:class:`WorkerShuffleReaderExec` leaves that stream peers' map output
over the DCN shuffle plane, broadcasts become pre-materialized
:class:`StaticBroadcastExec` payloads — and ships one fragment per
worker over the control plane (cluster/rpc.py).  Workers execute their
assigned child partitions and register the resulting map-output slots
back into a :class:`ClusterMapOutputTracker`, the driver's duck-typed
ShuffleTransport for that shuffle (reference: MapStatus registration
into MapOutputTracker; the tracker doubles as the reduce-side fetch
client the way RapidsCachingReader does).

Fault tolerance composes with the existing lineage machinery
(exec/recovery.py) rather than duplicating it: a dead worker surfaces
as a terminal fetch failure -> the tracker names every map output that
died with it in one MapOutputLostError -> ``_recover`` invalidates and
calls :class:`ClusterLineage`.recompute, which REASSIGNS the lost
child partitions to surviving workers and registers the fresh slots.
Anything the cluster path cannot express (non-deterministic
partitionings, unpicklable operators, upstream shuffles that fell back
in-process) falls back to the classic in-process materialization —
same rows, one process.
"""
from __future__ import annotations

import copy
import hashlib
import itertools
import pickle
import re
import sys
import threading
import time
from dataclasses import dataclass
from typing import Any, Iterator

from spark_rapids_tpu import types as T
from spark_rapids_tpu.cluster import (SPECULATION_ENABLED,
                                      SPECULATION_MIN_RUNTIME,
                                      SPECULATION_MULTIPLIER)
from spark_rapids_tpu.cluster.worker import MAP_ID_STRIDE, scrub_worker_conf
from spark_rapids_tpu.exec.core import ExecCtx, PlanNode
from spark_rapids_tpu.faults import crash_point
from spark_rapids_tpu.obs.registry import get_registry
from spark_rapids_tpu.shuffle.errors import (MapOutputLostError,
                                             ShuffleFetchError)

__all__ = ["WorkerShuffleReaderExec", "StaticBroadcastExec",
           "ClusterMapOutputTracker", "ClusterLineage",
           "cluster_do_shuffle", "WorkerFetchFailed", "ClusterExecError"]

#: node __dict__ keys holding lazily-built jit wrappers; they close over
#: runtime state and would poison fragment pickling — the worker's first
#: execution rebuilds them from the same compile-cache keys
_JIT_ATTR = re.compile(r"jit")


class ClusterExecError(RuntimeError):
    """Cluster scheduling failed in a way recovery cannot absorb (e.g.
    every worker died)."""


class WorkerFetchFailed(Exception):
    """A fragment's read from a peer worker's shuffle server failed
    terminally: the worker reports the peer to the driver, which marks
    it dead and routes the upstream shuffle into lineage recovery."""

    def __init__(self, address, shuffle_id, detail: str = ""):
        self.address = tuple(address)
        self.shuffle_id = shuffle_id
        super().__init__(
            f"fetch from worker {self.address[0]}:{self.address[1]} for "
            f"shuffle {shuffle_id} failed terminally"
            + (f": {detail}" if detail else ""))


class WorkerShuffleReaderExec(PlanNode):
    """Leaf that streams an upstream cluster shuffle's reduce
    partitions from the workers that hold them (the in-fragment analog
    of RemoteShuffleReaderExec, with a slot-ranged run list per output
    partition instead of one home address).

    ``groups[pid]`` is a list of ``(address, fetch_pid, lo, hi)`` runs:
    fetch slots [lo, hi) of the peer's reduce partition ``fetch_pid``.
    AQE coalesce/skew-split groups computed driver-side flatten into
    the same run shape.  ``_src`` records ``(shuffle_id, groups_spec)``
    so the driver can rebuild the runs from the live tracker after a
    recovery relocated slots (cluster/exec.py _refresh_readers)."""

    def __init__(self, shuffle_id, schema: T.Schema, groups,
                 src=None):
        super().__init__([])
        self.shuffle_id = shuffle_id
        self._schema = schema
        self.groups = [list(g) for g in groups]
        self._src = src

    @property
    def output_schema(self) -> T.Schema:
        return self._schema

    def num_partitions(self, ctx: ExecCtx) -> int:
        return len(self.groups)

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        from spark_rapids_tpu.shuffle.retry import fetch_remote_with_retry
        for address, fpid, lo, hi in self.groups[pid]:
            try:
                yield from fetch_remote_with_retry(
                    tuple(address), self.shuffle_id, fpid, lo=lo, hi=hi,
                    device=ctx.is_device, conf=ctx.conf,
                    lifecycle=ctx.lifecycle)
            except MapOutputLostError:
                raise
            except ShuffleFetchError as e:
                raise WorkerFetchFailed(address, self.shuffle_id,
                                        str(e)) from e

    def node_desc(self) -> str:
        return (f"WorkerShuffleReaderExec[shuffle="
                f"{str(self.shuffle_id)[:12]}, groups={len(self.groups)}]")


class StaticBroadcastExec(PlanNode):
    """Broadcast side pre-materialized ON THE DRIVER and shipped to
    workers as one serialized batch — the fragment-side analog of the
    reference's torrent-broadcast build side (GpuBroadcastExchangeExec
    collects on the driver and executors rebuild the device table from
    the broadcast blob)."""

    def __init__(self, data: bytes, schema: T.Schema):
        super().__init__([])
        self._data = data
        self._schema = schema

    @property
    def output_schema(self) -> T.Schema:
        return self._schema

    def num_partitions(self, ctx: ExecCtx) -> int:
        return 1

    def materialize(self, ctx: ExecCtx):
        from spark_rapids_tpu.shuffle.serializer import deserialize_batch
        return ctx.cached(("static_broadcast", id(self), ctx.backend),
                          lambda: deserialize_batch(
                              self._data, device=ctx.is_device))

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        yield self.materialize(ctx)

    def node_desc(self) -> str:
        return f"StaticBroadcastExec[{len(self._data)}B]"


class _Entry:
    """One registered map-output slot: where one (map batch, reduce
    partition) piece lives in the cluster."""

    __slots__ = ("map_id", "worker_id", "wslot", "size", "rows",
                 "epoch", "lost")

    def __init__(self, map_id: int, worker_id: str, wslot: int,
                 size: int, rows: int, epoch: int):
        self.map_id = map_id
        self.worker_id = worker_id
        self.wslot = wslot
        self.size = size
        self.rows = rows
        self.epoch = epoch
        self.lost = False


class ClusterMapOutputTracker:
    """Driver-side map-output directory + reduce-fetch client for ONE
    cluster shuffle; duck-types the ShuffleTransport SPI so the
    recovery loop (recovering_fetch/_recover), the AQE reader's
    statistics reads, and ExecCtx.close all work on it unchanged.

    Entries per reduce partition are kept sorted by composite map id
    ``cpid * MAP_ID_STRIDE + k`` — the same (child partition, batch)
    lexicographic order the single-process path's flat map indices
    produce — so the merged fetch stream is batch-for-batch identical
    to one process (the exactness argument behind the premerge equality
    gate)."""

    def __init__(self, cluster, ctx: ExecCtx, shuffle_id, num_parts: int):
        from spark_rapids_tpu.faults import FaultRegistry
        self.cluster = cluster
        self.ctx = ctx
        self.shuffle_id = shuffle_id
        self.num_parts = num_parts
        self._lock = threading.Lock()
        self._entries: list[list[_Entry]] = [[] for _ in range(num_parts)]
        self._epochs: dict[int, int] = {}
        # worker_id -> shuffle-plane address (recorded at registration)
        self._shuffle_addr: dict[str, tuple] = {}
        self._faults = ctx.cached(("fault_registry",),
                                  lambda: FaultRegistry.from_conf(ctx.conf))
        self._closed = False
        # write-ahead cluster journal (cluster/journal.py) when the
        # driver has one: registrations, invalidations, and the close
        # are journaled so a restarted driver can resume this shuffle
        self._journal = None
        # the driver weakly tracks live trackers so a graceful drain
        # can migrate a retiring worker's slots (elastic membership)
        reg_tracker = getattr(cluster, "register_tracker", None)
        if callable(reg_tracker):
            reg_tracker(self)

    # -- registration (dispatch rounds) ---------------------------------
    def register(self, worker_id: str, shuffle_addr, entries) -> None:
        """Fold one fragment reply's slot list in: a (pid, map_id) pair
        already present (a recovery recompute) is replaced in place so
        slot ORDER survives relocation; new pairs append and the
        partition re-sorts by map id.

        Commit is FIRST-WRITER-WINS per epoch: a slot already live at
        this epoch is never replaced, so a speculative duplicate (or a
        drain straggler) re-offering the same map output is discarded —
        the exactly-once guarantee behind speculation and migration."""
        with self._lock:
            self._shuffle_addr[worker_id] = tuple(shuffle_addr)
            dirty = set()
            for mid, pid, wslot, size, rows, epoch in entries:
                mid, pid = int(mid), int(pid)
                cur = self._epochs.get(mid, 0)
                if epoch < cur:
                    continue  # straggler from a pre-recovery attempt
                row = self._entries[pid]
                old = next((e for e in row if e.map_id == mid), None)
                if old is not None and not old.lost \
                        and int(epoch) <= old.epoch:
                    get_registry().inc(
                        "cluster.stale_registrations_discarded")
                    continue  # first writer already committed
                self._epochs[mid] = int(epoch)
                if old is not None:
                    old.worker_id = worker_id
                    old.wslot = int(wslot)
                    old.size = int(size)
                    old.rows = int(rows)
                    old.epoch = int(epoch)
                    old.lost = False
                else:
                    row.append(_Entry(mid, worker_id, int(wslot),
                                      int(size), int(rows), int(epoch)))
                    dirty.add(pid)
            for pid in dirty:
                self._entries[pid].sort(key=lambda e: e.map_id)
        if self._journal is not None and entries:
            self._journal.append(
                "map_register", sid=str(self.shuffle_id), wid=worker_id,
                shuffle=list(shuffle_addr),
                entries=[[int(m), int(p), int(w), int(s), int(r), int(e)]
                         for m, p, w, s, r, e in entries])

    def entries_owned_by(self, worker_id: str) -> dict[int, int]:
        """Live map ids (with current epochs) whose slots sit on the
        given worker — the loss payload when that worker dies."""
        with self._lock:
            out: dict[int, int] = {}
            for row in self._entries:
                for e in row:
                    if e.worker_id == worker_id and not e.lost:
                        out[e.map_id] = e.epoch
            return out

    def mark_worker_lost(self, worker_id: str) -> dict[int, int]:
        lost = self.entries_owned_by(worker_id)
        with self._lock:
            for row in self._entries:
                for e in row:
                    if e.worker_id == worker_id:
                        e.lost = True
        return lost

    # -- graceful-drain migration ---------------------------------------
    def begin_migration(self, worker_id: str, faults=None):
        """Plan the retiring worker's live slots as contiguous fetch
        runs, each slot tagged with its NEXT epoch: the copies the
        drain registers commit at that bumped epoch (register advances
        ``_epochs`` on success), so a straggling write from the old
        attempt — or a late speculative duplicate — is epoch-stale and
        discarded.  The tracker's OWN epoch map is NOT advanced here: a
        run that fails to migrate must still look lost at its old epoch
        so lineage recovery accepts the loss report.  Returns ``(runs,
        dropped)`` where each run is one ``migrate_slots`` RPC payload
        and ``dropped`` counts slots withheld by
        ``cluster.migrate.drop``.  A drop withholds the ENTIRE map
        output, not just the one slot: epochs are tracked per map_id,
        so migrating a map's other slots at epoch+1 while one slot
        stays lost at the old epoch would make that slot's loss report
        look stale forever (recovery filters on ``map_epoch <= lost
        epoch``) and the reduce would spin without recomputing.
        Withheld maps stay on the retiring worker at their old epoch
        and route through lineage recovery instead."""
        runs: list[dict] = []
        dropped = 0
        dropped_mids: set[int] = set()
        with self._lock:
            if faults is not None:
                for pid, row in enumerate(self._entries):
                    for e in row:
                        if e.worker_id != worker_id or e.lost:
                            continue
                        if e.map_id not in dropped_mids and faults.check(
                                "cluster.migrate.drop",
                                shuffle=self.shuffle_id, part=pid,
                                map=e.map_id) is not None:
                            dropped_mids.add(e.map_id)
            for pid, row in enumerate(self._entries):
                keep = []
                for e in row:
                    if e.worker_id != worker_id or e.lost:
                        continue
                    if e.map_id in dropped_mids:
                        dropped += 1
                        continue
                    keep.append(e)
                # contiguous source-slot ranges fetch as one stream each
                i, n = 0, len(keep)
                while i < n:
                    j = i + 1
                    while j < n and keep[j].wslot == keep[j - 1].wslot + 1:
                        j += 1
                    seg = keep[i:j]
                    runs.append({"pid": pid, "lo": seg[0].wslot,
                                 "hi": seg[-1].wslot + 1,
                                 "map_ids": [e.map_id for e in seg],
                                 "rows": [e.rows for e in seg],
                                 "epochs": [
                                     self._epochs.get(e.map_id, 0) + 1
                                     for e in seg]})
                    i = j
        return runs, dropped

    # -- ShuffleTransport SPI -------------------------------------------
    def write_partition(self, shuffle_id, map_id, part_id, batch,
                        epoch=None) -> None:
        raise RuntimeError(
            "ClusterMapOutputTracker is a read-side directory; map "
            "writes happen in the workers (ClusterLineage.recompute "
            "re-dispatches fragments instead of writing locally)")

    def map_epoch(self, shuffle_id, map_id: int) -> int:
        with self._lock:
            return self._epochs.get(map_id, 0)

    def map_output_present(self, shuffle_id, part_id: int,
                           map_id: int) -> bool:
        with self._lock:
            return any(e.map_id == map_id and not e.lost
                       for e in self._entries[part_id])

    def invalidate_map_outputs(self, shuffle_id,
                               map_ids) -> dict[int, int]:
        wanted = set(int(m) for m in map_ids)
        with self._lock:
            new_epochs = {m: self._epochs.get(m, 0) + 1 for m in wanted}
            self._epochs.update(new_epochs)
            for row in self._entries:
                for e in row:
                    if e.map_id in wanted:
                        e.lost = True
                        e.epoch = new_epochs[e.map_id]
        if self._journal is not None and new_epochs:
            self._journal.append(
                "map_invalidate", sid=str(self.shuffle_id),
                epochs={str(m): e for m, e in new_epochs.items()})
        return new_epochs

    def partition_sizes(self, shuffle_id) -> dict[int, int]:
        with self._lock:
            return {pid: sum(e.size for e in row if not e.lost)
                    for pid, row in enumerate(self._entries) if row}

    def partition_rows(self, shuffle_id) -> dict[int, int]:
        with self._lock:
            return {pid: sum(e.rows for e in row if not e.lost)
                    for pid, row in enumerate(self._entries) if row}

    def batch_sizes(self, shuffle_id, part_id: int) -> list[int]:
        with self._lock:
            return [e.size for e in self._entries[part_id]]

    def fetch_partition(self, shuffle_id, part_id: int, lo: int = 0,
                        hi: int | None = None) -> Iterator:
        """Stream slots [lo, hi) of one reduce partition from the
        workers holding them, in map-id order.  A worker whose fetch
        fails terminally is marked dead and ALL its map outputs for
        this shuffle surface in one MapOutputLostError, so one recovery
        round relocates everything it held (reference: one
        FetchFailed fails the stage once per lost executor, not once
        per missing block)."""
        crash_point(self._faults, "shuffle_read",
                    shuffle=str(shuffle_id)[:12], part=part_id)
        if self._faults is not None:
            with self._lock:
                snap = list(self._entries[part_id])[lo:hi]
            if snap:
                owner = snap[0].worker_id
                act = self._faults.check("cluster.worker.dead",
                                         shuffle=shuffle_id,
                                         part=part_id, worker=owner)
                if act is not None and len(self.cluster.live_workers()) > 1:
                    # SIGKILL the owner of the first requested slot —
                    # the fetch below then fails for real and the
                    # DETECTION + recovery machinery runs unfaked
                    self.cluster.kill_worker(owner)
        delivered = 0
        while True:
            self.ctx.check_cancel()
            with self._lock:
                snap = list(self._entries[part_id])[lo:hi]
            snap = snap[delivered:]
            lost = {e.map_id: e.epoch for e in snap if e.lost}
            if lost:
                raise MapOutputLostError(
                    shuffle_id, part_id, lost,
                    detail="slots invalidated pending recompute")
            if not snap:
                return
            resume = False
            for worker_id, wlo, whi in _runs(snap):
                addr = self._shuffle_addr[worker_id]
                try:
                    for batch in self._fetch_run(addr, part_id, wlo, whi):
                        yield batch
                        delivered += 1
                except MapOutputLostError:
                    raise
                except ShuffleFetchError as e:
                    # a graceful drain may have RELOCATED the remaining
                    # slots while this reader streamed: if nothing
                    # undelivered still lives on the failed worker,
                    # resume from the new owners instead of declaring a
                    # loss (the planned-scale-down copy, not a recompute)
                    with self._lock:
                        cur = list(self._entries[part_id])[lo:hi]
                    undelivered = cur[delivered:]
                    if undelivered and not any(
                            x.worker_id == worker_id and not x.lost
                            for x in undelivered):
                        get_registry().inc("cluster.migrated_refetches")
                        resume = True
                        break
                    handle = self.cluster.worker_by_id(worker_id)
                    if handle is not None:
                        self.cluster.mark_worker_lost(
                            worker_id, f"fetch failed: {e}")
                    all_lost = self.mark_worker_lost(worker_id)
                    if not all_lost:
                        raise
                    raise MapOutputLostError(
                        shuffle_id, part_id, all_lost,
                        detail=f"worker {worker_id} died mid-fetch: {e}"
                    ) from e
            if not resume:
                return

    def _fetch_run(self, addr, part_id, wlo, whi) -> Iterator:
        from spark_rapids_tpu.shuffle.retry import fetch_remote_with_retry
        ctx = self.ctx
        tracer = ctx.tracer
        trace = tracer.trace_header() if tracer is not None else None
        yield from fetch_remote_with_retry(
            addr, self.shuffle_id, part_id, lo=wlo, hi=whi,
            device=ctx.is_device, conf=ctx.conf, tracer=tracer,
            trace=trace, lifecycle=ctx.lifecycle)

    # -- downstream fragment support ------------------------------------
    def reader_groups(self, groups_spec=None):
        """(groups, locality) for a WorkerShuffleReaderExec consuming
        this shuffle.  ``groups_spec`` is the AQE reader's list of
        ``[(pid, lo, hi), ...]`` slices, or None for the identity
        mapping (one group per reduce partition).  ``locality[gi]`` maps
        worker_id -> bytes served, feeding locality-aware scheduling."""
        if groups_spec is None:
            groups_spec = [[(pid, 0, None)] for pid in
                           range(self.num_parts)]
        groups, locality = [], []
        with self._lock:
            for spec in groups_spec:
                runs, loc = [], {}
                for pid, lo, hi in spec:
                    snap = list(self._entries[pid])[lo:hi]
                    for worker_id, wlo, whi in _runs(snap):
                        runs.append((self._shuffle_addr[worker_id],
                                     pid, wlo, whi))
                    for e in snap:
                        loc[e.worker_id] = loc.get(e.worker_id, 0) + e.size
                groups.append(runs)
                locality.append(loc)
        return groups, locality

    def close(self) -> None:
        """Best-effort release of this shuffle's slots on every live
        worker (query teardown: ExecCtx.close closes every cached
        transport, this one included)."""
        if self._closed:
            return
        self._closed = True
        if self._journal is not None:
            # a closed shuffle is not resumable: drop it from the
            # journaled state so compaction forgets it
            self._journal.append("shuffle_close",
                                 sid=str(self.shuffle_id))
        from spark_rapids_tpu.cluster.rpc import rpc_call
        with self._lock:
            workers = list(self._shuffle_addr)
        for wid in workers:
            handle = self.cluster.worker_by_id(wid)
            if handle is None or not handle.alive:
                continue
            try:
                rpc_call(handle.rpc_addr, "release_shuffle",
                         {"shuffle_id": self.shuffle_id},
                         conf=self.ctx.conf, retries=0, timeout=5.0)
            except (ConnectionError, OSError):
                pass


def _runs(entries) -> Iterator[tuple]:
    """Group an ordered entry slice into per-worker contiguous-slot
    fetch runs ``(worker_id, wlo, whi)``."""
    i, n = 0, len(entries)
    while i < n:
        j = i + 1
        while (j < n and entries[j].worker_id == entries[i].worker_id
               and entries[j].wslot == entries[j - 1].wslot + 1):
            j += 1
        yield (entries[i].worker_id, entries[i].wslot,
               entries[j - 1].wslot + 1)
        i = j


@dataclass
class ClusterLineage:
    """Lineage handle for a cluster shuffle: recovery's ``recompute``
    re-dispatches the lost child partitions' fragments onto SURVIVING
    workers (reassignment) instead of re-draining locally — the
    DAGScheduler's resubmit-on-another-executor behavior."""

    exchange_clone: Any      # picklable fragment template
    cluster: Any             # ClusterDriver
    tracker: ClusterMapOutputTracker
    num_parts: int
    frag_conf: dict
    conf_fp: str | None = None

    def recompute(self, ctx: ExecCtx, transport,
                  epochs: dict[int, int]) -> int:
        if self.conf_fp is not None:
            from spark_rapids_tpu.exec.recovery import conf_fingerprint
            now = conf_fingerprint(ctx.conf)
            if now != self.conf_fp:
                raise RuntimeError(
                    f"cluster shuffle {self.tracker.shuffle_id}: conf "
                    f"changed since the map stage ran "
                    f"({self.conf_fp[:12]} -> {now[:12]}); lineage "
                    "recomputation would not be deterministic")
        lost_cpids = sorted({m // MAP_ID_STRIDE for m in epochs})
        _dispatch_fragments(self.cluster, ctx, self.tracker,
                            self.exchange_clone, self.num_parts,
                            lost_cpids, self.frag_conf, epochs=epochs)
        reg = get_registry()
        reg.inc("stage_recomputes")
        reg.inc("map_outputs_recomputed", len(epochs))
        return len(epochs)


# ---------------------------------------------------------------------------
# fragment cloning
# ---------------------------------------------------------------------------

def _clone_subtree(root, ctx: ExecCtx):
    """Clone a plan subtree into a picklable fragment body.

    Upstream CLUSTER shuffles materialize now (recursively, via
    ``_shuffled`` -> this module again) and become
    WorkerShuffleReaderExec leaves; broadcasts materialize driver-side
    into StaticBroadcastExec blobs; stage boundaries resolve to their
    adaptive replacement.  Returns (None, reason) when the subtree
    cannot run in a worker (a non-clusterable device exchange, or an
    upstream that itself fell back in-process) — the caller falls back
    to the in-process path.  Shared by the shuffle map-side clone
    (:func:`_clone_fragment`) and write fragments
    (:func:`dispatch_write_fragments`)."""
    from spark_rapids_tpu.exec.exchange import (AdaptiveShuffleReaderExec,
                                                BroadcastExchangeExec,
                                                ShuffleExchangeExec)
    from spark_rapids_tpu.exec.stage_boundary import StageBoundaryExec
    from spark_rapids_tpu.shuffle.serializer import serialize_batch
    memo: dict[int, Any] = {}
    poison: list[str] = []

    def reader_from(tr, src_sid, schema, groups_spec):
        groups, locality = tr.reader_groups(groups_spec)
        node = WorkerShuffleReaderExec(src_sid, schema, groups,
                                       src=(src_sid, groups_spec))
        node._cluster_locality = locality
        return node

    def walk(node):
        got = memo.get(id(node))
        if got is not None:
            return got
        if isinstance(node, StageBoundaryExec):
            out = walk(node._resolved(ctx))
            memo[id(node)] = out
            return out
        if isinstance(node, AdaptiveShuffleReaderExec) and \
                getattr(node.children[0], "_cluster_ok", False):
            ex = node.children[0]
            tr = ex._shuffled(ctx)  # stage barrier (recursive cluster run)
            if not isinstance(tr, ClusterMapOutputTracker):
                poison.append(f"upstream shuffle "
                              f"{str(ex.shuffle_id)[:12]} ran in-process")
                out = node
            else:
                out = reader_from(tr, ex.shuffle_id, node.output_schema,
                                  node._groups(ctx))
            memo[id(node)] = out
            return out
        if isinstance(node, ShuffleExchangeExec):
            if not getattr(node, "_cluster_ok", False):
                poison.append(f"non-clusterable exchange "
                              f"{node.node_desc()}")
                memo[id(node)] = node
                return node
            tr = node._shuffled(ctx)
            if not isinstance(tr, ClusterMapOutputTracker):
                poison.append(f"upstream shuffle "
                              f"{str(node.shuffle_id)[:12]} ran "
                              "in-process")
                memo[id(node)] = node
                return node
            out = reader_from(tr, node.shuffle_id, node.output_schema,
                              None)
            memo[id(node)] = out
            return out
        if isinstance(node, BroadcastExchangeExec):
            b = node.materialize(ctx)
            out = StaticBroadcastExec(serialize_batch(b),
                                      node.output_schema)
            memo[id(node)] = out
            return out
        if not node.children:
            memo[id(node)] = node
            return node
        c = copy.copy(node)
        # lazily-built jit wrappers close over the original node and do
        # not pickle; the worker rebuilds them (same compile-cache keys)
        for k in [k for k in vars(c) if _JIT_ATTR.search(k)]:
            c.__dict__.pop(k, None)
        c.children = tuple(walk(ch) for ch in node.children)
        memo[id(node)] = c
        return c

    walked = walk(root)
    if poison:
        return None, "; ".join(poison[:3])
    return walked, None


def _clone_fragment(exchange, ctx: ExecCtx):
    """Clone the exchange + child subtree into a picklable map fragment
    (see :func:`_clone_subtree` for the walk semantics)."""
    walked, reason = _clone_subtree(exchange.children[0], ctx)
    if walked is None:
        return None, reason
    clone = copy.copy(exchange)
    clone._shuffle_id = exchange.shuffle_id  # pin: id(n) never crosses
    clone.children = (walked,)
    return clone, None


def _readers(node, out=None) -> list:
    if out is None:
        out = []
    if isinstance(node, WorkerShuffleReaderExec):
        out.append(node)
    for c in node.children:
        _readers(c, out)
    return out


def _refresh_readers(clone, ctx: ExecCtx) -> None:
    """Rebuild every reader leaf's run list from the CURRENT upstream
    tracker state: a recovery may have relocated slots since the clone
    was built, and a re-dispatched fragment must read from where the
    data lives now."""
    for rd in _readers(clone):
        if rd._src is None:
            continue
        sid, groups_spec = rd._src
        tr = ctx.cache.get(("shuffle", sid, ctx.backend))
        if isinstance(tr, ClusterMapOutputTracker):
            groups, locality = tr.reader_groups(groups_spec)
            rd.groups = [list(g) for g in groups]
            rd._cluster_locality = locality


# ---------------------------------------------------------------------------
# scheduling + dispatch
# ---------------------------------------------------------------------------

def _locality(clone, ncpids: int) -> list[dict]:
    """Per child partition: worker_id -> upstream bytes already local.
    Sums every reader leaf's contribution; empty dicts when the
    fragment reads only base tables."""
    score: list[dict] = [dict() for _ in range(ncpids)]
    for rd in _readers(clone):
        loc = getattr(rd, "_cluster_locality", None)
        if not loc:
            continue
        for cpid in range(min(ncpids, len(loc))):
            for wid, nbytes in loc[cpid].items():
                score[cpid][wid] = score[cpid].get(wid, 0) + nbytes
    return score


def _assign_cpids(pending, live, score) -> dict[str, list[int]]:
    """Locality-first assignment: each child partition goes to the live
    worker already holding the most of its upstream bytes, tiebreak
    least-loaded (reference: DAGScheduler preferred locations from
    MapOutputTracker, then round-robin)."""
    reg = get_registry()
    load = {h.worker_id: 0 for h in live}
    assign: dict[str, list[int]] = {h.worker_id: [] for h in live}
    for cpid in sorted(pending):
        sc = score[cpid] if cpid < len(score) else {}
        best = min(live, key=lambda h: (-sc.get(h.worker_id, 0),
                                        load[h.worker_id], h.worker_id))
        if sc.get(best.worker_id, 0) > 0:
            reg.inc("cluster.locality_assignments")
        assign[best.worker_id].append(cpid)
        load[best.worker_id] += 1
    return {w: cps for w, cps in assign.items() if cps}


def _dispatch_fragments(cluster, ctx: ExecCtx, tracker, clone,
                        num_parts: int, cpids, frag_conf: dict,
                        epochs: dict[int, int] | None = None) -> None:
    """Run map fragments for the given child partitions over the live
    workers, retrying on surviving workers when one dies mid-round and
    cascading peer-loss reports into upstream lineage recovery.  All
    resulting slots are registered into ``tracker`` before returning
    (the stage barrier)."""
    from concurrent.futures import ThreadPoolExecutor
    from spark_rapids_tpu.cluster.rpc import RpcError, rpc_call
    reg = get_registry()
    journal = getattr(tracker, "_journal", None)
    speculate = SPECULATION_ENABLED.get(ctx.conf.settings)
    pending = sorted(int(c) for c in cpids)
    max_rounds = max(4, 2 * len(cluster.workers()) + 2)
    rounds = 0
    # every dispatch (retry round, speculative duplicate) carries a
    # distinct attempt id, echoed in the worker's reply — duplicate
    # attempts of one fragment are distinguishable at commit time
    attempt_seq = itertools.count()
    while pending:
        ctx.check_cancel()
        rounds += 1
        crash_point(tracker._faults, "dispatch", round=rounds,
                    shuffle=str(tracker.shuffle_id)[:12])
        if rounds > max_rounds:
            raise ClusterExecError(
                f"shuffle {str(tracker.shuffle_id)[:12]}: fragment "
                f"dispatch did not converge after {rounds - 1} rounds "
                f"({len(pending)} partitions still unplaced)")
        live = cluster.schedulable_workers()
        if not live:
            raise ClusterExecError(
                f"shuffle {str(tracker.shuffle_id)[:12]}: no live "
                "workers left to run map fragments")
        _refresh_readers(clone, ctx)
        assign = _assign_cpids(pending, live, _locality(clone,
                                                        max(pending) + 1))
        handles = {h.worker_id: h for h in live}

        tracer = ctx.tracer

        def run_one(wid: str, cps: list[int]):
            if tracker._faults is not None:
                act = tracker._faults.check(
                    "cluster.worker.slow", worker=wid,
                    shuffle=tracker.shuffle_id)
                if act is not None:
                    # a straggling executor, modelled driver-side so
                    # speculation's duplicate has a real head start
                    time.sleep(act.param("seconds", 2.0))
                act = tracker._faults.check(
                    "cluster.worker.flaky", worker=wid,
                    shuffle=tracker.shuffle_id)
                if act is not None:
                    raise RpcError(
                        f"injected fault: flaky worker {wid}")
            spec = {"exchange": clone, "num_parts": num_parts,
                    "cpids": cps, "conf": frag_conf,
                    "attempt": next(attempt_seq)}
            if tracer is not None:
                # propagate the query/trace ids: the worker's fragment
                # spans land under THIS query and ship back in the reply
                spec["trace"] = tracer.trace_header()
            if epochs:
                spec["epochs"] = {m: e for m, e in epochs.items()
                                  if m // MAP_ID_STRIDE in set(cps)}
            blob = pickle.dumps(spec, protocol=pickle.HIGHEST_PROTOCOL)
            reg.inc("cluster.fragments_dispatched")
            handle = handles.get(wid) or cluster.worker_by_id(wid)
            return rpc_call(handle.rpc_addr, "run_fragment",
                            {"shuffle_id": str(tracker.shuffle_id)},
                            blob=blob, conf=ctx.conf,
                            faults=tracker._faults)[0]

        next_pending: list[int] = []
        if speculate:
            _dispatch_round_speculative(cluster, ctx, tracker, tracer,
                                        assign, run_one, next_pending)
        else:
            results: dict[str, Any] = {}
            with ThreadPoolExecutor(max_workers=len(assign)) as pool:
                futs = {wid: pool.submit(run_one, wid, cps)
                        for wid, cps in assign.items()}
                for wid, fut in futs.items():
                    try:
                        results[wid] = fut.result()
                    except (RpcError, ConnectionError, OSError) as e:
                        results[wid] = e
            for wid, cps in assign.items():
                _consume_result(cluster, ctx, tracker, tracer, wid, cps,
                                results[wid], next_pending)
        round_pending = pending
        pending = sorted(next_pending)
        if journal is not None:
            # the dispatch frontier is journaled per round so a
            # restarted driver resumes from the last completed
            # partitions instead of re-running the whole stage
            newly_done = sorted(set(round_pending) - set(pending))
            if newly_done:
                journal.append("frontier",
                               sid=str(tracker.shuffle_id),
                               done=newly_done)


def _consume_result(cluster, ctx: ExecCtx, tracker, tracer, wid: str,
                    cps: list, res, next_pending: list,
                    register=None) -> None:
    """Fold one fragment attempt's outcome into the round: register a
    success, re-pool a structured failure (after driving upstream
    recovery), and pass a transport failure through the cluster's
    failure verdict (lost / quarantined / tolerated — all re-pool).

    ``register`` overrides what a success commits: shuffle fragments
    register map slots into ``tracker`` (the default); write fragments
    register task-attempt manifests with the job's commit coordinator.
    Either target applies its own first-writer-wins guard, so feeding
    it a duplicate attempt is always safe."""
    if isinstance(res, Exception):
        # control plane unreachable or flaky: the verdict decides
        # whether the worker is gone or just benched; either way its
        # partitions go back in the pool
        cluster.record_worker_failure(wid, f"run_fragment RPC: {res}")
        next_pending.extend(cps)
        return
    spans = res.get("spans")
    if tracer is not None and spans:
        # merge the worker's spans (success OR structured
        # failure) onto the driver timeline, one labelled lane
        # per worker pid
        tracer.ensure_lane(tracer.pid, "driver")
        tracer.ensure_lane(int(spans["pid"]),
                           f"cluster worker {wid}")
        tracer.ingest_wall(spans.get("events") or [])
    kind = res.get("error_kind")
    if kind == "draining":
        # a planned removal raced this dispatch: nobody died, the
        # partitions simply move to the survivors next round
        get_registry().inc("cluster.fragments_rejected_draining")
        next_pending.extend(cps)
        return
    if kind == "write_failed":
        # the worker's write attempt itself failed (I/O error while
        # staging): nothing visible happened — count a failure verdict
        # and re-pool so the next round retries under a fresh attempt id
        get_registry().inc("cluster.write_fragment_failures")
        cluster.record_worker_failure(
            wid, f"write fragment: {res.get('error')}")
        next_pending.extend(cps)
        return
    if kind:
        _handle_fragment_loss(cluster, ctx, res)
        next_pending.extend(cps)
        return
    cluster.note_worker_success(wid)
    if register is not None:
        register(wid, res)
    else:
        tracker.register(wid, res["shuffle"], res["entries"])


def _dispatch_round_speculative(cluster, ctx: ExecCtx, tracker, tracer,
                                assign, run_one, next_pending,
                                register=None) -> None:
    """One dispatch round with straggler speculation: every assignment
    runs as before, but a single attempt whose wall time exceeds
    ``speculation.multiplier`` × the round's running median gets a
    DUPLICATE on another schedulable worker; the first completed
    attempt per assignment wins and commits (the tracker's
    first-writer-wins epoch check rejects the loser's slots — the
    exactly-once guarantee).  Losers still running when the round
    completes are abandoned to finish in the background."""
    from concurrent.futures import ThreadPoolExecutor
    from spark_rapids_tpu.cluster.rpc import RpcError
    reg = get_registry()
    s = ctx.conf.settings
    mult = SPECULATION_MULTIPLIER.get(s)
    min_rt = SPECULATION_MIN_RUNTIME.get(s)
    pool = ThreadPoolExecutor(
        max_workers=2 * len(assign) + 1,
        thread_name_prefix="tpu-cluster-speculate")

    def attempt(wid, cps):
        def call():
            try:
                return run_one(wid, cps)
            except (RpcError, ConnectionError, OSError) as e:
                return e
        return (wid, pool.submit(call), time.monotonic())

    # key -> list of live attempts; first completion wins the key
    attempts = {tuple(cps): [attempt(wid, cps)]
                for wid, cps in assign.items()}
    owner = {tuple(cps): wid for wid, cps in assign.items()}
    walls: list[float] = []
    done_keys: set = set()
    try:
        while len(done_keys) < len(attempts):
            ctx.check_cancel()
            time.sleep(0.02)
            now = time.monotonic()
            for key, atts in attempts.items():
                if key in done_keys:
                    continue
                finished = [(w, f, t0) for (w, f, t0) in atts
                            if f.done()]
                winner = next(
                    ((w, f, t0) for (w, f, t0) in finished
                     if not isinstance(f.result(), Exception)
                     and not f.result().get("error_kind")), None)
                if winner is None and len(finished) == len(atts):
                    # every attempt failed: consume one failure so the
                    # partitions re-pool (and the loss is handled)
                    w, f, t0 = finished[-1]
                    _consume_result(cluster, ctx, tracker, tracer, w,
                                    list(key), f.result(), next_pending,
                                    register=register)
                    done_keys.add(key)
                    continue
                if winner is None:
                    # still running: speculate when the sole attempt
                    # has outlived the round's typical fragment
                    if len(atts) == 1 and walls:
                        import statistics
                        med = statistics.median(walls)
                        elapsed = now - atts[0][2]
                        if elapsed > max(min_rt, mult * med):
                            cand = [h for h in
                                    cluster.schedulable_workers()
                                    if h.worker_id not in
                                    {w for (w, _, _) in atts}]
                            if cand:
                                tgt = cand[0].worker_id
                                atts.append(attempt(tgt, list(key)))
                                reg.inc("speculative_launched")
                                print(f"cluster: speculating "
                                      f"{list(key)} of "
                                      f"{owner[key]} on {tgt}",
                                      file=sys.stderr)
                    continue
                w, f, t0 = winner
                wall = now - t0
                walls.append(wall)
                reg.observe("cluster.fragment.wall_seconds", wall)
                _consume_result(cluster, ctx, tracker, tracer, w,
                                list(key), f.result(), next_pending,
                                register=register)
                if len(atts) > 1:
                    # a duplicate existed: exactly one attempt's work
                    # is wasted (the loser's commit is epoch-rejected)
                    reg.inc("speculative_wasted", len(atts) - 1)
                    for (lw, lf, _) in atts:
                        if lf is f or not lf.done():
                            continue
                        lres = lf.result()
                        if not isinstance(lres, Exception) \
                                and not lres.get("error_kind"):
                            # commit the already-finished loser too:
                            # first-writer-wins discards its slots
                            # (write path: its manifests)
                            if register is not None:
                                register(lw, lres)
                            else:
                                tracker.register(lw, lres["shuffle"],
                                                 lres["entries"])
                done_keys.add(key)
    finally:
        # abandon still-running losers; their late replies are never
        # consumed and their slots are epoch-stale by construction
        pool.shutdown(wait=False)


def _handle_fragment_loss(cluster, ctx: ExecCtx, res: dict) -> None:
    """A fragment failed because UPSTREAM data disappeared: mark the
    dead peer, then drive the upstream shuffle's tracker through the
    standard recovery path so its slots are recomputed before the
    fragment retries."""
    from spark_rapids_tpu.exec import recovery
    sid = res.get("lost_sid")
    up = ctx.cache.get(("shuffle", sid, ctx.backend))
    if res.get("error_kind") == "peer_fetch":
        peer = tuple(res.get("peer") or ())
        handle = cluster.worker_by_shuffle_addr(peer)
        if handle is not None:
            cluster.mark_worker_lost(handle.worker_id,
                                     "peer fetch failed in fragment")
        if not isinstance(up, ClusterMapOutputTracker):
            raise ClusterExecError(
                f"fragment lost upstream shuffle {str(sid)[:12]} served "
                f"by {peer}, and no cluster tracker exists to recover it")
        lost = up.mark_worker_lost(handle.worker_id) if handle is not None \
            else {}
        if not lost:
            return  # already recovered by a concurrent reader
        err = MapOutputLostError(sid, -1, lost,
                                 detail="worker lost (reported by peer)")
    else:  # "map_lost": the peer's own store reported structured loss
        lost = {int(k): int(v)
                for k, v in (res.get("lost") or {}).items()}
        if not isinstance(up, ClusterMapOutputTracker) or not lost:
            raise ClusterExecError(
                f"fragment reported lost map outputs for shuffle "
                f"{str(sid)[:12]} but no cluster tracker exists")
        err = MapOutputLostError(sid, int(res.get("part", -1)), lost,
                                 detail="reported by fragment",
                                 observed_empty=bool(
                                     res.get("observed_empty")))
    recovery._recover(ctx, up, err)


# ---------------------------------------------------------------------------
# entry point (hooked from ShuffleExchangeExec._do_shuffle_device)
# ---------------------------------------------------------------------------

#: collapse object ids and other hex runs out of node descriptions:
#: shuffle/plan ids embed ``id(node)``, which never survives a driver
#: restart, so resume matching must hash the fragment's SHAPE instead
_UNSTABLE_HEX = re.compile(r"0x[0-9a-fA-F]+|[0-9a-f]{8,}")


def _stable_fragment_fp(clone) -> str:
    """Restart-stable identity of a map fragment: a digest over the
    clone subtree's node types, hex-scrubbed descriptions, and output
    schemas.  Two plans of the same query in different driver
    processes produce the same fingerprint even though their shuffle
    ids differ — the key the journal uses to hand a recovered
    shuffle's surviving map outputs to the resumed query."""
    h = hashlib.sha1()

    def walk(node):
        h.update(type(node).__name__.encode())
        h.update(_UNSTABLE_HEX.sub("#", node.node_desc()).encode())
        h.update(repr(node.output_schema).encode())
        h.update(b"(")
        for c in getattr(node, "children", None) or ():
            walk(c)
        h.update(b")")

    walk(clone)
    return h.hexdigest()


def cluster_do_shuffle(cluster, exchange, ctx: ExecCtx, child):
    """Materialize one cluster-tagged exchange's map side across the
    worker pool.  Returns the registered ClusterMapOutputTracker, or
    None to signal the caller to fall back to the classic in-process
    path (no live workers, unpicklable fragment, or a poisoned
    subtree)."""
    from spark_rapids_tpu.exec.recovery import conf_fingerprint
    reg = get_registry()
    if not cluster.live_workers():
        reg.inc("cluster.fallback_inprocess")
        return None
    n = exchange.partitioning.num_partitions
    sid = exchange.shuffle_id
    ncpids = child.num_partitions(ctx)
    clone, reason = _clone_fragment(exchange, ctx)
    if clone is None:
        reg.inc("cluster.fallback_inprocess")
        ctx.trace_event("cluster.fallback", "cluster",
                        shuffle=str(sid)[:12], reason=reason)
        return None
    frag_conf = scrub_worker_conf(dict(ctx.conf.settings))
    try:
        pickle.dumps(clone, protocol=pickle.HIGHEST_PROTOCOL)
    # enginelint: disable=RL001 (fallback to the in-process path is the handled outcome; the counter + trace event record it)
    except Exception:  # noqa: BLE001 - any unpicklable node falls back
        reg.inc("cluster.fragment_unpicklable")
        reg.inc("cluster.fallback_inprocess")
        ctx.trace_event("cluster.fallback", "cluster",
                        shuffle=str(sid)[:12],
                        reason="fragment not picklable")
        return None
    tracker = ClusterMapOutputTracker(cluster, ctx, sid, n)
    pending = list(range(ncpids))
    resume_epochs = None
    journal = getattr(cluster, "journal", None)
    if journal is not None:
        fp = _stable_fragment_fp(clone)
        jconf_fp = conf_fingerprint(frag_conf)
        # a recovered driver may hold this exact fragment's surviving
        # map outputs under the OLD shuffle id: claim them (workers
        # re-key their slots to the new id) before opening the new
        # journal record, then seed the tracker and shrink the
        # dispatch frontier to what was actually lost
        claim = None
        claimer = getattr(cluster, "claim_resume", None)
        if callable(claimer):
            claim = claimer(fp, str(sid), n, ncpids, jconf_fp)
        journal.append("shuffle_open", sid=str(sid), fp=fp,
                       num_parts=n, ncpids=ncpids, conf_fp=jconf_fp)
        tracker._journal = journal
        if claim is not None:
            tracker._epochs.update({int(m): int(e) for m, e
                                    in claim["epochs"].items()})
            seeded = 0
            for wid, ents in claim["entries"].items():
                tracker.register(wid, tuple(claim["addrs"][wid]), ents)
                seeded += len(ents)
            done = set(int(c) for c in claim["done"])
            if done:
                journal.append("frontier", sid=str(sid),
                               done=sorted(done))
            pending = [c for c in pending if c not in done]
            resume_epochs = {int(m): int(e) for m, e
                             in claim["epochs"].items()} or None
            reg.inc("cluster.map_outputs_resumed", seeded)
            ctx.trace_event("cluster.resume", "cluster",
                            shuffle=str(sid)[:12], seeded=seeded,
                            done=len(done),
                            recomputing=len(pending))
            lc = getattr(ctx, "lifecycle", None)
            if lc is not None and hasattr(lc, "annotations"):
                lc.annotations.setdefault("cluster.resumed", []).append(
                    {"shuffle": str(sid)[:12], "map_outputs": seeded,
                     "partitions_done": len(done),
                     "partitions_recomputing": len(pending)})
    with ctx.trace_span("cluster.map_stage", "cluster",
                        shuffle=str(sid)[:12], partitions=ncpids,
                        workers=len(cluster.live_workers())):
        _dispatch_fragments(cluster, ctx, tracker, clone, n,
                            pending, frag_conf, epochs=resume_epochs)
    tracer = ctx.tracer
    if tracer is not None:
        # spans a long fragment streamed back on heartbeats MID-run
        # (the completion reply only carries what was left unshipped)
        for ev in cluster.drain_query_spans(ctx.query_id):
            pid = ev.get("pid")
            if isinstance(pid, int):
                h = cluster.worker_by_pid(pid)
                tracer.ensure_lane(pid, f"cluster worker "
                                        f"{h.worker_id if h else pid}")
            tracer.ingest_wall([ev])
    ctx.register_lineage(sid, ClusterLineage(
        exchange_clone=clone, cluster=cluster, tracker=tracker,
        num_parts=n, frag_conf=frag_conf,
        conf_fp=getattr(exchange, "_conf_fp",
                        conf_fingerprint(ctx.conf))))
    reg.inc("cluster.shuffles_clustered")
    return tracker


# ---------------------------------------------------------------------------
# write fragments (hooked from exec/write_exec.run_write_job)
# ---------------------------------------------------------------------------

def dispatch_write_fragments(cluster, ctx: ExecCtx, coordinator,
                             write_node, tasks) -> bool:
    """Run a write job's tasks as cluster write fragments: each worker
    writes its assigned child partitions into private staging dirs under
    the job's ``_staging`` tree and ships back one manifest per task,
    which the driver-side ``coordinator`` arbitrates first-writer-wins.

    Rounds mirror :func:`_dispatch_fragments` — failures/draining
    replies re-pool onto survivors, upstream map loss drives lineage
    recovery, and straggler speculation may run duplicate attempts
    (each under its own attempt id; the coordinator discards the
    loser's manifests).  A task is only considered placed once the
    coordinator holds a winning manifest for it, so a dropped commit
    message re-dispatches the task under a fresh attempt.

    Returns False to signal the in-process fallback (no live workers,
    unpicklable or poisoned fragment body); the caller then runs the
    same attempt/commit protocol on the driver."""
    from concurrent.futures import ThreadPoolExecutor
    from spark_rapids_tpu.cluster.rpc import RpcError, rpc_call
    reg = get_registry()
    if not cluster.live_workers():
        reg.inc("cluster.write_fallback_inprocess")
        return False
    walked, reason = _clone_subtree(write_node.children[0], ctx)
    if walked is None:
        reg.inc("cluster.write_fallback_inprocess")
        ctx.trace_event("cluster.write_fallback", "cluster",
                        job=coordinator.job_id, reason=reason)
        return False
    try:
        pickle.dumps(walked, protocol=pickle.HIGHEST_PROTOCOL)
    # enginelint: disable=RL001 (fallback to the in-process write path is the handled outcome; the counter + trace event record it)
    except Exception:  # noqa: BLE001 - any unpicklable node falls back
        reg.inc("cluster.fragment_unpicklable")
        reg.inc("cluster.write_fallback_inprocess")
        ctx.trace_event("cluster.write_fallback", "cluster",
                        job=coordinator.job_id,
                        reason="fragment not picklable")
        return False
    cluster.register_write_coordinator(coordinator)
    faults = coordinator.faults
    frag_conf = scrub_worker_conf(dict(ctx.conf.settings))
    speculate = SPECULATION_ENABLED.get(ctx.conf.settings)
    wspec = {"path": coordinator.path, "fmt": write_node.fmt,
             "partition_by": list(write_node.partition_by),
             "options": dict(write_node.options),
             "job_id": coordinator.job_id}
    tracer = ctx.tracer
    tasks = sorted(int(t) for t in tasks)
    pending = list(tasks)
    max_rounds = max(4, 2 * len(cluster.workers()) + 2)
    rounds = 0
    with ctx.trace_span("cluster.write_stage", "cluster",
                        job=coordinator.job_id, tasks=len(tasks),
                        workers=len(cluster.live_workers())):
        while pending:
            ctx.check_cancel()
            rounds += 1
            if rounds > max_rounds:
                raise ClusterExecError(
                    f"write job {coordinator.job_id}: fragment dispatch "
                    f"did not converge after {rounds - 1} rounds "
                    f"({len(pending)} tasks without a committed attempt)")
            live = cluster.schedulable_workers()
            if not live:
                raise ClusterExecError(
                    f"write job {coordinator.job_id}: no live workers "
                    "left to run write fragments")
            _refresh_readers(walked, ctx)
            assign = _assign_cpids(pending, live,
                                   _locality(walked, max(pending) + 1))
            handles = {h.worker_id: h for h in live}

            def run_one(wid: str, cps: list[int]):
                if faults is not None:
                    act = faults.check("cluster.worker.slow", worker=wid,
                                       job=coordinator.job_id)
                    if act is not None:
                        time.sleep(act.param("seconds", 2.0))
                    act = faults.check("cluster.worker.flaky", worker=wid,
                                       job=coordinator.job_id)
                    if act is not None:
                        raise RpcError(
                            f"injected fault: flaky worker {wid}")
                    act = faults.check("cluster.worker.dead", worker=wid,
                                       job=coordinator.job_id)
                    if act is not None and len(cluster.live_workers()) > 1:
                        # kill the worker PROCESS shortly after dispatch
                        # so it dies mid-write: its partial attempt dirs
                        # stay in staging, never visible
                        t = threading.Timer(act.param("seconds", 0.15),
                                            cluster.kill_worker,
                                            args=[wid])
                        t.daemon = True
                        t.start()
                attempts = {int(c): coordinator.next_attempt(int(c))
                            for c in cps}
                spec = {"plan": walked, "write": wspec, "cpids": cps,
                        "attempts": attempts, "conf": frag_conf}
                if tracer is not None:
                    spec["trace"] = tracer.trace_header()
                blob = pickle.dumps(spec,
                                    protocol=pickle.HIGHEST_PROTOCOL)
                reg.inc("cluster.write_fragments_dispatched")
                handle = handles.get(wid) or cluster.worker_by_id(wid)
                return rpc_call(handle.rpc_addr, "run_write_fragment",
                                {"job_id": coordinator.job_id},
                                blob=blob, conf=ctx.conf,
                                faults=faults)[0]

            def register(wid: str, res: dict) -> None:
                for m in res.get("manifests") or ():
                    coordinator.register(m)

            next_pending: list[int] = []
            if speculate:
                _dispatch_round_speculative(cluster, ctx, None, tracer,
                                            assign, run_one, next_pending,
                                            register=register)
            else:
                results: dict[str, Any] = {}
                with ThreadPoolExecutor(max_workers=len(assign)) as pool:
                    futs = {wid: pool.submit(run_one, wid, cps)
                            for wid, cps in assign.items()}
                    for wid, fut in futs.items():
                        try:
                            results[wid] = fut.result()
                        except (RpcError, ConnectionError, OSError) as e:
                            results[wid] = e
                for wid, cps in assign.items():
                    _consume_result(cluster, ctx, None, tracer, wid, cps,
                                    results[wid], next_pending,
                                    register=register)
            # re-pool from the coordinator, the single source of truth:
            # a task stays pending until a manifest actually WON —
            # covering both failed attempts and commit messages the
            # io.write.commit.drop point swallowed
            pending = coordinator.missing(tasks)
    return True

"""Control-plane RPC for the cluster runtime (driver <-> worker).

One RPC is two frames on a fresh connection: a JSON control frame
``{op, payload, crc, codec, raw_len}`` and an optional DATA frame
carrying an opaque blob (pickled plan fragments, serialized broadcast
batches).  The wire format deliberately reuses the shuffle data
plane's helpers (shuffle/tcp.py): the same length-prefixed tagged
frames, the same negotiated-checksum scheme (``crc32c`` when the C
binding imports, ``crc32`` otherwise) prefixed to the blob, and the
same codec family (shuffle/compression.py) with an 8-byte raw-size
prefix so the receiver can size the inflate exactly.  Mirrors how the
reference rides its shuffle transport for control traffic instead of
inventing a second wire stack (RapidsShuffleServer handles metadata
requests on the data port).

Fault point ``cluster.rpc.drop`` fires before a dial and surfaces as a
ConnectionError the retry ladder absorbs — proving control-plane
flakiness degrades to retries, not query failure.
"""
from __future__ import annotations

import itertools
import json
import socket
import struct
import threading
import time
import uuid
from collections import OrderedDict

from spark_rapids_tpu.cluster import (RPC_COMPRESSION_CODEC,
                                      RPC_MAX_RETRIES, RPC_TIMEOUT)
from spark_rapids_tpu.obs.registry import get_registry
from spark_rapids_tpu.shuffle.compression import get_codec
from spark_rapids_tpu.shuffle.tcp import (_CRC, _CRC_ALGOS,
                                          _MAX_CTRL_FRAME, _recv_frame,
                                          _send_frame, _TAG_DATA,
                                          _TAG_ERROR, _TAG_JSON)

#: control frames carry op + JSON payload (partition lists, metrics
#: deltas) — bigger than shuffle control traffic, still bounded so a
#: desynced peer can't force a huge allocation
_MAX_RPC_CTRL = 8 << 20
#: blob frames carry pickled fragments / broadcast batches
_MAX_RPC_BLOB = 2 << 30
_RAW_LEN = struct.Struct(">Q")

#: idempotency identity of THIS process's outgoing calls: every
#: ``rpc_call`` carries ``(caller, seq)`` where caller folds in the
#: process id and its cluster epoch.  All retry attempts of one logical
#: call share one key, so a server that already RAN the handler (reply
#: lost in flight) replays the recorded reply instead of re-executing a
#: non-idempotent op — a retried ``run_fragment`` executes once.
_CALLER_ID = uuid.uuid4().hex[:12]
_SEQ = itertools.count(1)
_caller_epoch = 0

#: replies remembered per server for replay-dedup; heartbeats churn
#: through this quickly but a retry lands within a handful of calls
_REPLAY_CACHE_SIZE = 256


def set_caller_epoch(epoch: int) -> None:
    """Fold the driver's cluster epoch into this process's RPC caller
    identity: a recovered driver's calls carry a NEW caller id, so a
    worker's replay cache can never serve it a dead driver's reply."""
    global _caller_epoch
    _caller_epoch = int(epoch)


class RpcError(ConnectionError):
    """Control-plane call failed after retries (peer down, handler
    raised, or frame corruption)."""


class RpcHandlerError(RpcError):
    """The peer's handler raised: the error frame is authoritative and
    retrying the call would re-run the handler — not a transport
    failure, so the retry ladder re-raises it immediately."""


def _crc_of(algo: str, data: bytes) -> int:
    return _CRC_ALGOS[algo](data) & 0xFFFFFFFF


def _pack_blob(blob: bytes, codec_name: str) -> tuple[bytes, dict]:
    """(wire bytes, header fields) for one blob: codec-compress, then
    checksum the COMPRESSED bytes (what the wire actually carries)."""
    codec = get_codec(codec_name)
    raw_len = len(blob)
    body = codec.compress(blob) if codec is not None else blob
    algo = next(iter(_CRC_ALGOS))
    return (_CRC.pack(_crc_of(algo, body)) + _RAW_LEN.pack(raw_len) + body,
            {"codec": codec_name, "crc": algo})


def _unpack_blob(payload: bytes, header: dict, peer: str) -> bytes:
    if len(payload) < _CRC.size + _RAW_LEN.size:
        raise RpcError(f"rpc blob from {peer} truncated "
                       f"({len(payload)} bytes)")
    (want,) = _CRC.unpack(payload[:_CRC.size])
    (raw_len,) = _RAW_LEN.unpack(
        payload[_CRC.size:_CRC.size + _RAW_LEN.size])
    body = payload[_CRC.size + _RAW_LEN.size:]
    algo = header.get("crc", "crc32")
    fn = _CRC_ALGOS.get(algo)
    if fn is None:
        raise RpcError(f"rpc blob from {peer} uses unknown checksum "
                       f"algo {algo!r} (have {list(_CRC_ALGOS)})")
    if (fn(body) & 0xFFFFFFFF) != want:
        raise RpcError(f"rpc blob from {peer} failed {algo} check")
    codec_name = header.get("codec", "none")
    try:
        codec = get_codec(codec_name)
    except (ValueError, RuntimeError) as e:
        raise RpcError(f"rpc blob from {peer} compressed with "
                       f"unsupported codec {codec_name!r}: {e}") from e
    if codec is None:
        return body
    out = codec.decompress(body, raw_len)
    if len(out) != raw_len:
        raise RpcError(f"rpc blob from {peer} inflated to {len(out)} "
                       f"bytes, expected {raw_len}")
    return out


class RpcServer:
    """Serves control-plane ops from a handler table.

    ``handlers`` maps op name -> ``fn(payload: dict, blob: bytes) ->
    (reply: dict, reply_blob: bytes)``.  Each accepted connection gets
    its own thread; one connection serves one call (the callers are
    sparse — fragment dispatch and heartbeats — so connection reuse
    buys nothing and per-call connections keep failure isolation
    trivial)."""

    def __init__(self, handlers: dict, bind: str = "127.0.0.1",
                 port: int = 0, timeout: float | None = None,
                 codec_name: str = "none"):
        self._handlers = dict(handlers)
        self._codec_name = codec_name
        self.metrics = {"rpc_requests": 0, "rpc_errors": 0,
                        "rpc_bytes_in": 0, "rpc_bytes_out": 0,
                        "rpc_replays_deduped": 0}
        # (caller, seq) -> recorded reply frames; a retried call whose
        # handler already ran gets the SAME reply bytes back instead of
        # a second execution
        self._replay_lock = threading.Lock()
        self._replay: OrderedDict = OrderedDict()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((bind, port))
        self._sock.listen(16)
        host, bound_port = self._sock.getsockname()
        self.address = (host, bound_port)
        self._timeout = timeout
        self._closed = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True,
                                        name="tpu-cluster-rpc")
        self._thread.start()

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            conn.settimeout(self._timeout)
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            with conn:
                try:
                    tag, body = _recv_frame(conn, _MAX_RPC_CTRL)
                    req = json.loads(body.decode())
                except (ConnectionError, OSError, ValueError):
                    return
                blob = b""
                if req.get("has_blob"):
                    try:
                        tag, payload = _recv_frame(conn, _MAX_RPC_BLOB)
                        if tag != _TAG_DATA:
                            raise RpcError("expected rpc blob frame, "
                                           f"got tag {tag!r}")
                        blob = _unpack_blob(payload, req, "client")
                    except (ConnectionError, OSError):
                        return
                    except RpcError as e:
                        _send_frame(conn, _TAG_ERROR, str(e).encode())
                        return
                self.metrics["rpc_requests"] += 1
                self.metrics["rpc_bytes_in"] += len(body) + len(blob)
                op = req.get("op", "")
                idem = req.get("idem") or None
                key = ((idem["caller"], idem["seq"])
                       if isinstance(idem, dict) and "caller" in idem
                       and "seq" in idem else None)
                if key is not None:
                    with self._replay_lock:
                        frames = self._replay.get(key)
                        if frames is not None:
                            self._replay.move_to_end(key)
                    if frames is not None:
                        # the handler already ran for this logical call
                        # (the reply was lost in flight): resend the
                        # recorded reply, never re-execute
                        self.metrics["rpc_replays_deduped"] += 1
                        get_registry().inc("cluster.rpc.replays_deduped")
                        for tag2, data2 in frames:
                            _send_frame(conn, tag2, data2)
                        return
                fn = self._handlers.get(op)
                try:
                    if fn is None:
                        raise RpcError(f"unknown rpc op {op!r} "
                                       f"(have {sorted(self._handlers)})")
                    reply, reply_blob = fn(req.get("payload") or {}, blob)
                # enginelint: disable=RL001 (failure is surfaced to the peer as an error frame, not swallowed)
                except Exception as e:  # noqa: BLE001 - sent to peer
                    self.metrics["rpc_errors"] += 1
                    err = f"{type(e).__name__}: {e}".encode()
                    self._remember(key, [(_TAG_ERROR, err)])
                    _send_frame(conn, _TAG_ERROR, err)
                    return
                header: dict = {"ok": True, "payload": reply,
                                "has_blob": bool(reply_blob)}
                wire = b""
                if reply_blob:
                    wire, fields = _pack_blob(reply_blob, self._codec_name)
                    header.update(fields)
                out = json.dumps(header).encode()
                frames = [(_TAG_JSON, out)]
                if wire:
                    frames.append((_TAG_DATA, wire))
                self._remember(key, frames)
                for tag2, data2 in frames:
                    _send_frame(conn, tag2, data2)
                self.metrics["rpc_bytes_out"] += len(out) + len(wire)
        except (ConnectionError, OSError):
            pass

    def _remember(self, key, frames) -> None:
        """Record one handler outcome (success or error frame alike —
        both mean the handler RAN) for replay dedup, bounded LRU."""
        if key is None:
            return
        with self._replay_lock:
            self._replay[key] = frames
            self._replay.move_to_end(key)
            while len(self._replay) > _REPLAY_CACHE_SIZE:
                self._replay.popitem(last=False)

    def close(self) -> None:
        self._closed.set()
        # shutdown() before close(): closing a listening socket does
        # not reliably wake a thread blocked in accept(), which would
        # leak one tpu-cluster-rpc thread per server lifetime
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        self._thread.join(timeout=2.0)


def rpc_call(address, op: str, payload: dict | None = None,
             blob: bytes = b"", conf=None, faults=None,
             timeout: float | None = None,
             retries: int | None = None) -> tuple[dict, bytes]:
    """One control-plane call with a small connection-retry ladder.

    Returns ``(reply_payload, reply_blob)``.  Connection-level failures
    (dial refused, reset, timeout, frame desync) are retried up to
    ``cluster.rpc.maxRetries`` times; an error FRAME from the peer means
    the handler ran and failed — re-raised immediately as
    RpcHandlerError so callers can distinguish "peer down" from "peer
    rejected the op"."""
    settings = getattr(conf, "settings", None) or {}
    if timeout is None:
        timeout = RPC_TIMEOUT.get(settings)
    if retries is None:
        retries = RPC_MAX_RETRIES.get(settings)
    codec_name = RPC_COMPRESSION_CODEC.get(settings)
    reg = get_registry()
    host, port = address
    # ONE idempotency key for every retry attempt of this logical call:
    # if an earlier attempt's handler ran but the reply was lost, the
    # server's replay cache answers the retry without re-executing
    idem = {"caller": f"{_CALLER_ID}.e{_caller_epoch}",
            "seq": next(_SEQ)}
    last: Exception | None = None
    for attempt in range(retries + 1):
        if faults is not None:
            # deterministic control-plane flakiness: the dial "fails"
            # before any bytes move, exactly like a refused connection
            action = faults.check("cluster.rpc.drop", op=op)
            if action is not None:
                reg.inc("cluster.rpc.dropped")
                last = ConnectionError(
                    f"cluster.rpc.drop fault: {op} to {host}:{port}")
                continue
        try:
            t0 = time.perf_counter()
            out = _call_once(host, port, op, payload, blob, codec_name,
                             timeout, idem)
            reg.observe("cluster.rpc.round_trip_seconds",
                        time.perf_counter() - t0)
            return out
        except RpcHandlerError:
            raise
        except (ConnectionError, OSError, ValueError) as e:
            last = e
            reg.inc("cluster.rpc.retries")
    raise RpcError(f"rpc {op} to {host}:{port} failed after "
                   f"{retries + 1} attempts: {last}") from last


def _call_once(host, port, op, payload, blob, codec_name,
               timeout, idem=None) -> tuple[dict, bytes]:
    req: dict = {"op": op, "payload": payload or {},
                 "has_blob": bool(blob)}
    if idem is not None:
        req["idem"] = idem
    wire = b""
    if blob:
        wire, fields = _pack_blob(blob, codec_name)
        req.update(fields)
    with socket.create_connection((host, port), timeout=timeout) as sock:
        sock.settimeout(timeout)
        _send_frame(sock, _TAG_JSON, json.dumps(req).encode())
        if wire:
            _send_frame(sock, _TAG_DATA, wire)
        tag, body = _recv_frame(sock, _MAX_RPC_CTRL)
        if tag == _TAG_ERROR:
            raise RpcHandlerError(
                f"rpc {op} to {host}:{port} rejected: {body.decode()}")
        if tag != _TAG_JSON:
            raise RpcError(f"rpc {op}: expected header frame, got "
                           f"tag {tag!r}")
        header = json.loads(body.decode())
        reply_blob = b""
        if header.get("has_blob"):
            tag, data = _recv_frame(sock, _MAX_RPC_BLOB)
            if tag != _TAG_DATA:
                raise RpcError(f"rpc {op}: expected blob frame, got "
                               f"tag {tag!r}")
            reply_blob = _unpack_blob(data, header, f"{host}:{port}")
        return header.get("payload") or {}, reply_blob

"""Cluster runtime: driver/worker multi-process execution over the DCN
shuffle plane.

``spark.rapids.cluster.mode=local[N]`` turns one TpuSession into a
driver that spawns N worker subprocesses (cluster/worker.py).  The
driver keeps planning, admission, AQE, and broadcast materialization;
map-side shuffle work for clusterable exchanges is sharded over the
workers, each of which hosts its map output in a persistent
LocalShuffleTransport behind the existing TCP shuffle server
(shuffle/tcp.py).  Reduce-side reads stream over the same DCN shuffle
plane via fetch_remote_with_retry, and a dead worker feeds the standard
lineage-recovery machinery (exec/recovery.py) with REASSIGNMENT: lost
map outputs are recomputed on surviving workers.

The reference splits the same roles across Spark's driver/executor
processes (RapidsShuffleManager + RapidsShuffleServer/Client over UCX,
docs: rapids-shuffle.md); here the control plane is cluster/rpc.py —
CRC-framed JSON over TCP reusing the shuffle wire helpers — because the
engine is a standalone runtime without Spark's RPC env.

``cluster.mode=off`` (the default) is byte-identical to the
single-process engine: no tagging pass runs, no cache key is seeded,
no subprocess is spawned.
"""
from __future__ import annotations

import re

from spark_rapids_tpu.conf import (ConfEntry, bool_conf, float_conf,
                                   int_conf, register)

_MODE_RE = re.compile(r"local\[(\d+)\]")

CLUSTER_MODE = register(ConfEntry(
    "spark.rapids.cluster.mode", "off",
    "Cluster execution mode: 'off' runs the classic single-process "
    "engine (byte-identical plans and behavior); 'local[N]' spawns N "
    "worker subprocesses and shards map-side shuffle work for "
    "hash/single-partitioned exchanges across them over the DCN "
    "shuffle plane (cluster/driver.py). Analog of the reference's "
    "multi-executor RapidsShuffleManager deployment.",
    check=lambda v: v == "off" or bool(_MODE_RE.fullmatch(str(v))),
    check_doc="must be off or local[N] with N >= 1"))

HEARTBEAT_INTERVAL = float_conf(
    "spark.rapids.cluster.heartbeat.intervalSeconds", 1.0,
    "How often each worker heartbeats its liveness + metrics delta to "
    "the driver control plane.",
    check=lambda v: v > 0, check_doc="must be > 0")

HEARTBEAT_TIMEOUT = float_conf(
    "spark.rapids.cluster.heartbeat.timeoutSeconds", 10.0,
    "Heartbeat silence after which the driver declares a worker dead, "
    "SIGKILLs the process, and routes its map outputs into lineage "
    "recovery on the surviving workers.",
    check=lambda v: v > 0, check_doc="must be > 0")

RPC_TIMEOUT = float_conf(
    "spark.rapids.cluster.rpc.timeoutSeconds", 120.0,
    "Socket timeout for one control-plane RPC (fragment execution "
    "included, so size it for the slowest plan fragment).",
    check=lambda v: v > 0, check_doc="must be > 0")

RPC_MAX_RETRIES = int_conf(
    "spark.rapids.cluster.rpc.maxRetries", 3,
    "Connection-level retries for one control-plane RPC before the "
    "peer is reported failed to the caller.",
    check=lambda v: v >= 0, check_doc="must be >= 0")

RPC_COMPRESSION_CODEC = register(ConfEntry(
    "spark.rapids.cluster.rpc.compression.codec", "none",
    "Codec for control-plane blob payloads (plan fragments, broadcast "
    "batches): none, lz4, or zstd. Negotiated per call like the "
    "shuffle data plane's codec handshake.",
    check=lambda v: v in ("none", "lz4", "zstd"),
    check_doc="must be none|lz4|zstd"))

WORKER_STARTUP_TIMEOUT = float_conf(
    "spark.rapids.cluster.worker.startupTimeoutSeconds", 60.0,
    "How long the driver waits for a spawned worker subprocess to "
    "print its READY line (imports + JAX init included) before "
    "declaring the launch failed.",
    check=lambda v: v > 0, check_doc="must be > 0")

MIN_WORKERS = int_conf(
    "spark.rapids.cluster.minWorkers", 1,
    "Floor on live (non-retired) workers: ClusterDriver.remove_worker "
    "refuses a removal that would shrink the pool below it. Planned "
    "scale-down cannot strand a cluster with no map-side capacity.",
    check=lambda v: v >= 1, check_doc="must be >= 1")

MAX_WORKERS = int_conf(
    "spark.rapids.cluster.maxWorkers", 0,
    "Ceiling on live (non-retired) workers: ClusterDriver.add_worker "
    "refuses to grow past it. 0 (default): unbounded.",
    check=lambda v: v >= 0, check_doc="must be >= 0")

DRAIN_TIMEOUT = float_conf(
    "spark.rapids.cluster.drain.timeoutSeconds", 30.0,
    "Bound on one graceful drain (remove_worker(drain=True)): waiting "
    "for in-flight fragments to finish plus each map-output migration "
    "RPC. Past the deadline the retiring worker's remaining slots fall "
    "back to lineage recovery instead of blocking removal forever.",
    check=lambda v: v > 0, check_doc="must be > 0")

DEATH_PROBE_TIMEOUT = float_conf(
    "spark.rapids.cluster.death.probeTimeoutSeconds", 2.0,
    "Timeout for the single direct RPC ping the driver sends before a "
    "heartbeat-silence death verdict. A worker that answers (GC pause, "
    "scheduler stall, heartbeat-path congestion) is kept alive instead "
    "of paying a full lineage recompute of everything it holds.",
    check=lambda v: v > 0, check_doc="must be > 0")

SPECULATION_ENABLED = bool_conf(
    "spark.rapids.cluster.speculation.enabled", False,
    "Re-dispatch a duplicate of any map fragment exceeding "
    "speculation.multiplier x the running median fragment wall time "
    "onto another healthy worker; the first attempt to register wins "
    "and the loser's slots are discarded by the map-output tracker's "
    "epoch discipline (exactly-once). Off (default): the dispatch "
    "barrier waits for every fragment, byte-identical to the "
    "pre-elastic scheduler. (reference: spark.speculation)")

SPECULATION_MULTIPLIER = float_conf(
    "spark.rapids.cluster.speculation.multiplier", 3.0,
    "A running fragment is speculation-eligible once its wall time "
    "exceeds this multiple of the round's median completed-fragment "
    "wall time. (reference: spark.speculation.multiplier)",
    check=lambda v: v > 1.0, check_doc="must be > 1.0")

SPECULATION_MIN_RUNTIME = float_conf(
    "spark.rapids.cluster.speculation.minRuntimeSeconds", 1.0,
    "Floor below which no fragment is ever speculated, whatever the "
    "median says — protects sub-second fragments from duplicate "
    "dispatch on scheduling jitter.",
    check=lambda v: v >= 0, check_doc="must be >= 0")

QUARANTINE_MAX_FAILURES = int_conf(
    "spark.rapids.cluster.quarantine.maxFailures", 0,
    "Consecutive dispatch failures (RPC errors or fragment failures) "
    "after which a worker that still answers a direct ping is "
    "QUARANTINED — no new fragments, map outputs still servable — "
    "instead of being declared dead. 0 (default): disabled, any "
    "dispatch failure marks the worker lost exactly as before. "
    "(reference: spark.blacklist.application.maxFailedTasksPerExecutor)",
    check=lambda v: v >= 0, check_doc="must be >= 0")

QUARANTINE_PROBATION = float_conf(
    "spark.rapids.cluster.quarantine.probationSeconds", 30.0,
    "How long a quarantined worker sits out before the monitor "
    "re-admits it to scheduling with a cleared failure count. "
    "(reference: spark.blacklist.timeout)",
    check=lambda v: v > 0, check_doc="must be > 0")

JOURNAL_ENABLED = bool_conf(
    "spark.rapids.cluster.journal.enabled", True,
    "Write-ahead cluster journal (cluster/journal.py): the driver "
    "durably records worker membership, map-output registrations, "
    "write-commit decisions, and dispatch frontiers so a crashed "
    "driver can be rebuilt with ClusterDriver.recover() and resume "
    "queries against lingering workers without recomputing journaled "
    "map outputs. Only consulted in cluster mode — single-process "
    "sessions never import the journal. Disabling it restores the "
    "pre-journal driver byte for byte (a driver crash is then a "
    "cluster-wide reset). (reference: spark.deploy.recoveryMode)")

JOURNAL_DIR = register(ConfEntry(
    "spark.rapids.cluster.journal.dir",
    "",
    "Directory holding the cluster journal (journal.log + "
    "journal.snapshot). Empty (default): a throwaway temp directory, "
    "removed on clean shutdown — recovery across driver processes "
    "needs an explicit, stable path shared by the dead and the "
    "recovering driver. (reference: spark.deploy.recoveryDirectory)"))

JOURNAL_MAX_BYTES = int_conf(
    "spark.rapids.cluster.journal.maxBytes", 4 << 20,
    "Journal log size that triggers snapshot compaction: the replayed "
    "state is written as one snapshot record (tmp + fsync + rename) "
    "and the log restarts empty, so replay cost stays bounded however "
    "long the driver lives. replay(snapshot + tail) is equivalent to "
    "replay(full log) by construction.",
    check=lambda v: v >= 4096, check_doc="must be >= 4096")

REATTACH_GRACE = float_conf(
    "spark.rapids.cluster.driver.reattachGraceSeconds", 0.0,
    "How long a worker lingers after losing its driver (stdin EOF): "
    "it pauses fragment dispatch but keeps its RPC and shuffle "
    "servers up so a recovered driver can RECONNECT and resume "
    "queries against the surviving map outputs; past the grace the "
    "worker self-terminates (no orphans). 0 (default): the worker "
    "exits immediately on driver loss, the pre-journal behavior.",
    check=lambda v: v >= 0, check_doc="must be >= 0")


def parse_cluster_mode(conf) -> int:
    """Number of workers requested by spark.rapids.cluster.mode:
    0 for 'off', N for 'local[N]'."""
    settings = conf.settings if hasattr(conf, "settings") else dict(conf)
    mode = CLUSTER_MODE.get(settings)
    if mode == "off":
        return 0
    m = _MODE_RE.fullmatch(str(mode))
    return int(m.group(1)) if m else 0

"""Cluster runtime: driver/worker multi-process execution over the DCN
shuffle plane.

``spark.rapids.cluster.mode=local[N]`` turns one TpuSession into a
driver that spawns N worker subprocesses (cluster/worker.py).  The
driver keeps planning, admission, AQE, and broadcast materialization;
map-side shuffle work for clusterable exchanges is sharded over the
workers, each of which hosts its map output in a persistent
LocalShuffleTransport behind the existing TCP shuffle server
(shuffle/tcp.py).  Reduce-side reads stream over the same DCN shuffle
plane via fetch_remote_with_retry, and a dead worker feeds the standard
lineage-recovery machinery (exec/recovery.py) with REASSIGNMENT: lost
map outputs are recomputed on surviving workers.

The reference splits the same roles across Spark's driver/executor
processes (RapidsShuffleManager + RapidsShuffleServer/Client over UCX,
docs: rapids-shuffle.md); here the control plane is cluster/rpc.py —
CRC-framed JSON over TCP reusing the shuffle wire helpers — because the
engine is a standalone runtime without Spark's RPC env.

``cluster.mode=off`` (the default) is byte-identical to the
single-process engine: no tagging pass runs, no cache key is seeded,
no subprocess is spawned.
"""
from __future__ import annotations

import re

from spark_rapids_tpu.conf import (ConfEntry, float_conf, int_conf,
                                   register)

_MODE_RE = re.compile(r"local\[(\d+)\]")

CLUSTER_MODE = register(ConfEntry(
    "spark.rapids.cluster.mode", "off",
    "Cluster execution mode: 'off' runs the classic single-process "
    "engine (byte-identical plans and behavior); 'local[N]' spawns N "
    "worker subprocesses and shards map-side shuffle work for "
    "hash/single-partitioned exchanges across them over the DCN "
    "shuffle plane (cluster/driver.py). Analog of the reference's "
    "multi-executor RapidsShuffleManager deployment.",
    check=lambda v: v == "off" or bool(_MODE_RE.fullmatch(str(v))),
    check_doc="must be off or local[N] with N >= 1"))

HEARTBEAT_INTERVAL = float_conf(
    "spark.rapids.cluster.heartbeat.intervalSeconds", 1.0,
    "How often each worker heartbeats its liveness + metrics delta to "
    "the driver control plane.",
    check=lambda v: v > 0, check_doc="must be > 0")

HEARTBEAT_TIMEOUT = float_conf(
    "spark.rapids.cluster.heartbeat.timeoutSeconds", 10.0,
    "Heartbeat silence after which the driver declares a worker dead, "
    "SIGKILLs the process, and routes its map outputs into lineage "
    "recovery on the surviving workers.",
    check=lambda v: v > 0, check_doc="must be > 0")

RPC_TIMEOUT = float_conf(
    "spark.rapids.cluster.rpc.timeoutSeconds", 120.0,
    "Socket timeout for one control-plane RPC (fragment execution "
    "included, so size it for the slowest plan fragment).",
    check=lambda v: v > 0, check_doc="must be > 0")

RPC_MAX_RETRIES = int_conf(
    "spark.rapids.cluster.rpc.maxRetries", 3,
    "Connection-level retries for one control-plane RPC before the "
    "peer is reported failed to the caller.",
    check=lambda v: v >= 0, check_doc="must be >= 0")

RPC_COMPRESSION_CODEC = register(ConfEntry(
    "spark.rapids.cluster.rpc.compression.codec", "none",
    "Codec for control-plane blob payloads (plan fragments, broadcast "
    "batches): none, lz4, or zstd. Negotiated per call like the "
    "shuffle data plane's codec handshake.",
    check=lambda v: v in ("none", "lz4", "zstd"),
    check_doc="must be none|lz4|zstd"))

WORKER_STARTUP_TIMEOUT = float_conf(
    "spark.rapids.cluster.worker.startupTimeoutSeconds", 60.0,
    "How long the driver waits for a spawned worker subprocess to "
    "print its READY line (imports + JAX init included) before "
    "declaring the launch failed.",
    check=lambda v: v > 0, check_doc="must be > 0")


def parse_cluster_mode(conf) -> int:
    """Number of workers requested by spark.rapids.cluster.mode:
    0 for 'off', N for 'local[N]'."""
    settings = conf.settings if hasattr(conf, "settings") else dict(conf)
    mode = CLUSTER_MODE.get(settings)
    if mode == "off":
        return 0
    m = _MODE_RE.fullmatch(str(mode))
    return int(m.group(1)) if m else 0

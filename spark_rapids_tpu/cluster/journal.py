"""Write-ahead cluster journal: the driver's durable memory.

Every layer below the driver already self-heals (fetch retry, lineage
recompute, drain/quarantine/migrate, exactly-once write commits), but
the state that COORDINATES them — worker membership, the map-output
tracker's registrations, write-commit decisions, and each query's
dispatch frontier — lived only in the driver process.  This module
journals exactly that state so ``ClusterDriver.recover()`` can rebuild
a crashed driver and resume queries against lingering workers instead
of resetting the cluster (reference: spark.deploy.recoveryMode's
FILESYSTEM persistence engine, applied to the shuffle/write control
plane rather than app submission).

Disk discipline (same rules as obs/history.py):

* ``journal.log`` is append-only, one CRC-framed record per line
  (``<crc32 hex8> <json>\\n``).  Appends go through GROUP-COMMIT
  fsync: concurrent writers buffer under a lock, the first one through
  the I/O gate flushes and fsyncs the whole accumulated batch, and the
  rest observe durability without paying their own fsync.
* A torn tail (crash mid-write) is healed at open: the log is
  truncated back to the end of the last intact record.  A CRC-corrupt
  record mid-file stops replay at the last good record — everything
  after it is counted in ``journal_truncated_records``, never
  half-applied.
* Past ``spark.rapids.cluster.journal.maxBytes`` the log is
  snapshot-compacted: the fully replayed state is written to
  ``journal.snapshot`` (tmp + fsync + rename) and the log restarts
  empty.  Record application is idempotent by construction (the same
  first-writer-wins epoch rules as the live tracker), so
  replay(snapshot + tail) == replay(full log) even if a crash lands
  between the snapshot rename and the log truncate.

Fault points: ``cluster.journal.torn`` truncates the freshly appended
tail mid-record (a simulated crash inside the write syscall);
``cluster.journal.fsync.fail`` makes the fsync raise — the failure is
absorbed, counted (``journal_fsync_failures``), and the journal
degrades to flush-only rather than failing the query.

Dependency discipline: stdlib + obs.registry only (faults is injected
by the driver), and the module is imported ONLY by cluster-mode
drivers with the journal enabled — single-process sessions never load
it (premerge-asserted).
"""
from __future__ import annotations

import json
import os
import threading
import zlib

from spark_rapids_tpu.obs.registry import get_registry

__all__ = ["ClusterJournal", "JournalState"]

LOG_NAME = "journal.log"
SNAPSHOT_NAME = "journal.snapshot"

#: composite map id stride (mirrors cluster/worker.py MAP_ID_STRIDE;
#: duplicated as a literal so this module stays import-light)
_STRIDE = 1_000_000


def _frame(rec: dict) -> bytes:
    payload = json.dumps(rec, separators=(",", ":"),
                         sort_keys=True).encode("utf-8")
    return b"%08x %s\n" % (zlib.crc32(payload) & 0xFFFFFFFF, payload)


def _parse(line: bytes) -> "dict | None":
    """One framed line -> record, or None when the frame is corrupt
    (bad CRC, bad json, missing separator)."""
    if not line.endswith(b"\n"):
        return None
    body = line[:-1]
    sep = body.find(b" ")
    if sep != 8:
        return None
    try:
        want = int(body[:8], 16)
    except ValueError:
        return None
    payload = body[9:]
    if zlib.crc32(payload) & 0xFFFFFFFF != want:
        return None
    try:
        rec = json.loads(payload)
    except ValueError:
        return None
    return rec if isinstance(rec, dict) else None


class JournalState:
    """The replayed journal: everything a recovering driver cannot
    recompute.  ``apply`` is idempotent — re-applying a record already
    folded in (a compaction race, a duplicated group-commit batch)
    changes nothing, which is what makes snapshot + tail replay exact.
    """

    def __init__(self):
        self.epoch = 0
        #: wid -> {"pid", "rpc", "shuffle", "status": alive|gone}
        self.workers: dict = {}
        #: sid -> {"fp", "num_parts", "ncpids", "conf_fp",
        #:         "addrs": {wid: [h, p]},
        #:         "entries": {(pid, mid): [wid, wslot, size, rows, epoch]},
        #:         "epochs": {mid: epoch}, "done": set(cpids)}
        self.shuffles: dict = {}
        #: job_id -> {"path", "fmt", "winners": {task: manifest},
        #:            "commit": {"renames", "manifest"} | None,
        #:            "committed", "aborted"}
        self.write_jobs: dict = {}
        #: records dropped at the torn/corrupt tail of the last replay
        self.truncated_records = 0

    # -- record application ---------------------------------------------
    def apply(self, rec: dict) -> None:
        k = rec.get("k")
        fn = getattr(self, f"_ap_{k}", None)
        if fn is not None:
            fn(rec)

    def _ap_driver_start(self, r):
        self.epoch = max(self.epoch, int(r.get("epoch", 0)))

    def _ap_worker_ready(self, r):
        self.workers[r["wid"]] = {
            "pid": r.get("pid"), "rpc": r.get("rpc"),
            "shuffle": r.get("shuffle"), "status": "alive"}

    def _ap_worker_gone(self, r):
        w = self.workers.get(r["wid"])
        if w is not None:
            w["status"] = "gone"

    def _ap_shuffle_open(self, r):
        sid = r["sid"]
        if sid not in self.shuffles:
            self.shuffles[sid] = {
                "fp": r.get("fp"), "num_parts": int(r.get("num_parts", 0)),
                "ncpids": int(r.get("ncpids", 0)),
                "conf_fp": r.get("conf_fp"), "addrs": {},
                "entries": {}, "epochs": {}, "done": set()}

    def _ap_map_register(self, r):
        st = self.shuffles.get(r["sid"])
        if st is None:
            return
        wid = r["wid"]
        st["addrs"][wid] = list(r.get("shuffle") or ())
        for mid, pid, wslot, size, rows, epoch in r.get("entries") or ():
            mid, pid, epoch = int(mid), int(pid), int(epoch)
            if epoch < st["epochs"].get(mid, 0):
                continue  # straggler from a pre-invalidation attempt
            old = st["entries"].get((pid, mid))
            if old is not None and epoch <= old[4]:
                continue  # first writer already committed
            st["epochs"][mid] = epoch
            st["entries"][(pid, mid)] = [wid, int(wslot), int(size),
                                         int(rows), epoch]

    def _ap_map_invalidate(self, r):
        st = self.shuffles.get(r["sid"])
        if st is None:
            return
        for mid, epoch in (r.get("epochs") or {}).items():
            mid, epoch = int(mid), int(epoch)
            if epoch < st["epochs"].get(mid, 0):
                continue
            st["epochs"][mid] = epoch
            for key in [key for key in st["entries"] if key[1] == mid]:
                del st["entries"][key]

    def _ap_frontier(self, r):
        st = self.shuffles.get(r["sid"])
        if st is not None:
            st["done"].update(int(c) for c in r.get("done") or ())

    def _ap_shuffle_close(self, r):
        self.shuffles.pop(r["sid"], None)

    def _ap_write_start(self, r):
        self.write_jobs.setdefault(r["job"], {
            "path": r.get("path"), "fmt": r.get("fmt"),
            "winners": {}, "commit": None,
            "committed": False, "aborted": False})

    def _ap_write_win(self, r):
        j = self.write_jobs.get(r["job"])
        if j is not None:
            j["winners"].setdefault(int(r["task"]), r.get("manifest"))

    def _ap_write_commit_begin(self, r):
        j = self.write_jobs.get(r["job"])
        if j is not None and j["commit"] is None:
            j["commit"] = {"renames": [list(p) for p in
                                       r.get("renames") or ()],
                           "manifest": r.get("manifest")}

    def _ap_write_commit_done(self, r):
        j = self.write_jobs.get(r["job"])
        if j is not None:
            j["committed"] = True

    def _ap_write_abort(self, r):
        j = self.write_jobs.get(r["job"])
        if j is not None:
            j["aborted"] = True

    # -- snapshot (de)serialization --------------------------------------
    def to_json(self) -> dict:
        shuffles = {}
        for sid, st in self.shuffles.items():
            shuffles[sid] = {
                "fp": st["fp"], "num_parts": st["num_parts"],
                "ncpids": st["ncpids"], "conf_fp": st["conf_fp"],
                "addrs": st["addrs"],
                "entries": [[pid, mid, *v]
                            for (pid, mid), v in st["entries"].items()],
                "epochs": {str(m): e for m, e in st["epochs"].items()},
                "done": sorted(st["done"])}
        # committed/aborted jobs carry no recovery obligation: drop them
        # at the compaction boundary so the snapshot stays bounded
        jobs = {job: j for job, j in self.write_jobs.items()
                if not (j["committed"] or j["aborted"])}
        return {"epoch": self.epoch, "workers": self.workers,
                "shuffles": shuffles,
                "write_jobs": {job: {**j, "winners": {
                    str(t): m for t, m in j["winners"].items()}}
                    for job, j in jobs.items()}}

    @classmethod
    def from_json(cls, doc: dict) -> "JournalState":
        st = cls()
        st.epoch = int(doc.get("epoch", 0))
        st.workers = dict(doc.get("workers") or {})
        for sid, s in (doc.get("shuffles") or {}).items():
            st.shuffles[sid] = {
                "fp": s.get("fp"), "num_parts": int(s.get("num_parts", 0)),
                "ncpids": int(s.get("ncpids", 0)),
                "conf_fp": s.get("conf_fp"),
                "addrs": dict(s.get("addrs") or {}),
                "entries": {(int(e[0]), int(e[1])):
                            [e[2], int(e[3]), int(e[4]), int(e[5]),
                             int(e[6])]
                            for e in s.get("entries") or ()},
                "epochs": {int(m): int(e) for m, e in
                           (s.get("epochs") or {}).items()},
                "done": set(int(c) for c in s.get("done") or ())}
        for job, j in (doc.get("write_jobs") or {}).items():
            st.write_jobs[job] = {
                "path": j.get("path"), "fmt": j.get("fmt"),
                "winners": {int(t): m for t, m in
                            (j.get("winners") or {}).items()},
                "commit": j.get("commit"),
                "committed": bool(j.get("committed")),
                "aborted": bool(j.get("aborted"))}
        return st

    # -- recovery views ---------------------------------------------------
    def shuffle_done_cpids(self, sid) -> set:
        """Child partitions of one shuffle whose dispatch the journal
        proves COMPLETE: in the journaled frontier AND every journaled
        map output of theirs still present (reconciliation may have
        dropped entries — those cpids must re-dispatch)."""
        st = self.shuffles.get(sid)
        if st is None:
            return set()
        have = {}
        for (pid, mid) in st["entries"]:
            have.setdefault(mid // _STRIDE, set()).add(mid)
        journaled = {}
        for mid in st["epochs"]:
            journaled.setdefault(mid // _STRIDE, set()).add(mid)
        out = set()
        for c in st["done"]:
            # a cpid with zero journaled maps produced no rows at all:
            # the frontier record alone proves it complete
            if journaled.get(c, set()) <= have.get(c, set()):
                out.add(c)
        return out


class ClusterJournal:
    """Append-side handle over one journal directory.  Thread-safe:
    dispatch threads, the tracker's registration path, and the write
    coordinator all append concurrently through the group-commit gate.
    """

    def __init__(self, journal_dir: str, max_bytes: int = 4 << 20,
                 faults=None):
        self.dir = journal_dir
        self.max_bytes = int(max_bytes)
        self._faults = faults
        os.makedirs(journal_dir, exist_ok=True)
        self._log_path = os.path.join(journal_dir, LOG_NAME)
        self._snap_path = os.path.join(journal_dir, SNAPSHOT_NAME)
        self.metrics = {"journal_appends": 0, "journal_fsyncs": 0,
                        "journal_group_commits": 0,
                        "journal_fsync_failures": 0,
                        "journal_snapshots": 0,
                        "journal_truncated_records": 0}
        self._heal_tail()
        self._fh = open(self._log_path, "ab")
        # group commit: _mu guards the buffer/sequence, _io the file.
        # The first appender through _io flushes EVERYTHING buffered so
        # far; appenders whose records it covered observe _durable and
        # return without touching the file.
        self._mu = threading.Lock()
        self._io = threading.Lock()
        self._buf: list[bytes] = []
        self._seq = 0
        self._durable = 0
        self._closed = False
        get_registry().register_object_source("cluster.journal", self)

    # -- append side ------------------------------------------------------
    def append(self, kind: str, **fields) -> None:
        self.append_many([{"k": kind, **fields}])

    def append_many(self, recs) -> None:
        """Durably append the records (one fsync covers every record
        buffered by the time the leader flushes — group commit)."""
        lines = [_frame(r) for r in recs]
        if not lines:
            return
        with self._mu:
            if self._closed:
                return
            self._buf.extend(lines)
            self._seq += len(lines)
            my = self._seq
            self.metrics["journal_appends"] += len(lines)
        while True:
            with self._mu:
                if self._durable >= my or self._closed:
                    return
            with self._io:
                with self._mu:
                    if self._durable >= my or self._closed:
                        return
                    buf, self._buf = self._buf, []
                    top = self._seq
                self._flush_locked(buf)
                with self._mu:
                    self._durable = max(self._durable, top)

    def _flush_locked(self, buf: list) -> None:
        """Write + fsync one group (caller holds ``_io``)."""
        data = b"".join(buf)
        self._fh.write(data)
        self._fh.flush()
        if self._faults is not None:
            act = self._faults.check("cluster.journal.torn")
            if act is not None:
                # a crash mid-write: keep only half of the last record
                # past the previously durable prefix, exactly the state
                # replay's torn-tail healing must absorb
                end = self._fh.tell()
                cut = end - max(1, len(buf[-1]) // 2)
                self._fh.truncate(cut)
                self._fh.seek(cut)
                get_registry().inc("cluster.journal.torn_injected")
        self.metrics["journal_group_commits"] += 1
        try:
            if self._faults is not None and \
                    self._faults.check("cluster.journal.fsync.fail") \
                    is not None:
                raise OSError("injected fault: cluster.journal.fsync.fail")
            os.fsync(self._fh.fileno())
            self.metrics["journal_fsyncs"] += 1
        except OSError:
            # a filesystem that cannot fsync journals at flush-only
            # durability rather than failing the query; the counter is
            # the operator's signal that crash recovery is weakened
            self.metrics["journal_fsync_failures"] += 1
            get_registry().inc("cluster.journal.fsync_failures")
        if self._fh.tell() > self.max_bytes:
            self._compact_locked()

    def _compact_locked(self) -> None:
        """Snapshot-compact under the size bound (caller holds ``_io``;
        the buffer may keep accruing meanwhile).  Crash-safe: the
        snapshot lands via tmp + fsync + rename BEFORE the log is
        truncated, and replay is idempotent, so a crash between the two
        replays snapshot + old log to the identical state."""
        state = self.replay(self.dir, count=False)
        tmp = self._snap_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(_frame({"k": "snapshot", "state": state.to_json()}))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._snap_path)
        self._fh.truncate(0)
        self._fh.seek(0)
        self.metrics["journal_snapshots"] += 1
        get_registry().inc("cluster.journal.snapshots")

    def close(self) -> None:
        with self._mu:
            if self._closed:
                return
            buf, self._buf = self._buf, []
            self._closed = True
        with self._io:
            if buf:
                self._flush_locked(buf)
            try:
                self._fh.close()
            except OSError:
                pass
        get_registry().unregister_source("cluster.journal")

    # -- replay side ------------------------------------------------------
    def _heal_tail(self) -> None:
        """Truncate the log back to the end of its last INTACT record
        (a torn append, or a tail the torn fault cut mid-record).  Run
        before opening for append so new records never chain onto a
        corrupt line."""
        try:
            with open(self._log_path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            return
        good_end, dropped = _scan(raw)[1:]
        if good_end < len(raw):
            with open(self._log_path, "r+b") as f:
                f.truncate(good_end)
            self.metrics["journal_truncated_records"] += dropped
            get_registry().inc("cluster.journal.truncated_records",
                               dropped)

    @classmethod
    def replay(cls, journal_dir: str, count: bool = True) -> JournalState:
        """Rebuild the journaled state: snapshot first (when present),
        then every intact log record in order.  Replay STOPS at the
        first corrupt record — applying records past a corruption could
        interleave state from two torn writes — and the remainder is
        counted as truncated."""
        state = JournalState()
        snap_path = os.path.join(journal_dir, SNAPSHOT_NAME)
        try:
            with open(snap_path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            raw = b""
        if raw:
            recs, _, _ = _scan(raw)
            if recs and recs[0].get("k") == "snapshot":
                state = JournalState.from_json(recs[0].get("state") or {})
        try:
            with open(os.path.join(journal_dir, LOG_NAME), "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            raw = b""
        recs, _, dropped = _scan(raw)
        for rec in recs:
            state.apply(rec)
        state.truncated_records = dropped
        if dropped and count:
            get_registry().inc("cluster.journal.truncated_records",
                               dropped)
        return state


def _scan(raw: bytes):
    """Parse a framed byte stream -> (records, byte offset of the end
    of the last intact record, count of dropped trailing lines)."""
    recs: list[dict] = []
    pos = 0
    good_end = 0
    dropped = 0
    while pos < len(raw):
        nl = raw.find(b"\n", pos)
        if nl < 0:
            dropped += 1  # torn tail: no terminator
            break
        line = raw[pos:nl + 1]
        rec = _parse(line)
        if rec is None:
            # corrupt record: stop here — every complete line after it
            # is dropped too (replay must not skip-and-continue past a
            # corruption, order is the correctness contract)
            dropped += 1 + raw.count(b"\n", nl + 1)
            if not raw.endswith(b"\n"):
                dropped += 1
            break
        recs.append(rec)
        pos = nl + 1
        good_end = pos
    return recs, good_end, dropped

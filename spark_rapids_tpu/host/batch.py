"""Host-side columnar batch: the CPU engine's data representation.

This plays two roles, mirroring the reference architecture:

* the CPU *oracle* engine operates on these (the reference uses CPU Spark
  itself as the differential-test oracle,
  tests/SparkQueryCompareTestSuite.scala:153-167 — here the CPU engine is
  part of the framework, since we are standalone);
* the host staging format for device transfer (reference
  RapidsHostColumnVector.java, HostColumnarToGpu.scala).

Representation: numpy ``data`` + bool ``validity`` per column.  Strings use
numpy ``object`` arrays of ``str`` (exact semantics beat packing on the
oracle path); dates are int32 days since epoch, timestamps int64 micros —
the same physical encoding the device uses
(:mod:`spark_rapids_tpu.columnar.column`).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import numpy as np

from spark_rapids_tpu import types as T

__all__ = ["HostColumn", "HostBatch"]


@dataclass(frozen=True)
class HostColumn:
    """One host column. ``data`` entries at invalid slots are unspecified
    (kept zeroed / None by constructors for determinism)."""

    data: np.ndarray        # object ndarray for strings, else typed ndarray
    validity: np.ndarray    # bool ndarray, same length
    dtype: T.DataType

    def __len__(self) -> int:
        return len(self.data)

    @property
    def is_string(self) -> bool:
        return isinstance(self.dtype, T.StringType)

    @staticmethod
    def from_values(values: Sequence, dtype: T.DataType) -> "HostColumn":
        """Build from a python sequence; ``None`` entries become nulls.
        date/datetime values convert to days/micros since epoch."""
        import datetime as _dt
        n = len(values)
        validity = np.array([v is not None for v in values], dtype=np.bool_)
        if isinstance(dtype, T.StringType):
            data = np.array([v if v is not None else None for v in values],
                            dtype=object)
        elif isinstance(dtype, T.ArrayType):
            data = np.empty(n, dtype=object)
            for i, v in enumerate(values):
                data[i] = None if v is None else list(v)
        elif isinstance(dtype, T.MapType):
            data = np.empty(n, dtype=object)
            for i, v in enumerate(values):
                data[i] = None if v is None else dict(v)
        else:
            npdt = dtype.np_dtype
            data = np.zeros(n, dtype=npdt)
            for i, v in enumerate(values):
                if v is None:
                    continue
                if isinstance(v, _dt.datetime):
                    if v.tzinfo is None:
                        v = v.replace(tzinfo=_dt.timezone.utc)
                    epoch = _dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc)
                    v = round((v - epoch).total_seconds() * 1e6)
                elif isinstance(v, _dt.date):
                    v = (v - _dt.date(1970, 1, 1)).days
                data[i] = v
        return HostColumn(data, validity, dtype)

    @staticmethod
    def from_numpy(data: np.ndarray, validity: np.ndarray | None,
                   dtype: T.DataType) -> "HostColumn":
        if validity is None:
            validity = np.ones(len(data), dtype=np.bool_)
        return HostColumn(data, validity, dtype)

    def to_list(self) -> list:
        """Python values with None for nulls (test/collect surface);
        date/timestamp come back as datetime.date / datetime.datetime."""
        import datetime as _dt
        is_date = isinstance(self.dtype, T.DateType)
        is_ts = isinstance(self.dtype, T.TimestampType)
        out = []
        for i in range(len(self.data)):
            if not self.validity[i]:
                out.append(None)
            elif self.is_string:
                out.append(self.data[i])
            elif isinstance(self.dtype, T.ArrayType):
                out.append(list(self.data[i]))
            elif isinstance(self.dtype, T.MapType):
                out.append(dict(self.data[i]))
            elif is_date:
                out.append(_dt.date(1970, 1, 1)
                           + _dt.timedelta(days=int(self.data[i])))
            elif is_ts:
                out.append(_dt.datetime(1970, 1, 1)
                           + _dt.timedelta(microseconds=int(self.data[i])))
            else:
                out.append(self.data[i].item())
        return out

    def take(self, indices: np.ndarray) -> "HostColumn":
        return HostColumn(self.data[indices], self.validity[indices], self.dtype)

    def filter(self, mask: np.ndarray) -> "HostColumn":
        return HostColumn(self.data[mask], self.validity[mask], self.dtype)


class HostBatch:
    """A host columnar batch with a schema."""

    __slots__ = ("columns", "schema")

    def __init__(self, columns: Sequence[HostColumn], schema: T.Schema):
        self.columns = tuple(columns)
        self.schema = schema
        if columns:
            n = len(columns[0])
            assert all(len(c) == n for c in columns), "ragged batch"

    @property
    def num_rows(self) -> int:
        return len(self.columns[0]) if self.columns else 0

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    def column(self, i: int) -> HostColumn:
        return self.columns[i]

    # ------------------------------------------------------------------
    @staticmethod
    def from_pydict(data: dict, schema: T.Schema) -> "HostBatch":
        cols = [HostColumn.from_values(data[f.name], f.data_type)
                for f in schema]
        return HostBatch(cols, schema)

    def to_pydict(self) -> dict:
        return {f.name: c.to_list()
                for f, c in zip(self.schema, self.columns)}

    def to_rows(self) -> list[tuple]:
        cols = [c.to_list() for c in self.columns]
        return list(zip(*cols)) if cols else []

    # ------------------------------------------------------------------
    @staticmethod
    def from_arrow(rb) -> "HostBatch":
        import pyarrow as pa
        schema = T.Schema.from_arrow(rb.schema)
        n = rb.num_rows
        cols = []
        for i, field in enumerate(schema):
            arr = rb.column(i)
            if isinstance(arr, pa.ChunkedArray):
                arr = arr.combine_chunks()
            if arr.null_count == 0:
                validity = np.ones(n, dtype=np.bool_)
            else:
                validity = np.asarray(arr.is_valid(), dtype=np.bool_)
            dt = field.data_type
            if isinstance(dt, T.StringType):
                data = np.array(arr.to_pylist(), dtype=object)
            elif isinstance(dt, T.ArrayType):
                data = np.empty(n, dtype=object)
                for j, v in enumerate(arr.to_pylist()):
                    data[j] = v
            elif isinstance(dt, T.MapType):
                data = T.arrow_map_to_numpy(arr)
            else:
                data = T.arrow_fixed_to_numpy(arr, dt)
            cols.append(HostColumn(data, validity, dt))
        return HostBatch(cols, schema)

    def to_arrow(self):
        import pyarrow as pa
        arrays = []
        for f, c in zip(self.schema, self.columns):
            mask = ~c.validity
            at = T.to_arrow(f.data_type)
            if c.is_string:
                py = [None if m else v for v, m in zip(c.data, mask)]
                arrays.append(pa.array(py, type=pa.string()))
            elif isinstance(f.data_type, T.ArrayType):
                py = [None if m else list(v) for v, m in zip(c.data, mask)]
                arrays.append(pa.array(py, type=at))
            elif isinstance(f.data_type, T.MapType):
                py = [None if m else sorted(v.items())
                      for v, m in zip(c.data, mask)]
                arrays.append(pa.array(py, type=at))
            elif isinstance(f.data_type, (T.DateType, T.TimestampType)):
                base = pa.array(c.data, mask=mask)
                arrays.append(base.cast(at))
            else:
                arrays.append(pa.array(c.data, type=at, mask=mask))
        return pa.RecordBatch.from_arrays(arrays, schema=self.schema.to_arrow())

    # ------------------------------------------------------------------
    def to_device(self, capacity: int | None = None,
                  string_widths: dict | None = None):
        """H2D: build a ColumnBatch (via Arrow staging)."""
        from spark_rapids_tpu.columnar.batch import ColumnBatch
        return ColumnBatch.from_arrow(self.to_arrow(), capacity=capacity,
                                      string_widths=string_widths)

    @staticmethod
    def from_device(batch) -> "HostBatch":
        """D2H: materialize a ColumnBatch on host."""
        return HostBatch.from_arrow(batch.to_arrow())

    # ------------------------------------------------------------------
    def take(self, indices: np.ndarray) -> "HostBatch":
        return HostBatch([c.take(indices) for c in self.columns], self.schema)

    def filter(self, mask: np.ndarray) -> "HostBatch":
        return HostBatch([c.filter(mask) for c in self.columns], self.schema)

    def slice(self, start: int, length: int) -> "HostBatch":
        idx = np.arange(start, min(start + length, self.num_rows))
        return self.take(idx)

    @staticmethod
    def concat(batches: Sequence["HostBatch"]) -> "HostBatch":
        assert batches
        schema = batches[0].schema
        cols = []
        for ci in range(batches[0].num_columns):
            parts = [b.columns[ci] for b in batches]
            if parts[0].data.dtype == object:
                data = np.concatenate([p.data for p in parts]) if parts else \
                    np.zeros(0, object)
            else:
                data = np.concatenate([p.data for p in parts])
            validity = np.concatenate([p.validity for p in parts])
            cols.append(HostColumn(data, validity, parts[0].dtype))
        return HostBatch(cols, schema)

    @staticmethod
    def empty(schema: T.Schema) -> "HostBatch":
        cols = []
        for f in schema:
            if isinstance(f.data_type,
                          (T.StringType, T.ArrayType, T.MapType)):
                data = np.zeros(0, dtype=object)
            else:
                data = np.zeros(0, dtype=f.data_type.np_dtype)
            cols.append(HostColumn(data, np.zeros(0, np.bool_), f.data_type))
        return HostBatch(cols, schema)

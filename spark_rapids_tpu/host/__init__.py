from spark_rapids_tpu.host.batch import HostColumn, HostBatch

__all__ = ["HostColumn", "HostBatch"]

"""Columnar file writers: Parquet / ORC / CSV.

Reference: GpuParquetFileFormat.scala, GpuOrcFileFormat.scala,
ColumnarOutputWriter (ColumnarFileFormat.scala:57), GpuFileFormatWriter
(Spark write protocol: one part file per partition, _SUCCESS marker).
TPU path: batches come back D2H as Arrow and pyarrow writes them — the
host-encode mirror of the host-decode scan path.
"""
from __future__ import annotations

import os
import uuid
from typing import Iterator

from spark_rapids_tpu.columnar.batch import ColumnBatch
from spark_rapids_tpu.exec.core import ExecCtx, PlanNode
from spark_rapids_tpu.host.batch import HostBatch

__all__ = ["write_parquet", "write_orc", "write_csv"]


def _arrow_batches(plan: PlanNode, ctx: ExecCtx, pid: int) -> Iterator:
    """One partition's output as pyarrow RecordBatches."""
    import pyarrow as pa
    schema = plan.output_schema.to_arrow()
    for b in plan.partition_iter(ctx, pid):
        if isinstance(b, ColumnBatch):
            rb = b.to_arrow()
        else:
            rb = _host_to_arrow(b)
        if rb.num_rows:
            yield rb.cast(schema) if rb.schema != schema else rb


def _host_to_arrow(b: HostBatch):
    import pyarrow as pa
    from spark_rapids_tpu import types as T
    arrays = []
    for f, c in zip(b.schema, b.columns):
        at = T.to_arrow(f.data_type)
        mask = ~c.validity
        if isinstance(f.data_type, T.StringType):
            arrays.append(pa.array(
                [None if m else v for v, m in zip(c.data, mask)], type=at))
        elif isinstance(f.data_type, (T.DateType, T.TimestampType)):
            arrays.append(pa.Array.from_buffers(
                at, len(c.data),
                pa.array(c.data.astype(
                    "int32" if isinstance(f.data_type, T.DateType)
                    else "int64"), mask=mask).buffers()))
        else:
            arrays.append(pa.array(c.data, type=at, mask=mask))
    return pa.RecordBatch.from_arrays(arrays, schema=b.schema.to_arrow())


def _write(plan: PlanNode, path: str, fmt: str, ctx: ExecCtx | None = None,
           **options) -> list[str]:
    """Write the plan's output as one part file per partition under
    ``path`` (Spark directory-output protocol), returning written files."""
    import pyarrow as pa
    ctx = ctx or ExecCtx()
    os.makedirs(path, exist_ok=True)
    job_id = uuid.uuid4().hex[:8]
    schema = plan.output_schema.to_arrow()
    written: list[str] = []
    for pid in range(plan.num_partitions(ctx)):
        batches = list(_arrow_batches(plan, ctx, pid))
        if not batches and (written or pid != plan.num_partitions(ctx) - 1):
            continue
        # empty result: still emit one schema-bearing empty part file
        # (Spark's write protocol) so the output stays readable
        fname = os.path.join(
            path, f"part-{pid:05d}-{job_id}.{fmt}")
        table = pa.Table.from_batches(batches, schema=schema) if batches \
            else schema.empty_table()
        if fmt == "parquet":
            import pyarrow.parquet as pq
            pq.write_table(table, fname, **options)
        elif fmt == "orc":
            import pyarrow.orc as orc
            orc.write_table(table, fname)
        elif fmt == "csv":
            import pyarrow.csv as pc
            pc.write_csv(table, fname)
        else:
            raise ValueError(fmt)
        written.append(fname)
    # commit marker (Spark's _SUCCESS protocol)
    open(os.path.join(path, "_SUCCESS"), "w").close()
    return written


def write_parquet(plan: PlanNode, path: str, ctx: ExecCtx | None = None,
                  **options) -> list[str]:
    return _write(plan, path, "parquet", ctx, **options)


def write_orc(plan: PlanNode, path: str, ctx: ExecCtx | None = None
              ) -> list[str]:
    return _write(plan, path, "orc", ctx)


def write_csv(plan: PlanNode, path: str, ctx: ExecCtx | None = None
              ) -> list[str]:
    return _write(plan, path, "csv", ctx)

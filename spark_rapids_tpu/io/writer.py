"""Columnar file writers: Parquet / ORC / CSV + dynamic partitioning.

Reference: GpuParquetFileFormat.scala, GpuOrcFileFormat.scala,
ColumnarOutputWriter (ColumnarFileFormat.scala:57), GpuFileFormatWriter
(Spark write protocol incl. dynamic-partition writes,
GpuFileFormatWriter.scala:338, GpuFileFormatDataWriter.scala:419 —
single-directory and ``partitionBy`` concurrent-writer protocols) and
BasicColumnarWriteStatsTracker (per-task files/rows/bytes stats).
TPU path: batches come back D2H as Arrow and pyarrow writes them — the
host-encode mirror of the host-decode scan path.
"""
from __future__ import annotations

import json
import os
import shutil
import threading
import time
import uuid
import zlib
from dataclasses import dataclass, field
from typing import Iterator, Sequence

from spark_rapids_tpu.columnar.batch import ColumnBatch
from spark_rapids_tpu.conf import bool_conf, float_conf, int_conf
from spark_rapids_tpu.exec.core import ExecCtx, PlanNode
from spark_rapids_tpu.host.batch import HostBatch

__all__ = ["write_parquet", "write_orc", "write_csv", "WriteStats",
           "WriteCommitCoordinator", "WriteCommitError",
           "WriteIntegrityError", "write_task_attempt", "verify_manifest",
           "staging_attempt_dir", "gc_staging", "MANIFEST_NAME",
           "STAGING_DIR"]

#: job-commit manifest written atomically next to the data files
MANIFEST_NAME = "_MANIFEST.json"
#: per-job staging subtree under the output directory; `_`-prefixed so
#: directory scans never see attempt files (Spark `_temporary` analog)
STAGING_DIR = "_staging"

WRITE_TRANSACTIONAL = bool_conf(
    "spark.rapids.io.write.transactional.enabled", True,
    "Route DataFrame writes through the transactional write plane: "
    "task attempts write to private staging directories, a "
    "first-writer-wins commit coordinator picks one attempt per task, "
    "and an atomic rename-based job commit publishes the files plus a "
    "_MANIFEST.json. Off = legacy direct in-place writer (no "
    "exactly-once guarantee under retries/speculation).")

WRITE_CLUSTER_ENABLED = bool_conf(
    "spark.rapids.io.write.cluster.enabled", True,
    "With cluster mode on, dispatch write tasks to workers as write "
    "fragments (each worker writes its partitions into staging and "
    "ships back manifests). Off = the driver runs every write task "
    "in-process even when a cluster is attached.")

WRITE_TASK_MAX_ATTEMPTS = int_conf(
    "spark.rapids.io.write.task.maxAttempts", 4,
    "Maximum attempts per write task before the job aborts. Each "
    "retry gets a fresh attempt id and a fresh staging directory; "
    "failed attempts leave only garbage-collectable staging files.")

WRITE_RENAME_RETRIES = int_conf(
    "spark.rapids.io.write.commit.renameRetries", 2,
    "Extra retries for each staging->final rename during job commit "
    "before the commit rolls back and the job aborts.")

WRITE_STAGING_GC = bool_conf(
    "spark.rapids.io.write.staging.gc.enabled", True,
    "Garbage-collect leftover _staging/<job> trees from previous "
    "crashed or aborted jobs (older than the TTL) when a new write "
    "job starts on the same output directory.")

WRITE_STAGING_TTL = float_conf(
    "spark.rapids.io.write.staging.gc.ttlSeconds", 0.0,
    "Minimum age in seconds before a leftover staging tree is "
    "garbage-collected by a later job on the same directory. 0 = any "
    "staging tree not owned by the running job is collected.")

WRITE_VERIFY_CRC_ON_SCAN = bool_conf(
    "spark.rapids.io.write.verifyCrcOnScan", False,
    "On scans of a directory carrying a _MANIFEST.json, recompute each "
    "manifest file's CRC32 before reading and fail the scan on "
    "mismatch (read-back footer verification; costs one extra pass "
    "over the files).")


class WriteCommitError(RuntimeError):
    """Job-level write/commit failure (task attempts exhausted, rename
    failure after retries, commit after abort)."""


class WriteIntegrityError(RuntimeError):
    """Committed output failed read-back verification (missing file,
    size or CRC mismatch against _MANIFEST.json)."""


@dataclass
class WriteStats:
    """Job-level write statistics (reference
    BasicColumnarWriteStatsTracker/BasicWriteJobStatsTracker)."""
    num_files: int = 0
    num_rows: int = 0
    num_bytes: int = 0
    partitions: list = field(default_factory=list)  # dynamic partition dirs

    def _add_file(self, path: str, rows: int) -> None:
        self.num_files += 1
        self.num_rows += rows
        try:
            self.num_bytes += os.path.getsize(path)
        except OSError:
            pass


def _arrow_batches(plan: PlanNode, ctx: ExecCtx, pid: int) -> Iterator:
    """One partition's output as pyarrow RecordBatches."""
    import pyarrow as pa
    schema = plan.output_schema.to_arrow()
    for b in plan.partition_iter(ctx, pid):
        if isinstance(b, ColumnBatch):
            rb = b.to_arrow()
        else:
            rb = _host_to_arrow(b)
        if rb.num_rows:
            yield rb.cast(schema) if rb.schema != schema else rb


def _host_to_arrow(b: HostBatch):
    import pyarrow as pa
    from spark_rapids_tpu import types as T
    arrays = []
    for f, c in zip(b.schema, b.columns):
        at = T.to_arrow(f.data_type)
        mask = ~c.validity
        if isinstance(f.data_type, T.StringType):
            arrays.append(pa.array(
                [None if m else v for v, m in zip(c.data, mask)], type=at))
        elif isinstance(f.data_type, (T.DateType, T.TimestampType)):
            arrays.append(pa.Array.from_buffers(
                at, len(c.data),
                pa.array(c.data.astype(
                    "int32" if isinstance(f.data_type, T.DateType)
                    else "int64"), mask=mask).buffers()))
        else:
            arrays.append(pa.array(c.data, type=at, mask=mask))
    return pa.RecordBatch.from_arrays(arrays, schema=b.schema.to_arrow())


def _write_table(table, fname: str, fmt: str, **options) -> None:
    if fmt == "parquet":
        import pyarrow.parquet as pq
        pq.write_table(table, fname, **options)
    elif fmt == "orc":
        import pyarrow.orc as orc
        orc.write_table(table, fname)
    elif fmt == "csv":
        import pyarrow.csv as pc
        pc.write_csv(table, fname)
    else:
        raise ValueError(fmt)


def _partition_dir_value(v) -> str:
    """Hive-style directory encoding (Spark __HIVE_DEFAULT_PARTITION__
    for nulls)."""
    if v is None:
        return "__HIVE_DEFAULT_PARTITION__"
    s = str(v)
    return "".join("%%%02X" % ord(ch) if ch in '/\\:*?"<>|%' else ch
                   for ch in s)


def _write(plan: PlanNode, path: str, fmt: str, ctx: ExecCtx | None = None,
           partition_by: Sequence[str] | None = None,
           stats: WriteStats | None = None, **options) -> list[str]:
    """Write the plan's output under ``path`` (Spark directory-output
    protocol), returning written files.

    ``partition_by``: dynamic-partition writes — rows split by the named
    columns into hive-style ``col=value/`` directories, the partition
    columns dropped from the file contents (reference
    GpuFileFormatWriter.scala:338 dynamic-partition protocol)."""
    import pyarrow as pa
    ctx = ctx or ExecCtx()
    stats = stats if stats is not None else WriteStats()
    os.makedirs(path, exist_ok=True)
    job_id = uuid.uuid4().hex[:8]
    schema = plan.output_schema.to_arrow()
    written: list[str] = []
    seen_dirs: set[str] = set()

    if partition_by:
        names = plan.output_schema.names
        missing = [c for c in partition_by if c not in names]
        if missing:
            raise ValueError(f"partitionBy columns not in output: {missing}")
        data_cols = [n for n in names if n not in partition_by]
        if not data_cols:
            raise ValueError("partitionBy cannot cover every column")

    for pid in range(plan.num_partitions(ctx)):
        batches = list(_arrow_batches(plan, ctx, pid))
        if not partition_by:
            if not batches and (written or
                                pid != plan.num_partitions(ctx) - 1):
                continue
            # empty result: still emit one schema-bearing empty part file
            # (Spark's write protocol) so the output stays readable
            fname = os.path.join(path, f"part-{pid:05d}-{job_id}.{fmt}")
            table = pa.Table.from_batches(batches, schema=schema) \
                if batches else schema.empty_table()
            _write_table(table, fname, fmt, **options)
            written.append(fname)
            stats._add_file(fname, table.num_rows)
            continue
        # dynamic-partition path: group each batch's rows by the
        # partition-column tuple, append to per-directory part files
        if not batches:
            continue
        table = pa.Table.from_batches(batches, schema=schema)
        import pyarrow.compute as _pc  # host-side job driver, single thread
        keys = [table.column(c) for c in partition_by]
        combos = pa.Table.from_arrays(keys, names=list(partition_by)) \
            .group_by(list(partition_by)).aggregate([]).to_pylist()
        for combo in combos:
            mask = None
            for c in partition_by:
                v = combo[c]
                column = table.column(c)
                if v is None:
                    cm = _pc.is_null(column)
                elif isinstance(v, float) and v != v:
                    # NaN partition value: equal() matches nothing
                    cm = _pc.is_nan(column)
                else:
                    cm = _pc.equal(column, pa.scalar(v))
                mask = cm if mask is None else _pc.and_(mask, cm)
            part = table.filter(mask).select(data_cols)
            d = os.path.join(path, *(
                f"{c}={_partition_dir_value(combo[c])}"
                for c in partition_by))
            os.makedirs(d, exist_ok=True)
            if d not in seen_dirs:
                seen_dirs.add(d)
                stats.partitions.append(os.path.relpath(d, path))
            fname = os.path.join(d, f"part-{pid:05d}-{job_id}.{fmt}")
            _write_table(part, fname, fmt, **options)
            written.append(fname)
            stats._add_file(fname, part.num_rows)
    # commit marker (Spark's _SUCCESS protocol)
    open(os.path.join(path, "_SUCCESS"), "w").close()
    return written


def write_parquet(plan: PlanNode, path: str, ctx: ExecCtx | None = None,
                  partition_by: Sequence[str] | None = None,
                  stats: WriteStats | None = None, **options) -> list[str]:
    return _write(plan, path, "parquet", ctx, partition_by=partition_by,
                  stats=stats, **options)


def write_orc(plan: PlanNode, path: str, ctx: ExecCtx | None = None,
              partition_by: Sequence[str] | None = None,
              stats: WriteStats | None = None) -> list[str]:
    return _write(plan, path, "orc", ctx, partition_by=partition_by,
                  stats=stats)


def write_csv(plan: PlanNode, path: str, ctx: ExecCtx | None = None,
              partition_by: Sequence[str] | None = None,
              stats: WriteStats | None = None) -> list[str]:
    return _write(plan, path, "csv", ctx, partition_by=partition_by,
                  stats=stats)


# ---------------------------------------------------------------------------
# Transactional write plane: task-attempt staging + manifest + job commit.
#
# Two-phase protocol (reference: Spark's HadoopMapReduceCommitProtocol
# under GpuFileFormatWriter; here attempt-granular because the cluster
# runtime speculates and re-dispatches fragments):
#
#   1. every task ATTEMPT writes its files into a private staging dir
#      ``<out>/_staging/<job>/task-NNNNN-aNN/`` and produces a manifest
#      (relative paths, rows, bytes, per-file CRC32 of the on-disk
#      bytes — read back after write, so the manifest attests what the
#      filesystem actually holds);
#   2. the driver-side WriteCommitCoordinator accepts the FIRST manifest
#      per task (first-writer-wins, the map-output tracker's epoch-guard
#      discipline) and discards duplicates from speculation / retries /
#      drain re-dispatch;
#   3. job commit renames each winning file into place (os.replace —
#      atomic on POSIX), publishes ``_MANIFEST.json`` via tmp+replace,
#      drops ``_SUCCESS``, and removes the staging tree.
#
# Any crash before step 3 completes leaves only `_`-prefixed paths
# (staging dirs, tmp manifest) that scans never see and a later job
# garbage-collects — never visible partial output.
# ---------------------------------------------------------------------------


def staging_attempt_dir(path: str, job_id: str, task: int,
                        attempt: int) -> str:
    """Private staging directory for one task attempt."""
    return os.path.join(path, STAGING_DIR, job_id,
                        f"task-{task:05d}-a{attempt:02d}")


def _file_crc32(fname: str) -> int:
    crc = 0
    with open(fname, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                return crc
            crc = zlib.crc32(chunk, crc)


def write_task_attempt(plan: PlanNode, ctx: ExecCtx, task: int,
                       attempt_dir: str, fmt: str,
                       partition_by: Sequence[str] | None, options: dict,
                       *, job_id: str, attempt: int, worker: str = "driver",
                       faults=None) -> dict:
    """Run ONE task attempt: write partition ``task`` of ``plan`` into
    ``attempt_dir`` and return its manifest.  Runs on the driver or on a
    cluster worker; nothing here touches the final directory.

    The ``io.write.partial`` fault point fires after each file is
    written (a ``truncate`` action first shears the file) and raises —
    simulating a task death mid-write that leaves a partial staging dir
    behind."""
    import pyarrow as pa
    from spark_rapids_tpu.faults import InjectedFault
    from spark_rapids_tpu.obs.registry import get_registry

    schema = plan.output_schema.to_arrow()
    options = dict(options or {})
    manifest = {"task": int(task), "attempt": int(attempt),
                "worker": worker, "files": [], "partitions": []}

    def emit(table, rel: str) -> None:
        fname = os.path.join(attempt_dir, rel)
        os.makedirs(os.path.dirname(fname), exist_ok=True)
        _write_table(table, fname, fmt, **options)
        if faults is not None:
            act = faults.check("io.write.partial", task=task,
                               attempt=attempt, worker=worker,
                               file=os.path.basename(rel))
            if act is not None:
                if act.action == "truncate":
                    with open(fname, "r+b") as f:
                        f.truncate(max(1, os.path.getsize(fname) // 2))
                raise InjectedFault(
                    f"io.write.partial: task {task} attempt {attempt} "
                    f"died after {rel}")
        manifest["files"].append({
            "rel": rel, "rows": int(table.num_rows),
            "bytes": os.path.getsize(fname), "crc32": _file_crc32(fname)})

    batches = list(_arrow_batches(plan, ctx, task))
    base = f"part-{task:05d}-{job_id}-a{attempt:02d}.{fmt}"
    if not partition_by:
        if batches:
            emit(pa.Table.from_batches(batches, schema=schema), base)
    elif batches:
        import pyarrow.compute as _pc
        names = plan.output_schema.names
        missing = [c for c in partition_by if c not in names]
        if missing:
            raise ValueError(f"partitionBy columns not in output: {missing}")
        data_cols = [n for n in names if n not in partition_by]
        if not data_cols:
            raise ValueError("partitionBy cannot cover every column")
        table = pa.Table.from_batches(batches, schema=schema)
        keys = [table.column(c) for c in partition_by]
        combos = pa.Table.from_arrays(keys, names=list(partition_by)) \
            .group_by(list(partition_by)).aggregate([]).to_pylist()
        for combo in combos:
            mask = None
            for c in partition_by:
                v = combo[c]
                column = table.column(c)
                if v is None:
                    cm = _pc.is_null(column)
                elif isinstance(v, float) and v != v:
                    cm = _pc.is_nan(column)
                else:
                    cm = _pc.equal(column, pa.scalar(v))
                mask = cm if mask is None else _pc.and_(mask, cm)
            part = table.filter(mask).select(data_cols)
            reldir = os.path.join(*(f"{c}={_partition_dir_value(combo[c])}"
                                    for c in partition_by))
            manifest["partitions"].append(reldir)
            emit(part, os.path.join(reldir, base))
    reg = get_registry()
    reg.inc("write.task_attempts")
    reg.inc("write.files_staged", len(manifest["files"]))
    reg.inc("write.rows_staged",
            sum(f["rows"] for f in manifest["files"]))
    return manifest


def verify_manifest(path: str, full: bool = False) -> dict:
    """Read-back verification of a committed directory against its
    ``_MANIFEST.json``: every manifest file must exist with the recorded
    size; with ``full`` the CRC32 is recomputed over the on-disk bytes
    (catches torn/corrupted writes the size check misses).  Returns the
    parsed manifest; raises :class:`WriteIntegrityError` on mismatch."""
    mpath = os.path.join(path, MANIFEST_NAME)
    with open(mpath) as f:
        manifest = json.load(f)
    for ent in manifest.get("files", ()):
        fname = os.path.join(path, ent["rel"])
        try:
            size = os.path.getsize(fname)
        except OSError as e:
            raise WriteIntegrityError(
                f"manifest file missing: {fname}") from e
        if size != ent["bytes"]:
            raise WriteIntegrityError(
                f"size mismatch for {fname}: manifest {ent['bytes']}, "
                f"on disk {size}")
        if full and _file_crc32(fname) != ent["crc32"]:
            raise WriteIntegrityError(f"CRC32 mismatch for {fname}")
    return manifest


def gc_staging(path: str, ttl_s: float = 0.0, keep_job: str | None = None)\
        -> int:
    """Remove leftover staging trees under ``path/_staging`` older than
    ``ttl_s`` (crashed/aborted jobs), returning the number collected."""
    root = os.path.join(path, STAGING_DIR)
    try:
        jobs = os.listdir(root)
    except OSError:
        return 0
    now = time.time()
    collected = 0
    for j in jobs:
        if j == keep_job:
            continue
        jdir = os.path.join(root, j)
        try:
            if now - os.stat(jdir).st_mtime < ttl_s:
                continue
        except OSError:
            continue
        shutil.rmtree(jdir, ignore_errors=True)
        collected += 1
    if collected:
        from spark_rapids_tpu.obs.registry import get_registry
        get_registry().inc("write.staging_dirs_gced", collected)
    try:
        os.rmdir(root)  # only succeeds when empty
    except OSError:
        pass
    return collected


class WriteCommitCoordinator:
    """Driver-side commit arbiter for one write job.

    ``register`` applies the same first-writer-wins guard the cluster
    map-output tracker uses for shuffle registrations: the first
    manifest per task wins, every later attempt (speculative duplicate,
    retry of a task whose commit message was dropped, drain
    re-dispatch) is discarded.  Workers being drained or quarantined
    are fenced — their future registrations are rejected so a straggler
    finishing after its host was removed cannot steal a commit.

    ``commit_job`` publishes winners by atomic rename and rolls back
    (un-renames) on any failure, so the output directory is only ever
    observed fully-committed or untouched."""

    def __init__(self, path: str, fmt: str, job_id: str | None = None,
                 faults=None, conf=None):
        self.path = os.path.abspath(path)
        self.fmt = fmt
        self.job_id = job_id or uuid.uuid4().hex[:8]
        self.staging_root = os.path.join(self.path, STAGING_DIR,
                                         self.job_id)
        self.faults = faults
        self._conf = conf
        self._lock = threading.Lock()
        self._winners: dict[int, dict] = {}
        self._next_attempt: dict[int, int] = {}
        self._fenced: set[str] = set()
        self.committed = False
        self.aborted = False
        #: optional cluster journal (cluster/journal.py), set by the
        #: write-job runner when a journaling driver is attached: wins
        #: and the commit rename plan are journaled so a driver crash
        #: mid-commit rolls FORWARD (all renames were durable before the
        #: first one ran) instead of double-committing or losing files
        self.journal = None

    # -- attempt bookkeeping -------------------------------------------
    def next_attempt(self, task: int) -> int:
        """Allocate the next attempt id for a task (satellite: attempt
        ids are threaded into every dispatch so duplicates are
        distinguishable at commit time)."""
        with self._lock:
            a = self._next_attempt.get(task, 0)
            self._next_attempt[task] = a + 1
            return a

    def attempt_dir(self, task: int, attempt: int) -> str:
        return staging_attempt_dir(self.path, self.job_id, task, attempt)

    # -- commit arbitration --------------------------------------------
    def register(self, manifest: dict) -> bool:
        """First-writer-wins: record ``manifest`` as its task's winner
        unless one exists (or its worker is fenced / the job already
        resolved).  Returns whether this attempt won."""
        from spark_rapids_tpu.obs.registry import get_registry
        reg = get_registry()
        task = int(manifest["task"])
        worker = str(manifest.get("worker") or "")
        if self.faults is not None:
            act = self.faults.check("io.write.commit.drop", task=task,
                                    attempt=manifest.get("attempt"),
                                    worker=worker)
            if act is not None:
                # the attempt's commit message is lost in flight: the
                # coordinator behaves as if it never arrived, the task
                # shows no winner, and the runtime re-attempts it
                reg.inc("write.commit_msgs_dropped")
                return False
        with self._lock:
            if self.committed or self.aborted:
                reg.inc("write.attempts_discarded")
                return False
            if worker and worker in self._fenced:
                reg.inc("write.attempts_fenced")
                return False
            if task in self._winners:
                reg.inc("write.attempts_discarded")
                return False
            self._winners[task] = manifest
        if self.journal is not None:
            self.journal.append("write_win", job=self.job_id, task=task,
                                manifest=manifest)
        reg.inc("write.attempts_won")
        return True

    def has_winner(self, task: int) -> bool:
        with self._lock:
            return task in self._winners

    def missing(self, tasks) -> list[int]:
        with self._lock:
            return sorted(t for t in tasks if t not in self._winners)

    def winner(self, task: int) -> dict | None:
        with self._lock:
            return self._winners.get(task)

    def fence_worker(self, worker_id: str) -> None:
        """Reject all future registrations from ``worker_id`` (called
        when its worker is drained or quarantined mid-job)."""
        with self._lock:
            self._fenced.add(worker_id)

    # -- job commit / abort --------------------------------------------
    def _rename(self, src: str, dst: str) -> None:
        retries = 0
        if self._conf is not None:
            retries = int(self._conf.get(WRITE_RENAME_RETRIES))
        last: Exception | None = None
        for _ in range(retries + 1):
            if self.faults is not None:
                act = self.faults.check("io.write.rename.fail",
                                        file=os.path.basename(dst))
                if act is not None:
                    last = OSError(
                        f"io.write.rename.fail: injected rename failure "
                        f"for {dst}")
                    from spark_rapids_tpu.obs.registry import get_registry
                    get_registry().inc("write.rename_retries")
                    continue
            try:
                os.replace(src, dst)
                return
            except OSError as e:
                last = e
                from spark_rapids_tpu.obs.registry import get_registry
                get_registry().inc("write.rename_retries")
        raise WriteCommitError(
            f"rename {src} -> {dst} failed after {retries + 1} "
            f"tries") from last

    def commit_job(self, schema=None, options: dict | None = None) -> dict:
        """Atomically publish the winning attempts.  Renames every
        winner file into the final directory, writes ``_MANIFEST.json``
        (tmp + os.replace) and ``_SUCCESS``, then GCs staging.  On any
        failure every completed rename is rolled back before the error
        propagates — the directory never holds a partial commit."""
        from spark_rapids_tpu.faults import crash_point
        from spark_rapids_tpu.obs.registry import get_registry
        reg = get_registry()
        t0 = time.perf_counter()
        with self._lock:
            if self.aborted:
                raise WriteCommitError("commit after abort")
            winners = dict(self._winners)
        # phase 1 — PLAN: the complete rename list and the manifest are
        # computed before any rename executes, so the journal's
        # write_commit_begin record is a true write-ahead log: a driver
        # crash anywhere in phase 2 can roll the commit FORWARD from the
        # journal alone (renames are idempotent: done -> dst exists)
        files_out: list[dict] = []
        partitions: list[str] = []
        plan: list[tuple[str, str]] = []
        seen_dirs: set[str] = set()
        for task in sorted(winners):
            m = winners[task]
            adir = self.attempt_dir(task, int(m["attempt"]))
            for ent in m["files"]:
                plan.append((os.path.join(adir, ent["rel"]),
                             os.path.join(self.path, ent["rel"])))
                files_out.append(dict(ent))
        if not files_out and schema is not None:
            # empty result: emit one schema-bearing empty part file
            # (Spark's write protocol) so the output stays readable —
            # staged first, renamed in, like every other file
            rel = f"part-00000-{self.job_id}.{self.fmt}"
            os.makedirs(self.staging_root, exist_ok=True)
            src = os.path.join(self.staging_root, rel)
            _write_table(schema.empty_table(), src, self.fmt,
                         **(options or {}))
            plan.append((src, os.path.join(self.path, rel)))
            files_out.append({"rel": rel, "rows": 0,
                              "bytes": os.path.getsize(src),
                              "crc32": _file_crc32(src)})
        for _, dst in plan:
            d = os.path.dirname(dst)
            if d != self.path and d not in seen_dirs:
                seen_dirs.add(d)
                partitions.append(os.path.relpath(d, self.path))
        manifest = {
            "version": 1, "job_id": self.job_id, "format": self.fmt,
            "files": files_out, "partitions": sorted(set(partitions)),
            "num_rows": sum(f["rows"] for f in files_out),
            "num_bytes": sum(f["bytes"] for f in files_out)}
        if self.journal is not None:
            self.journal.append("write_commit_begin", job=self.job_id,
                                renames=[[s, d] for s, d in plan],
                                manifest=manifest)
        # phase 2 — EXECUTE; a soft failure still rolls back in-process
        # (the directory is never observed partially committed), while a
        # hard crash leaves the journaled plan for recovery
        renamed: list[tuple[str, str]] = []
        try:
            for src, dst in plan:
                os.makedirs(os.path.dirname(dst), exist_ok=True)
                self._rename(src, dst)
                renamed.append((src, dst))
                crash_point(self.faults, "write.commit", job=self.job_id,
                            file=os.path.basename(dst))
            tmp = os.path.join(self.path, MANIFEST_NAME + ".tmp")
            with open(tmp, "w") as f:
                json.dump(manifest, f, indent=1, sort_keys=True)
            os.replace(tmp, os.path.join(self.path, MANIFEST_NAME))
        except BaseException:
            for src, dst in reversed(renamed):
                try:
                    os.replace(dst, src)
                except OSError:
                    pass
            reg.inc("write.jobs_commit_failed")
            raise
        with self._lock:
            self.committed = True
        open(os.path.join(self.path, "_SUCCESS"), "w").close()
        shutil.rmtree(self.staging_root, ignore_errors=True)
        try:
            os.rmdir(os.path.join(self.path, STAGING_DIR))
        except OSError:
            pass
        if self.journal is not None:
            # AFTER the staging rmtree: recovery's roll-forward of a
            # missing write_commit_done also re-cleans staging
            self.journal.append("write_commit_done", job=self.job_id)
        reg.inc("write.jobs_committed")
        reg.inc("write.files_committed", len(files_out))
        reg.inc("write.rows_committed", manifest["num_rows"])
        reg.inc("write.bytes_committed", manifest["num_bytes"])
        reg.observe("write.commit_seconds", time.perf_counter() - t0)
        return manifest

    def abort_job(self) -> None:
        """Drop the job: no files become visible, staging is removed."""
        from spark_rapids_tpu.obs.registry import get_registry
        with self._lock:
            if self.committed or self.aborted:
                return
            self.aborted = True
        shutil.rmtree(self.staging_root, ignore_errors=True)
        try:
            os.rmdir(os.path.join(self.path, STAGING_DIR))
        except OSError:
            pass
        if self.journal is not None:
            self.journal.append("write_abort", job=self.job_id)
        get_registry().inc("write.jobs_aborted")


def stats_from_manifest(manifest: dict) -> WriteStats:
    return WriteStats(num_files=len(manifest["files"]),
                      num_rows=manifest["num_rows"],
                      num_bytes=manifest["num_bytes"],
                      partitions=list(manifest.get("partitions", ())))

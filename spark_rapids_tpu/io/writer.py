"""Columnar file writers: Parquet / ORC / CSV + dynamic partitioning.

Reference: GpuParquetFileFormat.scala, GpuOrcFileFormat.scala,
ColumnarOutputWriter (ColumnarFileFormat.scala:57), GpuFileFormatWriter
(Spark write protocol incl. dynamic-partition writes,
GpuFileFormatWriter.scala:338, GpuFileFormatDataWriter.scala:419 —
single-directory and ``partitionBy`` concurrent-writer protocols) and
BasicColumnarWriteStatsTracker (per-task files/rows/bytes stats).
TPU path: batches come back D2H as Arrow and pyarrow writes them — the
host-encode mirror of the host-decode scan path.
"""
from __future__ import annotations

import os
import uuid
from dataclasses import dataclass, field
from typing import Iterator, Sequence

from spark_rapids_tpu.columnar.batch import ColumnBatch
from spark_rapids_tpu.exec.core import ExecCtx, PlanNode
from spark_rapids_tpu.host.batch import HostBatch

__all__ = ["write_parquet", "write_orc", "write_csv", "WriteStats"]


@dataclass
class WriteStats:
    """Job-level write statistics (reference
    BasicColumnarWriteStatsTracker/BasicWriteJobStatsTracker)."""
    num_files: int = 0
    num_rows: int = 0
    num_bytes: int = 0
    partitions: list = field(default_factory=list)  # dynamic partition dirs

    def _add_file(self, path: str, rows: int) -> None:
        self.num_files += 1
        self.num_rows += rows
        try:
            self.num_bytes += os.path.getsize(path)
        except OSError:
            pass


def _arrow_batches(plan: PlanNode, ctx: ExecCtx, pid: int) -> Iterator:
    """One partition's output as pyarrow RecordBatches."""
    import pyarrow as pa
    schema = plan.output_schema.to_arrow()
    for b in plan.partition_iter(ctx, pid):
        if isinstance(b, ColumnBatch):
            rb = b.to_arrow()
        else:
            rb = _host_to_arrow(b)
        if rb.num_rows:
            yield rb.cast(schema) if rb.schema != schema else rb


def _host_to_arrow(b: HostBatch):
    import pyarrow as pa
    from spark_rapids_tpu import types as T
    arrays = []
    for f, c in zip(b.schema, b.columns):
        at = T.to_arrow(f.data_type)
        mask = ~c.validity
        if isinstance(f.data_type, T.StringType):
            arrays.append(pa.array(
                [None if m else v for v, m in zip(c.data, mask)], type=at))
        elif isinstance(f.data_type, (T.DateType, T.TimestampType)):
            arrays.append(pa.Array.from_buffers(
                at, len(c.data),
                pa.array(c.data.astype(
                    "int32" if isinstance(f.data_type, T.DateType)
                    else "int64"), mask=mask).buffers()))
        else:
            arrays.append(pa.array(c.data, type=at, mask=mask))
    return pa.RecordBatch.from_arrays(arrays, schema=b.schema.to_arrow())


def _write_table(table, fname: str, fmt: str, **options) -> None:
    if fmt == "parquet":
        import pyarrow.parquet as pq
        pq.write_table(table, fname, **options)
    elif fmt == "orc":
        import pyarrow.orc as orc
        orc.write_table(table, fname)
    elif fmt == "csv":
        import pyarrow.csv as pc
        pc.write_csv(table, fname)
    else:
        raise ValueError(fmt)


def _partition_dir_value(v) -> str:
    """Hive-style directory encoding (Spark __HIVE_DEFAULT_PARTITION__
    for nulls)."""
    if v is None:
        return "__HIVE_DEFAULT_PARTITION__"
    s = str(v)
    return "".join("%%%02X" % ord(ch) if ch in '/\\:*?"<>|%' else ch
                   for ch in s)


def _write(plan: PlanNode, path: str, fmt: str, ctx: ExecCtx | None = None,
           partition_by: Sequence[str] | None = None,
           stats: WriteStats | None = None, **options) -> list[str]:
    """Write the plan's output under ``path`` (Spark directory-output
    protocol), returning written files.

    ``partition_by``: dynamic-partition writes — rows split by the named
    columns into hive-style ``col=value/`` directories, the partition
    columns dropped from the file contents (reference
    GpuFileFormatWriter.scala:338 dynamic-partition protocol)."""
    import pyarrow as pa
    ctx = ctx or ExecCtx()
    stats = stats if stats is not None else WriteStats()
    os.makedirs(path, exist_ok=True)
    job_id = uuid.uuid4().hex[:8]
    schema = plan.output_schema.to_arrow()
    written: list[str] = []
    seen_dirs: set[str] = set()

    if partition_by:
        names = plan.output_schema.names
        missing = [c for c in partition_by if c not in names]
        if missing:
            raise ValueError(f"partitionBy columns not in output: {missing}")
        data_cols = [n for n in names if n not in partition_by]
        if not data_cols:
            raise ValueError("partitionBy cannot cover every column")

    for pid in range(plan.num_partitions(ctx)):
        batches = list(_arrow_batches(plan, ctx, pid))
        if not partition_by:
            if not batches and (written or
                                pid != plan.num_partitions(ctx) - 1):
                continue
            # empty result: still emit one schema-bearing empty part file
            # (Spark's write protocol) so the output stays readable
            fname = os.path.join(path, f"part-{pid:05d}-{job_id}.{fmt}")
            table = pa.Table.from_batches(batches, schema=schema) \
                if batches else schema.empty_table()
            _write_table(table, fname, fmt, **options)
            written.append(fname)
            stats._add_file(fname, table.num_rows)
            continue
        # dynamic-partition path: group each batch's rows by the
        # partition-column tuple, append to per-directory part files
        if not batches:
            continue
        table = pa.Table.from_batches(batches, schema=schema)
        import pyarrow.compute as _pc  # host-side job driver, single thread
        keys = [table.column(c) for c in partition_by]
        combos = pa.Table.from_arrays(keys, names=list(partition_by)) \
            .group_by(list(partition_by)).aggregate([]).to_pylist()
        for combo in combos:
            mask = None
            for c in partition_by:
                v = combo[c]
                column = table.column(c)
                if v is None:
                    cm = _pc.is_null(column)
                elif isinstance(v, float) and v != v:
                    # NaN partition value: equal() matches nothing
                    cm = _pc.is_nan(column)
                else:
                    cm = _pc.equal(column, pa.scalar(v))
                mask = cm if mask is None else _pc.and_(mask, cm)
            part = table.filter(mask).select(data_cols)
            d = os.path.join(path, *(
                f"{c}={_partition_dir_value(combo[c])}"
                for c in partition_by))
            os.makedirs(d, exist_ok=True)
            if d not in seen_dirs:
                seen_dirs.add(d)
                stats.partitions.append(os.path.relpath(d, path))
            fname = os.path.join(d, f"part-{pid:05d}-{job_id}.{fmt}")
            _write_table(part, fname, fmt, **options)
            written.append(fname)
            stats._add_file(fname, part.num_rows)
    # commit marker (Spark's _SUCCESS protocol)
    open(os.path.join(path, "_SUCCESS"), "w").close()
    return written


def write_parquet(plan: PlanNode, path: str, ctx: ExecCtx | None = None,
                  partition_by: Sequence[str] | None = None,
                  stats: WriteStats | None = None, **options) -> list[str]:
    return _write(plan, path, "parquet", ctx, partition_by=partition_by,
                  stats=stats, **options)


def write_orc(plan: PlanNode, path: str, ctx: ExecCtx | None = None,
              partition_by: Sequence[str] | None = None,
              stats: WriteStats | None = None) -> list[str]:
    return _write(plan, path, "orc", ctx, partition_by=partition_by,
                  stats=stats)


def write_csv(plan: PlanNode, path: str, ctx: ExecCtx | None = None,
              partition_by: Sequence[str] | None = None,
              stats: WriteStats | None = None) -> list[str]:
    return _write(plan, path, "csv", ctx, partition_by=partition_by,
                  stats=stats)

"""ORC stripe-statistics reader + predicate pruning.

The reference builds ORC SearchArguments so the reader skips whole
stripes whose statistics cannot match the pushed-down predicate
(GpuOrcScan.scala:240-245 pushedFilters -> SearchArgument,
:327-360 stripe selection).  pyarrow's ORC binding exposes stripe
COUNTS but not the statistics values, so this module reads them from
the file itself: the ORC file tail is

    [data][stripe footers][metadata][footer][postscript][ps_len byte]

where the metadata section is a protobuf ``Metadata`` message holding
one ``StripeStatistics`` per stripe (orc_proto.proto).  Only the tiny
subset needed for pruning is parsed — a hand-rolled varint walker, no
generated code — and only NONE/ZLIB compression (the common ORC
defaults) is handled; anything else returns None and the scan keeps
every stripe (pruning is an optimization, never a correctness gate).
"""
from __future__ import annotations

import struct
import zlib

__all__ = ["stripe_column_stats", "stripe_may_match"]

# orc_proto.proto CompressionKind
_NONE, _ZLIB = 0, 1


def _varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _zigzag(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def _fields(buf: bytes):
    """Iterate (field_number, wire_type, value) over a protobuf buffer.
    value: int for varint(0)/fixed(1,5), bytes for length-delimited(2)."""
    pos, n = 0, len(buf)
    while pos < n:
        key, pos = _varint(buf, pos)
        fno, wt = key >> 3, key & 7
        if wt == 0:
            v, pos = _varint(buf, pos)
        elif wt == 2:
            ln, pos = _varint(buf, pos)
            v = buf[pos:pos + ln]
            pos += ln
        elif wt == 1:
            v = buf[pos:pos + 8]
            pos += 8
        elif wt == 5:
            v = buf[pos:pos + 4]
            pos += 4
        else:  # groups: unsupported, bail conservatively
            raise ValueError(f"unsupported wire type {wt}")
        yield fno, wt, v


def _decompress(buf: bytes, kind: int) -> bytes:
    """An ORC compressed stream is chunked: each chunk has a 3-byte
    little-endian header ``(length << 1) | is_original``."""
    if kind == _NONE:
        return buf
    out, pos = [], 0
    while pos + 3 <= len(buf):
        hdr = buf[pos] | (buf[pos + 1] << 8) | (buf[pos + 2] << 16)
        pos += 3
        ln, orig = hdr >> 1, hdr & 1
        chunk = buf[pos:pos + ln]
        pos += ln
        out.append(chunk if orig else
                   zlib.decompressobj(-15).decompress(chunk))
    return b"".join(out)


def _col_stats(buf: bytes) -> dict:
    """ColumnStatistics: numberOfValues=1, intStatistics=2,
    doubleStatistics=3, stringStatistics=4, dateStatistics=7,
    hasNull=10."""
    st: dict = {"n": None, "has_null": None, "min": None, "max": None}
    for fno, wt, v in _fields(buf):
        if fno == 1 and wt == 0:
            st["n"] = v
        elif fno == 10 and wt == 0:
            st["has_null"] = bool(v)
        elif fno == 2 and wt == 2:  # IntegerStatistics: sint64 min=1 max=2
            for f2, w2, v2 in _fields(v):
                if f2 == 1 and w2 == 0:
                    st["min"] = _zigzag(v2)
                elif f2 == 2 and w2 == 0:
                    st["max"] = _zigzag(v2)
        elif fno == 3 and wt == 2:  # DoubleStatistics: double min=1 max=2
            for f2, w2, v2 in _fields(v):
                if f2 == 1 and w2 == 1:
                    st["min"] = struct.unpack("<d", v2)[0]
                elif f2 == 2 and w2 == 1:
                    st["max"] = struct.unpack("<d", v2)[0]
        elif fno == 4 and wt == 2:  # StringStatistics: string min=1 max=2
            for f2, w2, v2 in _fields(v):
                if f2 == 1 and w2 == 2:
                    st["min"] = v2.decode("utf-8", "replace")
                elif f2 == 2 and w2 == 2:
                    st["max"] = v2.decode("utf-8", "replace")
        elif fno == 7 and wt == 2:  # DateStatistics: sint32 days min=1 max=2
            for f2, w2, v2 in _fields(v):
                if f2 == 1 and w2 == 0:
                    st["min"] = _zigzag(v2)
                elif f2 == 2 and w2 == 0:
                    st["max"] = _zigzag(v2)
    return st


def stripe_column_stats(path: str) -> list[list[dict]] | None:
    """Per-stripe, per-flattened-column statistics, or None when the
    file can't be parsed (unsupported compression, nested types, any
    surprise — caller must treat None as "keep every stripe").

    For a flat struct schema, flattened column 0 is the root struct and
    columns 1..N are the fields in file-schema order."""
    try:
        with open(path, "rb") as f:
            f.seek(0, 2)
            flen = f.tell()
            tail_len = min(flen, 1 << 14)
            f.seek(flen - tail_len)
            tail = f.read(tail_len)
            ps_len = tail[-1]
            ps = tail[-1 - ps_len:-1]
            footer_len = meta_len = 0
            kind = _NONE
            for fno, wt, v in _fields(ps):
                if fno == 1 and wt == 0:
                    footer_len = v
                elif fno == 2 and wt == 0:
                    kind = v
                elif fno == 5 and wt == 0:
                    meta_len = v
            if kind not in (_NONE, _ZLIB):
                return None
            if meta_len == 0:
                return None
            need = 1 + ps_len + footer_len + meta_len
            if need > tail_len:
                f.seek(flen - need)
                tail = f.read(need)
            meta_buf = tail[-1 - ps_len - footer_len - meta_len:
                            -1 - ps_len - footer_len]
        meta = _decompress(meta_buf, kind)
        stripes = []
        for fno, wt, v in _fields(meta):
            if fno == 1 and wt == 2:  # StripeStatistics
                cols = [_col_stats(v2) for f2, w2, v2 in _fields(v)
                        if f2 == 1 and w2 == 2]
                stripes.append(cols)
        return stripes or None
    # enginelint: disable=RL001 (stats pruning is best-effort; None keeps every stripe)
    except Exception:  # noqa: BLE001 - pruning is best-effort
        return None


def stripe_may_match(pred, stats: list[dict],
                     col_index: dict[str, int]) -> bool:
    """Conservative interval check: False ONLY when no row in the
    stripe can satisfy ``pred`` (engine Expression).  Unknown operators
    and missing statistics answer True (keep the stripe)."""
    from spark_rapids_tpu.expr import predicates as P
    from spark_rapids_tpu.expr.core import Literal, UnresolvedAttribute

    def col_lit(e):
        """(stats, literal, flipped) for a col-vs-literal comparison."""
        a, b = e.children
        if isinstance(a, UnresolvedAttribute) and isinstance(b, Literal):
            i = col_index.get(a.name)
            return (stats[i] if i is not None and i < len(stats) else None,
                    b.value, False)
        if isinstance(b, UnresolvedAttribute) and isinstance(a, Literal):
            i = col_index.get(b.name)
            return (stats[i] if i is not None and i < len(stats) else None,
                    a.value, True)
        return None, None, False

    def cmp_ok(st, lit, lo_op):
        """May any value v in [min,max] satisfy ``v <op> lit``?"""
        if st is None or lit is None:
            return True
        mn, mx = st.get("min"), st.get("max")
        if mn is None or mx is None:
            return True
        if not isinstance(lit, type(mn)) and not (
                isinstance(lit, (int, float)) and isinstance(mn, (int, float))):
            return True  # type mismatch (e.g. date vs int): no claim
        try:
            return lo_op(mn, mx, lit)
        except TypeError:
            return True

    def may(e) -> bool:
        if isinstance(e, P.And):
            return may(e.children[0]) and may(e.children[1])
        if isinstance(e, P.Or):
            return may(e.children[0]) or may(e.children[1])
        if isinstance(e, P.EqualTo):
            st, lit, _ = col_lit(e)
            return cmp_ok(st, lit, lambda mn, mx, v: mn <= v <= mx)
        if isinstance(e, P.LessThan):
            st, lit, flip = col_lit(e)
            if flip:  # lit < col  <=>  col > lit
                return cmp_ok(st, lit, lambda mn, mx, v: mx > v)
            return cmp_ok(st, lit, lambda mn, mx, v: mn < v)
        if isinstance(e, P.LessThanOrEqual):
            st, lit, flip = col_lit(e)
            if flip:
                return cmp_ok(st, lit, lambda mn, mx, v: mx >= v)
            return cmp_ok(st, lit, lambda mn, mx, v: mn <= v)
        if isinstance(e, P.GreaterThan):
            st, lit, flip = col_lit(e)
            if flip:  # lit > col  <=>  col < lit
                return cmp_ok(st, lit, lambda mn, mx, v: mn < v)
            return cmp_ok(st, lit, lambda mn, mx, v: mx > v)
        if isinstance(e, P.GreaterThanOrEqual):
            st, lit, flip = col_lit(e)
            if flip:
                return cmp_ok(st, lit, lambda mn, mx, v: mn <= v)
            return cmp_ok(st, lit, lambda mn, mx, v: mx >= v)
        if isinstance(e, P.IsNull):
            c = e.children[0]
            if isinstance(c, UnresolvedAttribute):
                i = col_index.get(c.name)
                if i is not None and i < len(stats):
                    hn = stats[i].get("has_null")
                    if hn is not None:
                        return hn
            return True
        if isinstance(e, P.IsNotNull):
            c = e.children[0]
            if isinstance(c, UnresolvedAttribute):
                i = col_index.get(c.name)
                if i is not None and i < len(stats):
                    nv = stats[i].get("n")
                    if nv is not None:
                        return nv > 0
            return True
        return True  # unknown operator: no claim

    return may(pred)

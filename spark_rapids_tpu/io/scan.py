"""File scan execs: Parquet / ORC / CSV -> columnar batches.

Reference: GpuParquetScan.scala (PERFILE :1451 / COALESCING :824 /
MULTITHREADED :1145 reader modes; predicate pushdown via ParquetFilters
:217-271; schema clipping), GpuOrcScan.scala:63, GpuBatchScanExec.scala:465
(CSV).  TPU design: pyarrow decodes on host threads (prefetch pool ≈
MultiFileThreadPoolFactory, GpuParquetScan.scala:771-823) into Arrow record
batches; the device backend transfers them to HBM (``ColumnBatch.from_arrow``)
while the next files decode — the same I/O/compute overlap, with XLA compile
stability preserved by pow2 capacity/width bucketing.
"""
from __future__ import annotations

import concurrent.futures as cf
import glob as _glob
import os
from typing import Iterator, Sequence

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnBatch
from spark_rapids_tpu.conf import ConfEntry, register
from spark_rapids_tpu.exec.core import ExecCtx, PlanNode
from spark_rapids_tpu.expr.core import Expression

__all__ = ["FileScanExec", "ParquetScanExec", "OrcScanExec", "CsvScanExec"]

# per-format reader knobs, as in the reference (RapidsConf.scala:510,:548
# registers parquet-specific keys; orc/csv get their own here so setting
# one format's mode never changes another's behavior)
READER_TYPE = {
    fmt: register(ConfEntry(
        f"spark.rapids.sql.format.{fmt}.reader.type", "MULTITHREADED",
        "Reader mode: PERFILE, COALESCING, or MULTITHREADED (prefetching "
        "thread pool; reference RapidsConf.scala:510).",
        check=lambda v: v in ("PERFILE", "COALESCING", "MULTITHREADED"),
        check_doc="one of PERFILE|COALESCING|MULTITHREADED"))
    for fmt in ("parquet", "orc", "csv")
}
READER_THREADS = {
    fmt: register(ConfEntry(
        f"spark.rapids.sql.format.{fmt}.multiThreadedRead.numThreads", 4,
        "Prefetch threads per scan (reference RapidsConf.scala:548).",
        conv=int))
    for fmt in ("parquet", "orc", "csv")
}
BATCH_ROWS = register(ConfEntry(
    "spark.rapids.sql.reader.batchRows", 1 << 22,
    "Max rows per decoded batch (reference "
    "spark.rapids.sql.reader.batchSizeRows, RapidsConf.scala:370). The "
    "default is large on purpose: every device program launch pays "
    "host->device dispatch latency (severe over a tunneled PJRT link), "
    "so the TPU wants FEW LARGE batches — the reference's ~2GiB "
    "batchSizeBytes target (RapidsConf.scala:364) serves the same goal.",
    conv=int))


def _effective_batch_rows(schema: T.Schema, settings: dict) -> int:
    """Row cap honoring BOTH reader.batchRows and reader.batchSizeBytes
    (reference maxReadBatchSizeRows/maxReadBatchSizeBytes,
    RapidsConf.scala:370-386): bytes are converted to rows through a
    static per-row width estimate of the pruned schema."""
    from spark_rapids_tpu.conf import MAX_READER_BATCH_SIZE_BYTES
    rows = BATCH_ROWS.get(settings)
    byte_cap = MAX_READER_BATCH_SIZE_BYTES.get(settings)
    width = 1  # validity
    for f in schema:
        # ArrayType.np_dtype is the ELEMENT dtype — one element's
        # itemsize would undercount a row by up to max_len x, so arrays
        # use the variable-width estimate like strings and maps
        if f.data_type.np_dtype is None or \
                isinstance(f.data_type, T.ArrayType):
            width += 32          # offset + data estimate
        else:
            width += max(1, f.data_type.np_dtype.itemsize)
    # the floor protects only the bytes-derived cap (a degenerate byte
    # budget must not produce 0-row batches); an explicit row cap wins
    return min(rows, max(256, byte_cap // width))


def _expand_paths(paths) -> list[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            for f in sorted(_glob.glob(os.path.join(p, "**", "*"),
                                       recursive=True)):
                if not os.path.isfile(f):
                    continue
                # hidden-component filter applies to the WHOLE relative
                # path, not just the basename: files under `_staging/`
                # (io/writer.py task-attempt dirs) or `_metadata/` trees
                # must be invisible to scans — uncommitted attempts are
                # not data (reference Spark's HadoopFsRelation hidden-
                # file convention)
                rel = os.path.relpath(f, p)
                if any(part.startswith(("_", "."))
                       for part in rel.split(os.sep)):
                    continue
                out.append(f)
        else:
            out.append(p)
    return out


def _to_arrow_filter(e: Expression):
    """Convert a pushable predicate to a pyarrow.dataset filter expression;
    None when not convertible (reference ParquetFilters pushdown,
    GpuParquetScan.scala:217).  Applied identically on both backends so the
    differential oracle stays valid."""
    import pyarrow.dataset as ds
    from spark_rapids_tpu.expr import predicates as P
    from spark_rapids_tpu.expr.core import Literal, UnresolvedAttribute

    def conv(n: Expression):
        if isinstance(n, UnresolvedAttribute):
            return ds.field(n.name)
        if isinstance(n, Literal):
            # ds.scalar keeps both operands pyarrow Expressions, so
            # literal-on-left comparisons don't fall into Python's
            # NotImplemented reflected-operator path
            return ds.scalar(n.value)
        return None

    if isinstance(e, P.And):
        l, r = (_to_arrow_filter(c) for c in e.children)
        return l & r if l is not None and r is not None else None
    if isinstance(e, P.Or):
        l, r = (_to_arrow_filter(c) for c in e.children)
        return l | r if l is not None and r is not None else None
    binmap = {P.EqualTo: "__eq__", P.LessThan: "__lt__",
              P.LessThanOrEqual: "__le__", P.GreaterThan: "__gt__",
              P.GreaterThanOrEqual: "__ge__"}
    for cls, meth in binmap.items():
        if isinstance(e, cls):
            l, r = conv(e.children[0]), conv(e.children[1])
            if l is not None and r is not None:
                return getattr(l, meth)(r)
            return None
    if isinstance(e, P.IsNull):
        c = conv(e.children[0])
        return c.is_null() if c is not None else None
    if isinstance(e, P.IsNotNull):
        c = conv(e.children[0])
        return ~c.is_null() if c is not None else None
    return None


class FileScanExec(PlanNode):
    """Base scan: files split across partitions; per-partition batches
    decoded on host (optionally via a prefetch pool) then H2D on the
    device backend."""

    format_name = "file"

    def __init__(self, paths, columns: Sequence[str] | None = None,
                 partitions: int | None = None,
                 pushdown: Expression | None = None,
                 string_width: int | None = None):
        super().__init__([])
        #: directory roots among the requested paths — kept so the
        #: optional commit-manifest CRC verification (verifyCrcOnScan)
        #: knows where a ``_MANIFEST.json`` could live
        self._roots = [p for p in
                       ([paths] if isinstance(paths, str) else list(paths))
                       if os.path.isdir(p)]
        self._files = _expand_paths(paths)
        if not self._files:
            raise FileNotFoundError(f"no input files in {paths}")
        self._columns = list(columns) if columns else None
        self._requested_parts = partitions
        self._pushdown = pushdown
        if pushdown is not None and _to_arrow_filter(pushdown) is None:
            # refuse silently-unapplied predicates: the planner only pushes
            # supported ones (reference keeps a residual FilterExec above)
            raise ValueError(f"predicate not pushable: {pushdown!r}")
        self._string_width = string_width
        #: AQE dynamic filters (plan/adaptive.py): (column, values, lo, hi)
        #: tuples derived from a small materialized join build side and
        #: pushed here before the probe stage launches (the DPP analog).
        #: Applied at the arrow layer alongside the static pushdown.
        self._runtime_filters: list[tuple] = []
        self._buckets_cache: dict[int, list[list[str]]] = {}
        #: stripes/row-groups skipped via statistics pruning (diagnostic)
        self.stripes_skipped = 0
        #: set by the planner when this scan's (files, columns, pushdown)
        #: fingerprint appears MORE THAN ONCE in the plan: consumers then
        #: share one materialization parked spillable in the catalog
        #: instead of re-decoding + re-transferring per instance (q28
        #: reads store_sales 12x; the reference's analog is Spark's
        #: ReuseExchange over identical scan-bearing subtrees)
        self.share_output = False
        #: how many consumptions the planner counted for the shared
        #: fingerprint (0 = unknown): the last one closes the parked
        #: entries so the shared table's catalog registration (and its
        #: host/disk spill storage) is released as soon as every branch
        #: has read it, not at catalog close
        self.share_consumers = 0
        full = self._read_schema()
        if self._columns:
            fields = [full.field(c) for c in self._columns]
            self._schema = T.Schema(fields)
        else:
            self._schema = full

    # -- per-format hooks --------------------------------------------------
    def _read_schema(self) -> T.Schema:
        raise NotImplementedError

    def _read_file(self, path: str, batch_rows: int = 1 << 16):
        """Return an iterator of pyarrow.RecordBatch for one file with
        column pruning + pushdown applied, chunked at ``batch_rows``."""
        raise NotImplementedError

    # -- PlanNode ----------------------------------------------------------
    @property
    def output_schema(self) -> T.Schema:
        return self._schema

    def num_partitions(self, ctx: ExecCtx) -> int:
        return self._requested_parts or min(len(self._files), 8)

    def _partition_files(self, ctx: ExecCtx, pid: int) -> list[str]:
        nparts = self.num_partitions(ctx)
        if nparts not in self._buckets_cache:
            # greedy size-balanced assignment (reference FilePartition
            # packing), computed once per partition count
            sizes = sorted(((os.path.getsize(f), f) for f in self._files),
                           reverse=True)
            buckets: list[list[str]] = [[] for _ in range(nparts)]
            loads = [0] * nparts
            for sz, f in sizes:
                i = loads.index(min(loads))
                buckets[i].append(f)
                loads[i] += sz
            self._buckets_cache[nparts] = buckets
        return self._buckets_cache[nparts][pid]

    def add_runtime_filter(self, column: str, values=None, lo=None,
                           hi=None) -> None:
        """Install a join-key filter derived at runtime (AQE dynamic
        filter): either an IN-set (``values``) or a min-max range
        (``lo``/``hi``).  Only ever narrows the scan's output — rows it
        removes are exactly rows the downstream join would drop — so it
        is safe to install between stages of a running query."""
        assert not self.share_output, \
            "dynamic filters must not narrow a shared scan"
        assert column in self._schema.names
        self._runtime_filters.append(
            (column, tuple(values) if values is not None else None, lo, hi))

    def _arrow_filter(self):
        """The combined arrow-level filter: static pushdown composed with
        any runtime (AQE dynamic) filters."""
        import pyarrow.dataset as ds
        filt = _to_arrow_filter(self._pushdown) \
            if self._pushdown is not None else None
        for column, values, lo, hi in self._runtime_filters:
            if values is not None:
                f = ds.field(column).isin(list(values))
            else:
                f = (ds.field(column) >= ds.scalar(lo)) & \
                    (ds.field(column) <= ds.scalar(hi))
            filt = f if filt is None else (filt & f)
        return filt

    def scan_fingerprint(self) -> tuple:
        """Structural identity: two scans with equal fingerprints read
        the same files, columns, and pushdown — identical output."""
        return (self.format_name, tuple(self._files),
                tuple(self._schema.names), repr(self._pushdown),
                tuple(self._runtime_filters),
                self._string_width, self._requested_parts)

    def snapshot_fingerprint(self) -> tuple:
        """Input-snapshot identity: (path, size, mtime_ns) per file, so
        two scans with equal structural AND snapshot fingerprints read
        byte-identical inputs — the invalidation half of every
        result-cache key (exec/result_cache.py).  Raises OSError when a
        file vanished; callers treat that as "no provable snapshot"."""
        out = []
        for f in self._files:
            st = os.stat(f)
            out.append((f, st.st_size, st.st_mtime_ns))
        return tuple(out)

    def _maybe_verify_manifests(self, ctx: ExecCtx) -> None:
        """When ``spark.rapids.io.write.transactional.verifyCrcOnScan``
        is on, recompute each scanned output directory's committed-file
        CRCs against its ``_MANIFEST.json`` before reading — a paranoia
        tier that turns silent post-commit corruption into a
        WriteIntegrityError.  Verified once per (exec, directory)."""
        from spark_rapids_tpu.io.writer import (MANIFEST_NAME,
                                                WRITE_VERIFY_CRC_ON_SCAN,
                                                verify_manifest)
        if not WRITE_VERIFY_CRC_ON_SCAN.get(ctx.conf.settings):
            return
        for root in self._roots:
            if os.path.exists(os.path.join(root, MANIFEST_NAME)):
                ctx.cached(("scan_crc_verified", os.path.abspath(root)),
                           lambda r=root: verify_manifest(r, full=True))

    def partition_iter(self, ctx: ExecCtx, pid: int) -> Iterator:
        self._maybe_verify_manifests(ctx)
        files = self._partition_files(ctx, pid)
        mode = READER_TYPE[self.format_name].get(ctx.conf.settings)
        rbs = self._decode_iter(ctx, files, mode)
        if ctx.is_device:
            if self.share_output:
                from spark_rapids_tpu.exec.result_cache import maybe_cache
                rc = maybe_cache(ctx.conf)
                if rc is not None:
                    try:
                        snap = self.snapshot_fingerprint()
                    except OSError:
                        snap = None
                    if snap is not None:
                        # cross-query path: one host-read + pack shared
                        # by every concurrent query over this table at
                        # this snapshot.  Raw device batches (no
                        # catalog parking — a cached fragment must not
                        # die with one query's catalog); the entry is
                        # consumer-pinned for the drain and governor-
                        # evictable when idle.
                        from spark_rapids_tpu.exec.recovery import \
                            conf_fingerprint
                        fkey = ("scan", self.scan_fingerprint(), snap,
                                conf_fingerprint(ctx.conf), pid)
                        entry = rc.fragment_entry(
                            fkey, lambda: list(self._device_batches(rbs)),
                            lifecycle=ctx.cache.get("lifecycle"))
                        try:
                            yield from entry.value
                        finally:
                            rc.fragment_release(entry)
                        return
                from spark_rapids_tpu.memory.catalog import (
                    SpillableColumnarBatch, SpillPriority)
                key = ("scan_share", self.scan_fingerprint(), pid)
                parked = ctx.cached(
                    key,
                    lambda: [SpillableColumnarBatch(
                        b, ctx.catalog, SpillPriority.READ_SHUFFLE)
                        for b in self._device_batches(rbs)])
                for sb in parked:
                    b = sb.get()
                    # unpin immediately: the yielded pytree keeps the
                    # arrays alive for this consumer, while the catalog
                    # stays free to spill the parked copy between
                    # consumers (a held pin would make the whole shared
                    # table permanently unspillable — review finding)
                    sb.unpin()
                    yield b
                # consumer-counted close: once every sharing branch has
                # drained this partition, the parked entries are dead
                # weight in the catalog (formerly leaked until catalog
                # close — a session running many queries accumulated
                # every shared table in the spill tiers)
                if self.share_consumers:
                    ckey = ("scan_share_left", self.scan_fingerprint(), pid)
                    with ctx._lock:
                        left = ctx.cache.get(ckey, self.share_consumers) - 1
                        ctx.cache[ckey] = left
                        if left <= 0:
                            ctx.cache.pop(key, None)
                    if left <= 0:
                        for sb in parked:
                            sb.close()
                return
            yield from self._device_batches(rbs)
        else:
            for rb in rbs:
                if rb.num_rows == 0:
                    continue
                yield _arrow_to_host(rb, self._schema)

    def _device_batches(self, rbs) -> Iterator:
        """Stage-and-transfer pipeline: a worker thread encodes and
        device_puts batch k+1 while the consumer computes on batch k.
        Host-side staging (arrow decode + wire-codec encode) is the
        scan's serial CPU cost; overlapping it with device compute hides
        it entirely on multi-batch scans (reference: the multithreaded
        reader's decode-ahead does the same for the host half,
        GpuMultiFileReader.scala).  Window of 2 bounds host+HBM usage."""
        import queue
        import threading
        q: queue.Queue = queue.Queue(maxsize=2)
        DONE = object()
        stop = threading.Event()

        def put(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.25)
                    return True
                except queue.Full:
                    continue
            return False

        def worker():
            try:
                for rb in rbs:
                    if stop.is_set():
                        return
                    if rb.num_rows == 0:
                        continue
                    if not put(ColumnBatch.from_arrow(
                            rb, string_widths=self._width_map(rb))):
                        return
                put(DONE)
            # enginelint: disable=RL001 (prefetch thread forwards the exception through the queue; the consumer re-raises it)
            except BaseException as e:  # noqa: BLE001 - re-raised below
                put(e)

        t = threading.Thread(target=worker, daemon=True,
                             name="scan-prefetch")
        t.start()
        try:
            while True:
                item = q.get()
                if item is DONE:
                    break
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            # consumer abandoned the scan (limit) or errored: release
            # the worker, which may be blocked on a full queue
            stop.set()
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break

    def _width_map(self, rb) -> dict[str, int] | None:
        if self._string_width is None:
            return None
        return {f.name: self._string_width for f in self._schema
                if isinstance(f.data_type, T.StringType)}

    def _decode_iter(self, ctx: ExecCtx, files: list[str], mode: str):
        batch_rows = _effective_batch_rows(self._schema, ctx.conf.settings)
        try:
            # process-wide scan-volume counter (mirrors the shuffle
            # plane's shuffle.fetch.bytes): on-disk bytes this partition
            # is about to decode, metered per tenant by obs/metering
            from spark_rapids_tpu.obs.registry import get_registry
            get_registry().inc("scan.bytes", float(
                sum(os.path.getsize(p) for p in files)))
        # enginelint: disable=RL001 (accounting must never fail a scan)
        except Exception:
            pass
        if mode == "MULTITHREADED" and len(files) > 1:
            # prefetch pool: decode next files while current is consumed,
            # bounded to a numThreads-file window so host memory stays
            # bounded (reference MultiFileCloudParquetPartitionReader
            # inflight limits)
            from collections import deque
            nthreads = READER_THREADS[self.format_name].get(ctx.conf.settings)
            with cf.ThreadPoolExecutor(max_workers=nthreads) as pool:
                window: deque = deque()
                it = iter(files)
                for p in it:
                    window.append(pool.submit(
                        lambda p=p: list(self._read_file(p, batch_rows))))
                    if len(window) >= nthreads:
                        break
                for p in it:
                    yield from window.popleft().result()
                    window.append(pool.submit(
                        lambda p=p: list(self._read_file(p, batch_rows))))
                while window:
                    yield from window.popleft().result()
        elif mode == "COALESCING" and len(files) > 1:
            # stitch many small files into larger batches (reference
            # MultiFileParquetPartitionReader): concat arrow tables then
            # re-chunk at the target size. Files yielding zero batches
            # (e.g. empty ORC/CSV parts) are skipped.
            import pyarrow as pa
            tables = []
            for p in files:
                bs = list(self._read_file(p, batch_rows))
                if bs:
                    t = pa.Table.from_batches(bs)
                    if t.num_rows:
                        tables.append(t)
            if not tables:
                return
            # combine_chunks is what actually merges: concat_tables keeps
            # per-file chunk boundaries and to_batches only splits chunks
            merged = pa.concat_tables(tables).combine_chunks()
            yield from merged.to_batches(max_chunksize=batch_rows)
        else:
            for p in files:
                yield from self._read_file(p, batch_rows)

    def node_desc(self) -> str:
        return (f"{type(self).__name__}[{self.format_name}, "
                f"{len(self._files)} files, cols={self._schema.names}]")


def _arrow_to_host(rb, schema: T.Schema):
    import numpy as np
    import pyarrow as pa
    from spark_rapids_tpu.host.batch import HostBatch, HostColumn
    cols = []
    for i, f in enumerate(schema):
        arr = rb.column(i)
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        if pa.types.is_dictionary(arr.type):
            arr = arr.cast(pa.string())
        n = len(arr)
        validity = np.ones(n, np.bool_) if arr.null_count == 0 else \
            np.asarray(arr.is_valid(), dtype=np.bool_)
        if isinstance(f.data_type, T.StringType):
            data = np.array([x if x is not None else None
                             for x in arr.to_pylist()], dtype=object)
        elif isinstance(f.data_type, T.ArrayType):
            data = np.empty(n, dtype=object)
            for j, x in enumerate(arr.to_pylist()):
                data[j] = x
        elif isinstance(f.data_type, T.MapType):
            data = T.arrow_map_to_numpy(arr)
        else:
            data = T.arrow_fixed_to_numpy(arr, f.data_type)
        cols.append(HostColumn(data, validity, f.data_type))
    return HostBatch(cols, schema)


class ParquetScanExec(FileScanExec):
    """Parquet scan (reference GpuParquetScanBase:84-112): footer schema,
    column pruning, predicate pushdown at row-group granularity via
    pyarrow."""

    format_name = "parquet"

    def _read_schema(self) -> T.Schema:
        import pyarrow.parquet as pq
        return T.Schema.from_arrow(pq.read_schema(self._files[0]))

    def _read_file(self, path: str, batch_rows: int = 1 << 16):
        import pyarrow.dataset as ds
        dataset = ds.dataset(path, format="parquet")
        scanner = dataset.scanner(columns=self._schema.names,
                                  filter=self._arrow_filter(),
                                  batch_size=batch_rows)
        yield from scanner.to_batches()


class OrcScanExec(FileScanExec):
    """ORC scan (reference GpuOrcScanBase, GpuOrcScan.scala:63) with
    stripe pruning: stripes whose statistics cannot match the pushdown
    predicate are skipped without being read (reference SearchArgument
    stripe selection, GpuOrcScan.scala:240-245,327-360; statistics read
    by io/orc_meta.py since pyarrow doesn't expose them)."""

    format_name = "orc"

    def _read_schema(self) -> T.Schema:
        import pyarrow.orc as orc
        return T.Schema.from_arrow(orc.ORCFile(self._files[0]).schema)

    def _read_file(self, path: str, batch_rows: int = 1 << 16):
        import pyarrow as pa
        import pyarrow.orc as orc
        from spark_rapids_tpu.io import orc_meta
        f = orc.ORCFile(path)
        cols = self._schema.names
        # stripe pruning stays keyed on the STATIC pushdown; runtime
        # filters join at the residual row-level filter below
        filt = self._arrow_filter()
        stats = None
        if self._pushdown is not None:
            # flattened-stats index: root struct is column 0, fields
            # follow in FILE schema order — valid ONLY for flat schemas
            # (nested types interleave their children into the id
            # space, which would compare predicates against the wrong
            # column's statistics); nested files skip pruning entirely
            file_schema = f.schema
            if all(not pa.types.is_nested(fld.type)
                   for fld in file_schema):
                if not hasattr(self, "_orc_stats_cache"):
                    self._orc_stats_cache = {}
                if path not in self._orc_stats_cache:
                    self._orc_stats_cache[path] = \
                        orc_meta.stripe_column_stats(path)
                stats = self._orc_stats_cache[path]
                col_index = {n: i + 1
                             for i, n in enumerate(file_schema.names)}
        for stripe in range(f.nstripes):
            if stats is not None and stripe < len(stats) and \
                    not orc_meta.stripe_may_match(
                        self._pushdown, stats[stripe], col_index):
                self.stripes_skipped += 1
                continue
            out = f.read_stripe(stripe, columns=cols)
            # read_stripe returns columns in file order; re-select to the
            # requested order (RecordBatch or Table depending on version)
            if isinstance(out, pa.RecordBatch):
                out = pa.Table.from_batches([out])
            out = out.select(cols)
            if filt is not None:
                # residual row-level filter over surviving stripes (the
                # reference applies the same SearchArgument rows too)
                out = out.filter(filt)
            yield from out.to_batches(max_chunksize=batch_rows)


class CsvScanExec(FileScanExec):
    """CSV scan (reference GpuBatchScanExec.scala:465 Table.readCSV):
    host parse via pyarrow.csv with an explicit or inferred schema."""

    format_name = "csv"

    def __init__(self, paths, schema: T.Schema | None = None,
                 header: bool = True, delimiter: str = ",", **kw):
        self._explicit_schema = schema
        self._header = header
        self._delim = delimiter
        super().__init__(paths, **kw)

    def _csv_options(self):
        import pyarrow.csv as pc
        ropts = pc.ReadOptions()
        popts = pc.ParseOptions(delimiter=self._delim)
        copts = None
        if self._explicit_schema is not None:
            at = self._explicit_schema.to_arrow()
            if not self._header:
                ropts = pc.ReadOptions(column_names=[f.name for f in at])
            copts = pc.ConvertOptions(
                column_types={f.name: f.type for f in at})
        elif not self._header:
            # headerless without a schema: synthesize f0..fN names so the
            # first data row is NOT consumed as the header
            ropts = pc.ReadOptions(autogenerate_column_names=True)
        return ropts, popts, copts

    def _read_schema(self) -> T.Schema:
        if self._explicit_schema is not None:
            return self._explicit_schema
        import pyarrow.csv as pc
        ropts, popts, _ = self._csv_options()
        # streaming reader: schema comes from the first block without
        # decoding the whole file
        with pc.open_csv(self._files[0], read_options=ropts,
                         parse_options=popts) as reader:
            return T.Schema.from_arrow(reader.schema)

    def _read_file(self, path: str, batch_rows: int = 1 << 16):
        import pyarrow.csv as pc
        ropts, popts, copts = self._csv_options()
        tbl = pc.read_csv(path, read_options=ropts, parse_options=popts,
                          convert_options=copts)
        if self._columns:
            tbl = tbl.select(self._schema.names)
        filt = self._arrow_filter()
        if filt is not None:
            tbl = tbl.filter(filt)
        yield from tbl.to_batches(max_chunksize=batch_rows)

"""I/O layer: columnar file scans and writers (reference SURVEY §2.5).

Host-side decode is Arrow (pyarrow) — the TPU-first substitute for cuDF's
device Parquet/ORC/CSV decoders: files decode on host threads into Arrow
record batches that transfer to HBM without per-row conversion, with
multithreaded prefetch overlapping host I/O with device compute (reference
GpuParquetScan.scala MultiFileCloudParquetPartitionReader :1145).
"""
from spark_rapids_tpu.io.scan import (CsvScanExec, FileScanExec, OrcScanExec,
                                      ParquetScanExec)
from spark_rapids_tpu.io.writer import (write_csv, write_orc, write_parquet)

__all__ = ["FileScanExec", "ParquetScanExec", "OrcScanExec", "CsvScanExec",
           "write_parquet", "write_orc", "write_csv"]

"""TPC-DS queries as DataFrame code (the TpcdsLikeSpark.scala pattern).

Each builder takes a :class:`TpuSession` + data_dir and returns a
DataFrame for one TPC-DS query over the pruned generated tables
(reference: integration_tests/.../tpcds/TpcdsLikeSpark.scala — all 99
queries as Spark DataFrame code; this slice implements the
scan/filter/join/agg/sort/limit-shaped ones the baseline tracks,
starting with q6 = BASELINE configs[0]).

Scalar subqueries (q6's month_seq) are evaluated eagerly and folded as
literals — the same plan shape Spark produces after subquery execution.
"""
from __future__ import annotations

import os

from spark_rapids_tpu.expr.aggregates import Average, CountStar, Sum
from spark_rapids_tpu.expr.core import col, lit

__all__ = ["QUERIES", "build_query"]


def _t(session, data_dir: str, table: str, columns=None):
    return session.read_parquet(os.path.join(data_dir, table),
                                columns=columns)


def q3(session, data_dir: str):
    """TPC-DS q3: brand revenue by year for one manufacturer in November."""
    dt = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_year", "d_moy"]).where(col("d_moy") == lit(11))
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_brand_id", "i_brand", "i_manufact_id"]) \
        .where(col("i_manufact_id") == lit(128)) \
        .select(col("i_item_sk"), col("i_brand_id"), col("i_brand"))
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price"])
    return ss.join(dt, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(it, on=[("ss_item_sk", "i_item_sk")]) \
        .group_by("d_year", "i_brand_id", "i_brand") \
        .agg(Sum(col("ss_ext_sales_price")).alias("sum_agg")) \
        .order_by(("d_year", True), ("sum_agg", False),
                  ("i_brand_id", True)) \
        .limit(100)


def q6(session, data_dir: str):
    """TPC-DS q6: state count of customers buying items priced >=120% of
    their category average, for one month (BASELINE configs[0])."""
    dd = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_year", "d_moy", "d_month_seq"])
    # scalar subquery: the (distinct) month_seq of 2001-01
    ms_rows = dd.where((col("d_year") == lit(2001))
                       & (col("d_moy") == lit(1))) \
        .select(col("d_month_seq")).limit(1).collect()
    ms = ms_rows[0][0]
    dt = dd.where(col("d_month_seq") == lit(ms)).select(col("d_date_sk"))

    item = _t(session, data_dir, "item",
              ["i_item_sk", "i_category", "i_current_price"])
    avg_cat = item.group_by("i_category").agg(
        Average(col("i_current_price")).alias("avg_price")) \
        .select(col("i_category").alias("cat_avg_key"), col("avg_price"))
    it = item.join(avg_cat, on=[("i_category", "cat_avg_key")]) \
        .where(col("i_current_price") > lit(1.2) * col("avg_price")) \
        .select(col("i_item_sk"))

    cust = _t(session, data_dir, "customer",
              ["c_customer_sk", "c_current_addr_sk"])
    ca = _t(session, data_dir, "customer_address",
            ["ca_address_sk", "ca_state"])
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_item_sk", "ss_customer_sk"])

    return ss.join(dt, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(it, on=[("ss_item_sk", "i_item_sk")]) \
        .join(cust, on=[("ss_customer_sk", "c_customer_sk")]) \
        .join(ca, on=[("c_current_addr_sk", "ca_address_sk")]) \
        .group_by("ca_state") \
        .agg(CountStar().alias("cnt")) \
        .where(col("cnt") >= lit(10)) \
        .order_by(("cnt", True)) \
        .limit(100)


def q42(session, data_dir: str):
    """TPC-DS q42: category revenue for one month/year, manager 1."""
    dt = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_year", "d_moy"]) \
        .where((col("d_moy") == lit(11)) & (col("d_year") == lit(2000)))
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_category_id", "i_category", "i_manager_id"]) \
        .where(col("i_manager_id") == lit(1)) \
        .select(col("i_item_sk"), col("i_category_id"), col("i_category"))
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price"])
    return ss.join(dt, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(it, on=[("ss_item_sk", "i_item_sk")]) \
        .group_by("d_year", "i_category_id", "i_category") \
        .agg(Sum(col("ss_ext_sales_price")).alias("total_sales")) \
        .order_by(("total_sales", False), ("d_year", True),
                  ("i_category_id", True), ("i_category", True)) \
        .limit(100)


def q52(session, data_dir: str):
    """TPC-DS q52: brand revenue for one month/year, manager 1."""
    dt = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_year", "d_moy"]) \
        .where((col("d_moy") == lit(11)) & (col("d_year") == lit(2000)))
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_brand_id", "i_brand", "i_manager_id"]) \
        .where(col("i_manager_id") == lit(1)) \
        .select(col("i_item_sk"), col("i_brand_id"), col("i_brand"))
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price"])
    return ss.join(dt, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(it, on=[("ss_item_sk", "i_item_sk")]) \
        .group_by("d_year", "i_brand_id", "i_brand") \
        .agg(Sum(col("ss_ext_sales_price")).alias("ext_price")) \
        .order_by(("d_year", True), ("ext_price", False),
                  ("i_brand_id", True)) \
        .limit(100)


def q55(session, data_dir: str):
    """TPC-DS q55: brand revenue for manager 28, 1999-11."""
    dt = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_year", "d_moy"]) \
        .where((col("d_moy") == lit(11)) & (col("d_year") == lit(1999)))
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_brand_id", "i_brand", "i_manager_id"]) \
        .where(col("i_manager_id") == lit(28)) \
        .select(col("i_item_sk"), col("i_brand_id"), col("i_brand"))
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price"])
    return ss.join(dt, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(it, on=[("ss_item_sk", "i_item_sk")]) \
        .group_by("i_brand_id", "i_brand") \
        .agg(Sum(col("ss_ext_sales_price")).alias("ext_price")) \
        .order_by(("ext_price", False), ("i_brand_id", True)) \
        .limit(100)




# ---------------------------------------------------------------------------
# round-3 breadth: 15 more queries across plan shapes (window ratio,
# rollup, day-of-week pivot, semi/anti, demographics joins).  Re-derived
# as DataFrame code from the public TPC-DS query definitions (the
# reference stores them as SQL text, TpcdsLikeSpark.scala:1033).
# ---------------------------------------------------------------------------

def _date_sk(y: int, m: int, d: int) -> int:
    """d_date_sk for a calendar date (dsdgen epoch 2415022 = 1900-01-01)."""
    import datetime as _dt
    return 2415022 + (_dt.date(y, m, d) - _dt.date(1900, 1, 1)).days


def q7(session, data_dir: str):
    """TPC-DS q7: item averages for one demographic in 2000 with
    email-or-event promotions."""
    from spark_rapids_tpu.expr.predicates import Or, EqualTo
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_item_sk", "ss_cdemo_sk", "ss_promo_sk",
             "ss_quantity", "ss_list_price", "ss_coupon_amt",
             "ss_sales_price"])
    cd = _t(session, data_dir, "customer_demographics") \
        .where((col("cd_gender") == lit("M"))
               & (col("cd_marital_status") == lit("S"))
               & (col("cd_education_status") == lit("College"))) \
        .select(col("cd_demo_sk"))
    dt = _t(session, data_dir, "date_dim", ["d_date_sk", "d_year"]) \
        .where(col("d_year") == lit(2000)).select(col("d_date_sk"))
    pr = _t(session, data_dir, "promotion",
            ["p_promo_sk", "p_channel_email", "p_channel_event"]) \
        .where(Or(col("p_channel_email") == lit("N"),
                  col("p_channel_event") == lit("N"))) \
        .select(col("p_promo_sk"))
    it = _t(session, data_dir, "item", ["i_item_sk", "i_item_id"])
    return ss.join(cd, on=[("ss_cdemo_sk", "cd_demo_sk")]) \
        .join(dt, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(pr, on=[("ss_promo_sk", "p_promo_sk")]) \
        .join(it, on=[("ss_item_sk", "i_item_sk")]) \
        .group_by("i_item_id") \
        .agg(Average(col("ss_quantity")).alias("agg1"),
             Average(col("ss_list_price")).alias("agg2"),
             Average(col("ss_coupon_amt")).alias("agg3"),
             Average(col("ss_sales_price")).alias("agg4")) \
        .order_by(("i_item_id", True)).limit(100)


def _channel_ratio(sales, date_col, item_col, price_col, session, data_dir,
                   start, categories):
    """Shared shape of q12/q20/q98: 30-day revenue per item with a
    windowed class-revenue ratio."""
    from spark_rapids_tpu.expr.aggregates import Sum as _Sum
    from spark_rapids_tpu.expr.window import WindowExpression, WindowSpec
    from spark_rapids_tpu.expr.predicates import In
    import datetime as _dt
    y, m, d = start
    lo = _date_sk(y, m, d)
    hi = lo + 30
    dt_ = _t(session, data_dir, "date_dim", ["d_date_sk"]) \
        .where((col("d_date_sk") >= lit(lo)) & (col("d_date_sk") <= lit(hi)))
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_item_id", "i_item_desc", "i_category",
             "i_class", "i_current_price"]) \
        .where(In(col("i_category"), [lit(c) for c in categories]))
    base = sales.join(dt_, on=[(date_col, "d_date_sk")]) \
        .join(it, on=[(item_col, "i_item_sk")]) \
        .group_by("i_item_id", "i_item_desc", "i_category", "i_class",
                  "i_current_price") \
        .agg(_Sum(col(price_col)).alias("itemrevenue"))
    class_rev = WindowExpression(
        _Sum(col("itemrevenue")),
        WindowSpec(partition_by=(col("i_class"),)))
    return base.select(
        col("i_item_id"), col("i_item_desc"), col("i_category"),
        col("i_class"), col("i_current_price"), col("itemrevenue"),
        (col("itemrevenue") * lit(100.0) / class_rev).alias("revenueratio")) \
        .order_by(("i_category", True), ("i_class", True),
                  ("i_item_id", True), ("i_item_desc", True),
                  ("revenueratio", True)) \
        .limit(100)


def q12(session, data_dir: str):
    """TPC-DS q12: web revenue ratio by item class (window)."""
    ws = _t(session, data_dir, "web_sales",
            ["ws_sold_date_sk", "ws_item_sk", "ws_ext_sales_price"])
    return _channel_ratio(ws, "ws_sold_date_sk", "ws_item_sk",
                          "ws_ext_sales_price", session, data_dir,
                          (1999, 2, 22), ["Sports", "Books", "Home"])


def q20(session, data_dir: str):
    """TPC-DS q20: catalog revenue ratio by item class (window)."""
    cs = _t(session, data_dir, "catalog_sales",
            ["cs_sold_date_sk", "cs_item_sk", "cs_ext_sales_price"])
    return _channel_ratio(cs, "cs_sold_date_sk", "cs_item_sk",
                          "cs_ext_sales_price", session, data_dir,
                          (1999, 2, 22), ["Sports", "Books", "Home"])


def q98(session, data_dir: str):
    """TPC-DS q98: store revenue ratio by item class (window)."""
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price"])
    return _channel_ratio(ss, "ss_sold_date_sk", "ss_item_sk",
                          "ss_ext_sales_price", session, data_dir,
                          (1999, 2, 22), ["Sports", "Books", "Home"])


def q15(session, data_dir: str):
    """TPC-DS q15: catalog sales by customer zip for 2001Q1 (zip prefix
    / state / big-ticket filter)."""
    from spark_rapids_tpu.expr.predicates import In, Or
    from spark_rapids_tpu.expr.strings import Substring
    cs = _t(session, data_dir, "catalog_sales",
            ["cs_sold_date_sk", "cs_bill_customer_sk", "cs_sales_price"])
    cust = _t(session, data_dir, "customer",
              ["c_customer_sk", "c_current_addr_sk"])
    ca = _t(session, data_dir, "customer_address",
            ["ca_address_sk", "ca_state", "ca_zip"])
    dt = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_qoy", "d_year"]) \
        .where((col("d_qoy") == lit(1)) & (col("d_year") == lit(2001))) \
        .select(col("d_date_sk"))
    zips = ["85669", "86197", "88274", "83405", "86475",
            "85392", "85460", "80348", "81792"]
    cond = Or(Or(In(Substring(col("ca_zip"), lit(1), lit(5)),
                    [lit(z) for z in zips]),
                 In(col("ca_state"), [lit(s) for s in
                                      ("CA", "WA", "GA")])),
              col("cs_sales_price") > lit(500.0))
    return cs.join(cust, on=[("cs_bill_customer_sk", "c_customer_sk")]) \
        .join(ca, on=[("c_current_addr_sk", "ca_address_sk")]) \
        .join(dt, on=[("cs_sold_date_sk", "d_date_sk")]) \
        .where(cond) \
        .group_by("ca_zip") \
        .agg(Sum(col("cs_sales_price")).alias("sum_price")) \
        .order_by(("ca_zip", True)).limit(100)


def q19(session, data_dir: str):
    """TPC-DS q19-like: brand revenue for manager band, 1998-11, customers
    shopping outside their home state (store zip unavailable -> state)."""
    dt = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_year", "d_moy"]) \
        .where((col("d_moy") == lit(11)) & (col("d_year") == lit(1998))) \
        .select(col("d_date_sk"))
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_brand_id", "i_brand", "i_manufact_id",
             "i_manufact", "i_manager_id"]) \
        .where(col("i_manager_id") == lit(8)) \
        .select(col("i_item_sk"), col("i_brand_id"), col("i_brand"),
                col("i_manufact_id"), col("i_manufact"))
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_item_sk", "ss_customer_sk",
             "ss_store_sk", "ss_ext_sales_price"])
    cust = _t(session, data_dir, "customer",
              ["c_customer_sk", "c_current_addr_sk"])
    ca = _t(session, data_dir, "customer_address",
            ["ca_address_sk", "ca_state"])
    st = _t(session, data_dir, "store", ["s_store_sk", "s_state"])
    return ss.join(dt, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(it, on=[("ss_item_sk", "i_item_sk")]) \
        .join(cust, on=[("ss_customer_sk", "c_customer_sk")]) \
        .join(ca, on=[("c_current_addr_sk", "ca_address_sk")]) \
        .join(st, on=[("ss_store_sk", "s_store_sk")]) \
        .where(~(col("ca_state") == col("s_state"))) \
        .group_by("i_brand", "i_brand_id", "i_manufact_id", "i_manufact") \
        .agg(Sum(col("ss_ext_sales_price")).alias("ext_price")) \
        .order_by(("ext_price", False), ("i_brand", True),
                  ("i_brand_id", True), ("i_manufact_id", True),
                  ("i_manufact", True)) \
        .limit(100)


def q26(session, data_dir: str):
    """TPC-DS q26: catalog counterpart of q7."""
    from spark_rapids_tpu.expr.predicates import Or
    cs = _t(session, data_dir, "catalog_sales",
            ["cs_sold_date_sk", "cs_item_sk", "cs_bill_cdemo_sk",
             "cs_promo_sk", "cs_quantity", "cs_list_price",
             "cs_coupon_amt", "cs_sales_price"])
    cd = _t(session, data_dir, "customer_demographics") \
        .where((col("cd_gender") == lit("M"))
               & (col("cd_marital_status") == lit("S"))
               & (col("cd_education_status") == lit("College"))) \
        .select(col("cd_demo_sk"))
    dt = _t(session, data_dir, "date_dim", ["d_date_sk", "d_year"]) \
        .where(col("d_year") == lit(2000)).select(col("d_date_sk"))
    pr = _t(session, data_dir, "promotion",
            ["p_promo_sk", "p_channel_email", "p_channel_event"]) \
        .where(Or(col("p_channel_email") == lit("N"),
                  col("p_channel_event") == lit("N"))) \
        .select(col("p_promo_sk"))
    it = _t(session, data_dir, "item", ["i_item_sk", "i_item_id"])
    return cs.join(cd, on=[("cs_bill_cdemo_sk", "cd_demo_sk")]) \
        .join(dt, on=[("cs_sold_date_sk", "d_date_sk")]) \
        .join(pr, on=[("cs_promo_sk", "p_promo_sk")]) \
        .join(it, on=[("cs_item_sk", "i_item_sk")]) \
        .group_by("i_item_id") \
        .agg(Average(col("cs_quantity")).alias("agg1"),
             Average(col("cs_list_price")).alias("agg2"),
             Average(col("cs_coupon_amt")).alias("agg3"),
             Average(col("cs_sales_price")).alias("agg4")) \
        .order_by(("i_item_id", True)).limit(100)


def q27(session, data_dir: str):
    """TPC-DS q27: demographic item averages with ROLLUP(i_item_id,
    s_state)."""
    from spark_rapids_tpu.expr.predicates import In
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_item_sk", "ss_cdemo_sk", "ss_store_sk",
             "ss_quantity", "ss_list_price", "ss_coupon_amt",
             "ss_sales_price"])
    cd = _t(session, data_dir, "customer_demographics") \
        .where((col("cd_gender") == lit("M"))
               & (col("cd_marital_status") == lit("S"))
               & (col("cd_education_status") == lit("College"))) \
        .select(col("cd_demo_sk"))
    dt = _t(session, data_dir, "date_dim", ["d_date_sk", "d_year"]) \
        .where(col("d_year") == lit(2002)).select(col("d_date_sk"))
    st = _t(session, data_dir, "store", ["s_store_sk", "s_state"]) \
        .where(In(col("s_state"), [lit(s) for s in
                                   ("AL", "AK", "AZ", "AR", "CA", "CO")]))
    it = _t(session, data_dir, "item", ["i_item_sk", "i_item_id"])
    return ss.join(cd, on=[("ss_cdemo_sk", "cd_demo_sk")]) \
        .join(dt, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(st, on=[("ss_store_sk", "s_store_sk")]) \
        .join(it, on=[("ss_item_sk", "i_item_sk")]) \
        .rollup("i_item_id", "s_state") \
        .agg(Average(col("ss_quantity")).alias("agg1"),
             Average(col("ss_list_price")).alias("agg2"),
             Average(col("ss_coupon_amt")).alias("agg3"),
             Average(col("ss_sales_price")).alias("agg4")) \
        .order_by(("i_item_id", True), ("s_state", True)).limit(100)


def q36(session, data_dir: str):
    """TPC-DS q36: gross margin ROLLUP(i_category, i_class) with a rank
    window inside each hierarchy level."""
    from spark_rapids_tpu.expr.core import grouping_id
    from spark_rapids_tpu.expr.predicates import In
    from spark_rapids_tpu.expr.window import (Rank, WindowExpression,
                                              WindowSpec)
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_item_sk", "ss_store_sk",
             "ss_net_profit", "ss_ext_sales_price"])
    dt = _t(session, data_dir, "date_dim", ["d_date_sk", "d_year"]) \
        .where(col("d_year") == lit(2001)).select(col("d_date_sk"))
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_category", "i_class"])
    st = _t(session, data_dir, "store", ["s_store_sk", "s_state"]) \
        .where(In(col("s_state"), [lit(s) for s in
                                   ("AL", "AK", "AZ", "AR", "CA", "CO",
                                    "CT", "DE")]))
    base = ss.join(dt, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(it, on=[("ss_item_sk", "i_item_sk")]) \
        .join(st, on=[("ss_store_sk", "s_store_sk")]) \
        .rollup("i_category", "i_class") \
        .agg((Sum(col("ss_net_profit"))
              / Sum(col("ss_ext_sales_price"))).alias("gross_margin"),
             grouping_id().alias("lochierarchy"))
    rank = WindowExpression(
        Rank(), WindowSpec(
            partition_by=(col("lochierarchy"), col("i_category")),
            order_by=((col("gross_margin"), True),)))
    return base.select(col("gross_margin"), col("i_category"),
                       col("i_class"), col("lochierarchy"),
                       rank.alias("rank_within_parent")) \
        .order_by(("lochierarchy", False), ("i_category", True),
                  ("rank_within_parent", True)) \
        .limit(100)


def q43(session, data_dir: str):
    """TPC-DS q43: per-store day-of-week sales pivot (CASE WHEN)."""
    from spark_rapids_tpu.expr.conditional import CaseWhen
    dt = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_year", "d_dow"]) \
        .where(col("d_year") == lit(2000))
    st = _t(session, data_dir, "store",
            ["s_store_sk", "s_store_id", "s_store_name", "s_gmt_offset"]) \
        .where(col("s_gmt_offset") == lit(-5.0))
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_store_sk", "ss_sales_price"])

    def dow(n):
        return Sum(CaseWhen([(col("d_dow") == lit(n),
                              col("ss_sales_price"))], lit(None)))

    return ss.join(dt, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(st, on=[("ss_store_sk", "s_store_sk")]) \
        .group_by("s_store_name", "s_store_id") \
        .agg(dow(0).alias("sun_sales"), dow(1).alias("mon_sales"),
             dow(2).alias("tue_sales"), dow(3).alias("wed_sales"),
             dow(4).alias("thu_sales"), dow(5).alias("fri_sales"),
             dow(6).alias("sat_sales")) \
        .order_by(("s_store_name", True), ("s_store_id", True)) \
        .limit(100)


def _quarterly_outlier(session, data_dir, group_col, filter_expr):
    """Shared q53/q63 shape: quarterly sales vs the group's average."""
    from spark_rapids_tpu.expr.arithmetic import Abs as _Abs
    from spark_rapids_tpu.expr.window import WindowExpression, WindowSpec
    from spark_rapids_tpu.expr.aggregates import Average as _Avg
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_item_sk", "ss_store_sk",
             "ss_sales_price"])
    dt = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_month_seq", "d_qoy"]) \
        .where((col("d_month_seq") >= lit(1200))
               & (col("d_month_seq") <= lit(1211)))
    st = _t(session, data_dir, "store", ["s_store_sk"])
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_manufact_id", "i_manager_id", "i_category",
             "i_class", "i_brand"]).where(filter_expr)
    base = ss.join(dt, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(st, on=[("ss_store_sk", "s_store_sk")]) \
        .join(it, on=[("ss_item_sk", "i_item_sk")]) \
        .group_by(group_col, "d_qoy") \
        .agg(Sum(col("ss_sales_price")).alias("sum_sales"))
    avg_w = WindowExpression(
        _Avg(col("sum_sales")),
        WindowSpec(partition_by=(col(group_col),)))
    out = base.select(col(group_col), col("d_qoy"), col("sum_sales"),
                      avg_w.alias("avg_sales"))
    return out.where((col("avg_sales") > lit(0.0))
                     & (_Abs(col("sum_sales") - col("avg_sales"))
                        / col("avg_sales") > lit(0.1))) \
        .order_by((group_col, True), ("avg_sales", True),
                  ("sum_sales", True)) \
        .limit(100)


def q53(session, data_dir: str):
    """TPC-DS q53: manufacturers with outlier quarterly sales (window)."""
    from spark_rapids_tpu.expr.predicates import In
    return _quarterly_outlier(
        session, data_dir, "i_manufact_id",
        In(col("i_category"), [lit(c) for c in
                               ("Books", "Children", "Electronics")]))


def q63(session, data_dir: str):
    """TPC-DS q63: managers with outlier quarterly sales (window)."""
    from spark_rapids_tpu.expr.predicates import In
    return _quarterly_outlier(
        session, data_dir, "i_manager_id",
        In(col("i_class"), [lit(c) for c in
                            ("accent", "dresses", "fiction", "shirts")]))


def q69(session, data_dir: str):
    """TPC-DS q69: demographics of customers in 3 states who bought in
    store but not via web/catalog in 2001Q1-ish (semi + anti joins)."""
    from spark_rapids_tpu.expr.predicates import In
    cust = _t(session, data_dir, "customer",
              ["c_customer_sk", "c_current_addr_sk", "c_current_cdemo_sk"])
    ca = _t(session, data_dir, "customer_address",
            ["ca_address_sk", "ca_state"]) \
        .where(In(col("ca_state"), [lit(s) for s in ("KY", "GA", "NM")]))
    cd = _t(session, data_dir, "customer_demographics")
    dt = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_year", "d_moy"]) \
        .where((col("d_year") == lit(2001)) & (col("d_moy") >= lit(4))
               & (col("d_moy") <= lit(6))) \
        .select(col("d_date_sk"))
    ss = _t(session, data_dir, "store_sales",
            ["ss_customer_sk", "ss_sold_date_sk"]) \
        .join(dt, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .select(col("ss_customer_sk"))
    ws = _t(session, data_dir, "web_sales",
            ["ws_bill_customer_sk", "ws_sold_date_sk"]) \
        .join(dt, on=[("ws_sold_date_sk", "d_date_sk")]) \
        .select(col("ws_bill_customer_sk"))
    cs = _t(session, data_dir, "catalog_sales",
            ["cs_bill_customer_sk", "cs_sold_date_sk"]) \
        .join(dt, on=[("cs_sold_date_sk", "d_date_sk")]) \
        .select(col("cs_bill_customer_sk"))
    base = cust.join(ca, on=[("c_current_addr_sk", "ca_address_sk")]) \
        .join(ss, on=[("c_customer_sk", "ss_customer_sk")], how="semi") \
        .join(ws, on=[("c_customer_sk", "ws_bill_customer_sk")],
              how="anti") \
        .join(cs, on=[("c_customer_sk", "cs_bill_customer_sk")],
              how="anti") \
        .join(cd, on=[("c_current_cdemo_sk", "cd_demo_sk")])
    return base.group_by("cd_gender", "cd_marital_status",
                         "cd_education_status", "cd_purchase_estimate",
                         "cd_credit_rating") \
        .agg(CountStar().alias("cnt1")) \
        .order_by(("cd_gender", True), ("cd_marital_status", True),
                  ("cd_education_status", True),
                  ("cd_purchase_estimate", True),
                  ("cd_credit_rating", True)) \
        .limit(100)


def q89(session, data_dir: str):
    """TPC-DS q89: monthly store sales vs category/brand/store average
    (window over 4 keys)."""
    from spark_rapids_tpu.expr.arithmetic import Abs as _Abs
    from spark_rapids_tpu.expr.predicates import In, Or, And
    from spark_rapids_tpu.expr.window import WindowExpression, WindowSpec
    from spark_rapids_tpu.expr.aggregates import Average as _Avg
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_category", "i_class", "i_brand"])
    cond = Or(
        And(In(col("i_category"), [lit(c) for c in
                                   ("Books", "Electronics", "Sports")]),
            In(col("i_class"), [lit(c) for c in
                                ("computers", "fiction", "swimwear")])),
        And(In(col("i_category"), [lit(c) for c in
                                   ("Men", "Jewelry", "Women")]),
            In(col("i_class"), [lit(c) for c in
                                ("shirts", "jewelry boxes", "dresses")])))
    it = it.where(cond)
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_item_sk", "ss_store_sk",
             "ss_sales_price"])
    dt = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_year", "d_moy"]) \
        .where(col("d_year") == lit(1999))
    st = _t(session, data_dir, "store",
            ["s_store_sk", "s_store_name", "s_company_name"])
    base = ss.join(it, on=[("ss_item_sk", "i_item_sk")]) \
        .join(dt, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(st, on=[("ss_store_sk", "s_store_sk")]) \
        .group_by("i_category", "i_class", "i_brand", "s_store_name",
                  "s_company_name", "d_moy") \
        .agg(Sum(col("ss_sales_price")).alias("sum_sales"))
    avg_w = WindowExpression(
        _Avg(col("sum_sales")),
        WindowSpec(partition_by=(col("i_category"), col("i_brand"),
                                 col("s_store_name"),
                                 col("s_company_name"))))
    out = base.select(col("i_category"), col("i_class"), col("i_brand"),
                      col("s_store_name"), col("s_company_name"),
                      col("d_moy"), col("sum_sales"),
                      avg_w.alias("avg_monthly_sales"))
    return out.where((col("avg_monthly_sales") > lit(0.0))
                     & (_Abs(col("sum_sales") - col("avg_monthly_sales"))
                        / col("avg_monthly_sales") > lit(0.1))) \
        .order_by(("sum_sales", True), ("s_store_name", True),
                  ("i_category", True), ("i_brand", True)) \
        .limit(100)


def q96(session, data_dir: str):
    """TPC-DS q96: count of evening sales for dep_count=4 households at
    'ese' stores."""
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_time_sk", "ss_hdemo_sk", "ss_store_sk"])
    hd = _t(session, data_dir, "household_demographics",
            ["hd_demo_sk", "hd_dep_count"]) \
        .where(col("hd_dep_count") == lit(4)).select(col("hd_demo_sk"))
    td = _t(session, data_dir, "time_dim",
            ["t_time_sk", "t_hour", "t_minute"]) \
        .where((col("t_hour") == lit(20)) & (col("t_minute") >= lit(30))) \
        .select(col("t_time_sk"))
    st = _t(session, data_dir, "store", ["s_store_sk", "s_store_name"]) \
        .where(col("s_store_name") == lit("ese")).select(col("s_store_sk"))
    return ss.join(hd, on=[("ss_hdemo_sk", "hd_demo_sk")]) \
        .join(td, on=[("ss_sold_time_sk", "t_time_sk")]) \
        .join(st, on=[("ss_store_sk", "s_store_sk")]) \
        .agg(CountStar().alias("cnt"))


QUERIES = {"q3": q3, "q6": q6, "q7": q7, "q12": q12, "q15": q15,
           "q19": q19, "q20": q20, "q26": q26, "q27": q27, "q36": q36,
           "q42": q42, "q43": q43, "q52": q52, "q53": q53, "q55": q55,
           "q63": q63, "q69": q69, "q89": q89, "q96": q96, "q98": q98}


def build_query(name: str, session, data_dir: str):
    return QUERIES[name](session, data_dir)

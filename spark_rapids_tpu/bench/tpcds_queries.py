"""TPC-DS queries as DataFrame code (the TpcdsLikeSpark.scala pattern).

Each builder takes a :class:`TpuSession` + data_dir and returns a
DataFrame for one TPC-DS query over the pruned generated tables
(reference: integration_tests/.../tpcds/TpcdsLikeSpark.scala — all 99
queries as Spark DataFrame code; this slice implements the
scan/filter/join/agg/sort/limit-shaped ones the baseline tracks,
starting with q6 = BASELINE configs[0]).

Scalar subqueries (q6's month_seq) are evaluated eagerly and folded as
literals — the same plan shape Spark produces after subquery execution.
"""
from __future__ import annotations

import os

from spark_rapids_tpu.expr.aggregates import Average, CountStar, Sum
from spark_rapids_tpu.expr.core import col, lit

__all__ = ["QUERIES", "build_query"]


def _t(session, data_dir: str, table: str, columns=None):
    return session.read_parquet(os.path.join(data_dir, table),
                                columns=columns)


def q3(session, data_dir: str):
    """TPC-DS q3: brand revenue by year for one manufacturer in November."""
    dt = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_year", "d_moy"]).where(col("d_moy") == lit(11))
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_brand_id", "i_brand", "i_manufact_id"]) \
        .where(col("i_manufact_id") == lit(128)) \
        .select(col("i_item_sk"), col("i_brand_id"), col("i_brand"))
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price"])
    return ss.join(dt, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(it, on=[("ss_item_sk", "i_item_sk")]) \
        .group_by("d_year", "i_brand_id", "i_brand") \
        .agg(Sum(col("ss_ext_sales_price")).alias("sum_agg")) \
        .order_by(("d_year", True), ("sum_agg", False),
                  ("i_brand_id", True)) \
        .limit(100)


def q6(session, data_dir: str):
    """TPC-DS q6: state count of customers buying items priced >=120% of
    their category average, for one month (BASELINE configs[0])."""
    dd = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_year", "d_moy", "d_month_seq"])
    # scalar subquery: the (distinct) month_seq of 2001-01
    ms_rows = dd.where((col("d_year") == lit(2001))
                       & (col("d_moy") == lit(1))) \
        .select(col("d_month_seq")).limit(1).collect()
    ms = ms_rows[0][0]
    dt = dd.where(col("d_month_seq") == lit(ms)).select(col("d_date_sk"))

    item = _t(session, data_dir, "item",
              ["i_item_sk", "i_category", "i_current_price"])
    avg_cat = item.group_by("i_category").agg(
        Average(col("i_current_price")).alias("avg_price")) \
        .select(col("i_category").alias("cat_avg_key"), col("avg_price"))
    it = item.join(avg_cat, on=[("i_category", "cat_avg_key")]) \
        .where(col("i_current_price") > lit(1.2) * col("avg_price")) \
        .select(col("i_item_sk"))

    cust = _t(session, data_dir, "customer",
              ["c_customer_sk", "c_current_addr_sk"])
    ca = _t(session, data_dir, "customer_address",
            ["ca_address_sk", "ca_state"])
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_item_sk", "ss_customer_sk"])

    return ss.join(dt, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(it, on=[("ss_item_sk", "i_item_sk")]) \
        .join(cust, on=[("ss_customer_sk", "c_customer_sk")]) \
        .join(ca, on=[("c_current_addr_sk", "ca_address_sk")]) \
        .group_by("ca_state") \
        .agg(CountStar().alias("cnt")) \
        .where(col("cnt") >= lit(10)) \
        .order_by(("cnt", True)) \
        .limit(100)


def q42(session, data_dir: str):
    """TPC-DS q42: category revenue for one month/year, manager 1."""
    dt = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_year", "d_moy"]) \
        .where((col("d_moy") == lit(11)) & (col("d_year") == lit(2000)))
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_category_id", "i_category", "i_manager_id"]) \
        .where(col("i_manager_id") == lit(1)) \
        .select(col("i_item_sk"), col("i_category_id"), col("i_category"))
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price"])
    return ss.join(dt, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(it, on=[("ss_item_sk", "i_item_sk")]) \
        .group_by("d_year", "i_category_id", "i_category") \
        .agg(Sum(col("ss_ext_sales_price")).alias("total_sales")) \
        .order_by(("total_sales", False), ("d_year", True),
                  ("i_category_id", True), ("i_category", True)) \
        .limit(100)


def q52(session, data_dir: str):
    """TPC-DS q52: brand revenue for one month/year, manager 1."""
    dt = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_year", "d_moy"]) \
        .where((col("d_moy") == lit(11)) & (col("d_year") == lit(2000)))
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_brand_id", "i_brand", "i_manager_id"]) \
        .where(col("i_manager_id") == lit(1)) \
        .select(col("i_item_sk"), col("i_brand_id"), col("i_brand"))
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price"])
    return ss.join(dt, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(it, on=[("ss_item_sk", "i_item_sk")]) \
        .group_by("d_year", "i_brand_id", "i_brand") \
        .agg(Sum(col("ss_ext_sales_price")).alias("ext_price")) \
        .order_by(("d_year", True), ("ext_price", False),
                  ("i_brand_id", True)) \
        .limit(100)


def q55(session, data_dir: str):
    """TPC-DS q55: brand revenue for manager 28, 1999-11."""
    dt = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_year", "d_moy"]) \
        .where((col("d_moy") == lit(11)) & (col("d_year") == lit(1999)))
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_brand_id", "i_brand", "i_manager_id"]) \
        .where(col("i_manager_id") == lit(28)) \
        .select(col("i_item_sk"), col("i_brand_id"), col("i_brand"))
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price"])
    return ss.join(dt, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(it, on=[("ss_item_sk", "i_item_sk")]) \
        .group_by("i_brand_id", "i_brand") \
        .agg(Sum(col("ss_ext_sales_price")).alias("ext_price")) \
        .order_by(("ext_price", False), ("i_brand_id", True)) \
        .limit(100)




# ---------------------------------------------------------------------------
# round-3 breadth: 15 more queries across plan shapes (window ratio,
# rollup, day-of-week pivot, semi/anti, demographics joins).  Re-derived
# as DataFrame code from the public TPC-DS query definitions (the
# reference stores them as SQL text, TpcdsLikeSpark.scala:1033).
# ---------------------------------------------------------------------------

def _date_sk(y: int, m: int, d: int) -> int:
    """d_date_sk for a calendar date (dsdgen epoch 2415022 = 1900-01-01)."""
    import datetime as _dt
    return 2415022 + (_dt.date(y, m, d) - _dt.date(1900, 1, 1)).days


def q7(session, data_dir: str):
    """TPC-DS q7: item averages for one demographic in 2000 with
    email-or-event promotions."""
    from spark_rapids_tpu.expr.predicates import Or, EqualTo
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_item_sk", "ss_cdemo_sk", "ss_promo_sk",
             "ss_quantity", "ss_list_price", "ss_coupon_amt",
             "ss_sales_price"])
    cd = _t(session, data_dir, "customer_demographics") \
        .where((col("cd_gender") == lit("M"))
               & (col("cd_marital_status") == lit("S"))
               & (col("cd_education_status") == lit("College"))) \
        .select(col("cd_demo_sk"))
    dt = _t(session, data_dir, "date_dim", ["d_date_sk", "d_year"]) \
        .where(col("d_year") == lit(2000)).select(col("d_date_sk"))
    pr = _t(session, data_dir, "promotion",
            ["p_promo_sk", "p_channel_email", "p_channel_event"]) \
        .where(Or(col("p_channel_email") == lit("N"),
                  col("p_channel_event") == lit("N"))) \
        .select(col("p_promo_sk"))
    it = _t(session, data_dir, "item", ["i_item_sk", "i_item_id"])
    return ss.join(cd, on=[("ss_cdemo_sk", "cd_demo_sk")]) \
        .join(dt, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(pr, on=[("ss_promo_sk", "p_promo_sk")]) \
        .join(it, on=[("ss_item_sk", "i_item_sk")]) \
        .group_by("i_item_id") \
        .agg(Average(col("ss_quantity")).alias("agg1"),
             Average(col("ss_list_price")).alias("agg2"),
             Average(col("ss_coupon_amt")).alias("agg3"),
             Average(col("ss_sales_price")).alias("agg4")) \
        .order_by(("i_item_id", True)).limit(100)


def _channel_ratio(sales, date_col, item_col, price_col, session, data_dir,
                   start, categories):
    """Shared shape of q12/q20/q98: 30-day revenue per item with a
    windowed class-revenue ratio."""
    from spark_rapids_tpu.expr.aggregates import Sum as _Sum
    from spark_rapids_tpu.expr.window import WindowExpression, WindowSpec
    from spark_rapids_tpu.expr.predicates import In
    import datetime as _dt
    y, m, d = start
    lo = _date_sk(y, m, d)
    hi = lo + 30
    dt_ = _t(session, data_dir, "date_dim", ["d_date_sk"]) \
        .where((col("d_date_sk") >= lit(lo)) & (col("d_date_sk") <= lit(hi)))
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_item_id", "i_item_desc", "i_category",
             "i_class", "i_current_price"]) \
        .where(In(col("i_category"), [lit(c) for c in categories]))
    base = sales.join(dt_, on=[(date_col, "d_date_sk")]) \
        .join(it, on=[(item_col, "i_item_sk")]) \
        .group_by("i_item_id", "i_item_desc", "i_category", "i_class",
                  "i_current_price") \
        .agg(_Sum(col(price_col)).alias("itemrevenue"))
    class_rev = WindowExpression(
        _Sum(col("itemrevenue")),
        WindowSpec(partition_by=(col("i_class"),)))
    return base.select(
        col("i_item_id"), col("i_item_desc"), col("i_category"),
        col("i_class"), col("i_current_price"), col("itemrevenue"),
        (col("itemrevenue") * lit(100.0) / class_rev).alias("revenueratio")) \
        .order_by(("i_category", True), ("i_class", True),
                  ("i_item_id", True), ("i_item_desc", True),
                  ("revenueratio", True)) \
        .limit(100)


def q12(session, data_dir: str):
    """TPC-DS q12: web revenue ratio by item class (window)."""
    ws = _t(session, data_dir, "web_sales",
            ["ws_sold_date_sk", "ws_item_sk", "ws_ext_sales_price"])
    return _channel_ratio(ws, "ws_sold_date_sk", "ws_item_sk",
                          "ws_ext_sales_price", session, data_dir,
                          (1999, 2, 22), ["Sports", "Books", "Home"])


def q20(session, data_dir: str):
    """TPC-DS q20: catalog revenue ratio by item class (window)."""
    cs = _t(session, data_dir, "catalog_sales",
            ["cs_sold_date_sk", "cs_item_sk", "cs_ext_sales_price"])
    return _channel_ratio(cs, "cs_sold_date_sk", "cs_item_sk",
                          "cs_ext_sales_price", session, data_dir,
                          (1999, 2, 22), ["Sports", "Books", "Home"])


def q98(session, data_dir: str):
    """TPC-DS q98: store revenue ratio by item class (window)."""
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price"])
    return _channel_ratio(ss, "ss_sold_date_sk", "ss_item_sk",
                          "ss_ext_sales_price", session, data_dir,
                          (1999, 2, 22), ["Sports", "Books", "Home"])


def q15(session, data_dir: str):
    """TPC-DS q15: catalog sales by customer zip for 2001Q1 (zip prefix
    / state / big-ticket filter)."""
    from spark_rapids_tpu.expr.predicates import In, Or
    from spark_rapids_tpu.expr.strings import Substring
    cs = _t(session, data_dir, "catalog_sales",
            ["cs_sold_date_sk", "cs_bill_customer_sk", "cs_sales_price"])
    cust = _t(session, data_dir, "customer",
              ["c_customer_sk", "c_current_addr_sk"])
    ca = _t(session, data_dir, "customer_address",
            ["ca_address_sk", "ca_state", "ca_zip"])
    dt = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_qoy", "d_year"]) \
        .where((col("d_qoy") == lit(1)) & (col("d_year") == lit(2001))) \
        .select(col("d_date_sk"))
    zips = ["85669", "86197", "88274", "83405", "86475",
            "85392", "85460", "80348", "81792"]
    cond = Or(Or(In(Substring(col("ca_zip"), lit(1), lit(5)),
                    [lit(z) for z in zips]),
                 In(col("ca_state"), [lit(s) for s in
                                      ("CA", "WA", "GA")])),
              col("cs_sales_price") > lit(500.0))
    return cs.join(cust, on=[("cs_bill_customer_sk", "c_customer_sk")]) \
        .join(ca, on=[("c_current_addr_sk", "ca_address_sk")]) \
        .join(dt, on=[("cs_sold_date_sk", "d_date_sk")]) \
        .where(cond) \
        .group_by("ca_zip") \
        .agg(Sum(col("cs_sales_price")).alias("sum_price")) \
        .order_by(("ca_zip", True)).limit(100)


def q19(session, data_dir: str):
    """TPC-DS q19-like: brand revenue for manager band, 1998-11, customers
    shopping outside their home state (store zip unavailable -> state)."""
    dt = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_year", "d_moy"]) \
        .where((col("d_moy") == lit(11)) & (col("d_year") == lit(1998))) \
        .select(col("d_date_sk"))
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_brand_id", "i_brand", "i_manufact_id",
             "i_manufact", "i_manager_id"]) \
        .where(col("i_manager_id") == lit(8)) \
        .select(col("i_item_sk"), col("i_brand_id"), col("i_brand"),
                col("i_manufact_id"), col("i_manufact"))
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_item_sk", "ss_customer_sk",
             "ss_store_sk", "ss_ext_sales_price"])
    cust = _t(session, data_dir, "customer",
              ["c_customer_sk", "c_current_addr_sk"])
    ca = _t(session, data_dir, "customer_address",
            ["ca_address_sk", "ca_state"])
    st = _t(session, data_dir, "store", ["s_store_sk", "s_state"])
    return ss.join(dt, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(it, on=[("ss_item_sk", "i_item_sk")]) \
        .join(cust, on=[("ss_customer_sk", "c_customer_sk")]) \
        .join(ca, on=[("c_current_addr_sk", "ca_address_sk")]) \
        .join(st, on=[("ss_store_sk", "s_store_sk")]) \
        .where(~(col("ca_state") == col("s_state"))) \
        .group_by("i_brand", "i_brand_id", "i_manufact_id", "i_manufact") \
        .agg(Sum(col("ss_ext_sales_price")).alias("ext_price")) \
        .order_by(("ext_price", False), ("i_brand", True),
                  ("i_brand_id", True), ("i_manufact_id", True),
                  ("i_manufact", True)) \
        .limit(100)


def q26(session, data_dir: str):
    """TPC-DS q26: catalog counterpart of q7."""
    from spark_rapids_tpu.expr.predicates import Or
    cs = _t(session, data_dir, "catalog_sales",
            ["cs_sold_date_sk", "cs_item_sk", "cs_bill_cdemo_sk",
             "cs_promo_sk", "cs_quantity", "cs_list_price",
             "cs_coupon_amt", "cs_sales_price"])
    cd = _t(session, data_dir, "customer_demographics") \
        .where((col("cd_gender") == lit("M"))
               & (col("cd_marital_status") == lit("S"))
               & (col("cd_education_status") == lit("College"))) \
        .select(col("cd_demo_sk"))
    dt = _t(session, data_dir, "date_dim", ["d_date_sk", "d_year"]) \
        .where(col("d_year") == lit(2000)).select(col("d_date_sk"))
    pr = _t(session, data_dir, "promotion",
            ["p_promo_sk", "p_channel_email", "p_channel_event"]) \
        .where(Or(col("p_channel_email") == lit("N"),
                  col("p_channel_event") == lit("N"))) \
        .select(col("p_promo_sk"))
    it = _t(session, data_dir, "item", ["i_item_sk", "i_item_id"])
    return cs.join(cd, on=[("cs_bill_cdemo_sk", "cd_demo_sk")]) \
        .join(dt, on=[("cs_sold_date_sk", "d_date_sk")]) \
        .join(pr, on=[("cs_promo_sk", "p_promo_sk")]) \
        .join(it, on=[("cs_item_sk", "i_item_sk")]) \
        .group_by("i_item_id") \
        .agg(Average(col("cs_quantity")).alias("agg1"),
             Average(col("cs_list_price")).alias("agg2"),
             Average(col("cs_coupon_amt")).alias("agg3"),
             Average(col("cs_sales_price")).alias("agg4")) \
        .order_by(("i_item_id", True)).limit(100)


def q27(session, data_dir: str):
    """TPC-DS q27: demographic item averages with ROLLUP(i_item_id,
    s_state)."""
    from spark_rapids_tpu.expr.predicates import In
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_item_sk", "ss_cdemo_sk", "ss_store_sk",
             "ss_quantity", "ss_list_price", "ss_coupon_amt",
             "ss_sales_price"])
    cd = _t(session, data_dir, "customer_demographics") \
        .where((col("cd_gender") == lit("M"))
               & (col("cd_marital_status") == lit("S"))
               & (col("cd_education_status") == lit("College"))) \
        .select(col("cd_demo_sk"))
    dt = _t(session, data_dir, "date_dim", ["d_date_sk", "d_year"]) \
        .where(col("d_year") == lit(2002)).select(col("d_date_sk"))
    st = _t(session, data_dir, "store", ["s_store_sk", "s_state"]) \
        .where(In(col("s_state"), [lit(s) for s in
                                   ("AL", "AK", "AZ", "AR", "CA", "CO")]))
    it = _t(session, data_dir, "item", ["i_item_sk", "i_item_id"])
    return ss.join(cd, on=[("ss_cdemo_sk", "cd_demo_sk")]) \
        .join(dt, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(st, on=[("ss_store_sk", "s_store_sk")]) \
        .join(it, on=[("ss_item_sk", "i_item_sk")]) \
        .rollup("i_item_id", "s_state") \
        .agg(Average(col("ss_quantity")).alias("agg1"),
             Average(col("ss_list_price")).alias("agg2"),
             Average(col("ss_coupon_amt")).alias("agg3"),
             Average(col("ss_sales_price")).alias("agg4")) \
        .order_by(("i_item_id", True), ("s_state", True)).limit(100)


def q36(session, data_dir: str):
    """TPC-DS q36: gross margin ROLLUP(i_category, i_class) with a rank
    window inside each hierarchy level."""
    from spark_rapids_tpu.expr.core import grouping_id
    from spark_rapids_tpu.expr.predicates import In
    from spark_rapids_tpu.expr.window import (Rank, WindowExpression,
                                              WindowSpec)
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_item_sk", "ss_store_sk",
             "ss_net_profit", "ss_ext_sales_price"])
    dt = _t(session, data_dir, "date_dim", ["d_date_sk", "d_year"]) \
        .where(col("d_year") == lit(2001)).select(col("d_date_sk"))
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_category", "i_class"])
    st = _t(session, data_dir, "store", ["s_store_sk", "s_state"]) \
        .where(In(col("s_state"), [lit(s) for s in
                                   ("AL", "AK", "AZ", "AR", "CA", "CO",
                                    "CT", "DE")]))
    base = ss.join(dt, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(it, on=[("ss_item_sk", "i_item_sk")]) \
        .join(st, on=[("ss_store_sk", "s_store_sk")]) \
        .rollup("i_category", "i_class") \
        .agg((Sum(col("ss_net_profit"))
              / Sum(col("ss_ext_sales_price"))).alias("gross_margin"),
             grouping_id().alias("lochierarchy"))
    rank = WindowExpression(
        Rank(), WindowSpec(
            partition_by=(col("lochierarchy"), col("i_category")),
            order_by=((col("gross_margin"), True),)))
    return base.select(col("gross_margin"), col("i_category"),
                       col("i_class"), col("lochierarchy"),
                       rank.alias("rank_within_parent")) \
        .order_by(("lochierarchy", False), ("i_category", True),
                  ("rank_within_parent", True)) \
        .limit(100)


def q43(session, data_dir: str):
    """TPC-DS q43: per-store day-of-week sales pivot (CASE WHEN)."""
    from spark_rapids_tpu.expr.conditional import CaseWhen
    dt = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_year", "d_dow"]) \
        .where(col("d_year") == lit(2000))
    st = _t(session, data_dir, "store",
            ["s_store_sk", "s_store_id", "s_store_name", "s_gmt_offset"]) \
        .where(col("s_gmt_offset") == lit(-5.0))
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_store_sk", "ss_sales_price"])

    def dow(n):
        return Sum(CaseWhen([(col("d_dow") == lit(n),
                              col("ss_sales_price"))], lit(None)))

    return ss.join(dt, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(st, on=[("ss_store_sk", "s_store_sk")]) \
        .group_by("s_store_name", "s_store_id") \
        .agg(dow(0).alias("sun_sales"), dow(1).alias("mon_sales"),
             dow(2).alias("tue_sales"), dow(3).alias("wed_sales"),
             dow(4).alias("thu_sales"), dow(5).alias("fri_sales"),
             dow(6).alias("sat_sales")) \
        .order_by(("s_store_name", True), ("s_store_id", True)) \
        .limit(100)


def _quarterly_outlier(session, data_dir, group_col, filter_expr):
    """Shared q53/q63 shape: quarterly sales vs the group's average."""
    from spark_rapids_tpu.expr.arithmetic import Abs as _Abs
    from spark_rapids_tpu.expr.window import WindowExpression, WindowSpec
    from spark_rapids_tpu.expr.aggregates import Average as _Avg
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_item_sk", "ss_store_sk",
             "ss_sales_price"])
    dt = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_month_seq", "d_qoy"]) \
        .where((col("d_month_seq") >= lit(1200))
               & (col("d_month_seq") <= lit(1211)))
    st = _t(session, data_dir, "store", ["s_store_sk"])
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_manufact_id", "i_manager_id", "i_category",
             "i_class", "i_brand"]).where(filter_expr)
    base = ss.join(dt, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(st, on=[("ss_store_sk", "s_store_sk")]) \
        .join(it, on=[("ss_item_sk", "i_item_sk")]) \
        .group_by(group_col, "d_qoy") \
        .agg(Sum(col("ss_sales_price")).alias("sum_sales"))
    avg_w = WindowExpression(
        _Avg(col("sum_sales")),
        WindowSpec(partition_by=(col(group_col),)))
    out = base.select(col(group_col), col("d_qoy"), col("sum_sales"),
                      avg_w.alias("avg_sales"))
    return out.where((col("avg_sales") > lit(0.0))
                     & (_Abs(col("sum_sales") - col("avg_sales"))
                        / col("avg_sales") > lit(0.1))) \
        .order_by((group_col, True), ("avg_sales", True),
                  ("sum_sales", True)) \
        .limit(100)


def q53(session, data_dir: str):
    """TPC-DS q53: manufacturers with outlier quarterly sales (window)."""
    from spark_rapids_tpu.expr.predicates import In
    return _quarterly_outlier(
        session, data_dir, "i_manufact_id",
        In(col("i_category"), [lit(c) for c in
                               ("Books", "Children", "Electronics")]))


def q63(session, data_dir: str):
    """TPC-DS q63: managers with outlier quarterly sales (window)."""
    from spark_rapids_tpu.expr.predicates import In
    return _quarterly_outlier(
        session, data_dir, "i_manager_id",
        In(col("i_class"), [lit(c) for c in
                            ("accent", "dresses", "fiction", "shirts")]))


def q69(session, data_dir: str):
    """TPC-DS q69: demographics of customers in 3 states who bought in
    store but not via web/catalog in 2001Q1-ish (semi + anti joins)."""
    from spark_rapids_tpu.expr.predicates import In
    cust = _t(session, data_dir, "customer",
              ["c_customer_sk", "c_current_addr_sk", "c_current_cdemo_sk"])
    ca = _t(session, data_dir, "customer_address",
            ["ca_address_sk", "ca_state"]) \
        .where(In(col("ca_state"), [lit(s) for s in ("KY", "GA", "NM")]))
    cd = _t(session, data_dir, "customer_demographics")
    dt = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_year", "d_moy"]) \
        .where((col("d_year") == lit(2001)) & (col("d_moy") >= lit(4))
               & (col("d_moy") <= lit(6))) \
        .select(col("d_date_sk"))
    ss = _t(session, data_dir, "store_sales",
            ["ss_customer_sk", "ss_sold_date_sk"]) \
        .join(dt, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .select(col("ss_customer_sk"))
    ws = _t(session, data_dir, "web_sales",
            ["ws_bill_customer_sk", "ws_sold_date_sk"]) \
        .join(dt, on=[("ws_sold_date_sk", "d_date_sk")]) \
        .select(col("ws_bill_customer_sk"))
    cs = _t(session, data_dir, "catalog_sales",
            ["cs_bill_customer_sk", "cs_sold_date_sk"]) \
        .join(dt, on=[("cs_sold_date_sk", "d_date_sk")]) \
        .select(col("cs_bill_customer_sk"))
    base = cust.join(ca, on=[("c_current_addr_sk", "ca_address_sk")]) \
        .join(ss, on=[("c_customer_sk", "ss_customer_sk")], how="semi") \
        .join(ws, on=[("c_customer_sk", "ws_bill_customer_sk")],
              how="anti") \
        .join(cs, on=[("c_customer_sk", "cs_bill_customer_sk")],
              how="anti") \
        .join(cd, on=[("c_current_cdemo_sk", "cd_demo_sk")])
    return base.group_by("cd_gender", "cd_marital_status",
                         "cd_education_status", "cd_purchase_estimate",
                         "cd_credit_rating") \
        .agg(CountStar().alias("cnt1")) \
        .order_by(("cd_gender", True), ("cd_marital_status", True),
                  ("cd_education_status", True),
                  ("cd_purchase_estimate", True),
                  ("cd_credit_rating", True)) \
        .limit(100)


def q89(session, data_dir: str):
    """TPC-DS q89: monthly store sales vs category/brand/store average
    (window over 4 keys)."""
    from spark_rapids_tpu.expr.arithmetic import Abs as _Abs
    from spark_rapids_tpu.expr.predicates import In, Or, And
    from spark_rapids_tpu.expr.window import WindowExpression, WindowSpec
    from spark_rapids_tpu.expr.aggregates import Average as _Avg
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_category", "i_class", "i_brand"])
    cond = Or(
        And(In(col("i_category"), [lit(c) for c in
                                   ("Books", "Electronics", "Sports")]),
            In(col("i_class"), [lit(c) for c in
                                ("computers", "fiction", "swimwear")])),
        And(In(col("i_category"), [lit(c) for c in
                                   ("Men", "Jewelry", "Women")]),
            In(col("i_class"), [lit(c) for c in
                                ("shirts", "jewelry boxes", "dresses")])))
    it = it.where(cond)
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_item_sk", "ss_store_sk",
             "ss_sales_price"])
    dt = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_year", "d_moy"]) \
        .where(col("d_year") == lit(1999))
    st = _t(session, data_dir, "store",
            ["s_store_sk", "s_store_name", "s_company_name"])
    base = ss.join(it, on=[("ss_item_sk", "i_item_sk")]) \
        .join(dt, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(st, on=[("ss_store_sk", "s_store_sk")]) \
        .group_by("i_category", "i_class", "i_brand", "s_store_name",
                  "s_company_name", "d_moy") \
        .agg(Sum(col("ss_sales_price")).alias("sum_sales"))
    avg_w = WindowExpression(
        _Avg(col("sum_sales")),
        WindowSpec(partition_by=(col("i_category"), col("i_brand"),
                                 col("s_store_name"),
                                 col("s_company_name"))))
    out = base.select(col("i_category"), col("i_class"), col("i_brand"),
                      col("s_store_name"), col("s_company_name"),
                      col("d_moy"), col("sum_sales"),
                      avg_w.alias("avg_monthly_sales"))
    return out.where((col("avg_monthly_sales") > lit(0.0))
                     & (_Abs(col("sum_sales") - col("avg_monthly_sales"))
                        / col("avg_monthly_sales") > lit(0.1))) \
        .order_by(("sum_sales", True), ("s_store_name", True),
                  ("i_category", True), ("i_brand", True)) \
        .limit(100)


def q96(session, data_dir: str):
    """TPC-DS q96: count of evening sales for dep_count=4 households at
    'ese' stores."""
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_time_sk", "ss_hdemo_sk", "ss_store_sk"])
    hd = _t(session, data_dir, "household_demographics",
            ["hd_demo_sk", "hd_dep_count"]) \
        .where(col("hd_dep_count") == lit(4)).select(col("hd_demo_sk"))
    td = _t(session, data_dir, "time_dim",
            ["t_time_sk", "t_hour", "t_minute"]) \
        .where((col("t_hour") == lit(20)) & (col("t_minute") >= lit(30))) \
        .select(col("t_time_sk"))
    st = _t(session, data_dir, "store", ["s_store_sk", "s_store_name"]) \
        .where(col("s_store_name") == lit("ese")).select(col("s_store_sk"))
    return ss.join(hd, on=[("ss_hdemo_sk", "hd_demo_sk")]) \
        .join(td, on=[("ss_sold_time_sk", "t_time_sk")]) \
        .join(st, on=[("ss_store_sk", "s_store_sk")]) \
        .agg(CountStar().alias("cnt"))


# ---------------------------------------------------------------------------
# round-3 breadth, second tranche: ternary-OR demographic filters (q13/
# q48), tri-channel unions (q33/q60), cross-join ratios (q61/q65/q88),
# ticket-grain aggregations (q68/q73/q79).  Where the pruned generator
# lacks a column (e.g. ss_addr_sk), the address leg rides the customer's
# current address — noted per query.
# ---------------------------------------------------------------------------

def q13(session, data_dir: str):
    """TPC-DS q13: sales averages under OR'd demographic x price bands
    (address leg via customer current address: generator has no
    ss_addr_sk)."""
    from spark_rapids_tpu.expr.predicates import In, Or
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_store_sk", "ss_cdemo_sk", "ss_hdemo_sk",
             "ss_customer_sk", "ss_quantity", "ss_sales_price",
             "ss_ext_sales_price", "ss_ext_wholesale_cost", "ss_net_profit"])
    st = _t(session, data_dir, "store", ["s_store_sk"])
    dt = _t(session, data_dir, "date_dim", ["d_date_sk", "d_year"]) \
        .where(col("d_year") == lit(2001)).select(col("d_date_sk"))
    cd = _t(session, data_dir, "customer_demographics",
            ["cd_demo_sk", "cd_marital_status", "cd_education_status"])
    hd = _t(session, data_dir, "household_demographics",
            ["hd_demo_sk", "hd_dep_count"])
    cu = _t(session, data_dir, "customer",
            ["c_customer_sk", "c_current_addr_sk"])
    ca = _t(session, data_dir, "customer_address",
            ["ca_address_sk", "ca_state"])
    base = ss.join(st, on=[("ss_store_sk", "s_store_sk")]) \
        .join(dt, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(cd, on=[("ss_cdemo_sk", "cd_demo_sk")]) \
        .join(hd, on=[("ss_hdemo_sk", "hd_demo_sk")]) \
        .join(cu, on=[("ss_customer_sk", "c_customer_sk")]) \
        .join(ca, on=[("c_current_addr_sk", "ca_address_sk")])
    demo = Or(Or(
        (col("cd_marital_status") == lit("M"))
        & (col("cd_education_status") == lit("Advanced Degree"))
        & (col("ss_sales_price") >= lit(100.0))
        & (col("ss_sales_price") <= lit(150.0))
        & (col("hd_dep_count") == lit(3)),
        (col("cd_marital_status") == lit("S"))
        & (col("cd_education_status") == lit("College"))
        & (col("ss_sales_price") >= lit(50.0))
        & (col("ss_sales_price") <= lit(100.0))
        & (col("hd_dep_count") == lit(1))),
        (col("cd_marital_status") == lit("W"))
        & (col("cd_education_status") == lit("2 yr Degree"))
        & (col("ss_sales_price") >= lit(150.0))
        & (col("ss_sales_price") <= lit(200.0))
        & (col("hd_dep_count") == lit(1)))
    addr = Or(Or(
        In(col("ca_state"), [lit(s) for s in ("TX", "OH", "MI")])
        & (col("ss_net_profit") >= lit(100.0))
        & (col("ss_net_profit") <= lit(200.0)),
        In(col("ca_state"), [lit(s) for s in ("OR", "NM", "KY")])
        & (col("ss_net_profit") >= lit(150.0))
        & (col("ss_net_profit") <= lit(300.0))),
        In(col("ca_state"), [lit(s) for s in ("VA", "TX", "MS")])
        & (col("ss_net_profit") >= lit(50.0))
        & (col("ss_net_profit") <= lit(250.0)))
    return base.where(demo & addr).agg(
        Average(col("ss_quantity")).alias("avg_qty"),
        Average(col("ss_ext_sales_price")).alias("avg_esp"),
        Average(col("ss_ext_wholesale_cost")).alias("avg_ewc"),
        Sum(col("ss_ext_wholesale_cost")).alias("sum_ewc"))


def q48(session, data_dir: str):
    """TPC-DS q48: quantity sum under OR'd demographic/state bands
    (address leg via customer current address)."""
    from spark_rapids_tpu.expr.predicates import In, Or
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_store_sk", "ss_cdemo_sk",
             "ss_customer_sk", "ss_quantity", "ss_sales_price",
             "ss_net_profit"])
    st = _t(session, data_dir, "store", ["s_store_sk"])
    dt = _t(session, data_dir, "date_dim", ["d_date_sk", "d_year"]) \
        .where(col("d_year") == lit(2000)).select(col("d_date_sk"))
    cd = _t(session, data_dir, "customer_demographics",
            ["cd_demo_sk", "cd_marital_status", "cd_education_status"])
    cu = _t(session, data_dir, "customer",
            ["c_customer_sk", "c_current_addr_sk"])
    ca = _t(session, data_dir, "customer_address",
            ["ca_address_sk", "ca_state"])
    base = ss.join(st, on=[("ss_store_sk", "s_store_sk")]) \
        .join(dt, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(cd, on=[("ss_cdemo_sk", "cd_demo_sk")]) \
        .join(cu, on=[("ss_customer_sk", "c_customer_sk")]) \
        .join(ca, on=[("c_current_addr_sk", "ca_address_sk")])
    demo = Or(Or(
        (col("cd_marital_status") == lit("M"))
        & (col("cd_education_status") == lit("4 yr Degree"))
        & (col("ss_sales_price") >= lit(100.0))
        & (col("ss_sales_price") <= lit(150.0)),
        (col("cd_marital_status") == lit("D"))
        & (col("cd_education_status") == lit("2 yr Degree"))
        & (col("ss_sales_price") >= lit(50.0))
        & (col("ss_sales_price") <= lit(100.0))),
        (col("cd_marital_status") == lit("S"))
        & (col("cd_education_status") == lit("College"))
        & (col("ss_sales_price") >= lit(150.0))
        & (col("ss_sales_price") <= lit(200.0)))
    addr = Or(Or(
        In(col("ca_state"), [lit(s) for s in ("CO", "OH", "TX")])
        & (col("ss_net_profit") >= lit(0.0))
        & (col("ss_net_profit") <= lit(2000.0)),
        In(col("ca_state"), [lit(s) for s in ("OR", "MN", "KY")])
        & (col("ss_net_profit") >= lit(150.0))
        & (col("ss_net_profit") <= lit(3000.0))),
        In(col("ca_state"), [lit(s) for s in ("VA", "CA", "MS")])
        & (col("ss_net_profit") >= lit(50.0))
        & (col("ss_net_profit") <= lit(25000.0)))
    return base.where(demo & addr).agg(Sum(col("ss_quantity")).alias("q"))


def _channel_agg(session, data_dir, sales, date_col, item_col, price_col,
                 group_col, group_vals, year, moy):
    """One channel's month revenue grouped by an item attribute — the
    shared pipeline of q33 (i_manufact_id) and q60 (i_item_id)."""
    from spark_rapids_tpu.expr.predicates import In
    dt = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_year", "d_moy"]) \
        .where((col("d_year") == lit(year)) & (col("d_moy") == lit(moy))) \
        .select(col("d_date_sk"))
    it = _t(session, data_dir, "item", ["i_item_sk", group_col]) \
        .where(In(col(group_col), [lit(v) for v in group_vals]))
    return sales.join(dt, on=[(date_col, "d_date_sk")]) \
        .join(it, on=[(item_col, "i_item_sk")]) \
        .group_by(group_col) \
        .agg(Sum(col(price_col)).alias("total_sales"))


def q33(session, data_dir: str):
    """TPC-DS q33: Electronics manufacturer revenue summed across the
    three sales channels (union of per-channel aggregates).  The
    manufacturer-id set is the eagerly-folded scalar subquery (house
    pattern for subqueries)."""
    ids_rows = _t(session, data_dir, "item",
                  ["i_category", "i_manufact_id"]) \
        .where(col("i_category") == lit("Electronics")) \
        .group_by("i_manufact_id").agg(CountStar().alias("c")).collect()
    ids = sorted({r[0] for r in ids_rows})[:40]
    if not ids:
        ids = [-1]
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price"])
    cs = _t(session, data_dir, "catalog_sales",
            ["cs_sold_date_sk", "cs_item_sk", "cs_ext_sales_price"])
    ws = _t(session, data_dir, "web_sales",
            ["ws_sold_date_sk", "ws_item_sk", "ws_ext_sales_price"])
    u = _channel_agg(session, data_dir, ss, "ss_sold_date_sk",
                     "ss_item_sk", "ss_ext_sales_price", "i_manufact_id",
                     ids, 1998, 5) \
        .union(_channel_agg(session, data_dir, cs, "cs_sold_date_sk",
                            "cs_item_sk", "cs_ext_sales_price",
                            "i_manufact_id", ids, 1998, 5)) \
        .union(_channel_agg(session, data_dir, ws, "ws_sold_date_sk",
                            "ws_item_sk", "ws_ext_sales_price",
                            "i_manufact_id", ids, 1998, 5))
    return u.group_by("i_manufact_id") \
        .agg(Sum(col("total_sales")).alias("total_sales")) \
        .order_by(("total_sales", True)).limit(100)


def q60(session, data_dir: str):
    """TPC-DS q60: Music item revenue across the three channels (union
    of per-channel aggregates by item id)."""
    ids_rows = _t(session, data_dir, "item",
                  ["i_category", "i_item_id"]) \
        .where(col("i_category") == lit("Music")) \
        .group_by("i_item_id").agg(CountStar().alias("c")).collect()
    ids = sorted({r[0] for r in ids_rows})[:60]
    if not ids:
        ids = ["<none>"]

    def channel(sales, date_col, item_col, price_col):
        return _channel_agg(session, data_dir, sales, date_col, item_col,
                            price_col, "i_item_id", ids, 1998, 9)

    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price"])
    cs = _t(session, data_dir, "catalog_sales",
            ["cs_sold_date_sk", "cs_item_sk", "cs_ext_sales_price"])
    ws = _t(session, data_dir, "web_sales",
            ["ws_sold_date_sk", "ws_item_sk", "ws_ext_sales_price"])
    u = channel(ss, "ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price") \
        .union(channel(cs, "cs_sold_date_sk", "cs_item_sk",
                       "cs_ext_sales_price")) \
        .union(channel(ws, "ws_sold_date_sk", "ws_item_sk",
                       "ws_ext_sales_price"))
    return u.group_by("i_item_id") \
        .agg(Sum(col("total_sales")).alias("total_sales")) \
        .order_by(("i_item_id", True), ("total_sales", True)).limit(100)


def q61(session, data_dir: str):
    """TPC-DS q61: promotional-to-total sales ratio for one category and
    month (two aggregate branches cross-joined)."""
    from spark_rapids_tpu.expr.predicates import Or
    dt = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_year", "d_moy"]) \
        .where((col("d_year") == lit(1998)) & (col("d_moy") == lit(11))) \
        .select(col("d_date_sk"))
    it = _t(session, data_dir, "item", ["i_item_sk", "i_category"]) \
        .where(col("i_category") == lit("Jewelry")).select(col("i_item_sk"))
    st = _t(session, data_dir, "store", ["s_store_sk", "s_gmt_offset"]) \
        .where(col("s_gmt_offset") == lit(-5.0)).select(col("s_store_sk"))
    ss_cols = ["ss_sold_date_sk", "ss_item_sk", "ss_store_sk",
               "ss_promo_sk", "ss_ext_sales_price"]
    base = _t(session, data_dir, "store_sales", ss_cols) \
        .join(dt, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(it, on=[("ss_item_sk", "i_item_sk")]) \
        .join(st, on=[("ss_store_sk", "s_store_sk")])
    pr = _t(session, data_dir, "promotion",
            ["p_promo_sk", "p_channel_dmail", "p_channel_email",
             "p_channel_tv"]) \
        .where(Or(Or(col("p_channel_dmail") == lit("Y"),
                     col("p_channel_email") == lit("Y")),
                  col("p_channel_tv") == lit("Y"))) \
        .select(col("p_promo_sk"))
    promo = base.join(pr, on=[("ss_promo_sk", "p_promo_sk")]) \
        .agg(Sum(col("ss_ext_sales_price")).alias("promotions"))
    total = base.agg(Sum(col("ss_ext_sales_price")).alias("total"))
    return promo.join(total, how="cross").select(
        col("promotions"), col("total"),
        (col("promotions") * lit(100.0) / col("total")).alias("ratio"))


def q65(session, data_dir: str):
    """TPC-DS q65: items whose store revenue is <= 10% of that store's
    average item revenue (aggregate-over-aggregate join)."""
    dt = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_month_seq"]) \
        .where((col("d_month_seq") >= lit(1176))
               & (col("d_month_seq") <= lit(1187))) \
        .select(col("d_date_sk"))
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_store_sk", "ss_item_sk",
             "ss_sales_price"]) \
        .join(dt, on=[("ss_sold_date_sk", "d_date_sk")])
    sc = ss.group_by("ss_store_sk", "ss_item_sk") \
        .agg(Sum(col("ss_sales_price")).alias("revenue"))
    sb = sc.group_by("ss_store_sk") \
        .agg(Average(col("revenue")).alias("ave")) \
        .select(col("ss_store_sk").alias("b_store_sk"), col("ave"))
    st = _t(session, data_dir, "store", ["s_store_sk", "s_store_name"])
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_item_desc", "i_current_price", "i_brand"])
    return sc.join(sb, on=[("ss_store_sk", "b_store_sk")]) \
        .where(col("revenue") <= lit(0.1) * col("ave")) \
        .join(st, on=[("ss_store_sk", "s_store_sk")]) \
        .join(it, on=[("ss_item_sk", "i_item_sk")]) \
        .select(col("s_store_name"), col("i_item_desc"), col("revenue"),
                col("i_current_price"), col("i_brand")) \
        .order_by(("s_store_name", True), ("i_item_desc", True),
                  ("revenue", True), ("i_current_price", True),
                  ("i_brand", True)) \
        .limit(100)


def q68(session, data_dir: str):
    """TPC-DS q68: ticket-grain totals for dep-4/vehicle-3 households in
    two cities (the bought-city <> current-city predicate is omitted:
    the pruned generator has no ss_addr_sk; the current address supplies
    the reported city)."""
    from spark_rapids_tpu.expr.predicates import In, Or
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_store_sk", "ss_hdemo_sk",
             "ss_customer_sk", "ss_ticket_number", "ss_ext_sales_price",
             "ss_ext_wholesale_cost"])
    dt = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_dom", "d_year"]) \
        .where((col("d_dom") >= lit(1)) & (col("d_dom") <= lit(2))
               & In(col("d_year"), [lit(1998), lit(1999), lit(2000)])) \
        .select(col("d_date_sk"))
    st = _t(session, data_dir, "store", ["s_store_sk", "s_city"]) \
        .where(In(col("s_city"), [lit("City001"), lit("City002")])) \
        .select(col("s_store_sk"))
    hd = _t(session, data_dir, "household_demographics",
            ["hd_demo_sk", "hd_dep_count", "hd_vehicle_count"]) \
        .where(Or(col("hd_dep_count") == lit(4),
                  col("hd_vehicle_count") == lit(3))) \
        .select(col("hd_demo_sk"))
    cu = _t(session, data_dir, "customer",
            ["c_customer_sk", "c_current_addr_sk", "c_first_name",
             "c_last_name"])
    ca = _t(session, data_dir, "customer_address",
            ["ca_address_sk", "ca_city"])
    grouped = ss.join(dt, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(st, on=[("ss_store_sk", "s_store_sk")]) \
        .join(hd, on=[("ss_hdemo_sk", "hd_demo_sk")]) \
        .group_by("ss_ticket_number", "ss_customer_sk") \
        .agg(Sum(col("ss_ext_sales_price")).alias("extended_price"),
             Sum(col("ss_ext_wholesale_cost")).alias("extended_cost"))
    joined = grouped.join(cu, on=[("ss_customer_sk", "c_customer_sk")]) \
        .join(ca, on=[("c_current_addr_sk", "ca_address_sk")])
    return joined.select(
        col("c_last_name"), col("c_first_name"), col("ca_city"),
        col("ss_ticket_number"), col("extended_price"),
        col("extended_cost")) \
        .order_by(("c_last_name", True), ("ss_ticket_number", True),
                  ("c_first_name", True), ("ca_city", True),
                  ("extended_price", True), ("extended_cost", True)) \
        .limit(100)


def q73(session, data_dir: str):
    """TPC-DS q73: customers with 1-5 item tickets for high-buy-potential
    households (ticket-grain count + having)."""
    from spark_rapids_tpu.expr.predicates import In, Or
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_store_sk", "ss_hdemo_sk",
             "ss_customer_sk", "ss_ticket_number"])
    dt = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_dom", "d_year"]) \
        .where((col("d_dom") >= lit(1)) & (col("d_dom") <= lit(2))
               & In(col("d_year"), [lit(1999), lit(2000), lit(2001)])) \
        .select(col("d_date_sk"))
    hd = _t(session, data_dir, "household_demographics",
            ["hd_demo_sk", "hd_buy_potential", "hd_vehicle_count",
             "hd_dep_count"]) \
        .where(Or(col("hd_buy_potential") == lit(">10000"),
                  col("hd_buy_potential") == lit("Unknown"))
               & (col("hd_vehicle_count") > lit(0))) \
        .select(col("hd_demo_sk"))
    cu = _t(session, data_dir, "customer",
            ["c_customer_sk", "c_first_name", "c_last_name"])
    grouped = ss.join(dt, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(hd, on=[("ss_hdemo_sk", "hd_demo_sk")]) \
        .group_by("ss_ticket_number", "ss_customer_sk") \
        .agg(CountStar().alias("cnt")) \
        .where((col("cnt") >= lit(1)) & (col("cnt") <= lit(5)))
    return grouped.join(cu, on=[("ss_customer_sk", "c_customer_sk")]) \
        .select(col("c_last_name"), col("c_first_name"),
                col("ss_ticket_number"), col("cnt")) \
        .order_by(("cnt", False), ("c_last_name", True),
                  ("c_first_name", True), ("ss_ticket_number", True)) \
        .limit(100)


def q79(session, data_dir: str):
    """TPC-DS q79: per-ticket profit/coupon totals for dep-6-or-2-vehicle
    households on weekdays."""
    from spark_rapids_tpu.expr.predicates import Or
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_store_sk", "ss_hdemo_sk",
             "ss_customer_sk", "ss_ticket_number", "ss_coupon_amt",
             "ss_net_profit"])
    dt = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_dow", "d_year"]) \
        .where((col("d_dow") == lit(1))
               & (col("d_year") >= lit(1998))
               & (col("d_year") <= lit(2000))) \
        .select(col("d_date_sk"))
    st = _t(session, data_dir, "store", ["s_store_sk", "s_city"])
    hd = _t(session, data_dir, "household_demographics",
            ["hd_demo_sk", "hd_dep_count", "hd_vehicle_count"]) \
        .where(Or(col("hd_dep_count") == lit(6),
                  col("hd_vehicle_count") > lit(2))) \
        .select(col("hd_demo_sk"))
    cu = _t(session, data_dir, "customer",
            ["c_customer_sk", "c_first_name", "c_last_name"])
    grouped = ss.join(dt, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(st, on=[("ss_store_sk", "s_store_sk")]) \
        .join(hd, on=[("ss_hdemo_sk", "hd_demo_sk")]) \
        .group_by("ss_ticket_number", "ss_customer_sk", "s_city") \
        .agg(Sum(col("ss_coupon_amt")).alias("amt"),
             Sum(col("ss_net_profit")).alias("profit"))
    return grouped.join(cu, on=[("ss_customer_sk", "c_customer_sk")]) \
        .select(col("c_last_name"), col("c_first_name"), col("s_city"),
                col("ss_ticket_number"), col("amt"), col("profit")) \
        .order_by(("c_last_name", True), ("ss_ticket_number", True),
                  ("c_first_name", True), ("s_city", True),
                  ("amt", True), ("profit", True)) \
        .limit(100)


def q88(session, data_dir: str):
    """TPC-DS q88: store-hour traffic pivot — eight independent
    time-window counts cross-joined into one row."""
    from spark_rapids_tpu.expr.predicates import Or

    def window_count(alias, hour, half):
        ss = _t(session, data_dir, "store_sales",
                ["ss_sold_time_sk", "ss_hdemo_sk", "ss_store_sk"])
        hd = _t(session, data_dir, "household_demographics",
                ["hd_demo_sk", "hd_dep_count", "hd_vehicle_count"]) \
            .where(Or(Or(
                (col("hd_dep_count") == lit(4))
                & (col("hd_vehicle_count") <= lit(6)),
                (col("hd_dep_count") == lit(2))
                & (col("hd_vehicle_count") <= lit(4))),
                (col("hd_dep_count") == lit(0))
                & (col("hd_vehicle_count") <= lit(2)))) \
            .select(col("hd_demo_sk"))
        lo, hi = (30, 59) if half else (0, 29)
        td = _t(session, data_dir, "time_dim",
                ["t_time_sk", "t_hour", "t_minute"]) \
            .where((col("t_hour") == lit(hour))
                   & (col("t_minute") >= lit(lo))
                   & (col("t_minute") <= lit(hi))) \
            .select(col("t_time_sk"))
        st = _t(session, data_dir, "store",
                ["s_store_sk", "s_store_name"]) \
            .where(col("s_store_name") == lit("ese")).select(col("s_store_sk"))
        return ss.join(hd, on=[("ss_hdemo_sk", "hd_demo_sk")]) \
            .join(td, on=[("ss_sold_time_sk", "t_time_sk")]) \
            .join(st, on=[("ss_store_sk", "s_store_sk")]) \
            .agg(CountStar().alias(alias))

    out = window_count("h8_30", 8, True)
    for alias, hour, half in (("h9_00", 9, False), ("h9_30", 9, True),
                              ("h10_00", 10, False), ("h10_30", 10, True),
                              ("h11_00", 11, False), ("h11_30", 11, True),
                              ("h12_00", 12, False)):
        out = out.join(window_count(alias, hour, half), how="cross")
    return out


QUERIES = {"q3": q3, "q6": q6, "q7": q7, "q12": q12, "q13": q13,
           "q15": q15, "q19": q19, "q20": q20, "q26": q26, "q27": q27,
           "q33": q33, "q36": q36, "q42": q42, "q43": q43, "q48": q48,
           "q52": q52, "q53": q53, "q55": q55, "q60": q60, "q61": q61,
           "q63": q63, "q65": q65, "q68": q68, "q69": q69, "q73": q73,
           "q79": q79, "q88": q88, "q89": q89, "q96": q96, "q98": q98}

# full-suite tranches live in sibling modules to keep files reviewable
from spark_rapids_tpu.bench.tpcds_queries2 import QUERIES2  # noqa: E402
from spark_rapids_tpu.bench.tpcds_queries3 import QUERIES3  # noqa: E402
from spark_rapids_tpu.bench.tpcds_queries4 import QUERIES4  # noqa: E402
from spark_rapids_tpu.bench.tpcds_queries5 import QUERIES5  # noqa: E402

QUERIES.update(QUERIES2)
QUERIES.update(QUERIES3)
QUERIES.update(QUERIES4)
QUERIES.update(QUERIES5)


def build_query(name: str, session, data_dir: str):
    return QUERIES[name](session, data_dir)

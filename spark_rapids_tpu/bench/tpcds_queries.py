"""TPC-DS queries as DataFrame code (the TpcdsLikeSpark.scala pattern).

Each builder takes a :class:`TpuSession` + data_dir and returns a
DataFrame for one TPC-DS query over the pruned generated tables
(reference: integration_tests/.../tpcds/TpcdsLikeSpark.scala — all 99
queries as Spark DataFrame code; this slice implements the
scan/filter/join/agg/sort/limit-shaped ones the baseline tracks,
starting with q6 = BASELINE configs[0]).

Scalar subqueries (q6's month_seq) are evaluated eagerly and folded as
literals — the same plan shape Spark produces after subquery execution.
"""
from __future__ import annotations

import os

from spark_rapids_tpu.expr.aggregates import Average, CountStar, Sum
from spark_rapids_tpu.expr.core import col, lit

__all__ = ["QUERIES", "build_query"]


def _t(session, data_dir: str, table: str, columns=None):
    return session.read_parquet(os.path.join(data_dir, table),
                                columns=columns)


def q3(session, data_dir: str):
    """TPC-DS q3: brand revenue by year for one manufacturer in November."""
    dt = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_year", "d_moy"]).where(col("d_moy") == lit(11))
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_brand_id", "i_brand", "i_manufact_id"]) \
        .where(col("i_manufact_id") == lit(128)) \
        .select(col("i_item_sk"), col("i_brand_id"), col("i_brand"))
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price"])
    return ss.join(dt, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(it, on=[("ss_item_sk", "i_item_sk")]) \
        .group_by("d_year", "i_brand_id", "i_brand") \
        .agg(Sum(col("ss_ext_sales_price")).alias("sum_agg")) \
        .order_by(("d_year", True), ("sum_agg", False),
                  ("i_brand_id", True)) \
        .limit(100)


def q6(session, data_dir: str):
    """TPC-DS q6: state count of customers buying items priced >=120% of
    their category average, for one month (BASELINE configs[0])."""
    dd = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_year", "d_moy", "d_month_seq"])
    # scalar subquery: the (distinct) month_seq of 2001-01
    ms_rows = dd.where((col("d_year") == lit(2001))
                       & (col("d_moy") == lit(1))) \
        .select(col("d_month_seq")).limit(1).collect()
    ms = ms_rows[0][0]
    dt = dd.where(col("d_month_seq") == lit(ms)).select(col("d_date_sk"))

    item = _t(session, data_dir, "item",
              ["i_item_sk", "i_category", "i_current_price"])
    avg_cat = item.group_by("i_category").agg(
        Average(col("i_current_price")).alias("avg_price")) \
        .select(col("i_category").alias("cat_avg_key"), col("avg_price"))
    it = item.join(avg_cat, on=[("i_category", "cat_avg_key")]) \
        .where(col("i_current_price") > lit(1.2) * col("avg_price")) \
        .select(col("i_item_sk"))

    cust = _t(session, data_dir, "customer",
              ["c_customer_sk", "c_current_addr_sk"])
    ca = _t(session, data_dir, "customer_address",
            ["ca_address_sk", "ca_state"])
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_item_sk", "ss_customer_sk"])

    return ss.join(dt, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(it, on=[("ss_item_sk", "i_item_sk")]) \
        .join(cust, on=[("ss_customer_sk", "c_customer_sk")]) \
        .join(ca, on=[("c_current_addr_sk", "ca_address_sk")]) \
        .group_by("ca_state") \
        .agg(CountStar().alias("cnt")) \
        .where(col("cnt") >= lit(10)) \
        .order_by(("cnt", True)) \
        .limit(100)


def q42(session, data_dir: str):
    """TPC-DS q42: category revenue for one month/year, manager 1."""
    dt = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_year", "d_moy"]) \
        .where((col("d_moy") == lit(11)) & (col("d_year") == lit(2000)))
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_category_id", "i_category", "i_manager_id"]) \
        .where(col("i_manager_id") == lit(1)) \
        .select(col("i_item_sk"), col("i_category_id"), col("i_category"))
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price"])
    return ss.join(dt, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(it, on=[("ss_item_sk", "i_item_sk")]) \
        .group_by("d_year", "i_category_id", "i_category") \
        .agg(Sum(col("ss_ext_sales_price")).alias("total_sales")) \
        .order_by(("total_sales", False), ("d_year", True),
                  ("i_category_id", True), ("i_category", True)) \
        .limit(100)


def q52(session, data_dir: str):
    """TPC-DS q52: brand revenue for one month/year, manager 1."""
    dt = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_year", "d_moy"]) \
        .where((col("d_moy") == lit(11)) & (col("d_year") == lit(2000)))
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_brand_id", "i_brand", "i_manager_id"]) \
        .where(col("i_manager_id") == lit(1)) \
        .select(col("i_item_sk"), col("i_brand_id"), col("i_brand"))
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price"])
    return ss.join(dt, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(it, on=[("ss_item_sk", "i_item_sk")]) \
        .group_by("d_year", "i_brand_id", "i_brand") \
        .agg(Sum(col("ss_ext_sales_price")).alias("ext_price")) \
        .order_by(("d_year", True), ("ext_price", False),
                  ("i_brand_id", True)) \
        .limit(100)


def q55(session, data_dir: str):
    """TPC-DS q55: brand revenue for manager 28, 1999-11."""
    dt = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_year", "d_moy"]) \
        .where((col("d_moy") == lit(11)) & (col("d_year") == lit(1999)))
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_brand_id", "i_brand", "i_manager_id"]) \
        .where(col("i_manager_id") == lit(28)) \
        .select(col("i_item_sk"), col("i_brand_id"), col("i_brand"))
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price"])
    return ss.join(dt, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(it, on=[("ss_item_sk", "i_item_sk")]) \
        .group_by("i_brand_id", "i_brand") \
        .agg(Sum(col("ss_ext_sales_price")).alias("ext_price")) \
        .order_by(("ext_price", False), ("i_brand_id", True)) \
        .limit(100)


QUERIES = {"q3": q3, "q6": q6, "q42": q42, "q52": q52, "q55": q55}


def build_query(name: str, session, data_dir: str):
    return QUERIES[name](session, data_dir)

"""TPCx-BB (BigBench) queries as DataFrame code.

Reference: TpcxbbLikeSpark.scala (integration_tests .../tests/tpcxbb)
— the reference implements 19 of the 30 BigBench queries as Spark SQL
and REFUSES the other 11 (UDTF / external python / hive UDF stages,
:808-2130); this module mirrors both: the same 19 run against the
DataFrame API, and q1-q4, q8, q10, q18, q19, q27, q29, q30 raise with
the reference's reasons.

Documented deviations from the reference constants, forced by the
pruned generator's domains (tpcds_gen.py):
* q7 filters d_year 2001 (ref: 2004 — outside the generated 1998-2003
  sales span) and q15 store 1 (ref: 10 — only >= SF1 has 10 stores).
* q24 anchors item 100 (ref: 10000, which only exists at SF >= ~0.06).
* q11's ``corr`` and q20/q25's mixed count(distinct)+plain aggregates
  are expressed with their exact algebraic expansions (sums/counts and
  a distinct-frame join) — same results, engine-supported plan shapes.
"""
from __future__ import annotations

import datetime
import os

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.aggregates import (Average, Count, CountDistinct,
                                              CountStar, Max, Min, Sum)
from spark_rapids_tpu.expr.conditional import Coalesce, If
from spark_rapids_tpu.expr.core import Literal, col, lit
from spark_rapids_tpu.expr.math_ops import Round, Sqrt
from spark_rapids_tpu.expr.predicates import In, IsNotNull, IsNull

__all__ = ["TPCXBB_QUERIES", "UNSUPPORTED", "build_tpcxbb_query"]

_EPOCH = 2415022  # d_date_sk of 1900-01-01 (tpcds_gen._DATE_SK_EPOCH)


def _t(session, data_dir: str, table: str, columns=None):
    return session.read_parquet(os.path.join(data_dir, table),
                                columns=columns)


def _sk(day: str) -> int:
    """d_date_sk of an ISO day."""
    d = datetime.date.fromisoformat(day)
    return (d - datetime.date(1900, 1, 1)).days + _EPOCH


def _date(day: str):
    return lit(datetime.date.fromisoformat(day))


def _flag(cond):
    return If(cond, lit(1), lit(0))


def q5(session, data_dir: str):
    """Logistic-regression features: clicks per category vs demographics
    (TpcxbbLikeSpark.scala Q5Like)."""
    wcs = _t(session, data_dir, "web_clickstreams",
             ["wcs_item_sk", "wcs_user_sk"]) \
        .where(IsNotNull(col("wcs_user_sk")))
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_category", "i_category_id"])
    clicks = wcs.join(it, on=[("wcs_item_sk", "i_item_sk")]) \
        .group_by("wcs_user_sk") \
        .agg(Sum(_flag(col("i_category") == lit("Books")))
             .alias("clicks_in_category"),
             *[Sum(_flag(col("i_category_id") == lit(i)))
               .alias(f"clicks_in_{i}") for i in range(1, 8)])
    ct = _t(session, data_dir, "customer",
            ["c_customer_sk", "c_current_cdemo_sk"])
    cd = _t(session, data_dir, "customer_demographics",
            ["cd_demo_sk", "cd_gender", "cd_education_status"])
    return clicks.join(ct, on=[("wcs_user_sk", "c_customer_sk")]) \
        .join(cd, on=[("c_current_cdemo_sk", "cd_demo_sk")]) \
        .select(col("clicks_in_category"),
                _flag(In(col("cd_education_status"),
                         [lit(s) for s in ("Advanced Degree", "College",
                                           "4 yr Degree", "2 yr Degree")]))
                .alias("college_education"),
                _flag(col("cd_gender") == lit("M")).alias("male"),
                *[col(f"clicks_in_{i}") for i in range(1, 8)])


def _year_totals(session, data_dir, table, cust_col, date_col, val_exprs):
    """Per-customer first/second-year totals with HAVING first > 0
    (q6/q13 temp views)."""
    dd = _t(session, data_dir, "date_dim", ["d_date_sk", "d_year"]) \
        .where(In(col("d_year"), [lit(2001), lit(2002)]))
    sales = _t(session, data_dir, table,
               [cust_col, date_col] + val_exprs["cols"])
    v = val_exprs["value"]
    return sales.join(dd, on=[(date_col, "d_date_sk")]) \
        .group_by(cust_col) \
        .agg(Sum(If(col("d_year") == lit(2001), v, lit(0.0)))
             .alias("first_year_total"),
             Sum(If(col("d_year") == lit(2002), v, lit(0.0)))
             .alias("second_year_total")) \
        .where(col("first_year_total") > lit(0.0))


def q6(session, data_dir: str):
    """Store->web purchase-habit shift, top 100 by web increase ratio."""
    half = {"cols": ["ss_ext_list_price", "ss_ext_wholesale_cost",
                     "ss_ext_discount_amt", "ss_ext_sales_price"],
            "value": ((col("ss_ext_list_price")
                       - col("ss_ext_wholesale_cost")
                       - col("ss_ext_discount_amt")
                       + col("ss_ext_sales_price")) / lit(2.0))}
    whalf = {"cols": ["ws_ext_list_price", "ws_ext_wholesale_cost",
                      "ws_ext_discount_amt", "ws_ext_sales_price"],
             "value": ((col("ws_ext_list_price")
                        - col("ws_ext_wholesale_cost")
                        - col("ws_ext_discount_amt")
                        + col("ws_ext_sales_price")) / lit(2.0))}
    store = _year_totals(session, data_dir, "store_sales",
                         "ss_customer_sk", "ss_sold_date_sk", half) \
        .select(col("ss_customer_sk").alias("s_cust"),
                col("first_year_total").alias("s_first"),
                col("second_year_total").alias("s_second"))
    web = _year_totals(session, data_dir, "web_sales",
                       "ws_bill_customer_sk", "ws_sold_date_sk", whalf) \
        .select(col("ws_bill_customer_sk").alias("w_cust"),
                col("first_year_total").alias("w_first"),
                col("second_year_total").alias("w_second"))
    c = _t(session, data_dir, "customer",
           ["c_customer_sk", "c_first_name", "c_last_name",
            "c_preferred_cust_flag", "c_birth_country", "c_login",
            "c_email_address"])
    wr = (col("w_second") / col("w_first"))
    sr = (col("s_second") / col("s_first"))
    return store.join(web, on=[("s_cust", "w_cust")]) \
        .join(c, on=[("w_cust", "c_customer_sk")]) \
        .where(wr > sr) \
        .select(wr.alias("web_sales_increase_ratio"),
                col("c_customer_sk"), col("c_first_name"),
                col("c_last_name"), col("c_preferred_cust_flag"),
                col("c_birth_country"), col("c_login"),
                col("c_email_address")) \
        .order_by(("web_sales_increase_ratio", False),
                  ("c_customer_sk", True), ("c_first_name", True),
                  ("c_last_name", True), ("c_preferred_cust_flag", True),
                  ("c_birth_country", True), ("c_login", True)) \
        .limit(100)


def q7(session, data_dir: str):
    """States with >=10 customers buying items priced >=20% above their
    category average in one month (d_year 2001 deviation, see module
    docstring)."""
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_category", "i_current_price"])
    avg_price = it.group_by("i_category") \
        .agg((Average(col("i_current_price")) * lit(1.2))
             .alias("avg_price")) \
        .select(col("i_category").alias("ap_cat"), col("avg_price"))
    high = it.join(avg_price, on=[("i_category", "ap_cat")]) \
        .where(col("i_current_price") > col("avg_price")) \
        .select(col("i_item_sk").alias("hp_item_sk"))
    dd = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_year", "d_moy"]) \
        .where((col("d_year") == lit(2001)) & (col("d_moy") == lit(7))) \
        .select(col("d_date_sk"))
    ss = _t(session, data_dir, "store_sales",
            ["ss_customer_sk", "ss_item_sk", "ss_sold_date_sk"])
    ca = _t(session, data_dir, "customer_address",
            ["ca_address_sk", "ca_state"]) \
        .where(IsNotNull(col("ca_state")))
    c = _t(session, data_dir, "customer",
           ["c_customer_sk", "c_current_addr_sk"])
    return ss.join(high, on=[("ss_item_sk", "hp_item_sk")]) \
        .join(dd, on=[("ss_sold_date_sk", "d_date_sk")], how="semi") \
        .join(c, on=[("ss_customer_sk", "c_customer_sk")]) \
        .join(ca, on=[("c_current_addr_sk", "ca_address_sk")]) \
        .group_by("ca_state").agg(CountStar().alias("cnt")) \
        .where(col("cnt") >= lit(10)) \
        .order_by(("cnt", False), ("ca_state", True)).limit(10)


def q9(session, data_dir: str):
    """Total quantity over marital/education x state/profit slices."""
    dd = _t(session, data_dir, "date_dim", ["d_date_sk", "d_year"]) \
        .where(col("d_year") == lit(2001)).select(col("d_date_sk"))
    ca = _t(session, data_dir, "customer_address",
            ["ca_address_sk", "ca_country", "ca_state"])
    cd = _t(session, data_dir, "customer_demographics",
            ["cd_demo_sk", "cd_marital_status", "cd_education_status"])
    st = _t(session, data_dir, "store", ["s_store_sk"])
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_addr_sk", "ss_store_sk",
             "ss_cdemo_sk", "ss_quantity", "ss_sales_price",
             "ss_net_profit"])
    # the reference's three OR branches all use the SAME demographic
    # pair (M / 4 yr Degree — TpcxbbLikeSpark.scala Q9Like), so the
    # price bands legally collapse to 50..200; kept branch-by-branch
    # for parity with the reference text
    md = ((col("cd_marital_status") == lit("M"))
          & (col("cd_education_status") == lit("4 yr Degree")))
    sp = col("ss_sales_price")
    demo_ok = ((md & (lit(100.0) <= sp) & (sp <= lit(150.0)))
               | (md & (lit(50.0) <= sp) & (sp <= lit(200.0)))
               | (md & (lit(150.0) <= sp) & (sp <= lit(200.0))))
    npf = col("ss_net_profit")
    us = col("ca_country") == lit("United States")

    def states(*ab):
        return In(col("ca_state"), [lit(s) for s in ab])

    addr_ok = ((us & states("KY", "GA", "NM")
                & (lit(0.0) <= npf) & (npf <= lit(2000.0)))
               | (us & states("MT", "OR", "IN")
                  & (lit(150.0) <= npf) & (npf <= lit(3000.0)))
               | (us & states("WI", "MO", "WV")
                  & (lit(50.0) <= npf) & (npf <= lit(25000.0))))
    return ss.join(dd, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(ca, on=[("ss_addr_sk", "ca_address_sk")]) \
        .join(st, on=[("ss_store_sk", "s_store_sk")]) \
        .join(cd, on=[("ss_cdemo_sk", "cd_demo_sk")]) \
        .where(demo_ok & addr_ok) \
        .agg(Sum(col("ss_quantity")).alias("sum_qty"))


def q11(session, data_dir: str):
    """corr(reviews_count, avg_rating) of products vs monthly revenue —
    corr expanded algebraically over sum/count (module docstring)."""
    pr = _t(session, data_dir, "product_reviews",
            ["pr_item_sk", "pr_review_rating"]) \
        .where(IsNotNull(col("pr_item_sk"))) \
        .group_by("pr_item_sk") \
        .agg(CountStar().alias("r_count"),
             Average(col("pr_review_rating")).alias("avg_rating"))
    dd = _t(session, data_dir, "date_dim", ["d_date_sk", "d_date"]) \
        .where((col("d_date") >= _date("2003-01-02"))
               & (col("d_date") <= _date("2003-02-02"))) \
        .select(col("d_date_sk"))
    ws = _t(session, data_dir, "web_sales",
            ["ws_item_sk", "ws_sold_date_sk", "ws_net_paid"]) \
        .join(dd, on=[("ws_sold_date_sk", "d_date_sk")], how="semi") \
        .where(IsNotNull(col("ws_item_sk"))) \
        .group_by("ws_item_sk").agg(Sum(col("ws_net_paid"))
                                    .alias("revenue"))
    j = pr.join(ws, on=[("pr_item_sk", "ws_item_sk")]) \
        .select(col("r_count").cast(T.DoubleType()).alias("x"),
                col("avg_rating").alias("y"))
    stats = j.agg(CountStar().alias("n"), Sum(col("x")).alias("sx"),
                  Sum(col("y")).alias("sy"),
                  Sum(col("x") * col("y")).alias("sxy"),
                  Sum(col("x") * col("x")).alias("sxx"),
                  Sum(col("y") * col("y")).alias("syy"))
    n = col("n").cast(T.DoubleType())
    num = n * col("sxy") - col("sx") * col("sy")
    den = Sqrt((n * col("sxx") - col("sx") * col("sx"))
               * (n * col("syy") - col("sy") * col("sy")))
    return stats.select((num / den).alias("corr"))


def q12(session, data_dir: str):
    """Web views followed by an in-store purchase of the same-category
    item within three months."""
    it = _t(session, data_dir, "item", ["i_item_sk", "i_category"]) \
        .where(In(col("i_category"), [lit("Books"), lit("Electronics")]))
    d0 = _sk("2001-09-02")
    wcs = _t(session, data_dir, "web_clickstreams",
             ["wcs_user_sk", "wcs_click_date_sk", "wcs_item_sk",
              "wcs_sales_sk"]) \
        .where((col("wcs_click_date_sk") >= lit(d0))
               & (col("wcs_click_date_sk") <= lit(d0 + 30))
               & IsNotNull(col("wcs_user_sk"))
               & IsNull(col("wcs_sales_sk"))) \
        .join(it.select(col("i_item_sk").alias("wi")),
              on=[("wcs_item_sk", "wi")]) \
        .select(col("wcs_user_sk"), col("wcs_click_date_sk"))
    ss = _t(session, data_dir, "store_sales",
            ["ss_customer_sk", "ss_sold_date_sk", "ss_item_sk"]) \
        .where((col("ss_sold_date_sk") >= lit(d0))
               & (col("ss_sold_date_sk") <= lit(d0 + 90))
               & IsNotNull(col("ss_customer_sk"))) \
        .join(it.select(col("i_item_sk").alias("si")),
              on=[("ss_item_sk", "si")]) \
        .select(col("ss_customer_sk"), col("ss_sold_date_sk"))
    return wcs.join(ss, on=[("wcs_user_sk", "ss_customer_sk")],
                    condition=col("wcs_click_date_sk")
                    < col("ss_sold_date_sk")) \
        .select(col("wcs_user_sk")).distinct() \
        .order_by(("wcs_user_sk", True))


def q13(session, data_dir: str):
    """Consecutive-year web-over-store growth, top 100 (tpc-ds q74
    base)."""
    store = _year_totals(session, data_dir, "store_sales",
                         "ss_customer_sk", "ss_sold_date_sk",
                         {"cols": ["ss_net_paid"],
                          "value": col("ss_net_paid")}) \
        .select(col("ss_customer_sk").alias("s_cust"),
                col("first_year_total").alias("s_first"),
                col("second_year_total").alias("s_second"))
    web = _year_totals(session, data_dir, "web_sales",
                       "ws_bill_customer_sk", "ws_sold_date_sk",
                       {"cols": ["ws_net_paid"],
                        "value": col("ws_net_paid")}) \
        .select(col("ws_bill_customer_sk").alias("w_cust"),
                col("first_year_total").alias("w_first"),
                col("second_year_total").alias("w_second"))
    c = _t(session, data_dir, "customer",
           ["c_customer_sk", "c_first_name", "c_last_name"])
    wr = (col("w_second") / col("w_first"))
    sr = (col("s_second") / col("s_first"))
    return store.join(web, on=[("s_cust", "w_cust")]) \
        .join(c, on=[("w_cust", "c_customer_sk")]) \
        .where(wr > sr) \
        .select(col("c_customer_sk"), col("c_first_name"),
                col("c_last_name"), sr.alias("storeSalesIncreaseRatio"),
                wr.alias("webSalesIncreaseRatio")) \
        .order_by(("webSalesIncreaseRatio", False),
                  ("c_customer_sk", True), ("c_first_name", True),
                  ("c_last_name", True)) \
        .limit(100)


def q14(session, data_dir: str):
    """AM/PM sales ratio (tpc-ds q90 base)."""
    ws = _t(session, data_dir, "web_sales",
            ["ws_ship_hdemo_sk", "ws_web_page_sk", "ws_sold_time_sk"])
    hd = _t(session, data_dir, "household_demographics",
            ["hd_demo_sk", "hd_dep_count"]) \
        .where(col("hd_dep_count") == lit(5)).select(col("hd_demo_sk"))
    wp = _t(session, data_dir, "web_page",
            ["wp_web_page_sk", "wp_char_count"]) \
        .where((col("wp_char_count") >= lit(5000))
               & (col("wp_char_count") <= lit(6000))) \
        .select(col("wp_web_page_sk"))
    td = _t(session, data_dir, "time_dim", ["t_time_sk", "t_hour"]) \
        .where(In(col("t_hour"), [lit(h) for h in (7, 8, 19, 20)]))
    hourly = ws.join(hd, on=[("ws_ship_hdemo_sk", "hd_demo_sk")]) \
        .join(wp, on=[("ws_web_page_sk", "wp_web_page_sk")]) \
        .join(td, on=[("ws_sold_time_sk", "t_time_sk")]) \
        .group_by("t_hour").agg(CountStar().alias("c")) \
        .select(If((col("t_hour") >= lit(7)) & (col("t_hour") <= lit(8)),
                   col("c"), lit(0)).alias("amc1"),
                If((col("t_hour") >= lit(19))
                   & (col("t_hour") <= lit(20)),
                   col("c"), lit(0)).alias("pmc1"))
    return hourly.agg(Sum(col("amc1")).alias("amc"),
                      Sum(col("pmc1")).alias("pmc")) \
        .select(If(col("pmc") > lit(0),
                   col("amc").cast(T.DoubleType())
                   / col("pmc").cast(T.DoubleType()),
                   lit(-1.00)).alias("am_pm_ratio"))


def q15(session, data_dir: str):
    """Declining in-store categories via per-category regression slope
    (store 1 deviation, see module docstring)."""
    dd = _t(session, data_dir, "date_dim", ["d_date_sk", "d_date"]) \
        .where((col("d_date") >= _date("2001-09-02"))
               & (col("d_date") <= _date("2002-09-02"))) \
        .select(col("d_date_sk"))
    it = _t(session, data_dir, "item", ["i_item_sk", "i_category_id"]) \
        .where(IsNotNull(col("i_category_id")))
    ss = _t(session, data_dir, "store_sales",
            ["ss_item_sk", "ss_sold_date_sk", "ss_store_sk",
             "ss_net_paid"]) \
        .where(col("ss_store_sk") == lit(1))
    daily = ss.join(dd, on=[("ss_sold_date_sk", "d_date_sk")],
                    how="semi") \
        .join(it, on=[("ss_item_sk", "i_item_sk")]) \
        .group_by("i_category_id", "ss_sold_date_sk") \
        .agg(Sum(col("ss_net_paid")).alias("y")) \
        .select(col("i_category_id").alias("cat"),
                col("ss_sold_date_sk").cast(T.DoubleType()).alias("x"),
                col("y"))
    reg = daily.group_by("cat").agg(
        CountStar().alias("n"), Sum(col("x")).alias("sx"),
        Sum(col("y")).alias("sy"),
        Sum(col("x") * col("y")).alias("sxy"),
        Sum(col("x") * col("x")).alias("sxx"))
    n = col("n").cast(T.DoubleType())
    slope = ((n * col("sxy") - col("sx") * col("sy"))
             / (n * col("sxx") - col("sx") * col("sx")))
    return reg.select(col("cat"), slope.alias("slope"),
                      ((col("sy") - slope * col("sx")) / n)
                      .alias("intercept")) \
        .where(col("slope") <= lit(0.0)) \
        .order_by(("cat", True))


def q16(session, data_dir: str):
    """Sales impact 30 days around a price change (tpc-ds q40 base)."""
    anchor = _date("2001-03-16")
    dd = _t(session, data_dir, "date_dim", ["d_date_sk", "d_date"]) \
        .where((col("d_date") >= _date("2001-02-14"))
               & (col("d_date") <= _date("2001-04-15")))
    ws = _t(session, data_dir, "web_sales",
            ["ws_order_number", "ws_item_sk", "ws_warehouse_sk",
             "ws_sold_date_sk", "ws_sales_price"])
    wr = _t(session, data_dir, "web_returns",
            ["wr_order_number", "wr_item_sk", "wr_refunded_cash"]) \
        .select(col("wr_order_number").alias("r_ord"),
                col("wr_item_sk").alias("r_item"),
                col("wr_refunded_cash"))
    it = _t(session, data_dir, "item", ["i_item_sk", "i_item_id"])
    w = _t(session, data_dir, "warehouse",
           ["w_warehouse_sk", "w_state"])
    val = col("ws_sales_price") - Coalesce(col("wr_refunded_cash"),
                                           lit(0.0))
    return ws.join(wr, on=[("ws_order_number", "r_ord"),
                           ("ws_item_sk", "r_item")], how="left") \
        .join(it, on=[("ws_item_sk", "i_item_sk")]) \
        .join(w, on=[("ws_warehouse_sk", "w_warehouse_sk")]) \
        .join(dd, on=[("ws_sold_date_sk", "d_date_sk")]) \
        .group_by("w_state", "i_item_id") \
        .agg(Sum(If(col("d_date") < anchor, val, lit(0.0)))
             .alias("sales_before"),
             Sum(If(col("d_date") >= anchor, val, lit(0.0)))
             .alias("sales_after")) \
        .order_by(("w_state", True), ("i_item_id", True)).limit(100)


def q17(session, data_dir: str):
    """Promotional vs total sales ratio (tpc-ds q61 base)."""
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_item_sk", "ss_store_sk",
             "ss_customer_sk", "ss_promo_sk", "ss_ext_sales_price"])
    dd = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_year", "d_moy"]) \
        .where((col("d_year") == lit(2001)) & (col("d_moy") == lit(12)))
    it = _t(session, data_dir, "item", ["i_item_sk", "i_category"]) \
        .where(In(col("i_category"), [lit("Books"), lit("Music")]))
    st = _t(session, data_dir, "store", ["s_store_sk", "s_gmt_offset"]) \
        .where(col("s_gmt_offset") == lit(-5.0))
    ca = _t(session, data_dir, "customer_address",
            ["ca_address_sk", "ca_gmt_offset"]) \
        .where(col("ca_gmt_offset") == lit(-5.0))
    c = _t(session, data_dir, "customer",
           ["c_customer_sk", "c_current_addr_sk"]) \
        .join(ca, on=[("c_current_addr_sk", "ca_address_sk")],
              how="semi")
    p = _t(session, data_dir, "promotion",
           ["p_promo_sk", "p_channel_email", "p_channel_dmail",
            "p_channel_tv"])
    per_channel = ss \
        .join(dd, on=[("ss_sold_date_sk", "d_date_sk")], how="semi") \
        .join(it, on=[("ss_item_sk", "i_item_sk")], how="semi") \
        .join(st, on=[("ss_store_sk", "s_store_sk")], how="semi") \
        .join(c, on=[("ss_customer_sk", "c_customer_sk")], how="semi") \
        .join(p, on=[("ss_promo_sk", "p_promo_sk")]) \
        .group_by("p_channel_email", "p_channel_dmail", "p_channel_tv") \
        .agg(Sum(col("ss_ext_sales_price")).alias("total")) \
        .select(If((col("p_channel_dmail") == lit("Y"))
                   | (col("p_channel_email") == lit("Y"))
                   | (col("p_channel_tv") == lit("Y")),
                   col("total"), lit(0.0)).alias("promotional"),
                col("total"))
    return per_channel.agg(Sum(col("promotional")).alias("promotional"),
                           Sum(col("total")).alias("total")) \
        .select(col("promotional"), col("total"),
                If(col("total") > lit(0.0),
                   lit(100.0) * col("promotional") / col("total"),
                   lit(0.0)).alias("promo_percent")) \
        .order_by(("promotional", True), ("total", True)).limit(100)


def q20(session, data_dir: str):
    """Return-ratio segmentation features (count(distinct)+plain aggs
    expanded into a distinct-frame join, see module docstring)."""
    ss = _t(session, data_dir, "store_sales",
            ["ss_customer_sk", "ss_ticket_number", "ss_item_sk",
             "ss_net_paid"])
    plain_o = ss.group_by("ss_customer_sk") \
        .agg(Count(col("ss_item_sk")).alias("orders_items"),
             Sum(col("ss_net_paid")).alias("orders_money"))
    dist_o = ss.group_by("ss_customer_sk") \
        .agg(CountDistinct(col("ss_ticket_number"))
             .alias("orders_count")) \
        .select(col("ss_customer_sk").alias("oc_cust"),
                col("orders_count"))
    orders = plain_o.join(dist_o, on=[("ss_customer_sk", "oc_cust")])
    sr = _t(session, data_dir, "store_returns",
            ["sr_customer_sk", "sr_ticket_number", "sr_item_sk",
             "sr_return_amt"])
    plain_r = sr.group_by("sr_customer_sk") \
        .agg(Count(col("sr_item_sk")).alias("returns_items"),
             Sum(col("sr_return_amt")).alias("returns_money"))
    dist_r = sr.group_by("sr_customer_sk") \
        .agg(CountDistinct(col("sr_ticket_number"))
             .alias("returns_count")) \
        .select(col("sr_customer_sk").alias("rc_cust"),
                col("returns_count"))
    returned = plain_r.join(dist_r, on=[("sr_customer_sk", "rc_cust")]) \
        .select(col("sr_customer_sk"), col("returns_count"),
                col("returns_items"), col("returns_money"))

    def ratio(a, b):
        r = (a.cast(T.DoubleType()) / b.cast(T.DoubleType()))
        return Round(Coalesce(r, lit(0.0)), 7)

    return orders.join(returned, on=[("ss_customer_sk",
                                      "sr_customer_sk")], how="left") \
        .select(col("ss_customer_sk").alias("user_sk"),
                ratio(col("returns_count"), col("orders_count"))
                .alias("orderRatio"),
                ratio(col("returns_items"), col("orders_items"))
                .alias("itemsRatio"),
                ratio(col("returns_money"), col("orders_money"))
                .alias("monetaryRatio"),
                Round(Coalesce(col("returns_count").cast(T.DoubleType()),
                               lit(0.0)), 0).alias("frequency")) \
        .order_by(("user_sk", True))


def q21(session, data_dir: str):
    """Items returned then re-purchased on the web (tpc-ds q29 base)."""
    d1 = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_year", "d_moy"]) \
        .where((col("d_year") == lit(2003)) & (col("d_moy") == lit(1))) \
        .select(col("d_date_sk").alias("d1_sk"))
    d2 = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_year", "d_moy"]) \
        .where((col("d_year") == lit(2003)) & (col("d_moy") >= lit(1))
               & (col("d_moy") <= lit(7))) \
        .select(col("d_date_sk").alias("d2_sk"))
    d3 = _t(session, data_dir, "date_dim", ["d_date_sk", "d_year"]) \
        .where((col("d_year") >= lit(2003))
               & (col("d_year") <= lit(2005))) \
        .select(col("d_date_sk").alias("d3_sk"))
    sr = _t(session, data_dir, "store_returns",
            ["sr_returned_date_sk", "sr_item_sk", "sr_customer_sk",
             "sr_ticket_number", "sr_return_quantity"]) \
        .join(d2, on=[("sr_returned_date_sk", "d2_sk")])
    ws = _t(session, data_dir, "web_sales",
            ["ws_sold_date_sk", "ws_item_sk", "ws_bill_customer_sk",
             "ws_quantity"]) \
        .join(d3, on=[("ws_sold_date_sk", "d3_sk")])
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_item_sk", "ss_store_sk",
             "ss_customer_sk", "ss_ticket_number", "ss_quantity"]) \
        .join(d1, on=[("ss_sold_date_sk", "d1_sk")])
    st = _t(session, data_dir, "store", ["s_store_sk", "s_store_id",
                                         "s_store_name"])
    it = _t(session, data_dir, "item", ["i_item_sk", "i_item_id",
                                        "i_item_desc"])
    return sr.join(ws, on=[("sr_item_sk", "ws_item_sk"),
                           ("sr_customer_sk", "ws_bill_customer_sk")]) \
        .join(ss, on=[("sr_ticket_number", "ss_ticket_number"),
                      ("sr_item_sk", "ss_item_sk"),
                      ("sr_customer_sk", "ss_customer_sk")]) \
        .join(st, on=[("ss_store_sk", "s_store_sk")]) \
        .join(it, on=[("ss_item_sk", "i_item_sk")]) \
        .group_by("i_item_id", "i_item_desc", "s_store_id",
                  "s_store_name") \
        .agg(Sum(col("ss_quantity")).alias("store_sales_quantity"),
             Sum(col("sr_return_quantity"))
             .alias("store_returns_quantity"),
             Sum(col("ws_quantity")).alias("web_sales_quantity")) \
        .order_by(("i_item_id", True), ("i_item_desc", True),
                  ("s_store_id", True), ("s_store_name", True)) \
        .limit(100)


def q22(session, data_dir: str):
    """Inventory change 30 days around a price change (tpc-ds q21
    base)."""
    anchor = _date("2001-05-08")
    dd = _t(session, data_dir, "date_dim", ["d_date_sk", "d_date"]) \
        .where((col("d_date") >= _date("2001-04-08"))
               & (col("d_date") <= _date("2001-06-07")))
    inv = _t(session, data_dir, "inventory",
             ["inv_date_sk", "inv_item_sk", "inv_warehouse_sk",
              "inv_quantity_on_hand"])
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_item_id", "i_current_price"]) \
        .where((col("i_current_price") >= lit(0.98))
               & (col("i_current_price") <= lit(1.5)))
    w = _t(session, data_dir, "warehouse",
           ["w_warehouse_sk", "w_warehouse_name"])
    agg = inv.join(it, on=[("inv_item_sk", "i_item_sk")]) \
        .join(w, on=[("inv_warehouse_sk", "w_warehouse_sk")]) \
        .join(dd, on=[("inv_date_sk", "d_date_sk")]) \
        .group_by("w_warehouse_name", "i_item_id") \
        .agg(Sum(If(col("d_date") < anchor,
                    col("inv_quantity_on_hand"), lit(0)))
             .alias("inv_before"),
             Sum(If(col("d_date") >= anchor,
                    col("inv_quantity_on_hand"), lit(0)))
             .alias("inv_after"))
    ratio = (col("inv_after").cast(T.DoubleType())
             / col("inv_before").cast(T.DoubleType()))
    return agg.where((col("inv_before") > lit(0))
                     & (ratio >= lit(2.0 / 3.0))
                     & (ratio <= lit(1.5))) \
        .order_by(("w_warehouse_name", True), ("i_item_id", True)) \
        .limit(100)


def q23(session, data_dir: str):
    """Coefficient-of-variation pairs across consecutive months
    (tpc-ds q39 base)."""
    from spark_rapids_tpu.expr.aggregates import stddev_samp
    dd = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_year", "d_moy"]) \
        .where((col("d_year") == lit(2001)) & (col("d_moy") >= lit(1))
               & (col("d_moy") <= lit(2)))
    inv = _t(session, data_dir, "inventory",
             ["inv_date_sk", "inv_item_sk", "inv_warehouse_sk",
              "inv_quantity_on_hand"])
    cov = inv.join(dd, on=[("inv_date_sk", "d_date_sk")]) \
        .group_by("inv_warehouse_sk", "inv_item_sk", "d_moy") \
        .agg(stddev_samp(col("inv_quantity_on_hand")).alias("stdev"),
             Average(col("inv_quantity_on_hand")).alias("mean")) \
        .where((col("mean") > lit(0.0))
               & (col("stdev") / col("mean") >= lit(1.3))) \
        .select(col("inv_warehouse_sk"), col("inv_item_sk"),
                col("d_moy"), (col("stdev") / col("mean")).alias("cov"))
    inv1 = cov.where(col("d_moy") == lit(1)) \
        .select(col("inv_warehouse_sk"), col("inv_item_sk"),
                col("d_moy"), col("cov"))
    inv2 = cov.where(col("d_moy") == lit(2)) \
        .select(col("inv_warehouse_sk").alias("w2"),
                col("inv_item_sk").alias("i2"),
                col("d_moy").alias("moy2"), col("cov").alias("cov2"))
    return inv1.join(inv2, on=[("inv_warehouse_sk", "w2"),
                               ("inv_item_sk", "i2")]) \
        .select(col("inv_warehouse_sk"), col("inv_item_sk"),
                col("d_moy"), col("cov"), col("moy2"), col("cov2")) \
        .order_by(("inv_warehouse_sk", True), ("inv_item_sk", True))


def q24(session, data_dir: str):
    """Cross-price elasticity of demand (anchor item 100 deviation,
    see module docstring)."""
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_current_price"]) \
        .where(col("i_item_sk") == lit(100))
    imp = _t(session, data_dir, "item_marketprices",
             ["imp_sk", "imp_item_sk", "imp_competitor_price",
              "imp_start_date", "imp_end_date"])
    comp = it.join(imp, on=[("i_item_sk", "imp_item_sk")]) \
        .select(col("i_item_sk"), col("imp_sk"),
                ((col("imp_competitor_price") - col("i_current_price"))
                 / col("i_current_price")).alias("price_change"),
                col("imp_start_date"),
                (col("imp_end_date") - col("imp_start_date"))
                .alias("no_days_comp_price"))

    def quants(table, item_col, date_col, qty_col, cur, prev):
        sales = _t(session, data_dir, table,
                   [item_col, date_col, qty_col])
        j = sales.join(comp.select(
            col("i_item_sk").alias("c_item"), col("imp_sk"),
            col("price_change"), col("imp_start_date"),
            col("no_days_comp_price")), on=[(item_col, "c_item")])
        in_cur = ((col(date_col) >= col("imp_start_date"))
                  & (col(date_col) < (col("imp_start_date")
                                      + col("no_days_comp_price"))))
        in_prev = ((col(date_col) >= (col("imp_start_date")
                                      - col("no_days_comp_price")))
                   & (col(date_col) < col("imp_start_date")))
        return j.group_by(item_col, "imp_sk", "price_change") \
            .agg(Sum(If(in_cur, col(qty_col), lit(0))).alias(cur),
                 Sum(If(in_prev, col(qty_col), lit(0))).alias(prev))

    ws = quants("web_sales", "ws_item_sk", "ws_sold_date_sk",
                "ws_quantity", "current_ws_quant", "prev_ws_quant")
    ss = quants("store_sales", "ss_item_sk", "ss_sold_date_sk",
                "ss_quantity", "current_ss_quant", "prev_ss_quant") \
        .select(col("ss_item_sk"), col("imp_sk").alias("ss_imp"),
                col("current_ss_quant"), col("prev_ss_quant"))
    num = (col("current_ss_quant") + col("current_ws_quant")
           - col("prev_ss_quant") - col("prev_ws_quant")) \
        .cast(T.DoubleType())
    den = ((col("prev_ss_quant") + col("prev_ws_quant"))
           .cast(T.DoubleType()) * col("price_change"))
    return ws.join(ss, on=[("ws_item_sk", "ss_item_sk"),
                           ("imp_sk", "ss_imp")]) \
        .group_by("ws_item_sk") \
        .agg(Average(num / den).alias("cross_price_elasticity"))


def q25(session, data_dir: str):
    """RFM segmentation features over store + web purchases
    (count(distinct) expansion, see module docstring)."""
    cutoff = _date("2002-01-02")
    recency_sk = _sk("2003-01-02")

    def channel(table, cust, date_col, order_col, paid_col):
        dd = _t(session, data_dir, "date_dim",
                ["d_date_sk", "d_date"]) \
            .where(col("d_date") > cutoff).select(col("d_date_sk"))
        s = _t(session, data_dir, table,
               [cust, date_col, order_col, paid_col]) \
            .where(IsNotNull(col(cust))) \
            .join(dd, on=[(date_col, "d_date_sk")])
        plain = s.group_by(cust) \
            .agg(Max(col(date_col)).alias("most_recent_date"),
                 Sum(col(paid_col)).alias("amount"))
        dist = s.group_by(cust) \
            .agg(CountDistinct(col(order_col)).alias("frequency")) \
            .select(col(cust).alias("d_cust"), col("frequency"))
        return plain.join(dist, on=[(cust, "d_cust")]) \
            .select(col(cust).alias("cid"), col("frequency"),
                    col("most_recent_date"), col("amount"))

    both = channel("store_sales", "ss_customer_sk", "ss_sold_date_sk",
                   "ss_ticket_number", "ss_net_paid") \
        .union(channel("web_sales", "ws_bill_customer_sk",
                       "ws_sold_date_sk", "ws_order_number",
                       "ws_net_paid"))
    return both.group_by("cid") \
        .agg(Max(col("most_recent_date")).alias("mrd"),
             Sum(col("frequency")).alias("frequency"),
             Sum(col("amount")).alias("totalspend")) \
        .select(col("cid"),
                If(lit(recency_sk) - col("mrd") < lit(60),
                   lit(1.0), lit(0.0)).alias("recency"),
                col("frequency"), col("totalspend")) \
        .order_by(("cid", True))


def q26(session, data_dir: str):
    """Book-buyer clustering features: per-class purchase counts."""
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_category", "i_class_id"]) \
        .where(col("i_category") == lit("Books"))
    ss = _t(session, data_dir, "store_sales",
            ["ss_customer_sk", "ss_item_sk"]) \
        .where(IsNotNull(col("ss_customer_sk")))
    null_i = Literal(None, T.IntegerType())
    return ss.join(it, on=[("ss_item_sk", "i_item_sk")]) \
        .group_by("ss_customer_sk") \
        .agg(Count(col("ss_item_sk")).alias("item_count"),
             *[Count(If(col("i_class_id") == lit(i), lit(1), null_i))
               .alias(f"id{i}") for i in range(1, 16)]) \
        .where(col("item_count") > lit(5)) \
        .select(col("ss_customer_sk").alias("cid"),
                *[col(f"id{i}") for i in range(1, 16)]) \
        .order_by(("cid", True))


def q28(session, data_dir: str):
    """Sentiment-classifier data prep: the 10% testing split of reviews
    (the reference's multi-insert writes train+test tables; the
    returned frame here is the testing selection)."""
    pr = _t(session, data_dir, "product_reviews",
            ["pr_review_sk", "pr_review_rating", "pr_review_content"])
    return pr.where(col("pr_review_sk") % lit(10) == lit(0)) \
        .select(col("pr_review_sk"), col("pr_review_rating"),
                col("pr_review_content")) \
        .order_by(("pr_review_sk", True))


UNSUPPORTED = {
    "q1": "Q1 uses UDTF", "q2": "Q2 uses UDTF",
    "q3": "Q3 calls python", "q4": "Q4 calls python",
    "q8": "Q8 calls python", "q10": "Q10 uses UDF",
    "q18": "Q18 uses UDF", "q19": "Q19 uses UDF",
    "q27": "Q27 uses UDF", "q29": "Q29 uses UDTF",
    "q30": "Q30 uses UDTF",
}

TPCXBB_QUERIES = {
    "q5": q5, "q6": q6, "q7": q7, "q9": q9, "q11": q11, "q12": q12,
    "q13": q13, "q14": q14, "q15": q15, "q16": q16, "q17": q17,
    "q20": q20, "q21": q21, "q22": q22, "q23": q23, "q24": q24,
    "q25": q25, "q26": q26, "q28": q28,
}


def build_tpcxbb_query(name: str, session, data_dir: str):
    if name in UNSUPPORTED:
        # the reference refuses these the same way
        # (TpcxbbLikeSpark.scala UnsupportedOperationException)
        raise NotImplementedError(UNSUPPORTED[name])
    return TPCXBB_QUERIES[name](session, data_dir)

"""TPC-DS queries, full-suite tranche 5 (q1-q99 gap fill, part 4 of 4).

The heavyweight plans: lag/lead self-joins (q47/q57), cumulative
windows (q51), the 17-table q64, wide pivots (q66/q67), channel
profit unions (q75/q77/q78/q80), and the multi-CTE q14/q23/q24.
Same house rules as tpcds_queries2.py (reference:
TpcdsLikeSpark.scala:1385-4101).  q14/q23/q24/q39 implement the 'a'
variant of the reference's two-part queries.
"""
from __future__ import annotations

import os

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.aggregates import (Average, Count, CountDistinct,
                                              CountStar, Max, Min, Sum)
from spark_rapids_tpu.expr.conditional import CaseWhen, Coalesce, If
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.expr.math_ops import Round
from spark_rapids_tpu.expr.predicates import In, Or
from spark_rapids_tpu.expr.strings import Concat, Substring, Upper
from spark_rapids_tpu.expr.window import (Rank, WindowExpression,
                                          WindowFrame, WindowSpec,
                                          UNBOUNDED, CURRENT_ROW)

__all__ = ["QUERIES5"]


def _t(session, data_dir: str, table: str, columns=None):
    return session.read_parquet(os.path.join(data_dir, table),
                                columns=columns)


def _date_sk(y: int, m: int, d: int) -> int:
    import datetime as _dt
    return 2415022 + (_dt.date(y, m, d) - _dt.date(1900, 1, 1)).days


# ---------------------------------------------------------------------------
# q47 / q57: monthly sales vs yearly average with lag/lead self-joins
# ---------------------------------------------------------------------------

def _monthly_rank_frame(session, data_dir, use_store: bool):
    """v1 CTE: monthly sales + yearly-average window + rank-in-time."""
    dd = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_year", "d_moy"]) \
        .where(Or(Or(col("d_year") == lit(1999),
                     (col("d_year") == lit(1998)) & (col("d_moy") == lit(12))),
                  (col("d_year") == lit(2000)) & (col("d_moy") == lit(1))))
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_category", "i_brand"])
    if use_store:
        sales = _t(session, data_dir, "store_sales",
                   ["ss_item_sk", "ss_sold_date_sk", "ss_store_sk",
                    "ss_sales_price"])
        ent = _t(session, data_dir, "store",
                 ["s_store_sk", "s_store_name", "s_company_name"])
        base = sales.join(dd, on=[("ss_sold_date_sk", "d_date_sk")]) \
            .join(it, on=[("ss_item_sk", "i_item_sk")]) \
            .join(ent, on=[("ss_store_sk", "s_store_sk")])
        ent_cols = ["s_store_name", "s_company_name"]
        price = "ss_sales_price"
    else:
        sales = _t(session, data_dir, "catalog_sales",
                   ["cs_item_sk", "cs_sold_date_sk", "cs_call_center_sk",
                    "cs_sales_price"])
        ent = _t(session, data_dir, "call_center",
                 ["cc_call_center_sk", "cc_name"])
        base = sales.join(dd, on=[("cs_sold_date_sk", "d_date_sk")]) \
            .join(it, on=[("cs_item_sk", "i_item_sk")]) \
            .join(ent, on=[("cs_call_center_sk", "cc_call_center_sk")])
        ent_cols = ["cc_name"]
        price = "cs_sales_price"
    keys = ["i_category", "i_brand"] + ent_cols
    g = base.group_by(*keys, "d_year", "d_moy") \
        .agg(Sum(col(price)).alias("sum_sales"))
    part = tuple(col(k) for k in keys)
    avg_w = WindowExpression(
        Average(col("sum_sales")),
        WindowSpec(partition_by=part + (col("d_year"),)))
    rn = WindowExpression(
        Rank(), WindowSpec(partition_by=part,
                           order_by=((col("d_year"), True),
                                     (col("d_moy"), True))))
    return g.select(*[col(k) for k in keys], col("d_year"), col("d_moy"),
                    col("sum_sales"), avg_w.alias("avg_monthly_sales"),
                    rn.alias("rn")), keys


def _lag_lead_query(session, data_dir, use_store: bool):
    from spark_rapids_tpu.expr.arithmetic import Abs
    v1, keys = _monthly_rank_frame(session, data_dir, use_store)
    lag = v1.select(*[col(k).alias(f"lag_{k}") for k in keys],
                    (col("rn") + lit(1)).cast(T.IntegerType()).alias("lag_rn"),
                    col("sum_sales").alias("psum"))
    lead = v1.select(*[col(k).alias(f"lead_{k}") for k in keys],
                     (col("rn") - lit(1)).cast(T.IntegerType()).alias("lead_rn"),
                     col("sum_sales").alias("nsum"))
    on_lag = [(k, f"lag_{k}") for k in keys] + [("rn", "lag_rn")]
    on_lead = [(k, f"lead_{k}") for k in keys] + [("rn", "lead_rn")]
    v2 = v1.join(lag, on=on_lag).join(lead, on=on_lead)
    out = v2.where((col("d_year") == lit(1999))
                   & (col("avg_monthly_sales") > lit(0.0))
                   & (Abs(col("sum_sales") - col("avg_monthly_sales"))
                      / col("avg_monthly_sales") > lit(0.1)))
    sel = [col(k) for k in keys] + [col("d_year"), col("d_moy"),
                                    col("avg_monthly_sales"),
                                    col("sum_sales"), col("psum"),
                                    col("nsum")]
    return out.select(*sel) \
        .with_column("delta", col("sum_sales") - col("avg_monthly_sales")) \
        .order_by(("delta", True), (keys[2], True), ("d_year", True),
                  ("d_moy", True)) \
        .select(*[c.name for c in sel]) \
        .limit(100)


def q47(session, data_dir: str):
    """TPC-DS q47: store monthly outliers with prev/next month sales."""
    return _lag_lead_query(session, data_dir, use_store=True)


def q57(session, data_dir: str):
    """TPC-DS q57: catalog call-center monthly outliers with prev/next."""
    return _lag_lead_query(session, data_dir, use_store=False)


# ---------------------------------------------------------------------------
# q51: cumulative web-vs-store revenue
# ---------------------------------------------------------------------------

def q51(session, data_dir: str):
    """TPC-DS q51: first dates where cumulative web sales exceed
    cumulative store sales per item."""
    dd = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_date", "d_month_seq"]) \
        .where((col("d_month_seq") >= lit(1200))
               & (col("d_month_seq") <= lit(1211)))
    cum = WindowFrame("rows", UNBOUNDED, CURRENT_ROW)

    def v1(sales, item_c, date_c, price_c, name):
        g = sales.where(col(item_c).is_not_null()) \
            .join(dd, on=[(date_c, "d_date_sk")]) \
            .group_by(item_c, "d_date") \
            .agg(Sum(col(price_c)).alias("day_sales"))
        cume = WindowExpression(
            Sum(col("day_sales")),
            WindowSpec(partition_by=(col(item_c),),
                       order_by=((col("d_date"), True),), frame=cum))
        return g.select(col(item_c).alias(f"{name}_item_sk"),
                        col("d_date").alias(f"{name}_date"),
                        cume.alias(f"{name}_cume"))

    web = v1(_t(session, data_dir, "web_sales",
                ["ws_item_sk", "ws_sold_date_sk", "ws_sales_price"]),
             "ws_item_sk", "ws_sold_date_sk", "ws_sales_price", "web")
    sto = v1(_t(session, data_dir, "store_sales",
                ["ss_item_sk", "ss_sold_date_sk", "ss_sales_price"]),
             "ss_item_sk", "ss_sold_date_sk", "ss_sales_price", "store")
    j = web.join(sto, on=[("web_item_sk", "store_item_sk"),
                          ("web_date", "store_date")], how="full")
    merged = j.select(
        Coalesce(col("web_item_sk"), col("store_item_sk"))
        .alias("item_sk"),
        Coalesce(col("web_date"), col("store_date")).alias("d_date"),
        col("web_cume").alias("web_sales"),
        col("store_cume").alias("store_sales"))
    web_c = WindowExpression(
        Max(col("web_sales")),
        WindowSpec(partition_by=(col("item_sk"),),
                   order_by=((col("d_date"), True),), frame=cum))
    sto_c = WindowExpression(
        Max(col("store_sales")),
        WindowSpec(partition_by=(col("item_sk"),),
                   order_by=((col("d_date"), True),), frame=cum))
    y = merged.select(col("item_sk"), col("d_date"), col("web_sales"),
                      col("store_sales"), web_c.alias("web_cumulative"),
                      sto_c.alias("store_cumulative"))
    return y.where(col("web_cumulative") > col("store_cumulative")) \
        .order_by(("item_sk", True), ("d_date", True)).limit(100)


# ---------------------------------------------------------------------------
# q64: cross-store repeat purchases (the 17-table join)
# ---------------------------------------------------------------------------

def q64(session, data_dir: str):
    """TPC-DS q64: item repurchase stats joined across two years."""
    cs = _t(session, data_dir, "catalog_sales",
            ["cs_item_sk", "cs_order_number", "cs_ext_list_price"])
    cr = _t(session, data_dir, "catalog_returns",
            ["cr_item_sk", "cr_order_number", "cr_refunded_cash",
             "cr_reversed_charge", "cr_store_credit"])
    cs_ui = cs.join(cr, on=[("cs_item_sk", "cr_item_sk"),
                            ("cs_order_number", "cr_order_number")]) \
        .group_by("cs_item_sk") \
        .agg(Sum(col("cs_ext_list_price")).alias("sale"),
             Sum(col("cr_refunded_cash") + col("cr_reversed_charge")
                 + col("cr_store_credit")).alias("refund")) \
        .where(col("sale") > lit(2.0) * col("refund")) \
        .select(col("cs_item_sk").alias("ui_item_sk"))

    ss = _t(session, data_dir, "store_sales",
            ["ss_item_sk", "ss_ticket_number", "ss_store_sk",
             "ss_sold_date_sk", "ss_customer_sk", "ss_cdemo_sk",
             "ss_hdemo_sk", "ss_addr_sk", "ss_promo_sk",
             "ss_wholesale_cost", "ss_list_price", "ss_coupon_amt"])
    sr = _t(session, data_dir, "store_returns",
            ["sr_item_sk", "sr_ticket_number"])
    st = _t(session, data_dir, "store",
            ["s_store_sk", "s_store_name", "s_zip"])
    cu = _t(session, data_dir, "customer",
            ["c_customer_sk", "c_current_cdemo_sk", "c_current_hdemo_sk",
             "c_current_addr_sk", "c_first_sales_date_sk",
             "c_first_shipto_date_sk"])
    cd1 = _t(session, data_dir, "customer_demographics",
             ["cd_demo_sk", "cd_marital_status"]) \
        .select(col("cd_demo_sk").alias("cd1_sk"),
                col("cd_marital_status").alias("cd1_ms"))
    cd2 = _t(session, data_dir, "customer_demographics",
             ["cd_demo_sk", "cd_marital_status"]) \
        .select(col("cd_demo_sk").alias("cd2_sk"),
                col("cd_marital_status").alias("cd2_ms"))
    hd1 = _t(session, data_dir, "household_demographics",
             ["hd_demo_sk", "hd_income_band_sk"]) \
        .select(col("hd_demo_sk").alias("hd1_sk"),
                col("hd_income_band_sk").alias("hd1_ib"))
    hd2 = _t(session, data_dir, "household_demographics",
             ["hd_demo_sk", "hd_income_band_sk"]) \
        .select(col("hd_demo_sk").alias("hd2_sk"),
                col("hd_income_band_sk").alias("hd2_ib"))
    ad1 = _t(session, data_dir, "customer_address",
             ["ca_address_sk", "ca_street_number", "ca_street_name",
              "ca_city", "ca_zip"]) \
        .select(col("ca_address_sk").alias("ad1_sk"),
                col("ca_street_number").alias("b_street_number"),
                col("ca_street_name").alias("b_street_name"),
                col("ca_city").alias("b_city"),
                col("ca_zip").alias("b_zip"))
    ad2 = _t(session, data_dir, "customer_address",
             ["ca_address_sk", "ca_street_number", "ca_street_name",
              "ca_city", "ca_zip"]) \
        .select(col("ca_address_sk").alias("ad2_sk"),
                col("ca_street_number").alias("c_street_number"),
                col("ca_street_name").alias("c_street_name"),
                col("ca_city").alias("c_city"),
                col("ca_zip").alias("c_zip"))
    ib1 = _t(session, data_dir, "income_band", ["ib_income_band_sk"]) \
        .select(col("ib_income_band_sk").alias("ib1_sk"))
    ib2 = _t(session, data_dir, "income_band", ["ib_income_band_sk"]) \
        .select(col("ib_income_band_sk").alias("ib2_sk"))
    pr = _t(session, data_dir, "promotion", ["p_promo_sk"])
    d1 = _t(session, data_dir, "date_dim", ["d_date_sk", "d_year"]) \
        .select(col("d_date_sk").alias("d1_sk"),
                col("d_year").alias("syear"))
    d2 = _t(session, data_dir, "date_dim", ["d_date_sk", "d_year"]) \
        .select(col("d_date_sk").alias("d2_sk"),
                col("d_year").alias("fsyear"))
    d3 = _t(session, data_dir, "date_dim", ["d_date_sk", "d_year"]) \
        .select(col("d_date_sk").alias("d3_sk"),
                col("d_year").alias("s2year"))
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_product_name", "i_color",
             "i_current_price"]) \
        .where(In(col("i_color"),
                  [lit(c) for c in ("purple", "burlywood", "indian",
                                    "spring", "floral", "medium")])
               & (col("i_current_price") >= lit(64.0))
               & (col("i_current_price") <= lit(74.0))
               & (col("i_current_price") >= lit(65.0))
               & (col("i_current_price") <= lit(79.0)))
    base = ss.join(sr, on=[("ss_item_sk", "sr_item_sk"),
                           ("ss_ticket_number", "sr_ticket_number")]) \
        .join(cs_ui, on=[("ss_item_sk", "ui_item_sk")], how="semi") \
        .join(st, on=[("ss_store_sk", "s_store_sk")]) \
        .join(d1, on=[("ss_sold_date_sk", "d1_sk")]) \
        .join(cu, on=[("ss_customer_sk", "c_customer_sk")]) \
        .join(cd1, on=[("ss_cdemo_sk", "cd1_sk")]) \
        .join(hd1, on=[("ss_hdemo_sk", "hd1_sk")]) \
        .join(ad1, on=[("ss_addr_sk", "ad1_sk")]) \
        .join(cd2, on=[("c_current_cdemo_sk", "cd2_sk")]) \
        .join(hd2, on=[("c_current_hdemo_sk", "hd2_sk")]) \
        .join(ad2, on=[("c_current_addr_sk", "ad2_sk")]) \
        .join(d2, on=[("c_first_sales_date_sk", "d2_sk")]) \
        .join(d3, on=[("c_first_shipto_date_sk", "d3_sk")]) \
        .join(pr, on=[("ss_promo_sk", "p_promo_sk")], how="semi") \
        .join(ib1, on=[("hd1_ib", "ib1_sk")], how="semi") \
        .join(ib2, on=[("hd2_ib", "ib2_sk")], how="semi") \
        .join(it, on=[("ss_item_sk", "i_item_sk")]) \
        .where(~(col("cd1_ms") == col("cd2_ms")))
    keys = ["i_product_name", "i_item_sk", "s_store_name", "s_zip",
            "b_street_number", "b_street_name", "b_city", "b_zip",
            "c_street_number", "c_street_name", "c_city", "c_zip",
            "syear", "fsyear", "s2year"]
    cross_sales = base.group_by(*keys).agg(
        CountStar().alias("cnt"),
        Sum(col("ss_wholesale_cost")).alias("s1"),
        Sum(col("ss_list_price")).alias("s2"),
        Sum(col("ss_coupon_amt")).alias("s3"))
    cs1 = cross_sales.where(col("syear") == lit(1999))
    cs2 = cross_sales.where(col("syear") == lit(2000)).select(
        col("i_item_sk").alias("cs2_item_sk"),
        col("s_store_name").alias("cs2_store_name"),
        col("s_zip").alias("cs2_zip"),
        col("syear").alias("cs2_syear"), col("cnt").alias("cs2_cnt"),
        col("s1").alias("cs2_s1"), col("s2").alias("cs2_s2"),
        col("s3").alias("cs2_s3"))
    return cs1.join(cs2, on=[("i_item_sk", "cs2_item_sk"),
                             ("s_store_name", "cs2_store_name"),
                             ("s_zip", "cs2_zip")]) \
        .where(col("cs2_cnt") <= col("cnt")) \
        .select(col("i_product_name"), col("s_store_name"), col("s_zip"),
                col("b_street_number"), col("b_street_name"),
                col("b_city"), col("b_zip"), col("c_street_number"),
                col("c_street_name"), col("c_city"), col("c_zip"),
                col("syear"), col("cnt"), col("s1"), col("s2"),
                col("s3"), col("cs2_s1"), col("cs2_s2"), col("cs2_s3"),
                col("cs2_syear"), col("cs2_cnt")) \
        .order_by(("i_product_name", True), ("s_store_name", True),
                  ("cs2_cnt", True))


# ---------------------------------------------------------------------------
# q66: warehouse monthly shipping pivot
# ---------------------------------------------------------------------------

def q66(session, data_dir: str):
    """TPC-DS q66: per-warehouse monthly sales/net pivot for DHL+BARIAN
    shipments in a time band, web + catalog."""
    months = ["jan", "feb", "mar", "apr", "may", "jun", "jul", "aug",
              "sep", "oct", "nov", "dec"]
    dd = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_year", "d_moy"]) \
        .where(col("d_year") == lit(2001))
    td = _t(session, data_dir, "time_dim", ["t_time_sk", "t_time"]) \
        .where((col("t_time") >= lit(30838))
               & (col("t_time") <= lit(30838 + 28800))) \
        .select(col("t_time_sk"))
    sm = _t(session, data_dir, "ship_mode",
            ["sm_ship_mode_sk", "sm_carrier"]) \
        .where(In(col("sm_carrier"), [lit("DHL"), lit("BARIAN")])) \
        .select(col("sm_ship_mode_sk"))
    wh = _t(session, data_dir, "warehouse",
            ["w_warehouse_sk", "w_warehouse_name", "w_warehouse_sq_ft",
             "w_city", "w_county", "w_state", "w_country"])
    wkeys = ["w_warehouse_name", "w_warehouse_sq_ft", "w_city", "w_county",
             "w_state", "w_country"]

    def leg(sales, wh_c, date_c, time_c, mode_c, sales_expr, net_expr):
        base = sales.join(dd, on=[(date_c, "d_date_sk")]) \
            .join(td, on=[(time_c, "t_time_sk")], how="semi") \
            .join(sm, on=[(mode_c, "sm_ship_mode_sk")], how="semi") \
            .join(wh, on=[(wh_c, "w_warehouse_sk")])
        aggs = []
        for i, m in enumerate(months, 1):
            aggs.append(Sum(If(col("d_moy") == lit(i), sales_expr,
                               lit(0.0))).alias(f"{m}_sales"))
        for i, m in enumerate(months, 1):
            aggs.append(Sum(If(col("d_moy") == lit(i), net_expr,
                               lit(0.0))).alias(f"{m}_net"))
        return base.group_by(*wkeys, "d_year").agg(*aggs)

    ws = _t(session, data_dir, "web_sales",
            ["ws_warehouse_sk", "ws_sold_date_sk", "ws_sold_time_sk",
             "ws_ship_mode_sk", "ws_ext_sales_price", "ws_quantity",
             "ws_net_paid"])
    web = leg(ws, "ws_warehouse_sk", "ws_sold_date_sk", "ws_sold_time_sk",
              "ws_ship_mode_sk",
              col("ws_ext_sales_price") * col("ws_quantity"),
              col("ws_net_paid") * col("ws_quantity"))
    cs = _t(session, data_dir, "catalog_sales",
            ["cs_warehouse_sk", "cs_sold_date_sk", "cs_sold_time_sk",
             "cs_ship_mode_sk", "cs_sales_price", "cs_quantity",
             "cs_net_paid_inc_tax"])
    cat = leg(cs, "cs_warehouse_sk", "cs_sold_date_sk", "cs_sold_time_sk",
              "cs_ship_mode_sk",
              col("cs_sales_price") * col("cs_quantity"),
              col("cs_net_paid_inc_tax") * col("cs_quantity"))
    u = web.union(cat)
    aggs = [Sum(col(f"{m}_sales")).alias(f"{m}_sales") for m in months]
    aggs += [Sum(col(f"{m}_sales") / col("w_warehouse_sq_ft"))
             .alias(f"{m}_sales_per_sq_foot") for m in months]
    aggs += [Sum(col(f"{m}_net")).alias(f"{m}_net") for m in months]
    return u.group_by(*wkeys, "d_year").agg(*aggs) \
        .with_column("ship_carriers", lit("DHL,BARIAN")) \
        .order_by(("w_warehouse_name", True)).limit(100)


# ---------------------------------------------------------------------------
# q67: top items per category over a full rollup
# ---------------------------------------------------------------------------

def q67(session, data_dir: str):
    """TPC-DS q67: rank stores/items inside category over an 8-level
    ROLLUP."""
    dd = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_month_seq", "d_year", "d_qoy", "d_moy"]) \
        .where((col("d_month_seq") >= lit(1200))
               & (col("d_month_seq") <= lit(1211)))
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_item_sk", "ss_store_sk",
             "ss_sales_price", "ss_quantity"])
    st = _t(session, data_dir, "store", ["s_store_sk", "s_store_id"])
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_category", "i_class", "i_brand",
             "i_product_name"])
    base = ss.join(dd, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(st, on=[("ss_store_sk", "s_store_sk")]) \
        .join(it, on=[("ss_item_sk", "i_item_sk")]) \
        .rollup("i_category", "i_class", "i_brand", "i_product_name",
                "d_year", "d_qoy", "d_moy", "s_store_id") \
        .agg(Sum(Coalesce(col("ss_sales_price") * col("ss_quantity"),
                          lit(0.0))).alias("sumsales"))
    rk = WindowExpression(
        Rank(), WindowSpec(partition_by=(col("i_category"),),
                           order_by=((col("sumsales"), False),)))
    ranked = base.select(col("i_category"), col("i_class"), col("i_brand"),
                         col("i_product_name"), col("d_year"),
                         col("d_qoy"), col("d_moy"), col("s_store_id"),
                         col("sumsales"), rk.alias("rk"))
    return ranked.where(col("rk") <= lit(100)) \
        .order_by(("i_category", True), ("i_class", True),
                  ("i_brand", True), ("i_product_name", True),
                  ("d_year", True), ("d_qoy", True), ("d_moy", True),
                  ("s_store_id", True), ("sumsales", True), ("rk", True)) \
        .limit(100)


# ---------------------------------------------------------------------------
# q70: profitable states rollup
# ---------------------------------------------------------------------------

def q70(session, data_dir: str):
    """TPC-DS q70: net profit ROLLUP(state, county) limited to top-5
    ranked states."""
    from spark_rapids_tpu.expr.core import grouping_id
    dd = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_month_seq"]) \
        .where((col("d_month_seq") >= lit(1200))
               & (col("d_month_seq") <= lit(1211))) \
        .select(col("d_date_sk"))
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_store_sk", "ss_net_profit"])
    st = _t(session, data_dir, "store",
            ["s_store_sk", "s_state", "s_county"])
    joined = ss.join(dd, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(st, on=[("ss_store_sk", "s_store_sk")])
    by_state = joined.group_by("s_state") \
        .agg(Sum(col("ss_net_profit")).alias("sp"))
    rank_w = WindowExpression(
        Rank(), WindowSpec(partition_by=(),
                           order_by=((col("sp"), False),)))
    top5 = by_state.select(col("s_state").alias("top_state"),
                           rank_w.alias("ranking")) \
        .where(col("ranking") <= lit(5)).select(col("top_state"))
    base = joined.join(top5, on=[("s_state", "top_state")], how="semi") \
        .rollup("s_state", "s_county") \
        .agg(Sum(col("ss_net_profit")).alias("total_sum"),
             grouping_id().alias("lochierarchy"))
    rk = WindowExpression(
        Rank(), WindowSpec(partition_by=(col("lochierarchy"),
                                         col("s_state")),
                           order_by=((col("total_sum"), False),)))
    return base.select(col("total_sum"), col("s_state"), col("s_county"),
                       col("lochierarchy"),
                       rk.alias("rank_within_parent")) \
        .order_by(("lochierarchy", False), ("s_state", True),
                  ("rank_within_parent", True)) \
        .limit(100)


# ---------------------------------------------------------------------------
# q71: brand revenue by meal-time minute
# ---------------------------------------------------------------------------

def q71(session, data_dir: str):
    """TPC-DS q71: manager-1 brand revenue at breakfast/dinner minutes
    across the three channels, Nov 1999."""
    dd = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_moy", "d_year"]) \
        .where((col("d_moy") == lit(11)) & (col("d_year") == lit(1999))) \
        .select(col("d_date_sk"))
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_brand_id", "i_brand", "i_manager_id"]) \
        .where(col("i_manager_id") == lit(1)) \
        .select(col("i_item_sk"), col("i_brand_id"), col("i_brand"))
    td = _t(session, data_dir, "time_dim",
            ["t_time_sk", "t_hour", "t_minute", "t_meal_time"]) \
        .where(Or(col("t_meal_time") == lit("breakfast"),
                  col("t_meal_time") == lit("dinner")))

    def leg(sales, price_c, date_c, item_c, time_c):
        return sales.join(dd, on=[(date_c, "d_date_sk")]) \
            .select(col(price_c).alias("ext_price"),
                    col(item_c).alias("sold_item_sk"),
                    col(time_c).alias("time_sk"))

    ws = leg(_t(session, data_dir, "web_sales",
                ["ws_ext_sales_price", "ws_sold_date_sk", "ws_item_sk",
                 "ws_sold_time_sk"]),
             "ws_ext_sales_price", "ws_sold_date_sk", "ws_item_sk",
             "ws_sold_time_sk")
    cs = leg(_t(session, data_dir, "catalog_sales",
                ["cs_ext_sales_price", "cs_sold_date_sk", "cs_item_sk",
                 "cs_sold_time_sk"]),
             "cs_ext_sales_price", "cs_sold_date_sk", "cs_item_sk",
             "cs_sold_time_sk")
    ss = leg(_t(session, data_dir, "store_sales",
                ["ss_ext_sales_price", "ss_sold_date_sk", "ss_item_sk",
                 "ss_sold_time_sk"]),
             "ss_ext_sales_price", "ss_sold_date_sk", "ss_item_sk",
             "ss_sold_time_sk")
    return ws.union(cs).union(ss) \
        .join(it, on=[("sold_item_sk", "i_item_sk")]) \
        .join(td, on=[("time_sk", "t_time_sk")]) \
        .group_by("i_brand", "i_brand_id", "t_hour", "t_minute") \
        .agg(Sum(col("ext_price")).alias("ext_price")) \
        .select(col("i_brand_id").alias("brand_id"),
                col("i_brand").alias("brand"), col("t_hour"),
                col("t_minute"), col("ext_price")) \
        .order_by(("ext_price", False), ("brand_id", True),
                  ("t_hour", True), ("t_minute", True), ("brand", True))


# ---------------------------------------------------------------------------
# q72: inventory shortfalls on promoted catalog sales
# ---------------------------------------------------------------------------

def q72(session, data_dir: str):
    """TPC-DS q72: catalog demand exceeding inventory, by week, with
    promo split."""
    cs = _t(session, data_dir, "catalog_sales",
            ["cs_item_sk", "cs_order_number", "cs_bill_cdemo_sk",
             "cs_bill_hdemo_sk", "cs_sold_date_sk", "cs_ship_date_sk",
             "cs_promo_sk", "cs_quantity"])
    inv = _t(session, data_dir, "inventory")
    wh = _t(session, data_dir, "warehouse",
            ["w_warehouse_sk", "w_warehouse_name"])
    it = _t(session, data_dir, "item", ["i_item_sk", "i_item_desc"])
    cd = _t(session, data_dir, "customer_demographics",
            ["cd_demo_sk", "cd_marital_status"]) \
        .where(col("cd_marital_status") == lit("D")) \
        .select(col("cd_demo_sk"))
    hd = _t(session, data_dir, "household_demographics",
            ["hd_demo_sk", "hd_buy_potential"]) \
        .where(col("hd_buy_potential") == lit(">10000")) \
        .select(col("hd_demo_sk"))
    d1 = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_week_seq", "d_year"]) \
        .where(col("d_year") == lit(1999)) \
        .select(col("d_date_sk").alias("d1_sk"),
                col("d_week_seq").alias("d1_week_seq"))
    d2 = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_week_seq"]) \
        .select(col("d_date_sk").alias("d2_sk"),
                col("d_week_seq").alias("d2_week_seq"))
    d3 = _t(session, data_dir, "date_dim", ["d_date_sk"]) \
        .select(col("d_date_sk").alias("d3_sk"))
    pr = _t(session, data_dir, "promotion", ["p_promo_sk"]) \
        .select(col("p_promo_sk"))
    cr = _t(session, data_dir, "catalog_returns",
            ["cr_item_sk", "cr_order_number"]) \
        .select(col("cr_item_sk").alias("crj_item_sk"),
                col("cr_order_number").alias("crj_order_number"),
                lit(1).alias("cr_hit"))
    base = cs.join(inv, on=[("cs_item_sk", "inv_item_sk")]) \
        .join(wh, on=[("inv_warehouse_sk", "w_warehouse_sk")]) \
        .join(it, on=[("cs_item_sk", "i_item_sk")]) \
        .join(cd, on=[("cs_bill_cdemo_sk", "cd_demo_sk")], how="semi") \
        .join(hd, on=[("cs_bill_hdemo_sk", "hd_demo_sk")], how="semi") \
        .join(d1, on=[("cs_sold_date_sk", "d1_sk")]) \
        .join(d2, on=[("inv_date_sk", "d2_sk")]) \
        .join(d3, on=[("cs_ship_date_sk", "d3_sk")]) \
        .where((col("d1_week_seq") == col("d2_week_seq"))
               & (col("inv_quantity_on_hand") < col("cs_quantity"))
               & (col("d3_sk").cast(T.LongType())
                  > col("d1_sk").cast(T.LongType()) + lit(5))) \
        .join(pr, on=[("cs_promo_sk", "p_promo_sk")], how="left") \
        .join(cr, on=[("cs_item_sk", "crj_item_sk"),
                      ("cs_order_number", "crj_order_number")],
              how="left")
    return base.group_by("i_item_desc", "w_warehouse_name", "d1_week_seq") \
        .agg(Sum(If(col("p_promo_sk").is_null(), lit(1), lit(0)))
             .alias("no_promo"),
             Sum(If(col("p_promo_sk").is_not_null(), lit(1), lit(0)))
             .alias("promo"),
             CountStar().alias("total_cnt")) \
        .order_by(("total_cnt", False), ("i_item_desc", True),
                  ("w_warehouse_name", True), ("d1_week_seq", True)) \
        .limit(100)


# ---------------------------------------------------------------------------
# q75: year-over-year sales counts net of returns
# ---------------------------------------------------------------------------

def q75(session, data_dir: str):
    """TPC-DS q75: Books items whose sales count shrank >10% year over
    year, net of returns."""
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_brand_id", "i_class_id", "i_category_id",
             "i_category", "i_manufact_id"]) \
        .where(col("i_category") == lit("Books"))
    dd = _t(session, data_dir, "date_dim", ["d_date_sk", "d_year"])

    def leg(sales_tbl, s_cols, item_c, date_c, order_c, qty_c, price_c,
            ret_tbl, r_item, r_order, r_qty, r_amt):
        sales = _t(session, data_dir, sales_tbl, s_cols)
        rets = _t(session, data_dir, ret_tbl,
                  [r_item, r_order, r_qty, r_amt]) \
            .select(col(r_item).alias("rj_item"),
                    col(r_order).alias("rj_order"),
                    col(r_qty).alias("r_qty"),
                    col(r_amt).alias("r_amt"))
        return sales.join(it, on=[(item_c, "i_item_sk")]) \
            .join(dd, on=[(date_c, "d_date_sk")]) \
            .join(rets, on=[(order_c, "rj_order"), (item_c, "rj_item")],
                  how="left") \
            .select(col("d_year"), col("i_brand_id"), col("i_class_id"),
                    col("i_category_id"), col("i_manufact_id"),
                    (col(qty_c) - Coalesce(col("r_qty"), lit(0)))
                    .alias("sales_cnt"),
                    (col(price_c) - Coalesce(col("r_amt"), lit(0.0)))
                    .alias("sales_amt"))

    cs = leg("catalog_sales",
             ["cs_item_sk", "cs_order_number", "cs_sold_date_sk",
              "cs_quantity", "cs_ext_sales_price"],
             "cs_item_sk", "cs_sold_date_sk", "cs_order_number",
             "cs_quantity", "cs_ext_sales_price",
             "catalog_returns", "cr_item_sk", "cr_order_number",
             "cr_return_quantity", "cr_return_amount")
    ss = leg("store_sales",
             ["ss_item_sk", "ss_ticket_number", "ss_sold_date_sk",
              "ss_quantity", "ss_ext_sales_price"],
             "ss_item_sk", "ss_sold_date_sk", "ss_ticket_number",
             "ss_quantity", "ss_ext_sales_price",
             "store_returns", "sr_item_sk", "sr_ticket_number",
             "sr_return_quantity", "sr_return_amt")
    ws = leg("web_sales",
             ["ws_item_sk", "ws_order_number", "ws_sold_date_sk",
              "ws_quantity", "ws_ext_sales_price"],
             "ws_item_sk", "ws_sold_date_sk", "ws_order_number",
             "ws_quantity", "ws_ext_sales_price",
             "web_returns", "wr_item_sk", "wr_order_number",
             "wr_return_quantity", "wr_return_amt")
    all_sales = cs.union(ss).union(ws).distinct() \
        .group_by("d_year", "i_brand_id", "i_class_id", "i_category_id",
                  "i_manufact_id") \
        .agg(Sum(col("sales_cnt")).alias("sales_cnt"),
             Sum(col("sales_amt")).alias("sales_amt"))
    curr = all_sales.where(col("d_year") == lit(2002))
    prev = all_sales.where(col("d_year") == lit(2001)).select(
        col("i_brand_id").alias("p_brand_id"),
        col("i_class_id").alias("p_class_id"),
        col("i_category_id").alias("p_category_id"),
        col("i_manufact_id").alias("p_manufact_id"),
        col("d_year").alias("prev_year"),
        col("sales_cnt").alias("prev_cnt"),
        col("sales_amt").alias("prev_amt"))
    j = curr.join(prev, on=[("i_brand_id", "p_brand_id"),
                            ("i_class_id", "p_class_id"),
                            ("i_category_id", "p_category_id"),
                            ("i_manufact_id", "p_manufact_id")])
    return j.where(col("sales_cnt").cast(T.DoubleType())
                   / col("prev_cnt").cast(T.DoubleType()) < lit(0.9)) \
        .select(col("prev_year"), col("d_year").alias("year"),
                col("i_brand_id"), col("i_class_id"),
                col("i_category_id"), col("i_manufact_id"),
                col("prev_cnt").alias("prev_yr_cnt"),
                col("sales_cnt").alias("curr_yr_cnt"),
                (col("sales_cnt") - col("prev_cnt"))
                .alias("sales_cnt_diff"),
                (col("sales_amt") - col("prev_amt"))
                .alias("sales_amt_diff")) \
        .order_by(("sales_cnt_diff", True)).limit(100)


# ---------------------------------------------------------------------------
# q77: channel profit and loss
# ---------------------------------------------------------------------------

def q77(session, data_dir: str):
    """TPC-DS q77: 30-day profit and returns per channel, ROLLUP."""
    lo = _date_sk(2000, 8, 23)
    dd = _t(session, data_dir, "date_dim", ["d_date_sk"]) \
        .where((col("d_date_sk") >= lit(lo))
               & (col("d_date_sk") <= lit(lo + 30)))

    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_store_sk", "ss_ext_sales_price",
             "ss_net_profit"]) \
        .join(dd, on=[("ss_sold_date_sk", "d_date_sk")], how="semi") \
        .group_by("ss_store_sk") \
        .agg(Sum(col("ss_ext_sales_price")).alias("sales"),
             Sum(col("ss_net_profit")).alias("profit"))
    sr = _t(session, data_dir, "store_returns",
            ["sr_returned_date_sk", "sr_store_sk", "sr_return_amt",
             "sr_net_loss"]) \
        .join(dd, on=[("sr_returned_date_sk", "d_date_sk")], how="semi") \
        .group_by("sr_store_sk") \
        .agg(Sum(col("sr_return_amt")).alias("s_returns"),
             Sum(col("sr_net_loss")).alias("profit_loss"))
    store = ss.join(sr, on=[("ss_store_sk", "sr_store_sk")], how="left") \
        .select(lit("store channel").alias("channel"),
                col("ss_store_sk").alias("id"), col("sales"),
                Coalesce(col("s_returns"), lit(0.0)).alias("returns"),
                (col("profit") - Coalesce(col("profit_loss"), lit(0.0)))
                .alias("profit"))

    cs = _t(session, data_dir, "catalog_sales",
            ["cs_sold_date_sk", "cs_call_center_sk", "cs_ext_sales_price",
             "cs_net_profit"]) \
        .join(dd, on=[("cs_sold_date_sk", "d_date_sk")], how="semi") \
        .group_by("cs_call_center_sk") \
        .agg(Sum(col("cs_ext_sales_price")).alias("sales"),
             Sum(col("cs_net_profit")).alias("profit"))
    cr = _t(session, data_dir, "catalog_returns",
            ["cr_returned_date_sk", "cr_return_amount", "cr_net_loss"]) \
        .join(dd, on=[("cr_returned_date_sk", "d_date_sk")], how="semi") \
        .agg(Sum(col("cr_return_amount")).alias("c_returns"),
             Sum(col("cr_net_loss")).alias("c_profit_loss"))
    catalog = cs.join(cr, how="cross") \
        .select(lit("catalog channel").alias("channel"),
                col("cs_call_center_sk").alias("id"), col("sales"),
                col("c_returns").alias("returns"),
                (col("profit") - col("c_profit_loss")).alias("profit"))

    wsf = _t(session, data_dir, "web_sales",
             ["ws_sold_date_sk", "ws_web_page_sk", "ws_ext_sales_price",
              "ws_net_profit"]) \
        .join(dd, on=[("ws_sold_date_sk", "d_date_sk")], how="semi") \
        .group_by("ws_web_page_sk") \
        .agg(Sum(col("ws_ext_sales_price")).alias("sales"),
             Sum(col("ws_net_profit")).alias("profit"))
    wrf = _t(session, data_dir, "web_returns",
             ["wr_returned_date_sk", "wr_web_page_sk", "wr_return_amt",
              "wr_net_loss"]) \
        .join(dd, on=[("wr_returned_date_sk", "d_date_sk")], how="semi") \
        .group_by("wr_web_page_sk") \
        .agg(Sum(col("wr_return_amt")).alias("w_returns"),
             Sum(col("wr_net_loss")).alias("w_profit_loss"))
    web = wsf.join(wrf, on=[("ws_web_page_sk", "wr_web_page_sk")],
                   how="left") \
        .select(lit("web channel").alias("channel"),
                col("ws_web_page_sk").alias("id"), col("sales"),
                Coalesce(col("w_returns"), lit(0.0)).alias("returns"),
                (col("profit") - Coalesce(col("w_profit_loss"), lit(0.0)))
                .alias("profit"))

    return store.union(catalog).union(web).rollup("channel", "id").agg(
        Sum(col("sales")).alias("sales"),
        Sum(col("returns")).alias("returns"),
        Sum(col("profit")).alias("profit")) \
        .order_by(("channel", True), ("id", True)).limit(100)


# ---------------------------------------------------------------------------
# q78: store loyalty vs other channels
# ---------------------------------------------------------------------------

def q78(session, data_dir: str):
    """TPC-DS q78: unreturned per-customer-item sales, store vs other
    channels, year 2000."""
    dd = _t(session, data_dir, "date_dim", ["d_date_sk", "d_year"])

    def leg(sales_tbl, cols, item_c, cust_c, date_c, order_c, qty_c, wc_c,
            sp_c, ret_tbl, r_item, r_order, tag):
        sales = _t(session, data_dir, sales_tbl, cols)
        rets = _t(session, data_dir, ret_tbl, [r_item, r_order]) \
            .select(col(r_item).alias("rj_item"),
                    col(r_order).alias("rj_order"))
        return sales.join(rets, on=[(order_c, "rj_order"),
                                    (item_c, "rj_item")], how="anti") \
            .join(dd, on=[(date_c, "d_date_sk")]) \
            .group_by("d_year", item_c, cust_c) \
            .agg(Sum(col(qty_c)).alias(f"{tag}_qty"),
                 Sum(col(wc_c)).alias(f"{tag}_wc"),
                 Sum(col(sp_c)).alias(f"{tag}_sp")) \
            .select(col("d_year").alias(f"{tag}_sold_year"),
                    col(item_c).alias(f"{tag}_item_sk"),
                    col(cust_c).alias(f"{tag}_customer_sk"),
                    col(f"{tag}_qty"), col(f"{tag}_wc"),
                    col(f"{tag}_sp"))

    ws = leg("web_sales",
             ["ws_item_sk", "ws_bill_customer_sk", "ws_sold_date_sk",
              "ws_order_number", "ws_quantity", "ws_wholesale_cost",
              "ws_sales_price"],
             "ws_item_sk", "ws_bill_customer_sk", "ws_sold_date_sk",
             "ws_order_number", "ws_quantity", "ws_wholesale_cost",
             "ws_sales_price", "web_returns", "wr_item_sk",
             "wr_order_number", "ws")
    cs = leg("catalog_sales",
             ["cs_item_sk", "cs_bill_customer_sk", "cs_sold_date_sk",
              "cs_order_number", "cs_quantity", "cs_wholesale_cost",
              "cs_sales_price"],
             "cs_item_sk", "cs_bill_customer_sk", "cs_sold_date_sk",
             "cs_order_number", "cs_quantity", "cs_wholesale_cost",
             "cs_sales_price", "catalog_returns", "cr_item_sk",
             "cr_order_number", "cs")
    ss = leg("store_sales",
             ["ss_item_sk", "ss_customer_sk", "ss_sold_date_sk",
              "ss_ticket_number", "ss_quantity", "ss_wholesale_cost",
              "ss_sales_price"],
             "ss_item_sk", "ss_customer_sk", "ss_sold_date_sk",
             "ss_ticket_number", "ss_quantity", "ss_wholesale_cost",
             "ss_sales_price", "store_returns", "sr_item_sk",
             "sr_ticket_number", "ss")
    j = ss.join(ws, on=[("ss_sold_year", "ws_sold_year"),
                        ("ss_item_sk", "ws_item_sk"),
                        ("ss_customer_sk", "ws_customer_sk")],
                how="left") \
        .join(cs, on=[("ss_sold_year", "cs_sold_year"),
                      ("ss_item_sk", "cs_item_sk"),
                      ("ss_customer_sk", "cs_customer_sk")],
              how="left")
    other_qty = Coalesce(col("ws_qty"), lit(0)) + Coalesce(col("cs_qty"),
                                                           lit(0))
    return j.where((col("ss_sold_year") == lit(2000))
                   & (other_qty > lit(0))) \
        .select(col("ss_sold_year"), col("ss_item_sk"),
                col("ss_customer_sk"),
                Round(col("ss_qty").cast(T.DoubleType())
                      / If(other_qty == lit(0), lit(1),
                           other_qty).cast(T.DoubleType()), 2)
                .alias("ratio"),
                col("ss_qty").alias("store_qty"),
                col("ss_wc").alias("store_wholesale_cost"),
                col("ss_sp").alias("store_sales_price"),
                other_qty.alias("other_chan_qty"),
                (Coalesce(col("ws_wc"), lit(0.0))
                 + Coalesce(col("cs_wc"), lit(0.0)))
                .alias("other_chan_wholesale_cost"),
                (Coalesce(col("ws_sp"), lit(0.0))
                 + Coalesce(col("cs_sp"), lit(0.0)))
                .alias("other_chan_sales_price")) \
        .order_by(("ss_sold_year", True), ("ss_item_sk", True),
                  ("ss_customer_sk", True), ("store_qty", False),
                  ("store_wholesale_cost", False),
                  ("store_sales_price", False)) \
        .limit(100)


# ---------------------------------------------------------------------------
# q80: channel profit report with promo filter
# ---------------------------------------------------------------------------

def q80(session, data_dir: str):
    """TPC-DS q80: 30-day sales/returns/profit per channel entity for
    non-TV-promoted expensive items."""
    lo = _date_sk(2000, 8, 23)
    dd = _t(session, data_dir, "date_dim", ["d_date_sk"]) \
        .where((col("d_date_sk") >= lit(lo))
               & (col("d_date_sk") <= lit(lo + 30)))
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_current_price"]) \
        .where(col("i_current_price") > lit(50.0)).select(col("i_item_sk"))
    pr = _t(session, data_dir, "promotion",
            ["p_promo_sk", "p_channel_tv"]) \
        .where(col("p_channel_tv") == lit("N")).select(col("p_promo_sk"))

    def leg(sales_tbl, s_cols, date_c, item_c, promo_c, ent_c, price_c,
            profit_c, ret_tbl, r_cols, r_item, r_order, s_order, r_amt,
            r_loss, ent_tbl, ent_sk, ent_id):
        sales = _t(session, data_dir, sales_tbl, s_cols)
        rets = _t(session, data_dir, ret_tbl, r_cols) \
            .select(col(r_item).alias("rj_item"),
                    col(r_order).alias("rj_order"),
                    col(r_amt).alias("r_amt"), col(r_loss).alias("r_loss"))
        ent = _t(session, data_dir, ent_tbl, [ent_sk, ent_id])
        return sales \
            .join(rets, on=[(item_c, "rj_item"), (s_order, "rj_order")],
                  how="left") \
            .join(dd, on=[(date_c, "d_date_sk")], how="semi") \
            .join(it, on=[(item_c, "i_item_sk")], how="semi") \
            .join(pr, on=[(promo_c, "p_promo_sk")], how="semi") \
            .join(ent, on=[(ent_c, ent_sk)]) \
            .group_by(ent_id) \
            .agg(Sum(col(price_c)).alias("sales"),
                 Sum(Coalesce(col("r_amt"), lit(0.0))).alias("returns"),
                 Sum(col(profit_c) - Coalesce(col("r_loss"), lit(0.0)))
                 .alias("profit"))

    ssr = leg("store_sales",
              ["ss_sold_date_sk", "ss_store_sk", "ss_item_sk",
               "ss_promo_sk", "ss_ticket_number", "ss_ext_sales_price",
               "ss_net_profit"],
              "ss_sold_date_sk", "ss_item_sk", "ss_promo_sk",
              "ss_store_sk", "ss_ext_sales_price", "ss_net_profit",
              "store_returns",
              ["sr_item_sk", "sr_ticket_number", "sr_return_amt",
               "sr_net_loss"],
              "sr_item_sk", "sr_ticket_number", "ss_ticket_number",
              "sr_return_amt", "sr_net_loss",
              "store", "s_store_sk", "s_store_id")
    csr = leg("catalog_sales",
              ["cs_sold_date_sk", "cs_catalog_page_sk", "cs_item_sk",
               "cs_promo_sk", "cs_order_number", "cs_ext_sales_price",
               "cs_net_profit"],
              "cs_sold_date_sk", "cs_item_sk", "cs_promo_sk",
              "cs_catalog_page_sk", "cs_ext_sales_price", "cs_net_profit",
              "catalog_returns",
              ["cr_item_sk", "cr_order_number", "cr_return_amount",
               "cr_net_loss"],
              "cr_item_sk", "cr_order_number", "cs_order_number",
              "cr_return_amount", "cr_net_loss",
              "catalog_page", "cp_catalog_page_sk", "cp_catalog_page_id")
    wsr = leg("web_sales",
              ["ws_sold_date_sk", "ws_web_site_sk", "ws_item_sk",
               "ws_promo_sk", "ws_order_number", "ws_ext_sales_price",
               "ws_net_profit"],
              "ws_sold_date_sk", "ws_item_sk", "ws_promo_sk",
              "ws_web_site_sk", "ws_ext_sales_price", "ws_net_profit",
              "web_returns",
              ["wr_item_sk", "wr_order_number", "wr_return_amt",
               "wr_net_loss"],
              "wr_item_sk", "wr_order_number", "ws_order_number",
              "wr_return_amt", "wr_net_loss",
              "web_site", "web_site_sk", "web_site_id")

    def channel(frame, label, prefix, id_col):
        return frame.select(
            lit(label).alias("channel"),
            Concat(lit(prefix), col(id_col)).alias("id"),
            col("sales"), col("returns"), col("profit"))

    u = channel(ssr, "store channel", "store", "s_store_id") \
        .union(channel(csr, "catalog channel", "catalog_page",
                       "cp_catalog_page_id")) \
        .union(channel(wsr, "web channel", "web_site", "web_site_id"))
    return u.rollup("channel", "id").agg(
        Sum(col("sales")).alias("sales"),
        Sum(col("returns")).alias("returns"),
        Sum(col("profit")).alias("profit")) \
        .order_by(("channel", True), ("id", True)).limit(100)


# ---------------------------------------------------------------------------
# q14 (variant a): cross-channel item comparison
# ---------------------------------------------------------------------------

def q14(session, data_dir: str):
    """TPC-DS q14a: channel sales of items sold in ALL three channels,
    vs the overall average."""
    dd = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_year", "d_moy"])
    years = dd.where((col("d_year") >= lit(1999))
                     & (col("d_year") <= lit(2001))) \
        .select(col("d_date_sk"))
    it_full = _t(session, data_dir, "item",
                 ["i_item_sk", "i_brand_id", "i_class_id",
                  "i_category_id"])

    def sold_triples(sales_tbl, item_c, date_c):
        return _t(session, data_dir, sales_tbl, [item_c, date_c]) \
            .join(years, on=[(date_c, "d_date_sk")], how="semi") \
            .join(it_full, on=[(item_c, "i_item_sk")]) \
            .select(col("i_brand_id"), col("i_class_id"),
                    col("i_category_id")).distinct()

    triples = sold_triples("store_sales", "ss_item_sk", "ss_sold_date_sk") \
        .intersect(sold_triples("catalog_sales", "cs_item_sk",
                                "cs_sold_date_sk")) \
        .intersect(sold_triples("web_sales", "ws_item_sk",
                                "ws_sold_date_sk")) \
        .select(col("i_brand_id").alias("t_brand"),
                col("i_class_id").alias("t_class"),
                col("i_category_id").alias("t_cat"))
    cross_items = it_full.join(
        triples, on=[("i_brand_id", "t_brand"), ("i_class_id", "t_class"),
                     ("i_category_id", "t_cat")], how="semi") \
        .select(col("i_item_sk").alias("ci_item_sk"))

    def qlp(sales_tbl, qty_c, price_c, date_c):
        return _t(session, data_dir, sales_tbl,
                  [qty_c, price_c, date_c]) \
            .join(years, on=[(date_c, "d_date_sk")], how="semi") \
            .select((col(qty_c) * col(price_c)).alias("qlp"))

    avg_rows = qlp("store_sales", "ss_quantity", "ss_list_price",
                   "ss_sold_date_sk") \
        .union(qlp("catalog_sales", "cs_quantity", "cs_list_price",
                   "cs_sold_date_sk")) \
        .union(qlp("web_sales", "ws_quantity", "ws_list_price",
                   "ws_sold_date_sk")) \
        .agg(Average(col("qlp")).alias("average_sales")).collect()
    average_sales = avg_rows[0][0] or 0.0

    target = dd.where((col("d_year") == lit(2001))
                      & (col("d_moy") == lit(11))) \
        .select(col("d_date_sk"))

    def channel(sales_tbl, item_c, qty_c, price_c, date_c, label):
        sales = _t(session, data_dir, sales_tbl,
                   [item_c, qty_c, price_c, date_c])
        return sales.join(target, on=[(date_c, "d_date_sk")], how="semi") \
            .join(cross_items, on=[(item_c, "ci_item_sk")], how="semi") \
            .join(it_full, on=[(item_c, "i_item_sk")]) \
            .group_by("i_brand_id", "i_class_id", "i_category_id") \
            .agg(Sum(col(qty_c) * col(price_c)).alias("sales"),
                 CountStar().alias("number_sales")) \
            .where(col("sales") > lit(average_sales)) \
            .select(lit(label).alias("channel"), col("i_brand_id"),
                    col("i_class_id"), col("i_category_id"),
                    col("sales"), col("number_sales"))

    u = channel("store_sales", "ss_item_sk", "ss_quantity",
                "ss_list_price", "ss_sold_date_sk", "store") \
        .union(channel("catalog_sales", "cs_item_sk", "cs_quantity",
                       "cs_list_price", "cs_sold_date_sk", "catalog")) \
        .union(channel("web_sales", "ws_item_sk", "ws_quantity",
                       "ws_list_price", "ws_sold_date_sk", "web"))
    return u.rollup("channel", "i_brand_id", "i_class_id",
                    "i_category_id") \
        .agg(Sum(col("sales")).alias("sum_sales"),
             Sum(col("number_sales")).alias("sum_number_sales")) \
        .order_by(("channel", True), ("i_brand_id", True),
                  ("i_class_id", True), ("i_category_id", True)) \
        .limit(100)


# ---------------------------------------------------------------------------
# q23 (variant a): frequent items bought by best customers
# ---------------------------------------------------------------------------

def q23(session, data_dir: str):
    """TPC-DS q23a: catalog+web revenue in Feb 2000 from frequently
    store-sold items bought by the biggest store customers."""
    dd = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_date", "d_year", "d_moy"])
    years = dd.where(In(col("d_year"),
                        [lit(y) for y in (2000, 2001, 2002, 2003)]))
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_item_sk", "ss_customer_sk",
             "ss_quantity", "ss_sales_price"])
    it = _t(session, data_dir, "item", ["i_item_sk", "i_item_desc"])
    frequent = ss.join(years.select(col("d_date_sk"), col("d_date")),
                       on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(it, on=[("ss_item_sk", "i_item_sk")]) \
        .with_column("itemdesc", Substring(col("i_item_desc"), lit(1),
                                           lit(30))) \
        .group_by("itemdesc", "ss_item_sk", "d_date") \
        .agg(CountStar().alias("cnt")) \
        .where(col("cnt") > lit(4)) \
        .select(col("ss_item_sk").alias("freq_item_sk")).distinct()

    cu = _t(session, data_dir, "customer", ["c_customer_sk"])
    sales_by_cust = ss.join(cu, on=[("ss_customer_sk", "c_customer_sk")]) \
        .group_by("c_customer_sk") \
        .agg(Sum(col("ss_quantity") * col("ss_sales_price"))
             .alias("csales"))
    in_window = ss.join(years.select(col("d_date_sk")),
                        on=[("ss_sold_date_sk", "d_date_sk")], how="semi") \
        .join(cu, on=[("ss_customer_sk", "c_customer_sk")]) \
        .group_by("c_customer_sk") \
        .agg(Sum(col("ss_quantity") * col("ss_sales_price"))
             .alias("csales"))
    max_rows = in_window.agg(Max(col("csales")).alias("m")).collect()
    tpcds_cmax = max_rows[0][0] or 0.0
    best = sales_by_cust \
        .where(col("csales") > lit(0.95 * float(tpcds_cmax))) \
        .select(col("c_customer_sk").alias("best_cust_sk"))

    feb = dd.where((col("d_year") == lit(2000))
                   & (col("d_moy") == lit(2))).select(col("d_date_sk"))

    def channel(sales_tbl, item_c, cust_c, qty_c, price_c, date_c):
        return _t(session, data_dir, sales_tbl,
                  [item_c, cust_c, qty_c, price_c, date_c]) \
            .join(feb, on=[(date_c, "d_date_sk")], how="semi") \
            .join(frequent, on=[(item_c, "freq_item_sk")], how="semi") \
            .join(best, on=[(cust_c, "best_cust_sk")], how="semi") \
            .select((col(qty_c) * col(price_c)).alias("sales"))

    u = channel("catalog_sales", "cs_item_sk", "cs_bill_customer_sk",
                "cs_quantity", "cs_list_price", "cs_sold_date_sk") \
        .union(channel("web_sales", "ws_item_sk", "ws_bill_customer_sk",
                       "ws_quantity", "ws_list_price", "ws_sold_date_sk"))
    return u.agg(Sum(col("sales")).alias("total")).limit(100)


# ---------------------------------------------------------------------------
# q24 (variant a): customer net-paid by color
# ---------------------------------------------------------------------------

def q24(session, data_dir: str):
    """TPC-DS q24a: pale-item net paid per customer/store, above 5% of
    the average."""
    ss = _t(session, data_dir, "store_sales",
            ["ss_ticket_number", "ss_item_sk", "ss_customer_sk",
             "ss_store_sk", "ss_net_paid"])
    sr = _t(session, data_dir, "store_returns",
            ["sr_ticket_number", "sr_item_sk"])
    st = _t(session, data_dir, "store",
            ["s_store_sk", "s_store_name", "s_market_id", "s_state",
             "s_zip"]) \
        .where(col("s_market_id") == lit(8))
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_color", "i_current_price", "i_manager_id",
             "i_units", "i_size"])
    cu = _t(session, data_dir, "customer",
            ["c_customer_sk", "c_first_name", "c_last_name",
             "c_birth_country"])
    ca = _t(session, data_dir, "customer_address",
            ["ca_address_sk", "ca_state", "ca_country", "ca_zip"]) \
        .with_column("ca_country_up", Upper(col("ca_country")))
    base = ss.join(sr, on=[("ss_ticket_number", "sr_ticket_number"),
                           ("ss_item_sk", "sr_item_sk")]) \
        .join(st, on=[("ss_store_sk", "s_store_sk")]) \
        .join(it, on=[("ss_item_sk", "i_item_sk")]) \
        .join(cu, on=[("ss_customer_sk", "c_customer_sk")]) \
        .join(ca, on=[("c_birth_country", "ca_country_up"),
                      ("s_zip", "ca_zip")])
    ssales = base.group_by("c_last_name", "c_first_name", "s_store_name",
                           "ca_state", "s_state", "i_color",
                           "i_current_price", "i_manager_id", "i_units",
                           "i_size") \
        .agg(Sum(col("ss_net_paid")).alias("netpaid"))
    avg_rows = ssales.agg(Average(col("netpaid")).alias("a")).collect()
    threshold = 0.05 * float(avg_rows[0][0] or 0.0)
    return ssales.where(col("i_color") == lit("pale")) \
        .group_by("c_last_name", "c_first_name", "s_store_name") \
        .agg(Sum(col("netpaid")).alias("paid")) \
        .where(col("paid") > lit(threshold)) \
        .order_by(("c_last_name", True), ("c_first_name", True),
                  ("s_store_name", True), ("paid", True))


QUERIES5 = {"q14": q14, "q23": q23, "q24": q24, "q47": q47, "q51": q51,
            "q57": q57, "q64": q64, "q66": q66, "q67": q67, "q70": q70,
            "q71": q71, "q72": q72, "q75": q75, "q77": q77, "q78": q78,
            "q80": q80}

"""Synthetic TPC-H data generator (pruned, self-consistent, seeded).

Reference: integration_tests/.../tpch/TpchLikeSpark.scala defines the 8
TPC-H tables + 22 queries as Spark DataFrame code; this generator
produces the same relational structure (orders->lineitem parentage,
part/supplier cross links) at a requested scale factor, the same way
tpcds_gen.py does for TPC-DS.  It measures engine speed, not dbgen
bit-exactness.
"""
from __future__ import annotations

import os

import numpy as np

TABLES = ("region", "nation", "supplier", "customer", "part", "partsupp",
          "orders", "lineitem")

_SCHEMA_VERSION = "v1"

_REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
_NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1)]
_SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY",
             "HOUSEHOLD"]
_PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED",
               "5-LOW"]
_SHIPMODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
_INSTRUCTIONS = ["DELIVER IN PERSON", "COLLECT COD", "NONE",
                 "TAKE BACK RETURN"]
_TYPES = [f"{a} {b} {c}" for a in ("STANDARD", "SMALL", "MEDIUM", "LARGE",
                                   "ECONOMY", "PROMO")
          for b in ("ANODIZED", "BURNISHED", "PLATED", "POLISHED",
                    "BRUSHED")
          for c in ("TIN", "NICKEL", "BRASS", "STEEL", "COPPER")]
_CONTAINERS = [f"{a} {b}" for a in ("SM", "LG", "MED", "JUMBO", "WRAP")
               for b in ("CASE", "BOX", "BAG", "JAR", "PKG", "PACK",
                         "CAN", "DRUM")]

#: dates are DAYS since 1970-01-01 (DateType), TPC-H range 1992..1998
_DATE_LO = 8035    # 1992-01-01
_DATE_HI = 10591   # 1998-12-31


def table_row_counts(sf: float) -> dict[str, int]:
    return {
        "region": 5,
        "nation": 25,
        "supplier": max(10, int(10_000 * sf)),
        "customer": max(30, int(150_000 * sf)),
        "part": max(40, int(200_000 * sf)),
        "partsupp": max(160, int(800_000 * sf)),
        "orders": max(300, int(1_500_000 * sf)),
        "lineitem": max(1200, int(6_000_000 * sf)),
    }


def _gen_region() -> dict[str, np.ndarray]:
    return {
        "r_regionkey": np.arange(5, dtype=np.int32),
        "r_name": np.array(_REGIONS, dtype=object),
        "r_comment": np.array([f"region comment {i}" for i in range(5)],
                              dtype=object),
    }


def _gen_nation() -> dict[str, np.ndarray]:
    return {
        "n_nationkey": np.arange(25, dtype=np.int32),
        "n_name": np.array([n for n, _ in _NATIONS], dtype=object),
        "n_regionkey": np.array([r for _, r in _NATIONS], dtype=np.int32),
        "n_comment": np.array([f"nation comment {i}" for i in range(25)],
                              dtype=object),
    }


def _gen_supplier(rng, n: int) -> dict[str, np.ndarray]:
    comments = np.array([f"supplier comment {i}" for i in range(n)],
                        dtype=object)
    # dbgen plants Complaint/Recommends markers used by q16
    for i in rng.choice(n, size=max(1, n // 100), replace=False):
        comments[i] = f"blah Customer Complaints blah {i}"
    return {
        "s_suppkey": np.arange(1, n + 1, dtype=np.int32),
        "s_name": np.array([f"Supplier#{k:09d}" for k in range(1, n + 1)],
                           dtype=object),
        "s_address": np.array([f"addr {k}" for k in range(n)],
                              dtype=object),
        "s_nationkey": rng.integers(0, 25, n).astype(np.int32),
        "s_phone": np.array([f"{11 + k % 25}-{k % 999:03d}-555-{k % 9999:04d}"
                             for k in range(n)], dtype=object),
        "s_acctbal": np.round(rng.uniform(-999.99, 9999.99, n), 2),
        "s_comment": comments,
    }


def _gen_customer(rng, n: int) -> dict[str, np.ndarray]:
    nat = rng.integers(0, 25, n).astype(np.int32)
    return {
        "c_custkey": np.arange(1, n + 1, dtype=np.int32),
        "c_name": np.array([f"Customer#{k:09d}" for k in range(1, n + 1)],
                           dtype=object),
        "c_address": np.array([f"addr {k}" for k in range(n)],
                              dtype=object),
        "c_nationkey": nat,
        "c_phone": np.array([f"{11 + v}-{k % 999:03d}-555-{k % 9999:04d}"
                             for k, v in enumerate(nat)], dtype=object),
        "c_acctbal": np.round(rng.uniform(-999.99, 9999.99, n), 2),
        "c_mktsegment": np.array([_SEGMENTS[v] for v in
                                  rng.integers(0, 5, n)], dtype=object),
        "c_comment": np.array([f"customer comment {k}" for k in range(n)],
                              dtype=object),
    }


def _gen_part(rng, n: int) -> dict[str, np.ndarray]:
    colors = ["almond", "antique", "aquamarine", "azure", "beige", "bisque",
              "black", "blanched", "blue", "blush", "brown", "burlywood",
              "burnished", "chartreuse", "chiffon", "chocolate", "coral",
              "cornflower", "cornsilk", "cream", "cyan", "dark", "deep",
              "dim", "dodger", "drab", "firebrick", "floral", "forest",
              "frosted", "gainsboro", "ghost", "goldenrod", "green",
              "grey", "honeydew", "hot", "hot pink", "indian", "ivory",
              "khaki", "lace", "lavender", "lawn", "lemon", "light",
              "lime", "linen", "magenta", "maroon", "medium", "metallic",
              "midnight", "mint", "misty", "moccasin", "navajo", "navy",
              "olive", "orange", "orchid", "pale", "papaya", "peach",
              "peru", "pink", "plum", "powder", "puff", "purple", "red",
              "rose", "rosy", "royal", "saddle", "salmon", "sandy",
              "seashell", "sienna", "sky", "slate", "smoke", "snow",
              "spring", "steel", "tan", "thistle", "tomato", "turquoise",
              "violet", "wheat", "white", "yellow"]
    c1 = rng.integers(0, len(colors), n)
    c2 = rng.integers(0, len(colors), n)
    return {
        "p_partkey": np.arange(1, n + 1, dtype=np.int32),
        "p_name": np.array([f"{colors[a]} {colors[b]}"
                            for a, b in zip(c1, c2)], dtype=object),
        "p_mfgr": np.array([f"Manufacturer#{1 + k % 5}" for k in range(n)],
                           dtype=object),
        "p_brand": np.array([f"Brand#{1 + k % 5}{1 + (k // 5) % 5}"
                             for k in range(n)], dtype=object),
        "p_type": np.array([_TYPES[v] for v in
                            rng.integers(0, len(_TYPES), n)], dtype=object),
        "p_size": rng.integers(1, 51, n).astype(np.int32),
        "p_container": np.array([_CONTAINERS[v] for v in
                                 rng.integers(0, len(_CONTAINERS), n)],
                                dtype=object),
        "p_retailprice": np.round(900.0 + rng.uniform(0, 1200, n), 2),
        "p_comment": np.array([f"part comment {k}" for k in range(n)],
                              dtype=object),
    }


def _gen_partsupp(rng, n: int, n_part: int,
                  n_supp: int) -> dict[str, np.ndarray]:
    # 4 suppliers per part, dbgen-style
    part = np.repeat(np.arange(1, n_part + 1, dtype=np.int32), 4)[:n]
    supp = ((part * 7919 + np.tile(np.arange(4), n_part)[:n] *
             (n_supp // 4 + 1)) % n_supp + 1).astype(np.int32)
    m = len(part)
    return {
        "ps_partkey": part,
        "ps_suppkey": supp,
        "ps_availqty": rng.integers(1, 10_000, m).astype(np.int32),
        "ps_supplycost": np.round(rng.uniform(1.0, 1000.0, m), 2),
        "ps_comment": np.array([f"partsupp comment {k}" for k in range(m)],
                               dtype=object),
    }


def _gen_orders(rng, n: int, n_cust: int) -> dict[str, np.ndarray]:
    odate = rng.integers(_DATE_LO, _DATE_HI - 121, n).astype(np.int32)
    return {
        "o_orderkey": np.arange(1, n + 1, dtype=np.int32),
        # dbgen: only ~2/3 of customers have orders
        "o_custkey": (rng.integers(1, max(n_cust * 2 // 3, 2), n)
                      .astype(np.int32)),
        "o_orderstatus": np.array([("F", "O", "P")[v] for v in
                                   rng.integers(0, 3, n)], dtype=object),
        "o_totalprice": np.round(rng.uniform(800.0, 500_000.0, n), 2),
        "o_orderdate": odate,
        "o_orderpriority": np.array([_PRIORITIES[v] for v in
                                     rng.integers(0, 5, n)], dtype=object),
        "o_clerk": np.array([f"Clerk#{k % 1000:09d}" for k in range(n)],
                            dtype=object),
        "o_shippriority": np.zeros(n, dtype=np.int32),
        "o_comment": np.array([f"order comment {k}" for k in range(n)],
                              dtype=object),
    }


def _gen_lineitem(rng, n: int, orders: dict,
                  n_part: int, n_supp: int) -> dict[str, np.ndarray]:
    n_ord = len(orders["o_orderkey"])
    # ~4 lines per order, line numbers 1..7
    oidx = np.sort(rng.integers(0, n_ord, n))
    okey = orders["o_orderkey"][oidx]
    odate = orders["o_orderdate"][oidx].astype(np.int64)
    linenumber = np.ones(n, dtype=np.int64)
    same = np.concatenate([[False], okey[1:] == okey[:-1]])
    for i in range(1, n):
        if same[i]:
            linenumber[i] = linenumber[i - 1] + 1
    qty = rng.integers(1, 51, n).astype(np.int32)
    price = np.round(rng.uniform(900.0, 2100.0, n) * qty, 2)
    disc = np.round(rng.integers(0, 11, n) * 0.01, 2)
    tax = np.round(rng.integers(0, 9, n) * 0.01, 2)
    ship = odate + rng.integers(1, 122, n)
    commit = odate + rng.integers(30, 91, n)
    receipt = ship + rng.integers(1, 31, n)
    returnflag = np.where(
        receipt <= 9204,  # 1995-06-17-ish split, dbgen uses receipt date
        np.array([("R", "A")[v] for v in rng.integers(0, 2, n)],
                 dtype=object),
        "N")
    linestatus = np.where(ship > 9204, "O", "F")
    return {
        "l_orderkey": okey.astype(np.int32),
        "l_partkey": rng.integers(1, n_part + 1, n).astype(np.int32),
        "l_suppkey": rng.integers(1, n_supp + 1, n).astype(np.int32),
        "l_linenumber": linenumber.astype(np.int32),
        "l_quantity": qty.astype(np.float64),
        "l_extendedprice": price,
        "l_discount": disc,
        "l_tax": tax,
        "l_returnflag": returnflag.astype(object),
        "l_linestatus": linestatus.astype(object),
        "l_shipdate": ship.astype(np.int32),
        "l_commitdate": commit.astype(np.int32),
        "l_receiptdate": receipt.astype(np.int32),
        "l_shipinstruct": np.array(
            [_INSTRUCTIONS[v] for v in rng.integers(0, 4, n)],
            dtype=object),
        "l_shipmode": np.array(
            [_SHIPMODES[v] for v in rng.integers(0, 7, n)], dtype=object),
        "l_comment": np.array([f"line comment {k}" for k in range(n)],
                              dtype=object),
    }


_DATE_COLS = {"o_orderdate", "l_shipdate", "l_commitdate",
              "l_receiptdate"}


def _write_parquet(path: str, data: dict, date_cols=()) -> None:
    import pyarrow as pa
    import pyarrow.parquet as pq
    arrays, names = [], []
    for name, arr in data.items():
        if name in _DATE_COLS:
            arrays.append(pa.array(np.asarray(arr, dtype=np.int32),
                                   type=pa.date32()))
        elif isinstance(arr, np.ndarray) and arr.dtype == object:
            arrays.append(pa.array(arr.tolist()))
        else:
            arrays.append(pa.array(arr))
        names.append(name)
    os.makedirs(path, exist_ok=True)
    pq.write_table(pa.Table.from_arrays(arrays, names=names),
                   os.path.join(path, "part-0.parquet"))


def generate_tpch(data_dir: str, sf: float = 0.01, seed: int = 7,
                  tables=None) -> None:
    """Generate (or re-use) the TPC-H tables under ``data_dir``."""
    counts = table_row_counts(sf)
    stamp = os.path.join(data_dir, f".generated_{_SCHEMA_VERSION}_"
                                   f"sf{sf:g}_seed{seed}")
    if os.path.exists(stamp) and tables is None:
        return
    os.makedirs(data_dir, exist_ok=True)
    want = set(tables or TABLES)
    rng = np.random.default_rng(seed)
    datasets: dict[str, dict] = {}
    datasets["region"] = _gen_region()
    datasets["nation"] = _gen_nation()
    datasets["supplier"] = _gen_supplier(rng, counts["supplier"])
    datasets["customer"] = _gen_customer(rng, counts["customer"])
    datasets["part"] = _gen_part(rng, counts["part"])
    datasets["partsupp"] = _gen_partsupp(rng, counts["partsupp"],
                                         counts["part"],
                                         counts["supplier"])
    datasets["orders"] = _gen_orders(rng, counts["orders"],
                                     counts["customer"])
    datasets["lineitem"] = _gen_lineitem(rng, counts["lineitem"],
                                         datasets["orders"],
                                         counts["part"],
                                         counts["supplier"])
    for t in TABLES:
        if t in want:
            _write_parquet(os.path.join(data_dir, t), datasets[t])
    if tables is None:
        with open(stamp, "w") as f:
            f.write("ok\n")

"""Mortgage benchmark: the reference's Fannie-Mae ETL + aggregate jobs.

Reference: integration_tests .../tests/mortgage/MortgageSpark.scala —
ReadPerformanceCsv/ReadAcquisitionCsv (:34-120, pipe-delimited
headerless CSVs, quarter from the file name), NameMapping (:120),
CreatePerformanceDelinquency (:216-298, the 12-month delinquency
window expansion), CreateAcquisition/CleanAcquisitionPrime (:300-324),
and the three aggregate jobs SimpleAggregates /
AggregatesWithPercentiles / AggregatesWithJoin (:350-437).

BASELINE.json config 5 runs this ETL as the feature-engineering stage
of the mortgage->XGBoost pipeline; the queries here are the
spark-rapids-runnable SQL part of that pipeline.
"""
from __future__ import annotations

import glob
import os

from spark_rapids_tpu import types as T
from spark_rapids_tpu.bench.mortgage_gen import (SELLERS, acq_schema,
                                                 generate_mortgage,
                                                 perf_schema)
from spark_rapids_tpu.expr.aggregates import (Average, First, Max, Min,
                                              Percentile)
from spark_rapids_tpu.expr.conditional import Coalesce, If
from spark_rapids_tpu.expr.core import Literal, col, lit
from spark_rapids_tpu.expr.datetime_ops import Month, ParseDateFixed, Year
from spark_rapids_tpu.expr.hashing import Murmur3Hash
from spark_rapids_tpu.expr.math_ops import Floor, Round
from spark_rapids_tpu.expr.strings import Hex

__all__ = ["generate_mortgage", "MORTGAGE_QUERIES",
           "build_mortgage_query", "read_performance", "read_acquisition"]

# the reference's seller-name canonicalization (NameMapping) — a small
# broadcast-joined lookup; subsetted to the sellers the generator emits
NAME_MAPPING = [
    ("WELLS FARGO BANK, N.A.", "Wells Fargo"),
    ("JPMORGAN CHASE BANK, NATIONAL ASSOCIATION", "JP Morgan Chase"),
    ("BANK OF AMERICA, N.A.", "Bank of America"),
    ("CITIMORTGAGE, INC.", "Citi"),
    ("QUICKEN LOANS INC.", "Quicken Loans"),
    ("USAA FEDERAL SAVINGS BANK", "USAA"),
    ("FLAGSTAR BANK, FSB", "Flagstar Bank"),
    ("PNC BANK, N.A.", "PNC"),
    ("SUNTRUST MORTGAGE INC.", "Suntrust"),
    ("AMTRUST BANK", "AmTrust"),
    ("METLIFE BANK, NA", "Metlife"),
    ("GMAC MORTGAGE, LLC", "GMAC"),
]


def _quarter_of(path: str) -> str:
    # .../Performance_2003Q4.txt_0 -> 2003Q4 (GetQuarterFromCsvFileName)
    base = os.path.basename(path).split(".")[0]
    return base.split("_")[-1]


def _read_with_quarter(session, pattern: str, schema: T.Schema):
    """Per-file scans unioned with a literal quarter column — the
    engine-level equivalent of the reference's
    input_file_name()-derived quarter."""
    paths = sorted(glob.glob(pattern))
    if not paths:
        raise FileNotFoundError(
            f"no mortgage data files match {pattern!r} — run "
            "generate_mortgage(data_dir, sf) first")
    dfs = []
    for p in paths:
        df = session.read_csv(p, schema=schema, header=False,
                              delimiter="|")
        dfs.append(df.with_column("quarter", lit(_quarter_of(p))))
    out = dfs[0]
    for d in dfs[1:]:
        out = out.union(d)
    return out


def read_performance(session, data_dir: str):
    return _read_with_quarter(
        session, os.path.join(data_dir, "perf", "Performance_*"),
        perf_schema())


def read_acquisition(session, data_dir: str):
    return _read_with_quarter(
        session, os.path.join(data_dir, "acq", "Acquisition_*"),
        acq_schema())


def _null(dtype):
    return Literal(None, dtype)


def _when(cond, value, dtype):
    return If(cond, value, _null(dtype))


def _prepare_performance(df):
    """CreatePerformanceDelinquency.prepare: string dates -> DateType +
    month/year/day extracts (device ParseDateFixed)."""
    d = ParseDateFixed(col("monthly_reporting_period"), "MM/dd/yyyy")
    return df.with_column("monthly_reporting_period", d) \
        .with_column("monthly_reporting_period_month",
                     Month(col("monthly_reporting_period"))) \
        .with_column("monthly_reporting_period_year",
                     Year(col("monthly_reporting_period")))


def _performance_delinquency(session, df):
    """CreatePerformanceDelinquency.apply: the 12-month delinquency
    window expansion (MortgageSpark.scala:232-298)."""
    status = col("current_loan_delinquency_status")
    agg_df = df.select(
        col("quarter"), col("loan_id"), status,
        _when(status >= lit(1), col("monthly_reporting_period"),
              T.DateType()).alias("delinquency_30"),
        _when(status >= lit(3), col("monthly_reporting_period"),
              T.DateType()).alias("delinquency_90"),
        _when(status >= lit(6), col("monthly_reporting_period"),
              T.DateType()).alias("delinquency_180")) \
        .group_by("quarter", "loan_id") \
        .agg(Max(status).alias("delinquency_12"),
             Min(col("delinquency_30")).alias("delinquency_30"),
             Min(col("delinquency_90")).alias("delinquency_90"),
             Min(col("delinquency_180")).alias("delinquency_180")) \
        .select(col("quarter"), col("loan_id"),
                (col("delinquency_12") >= lit(1)).alias("ever_30"),
                (col("delinquency_12") >= lit(3)).alias("ever_90"),
                (col("delinquency_12") >= lit(6)).alias("ever_180"),
                col("delinquency_30"), col("delinquency_90"),
                col("delinquency_180"))

    joined = df.select(
        col("quarter"), col("loan_id"),
        col("monthly_reporting_period").alias("timestamp"),
        col("current_loan_delinquency_status").alias("delinquency_12"),
        col("current_actual_upb").alias("upb_12"),
        col("monthly_reporting_period_month").alias("timestamp_month"),
        col("monthly_reporting_period_year").alias("timestamp_year")) \
        .join(agg_df.select(col("loan_id").alias("a_loan_id"),
                            col("quarter").alias("a_quarter"),
                            col("ever_30"), col("ever_90"),
                            col("ever_180"), col("delinquency_30"),
                            col("delinquency_90"),
                            col("delinquency_180")),
              on=[("loan_id", "a_loan_id"), ("quarter", "a_quarter")],
              how="left") \
        .select(col("quarter"), col("loan_id"), col("timestamp"),
                col("delinquency_12"), col("upb_12"),
                col("timestamp_month"), col("timestamp_year"),
                col("ever_30"), col("ever_90"), col("ever_180"),
                col("delinquency_30"), col("delinquency_90"),
                col("delinquency_180"))

    # explode(0..11): cross join with a 12-row literal month frame (the
    # reference notes explode-of-a-literal beats a cross join on GPU;
    # here the cross join IS the engine's explode of a constant)
    months_df = session.from_pydict(
        {"month_y": list(range(12))},
        T.Schema([T.StructField("month_y", T.IntegerType())]))
    months = lit(12)
    base = (col("timestamp_year") * lit(12) + col("timestamp_month")
            - lit(24000))
    test_df = joined.join(months_df, how="cross") \
        .select(
            col("quarter"),
            Floor((base - col("month_y")).cast(T.DoubleType())
                  / lit(12.0)).alias("josh_mody_n"),
            col("ever_30"), col("ever_90"), col("ever_180"),
            col("delinquency_30"), col("delinquency_90"),
            col("delinquency_180"),
            col("loan_id"), col("month_y"), col("delinquency_12"),
            col("upb_12")) \
        .group_by("quarter", "loan_id", "josh_mody_n", "ever_30",
                  "ever_90", "ever_180", "delinquency_30",
                  "delinquency_90", "delinquency_180", "month_y") \
        .agg(Max(col("delinquency_12")).alias("delinquency_12"),
             Min(col("upb_12")).alias("upb_12"))
    mody_base = (lit(24000.0) + col("josh_mody_n") * months.cast(
        T.DoubleType()))
    tmp = (mody_base + col("month_y").cast(T.DoubleType())) % lit(12.0)
    test_df = test_df \
        .with_column("timestamp_year",
                     Floor((mody_base + (col("month_y") - lit(1))
                            .cast(T.DoubleType())) / lit(12.0))
                     .cast(T.IntegerType())) \
        .with_column("timestamp_month",
                     If(tmp == lit(0.0), Literal(12, T.IntegerType()),
                        tmp.cast(T.IntegerType()))) \
        .with_column("delinquency_12",
                     (col("delinquency_12") > lit(3)).cast(T.IntegerType())
                     + (col("upb_12") == lit(0.0)).cast(T.IntegerType()))
    test_df = test_df.select(
        col("quarter").alias("t_quarter"),
        col("loan_id").alias("t_loan_id"),
        col("timestamp_year").alias("t_year"),
        col("timestamp_month").alias("t_month"),
        col("ever_30"), col("ever_90"), col("ever_180"),
        col("delinquency_30"), col("delinquency_90"),
        col("delinquency_180"), col("delinquency_12"), col("upb_12"))

    return df.select(
        col("quarter"), col("loan_id"),
        col("monthly_reporting_period"), col("interest_rate"),
        col("current_actual_upb"), col("loan_age"),
        col("monthly_reporting_period_month").alias("timestamp_month"),
        col("monthly_reporting_period_year").alias("timestamp_year")) \
        .join(test_df, on=[("quarter", "t_quarter"),
                           ("loan_id", "t_loan_id"),
                           ("timestamp_year", "t_year"),
                           ("timestamp_month", "t_month")], how="left") \
        .select(col("quarter"), col("loan_id"),
                col("monthly_reporting_period"), col("interest_rate"),
                col("current_actual_upb"), col("loan_age"),
                col("ever_30"), col("ever_90"), col("ever_180"),
                col("delinquency_12"), col("upb_12"))


def _acquisition(session, df):
    """CreateAcquisition: canonicalize seller names through the
    NameMapping broadcast lookup + date parsing."""
    mapping = session.from_pydict(
        {"from_seller_name": [a for a, _ in NAME_MAPPING],
         "to_seller_name": [b for _, b in NAME_MAPPING]},
        T.Schema([T.StructField("from_seller_name", T.StringType()),
                  T.StructField("to_seller_name", T.StringType())]))
    return df.join(mapping, on=[("seller_name", "from_seller_name")],
                   how="left") \
        .with_column("old_name", col("seller_name")) \
        .with_column("seller_name", Coalesce(col("to_seller_name"),
                                             col("seller_name"))) \
        .with_column("orig_date",
                     ParseDateFixed(col("orig_date"), "MM/yyyy")) \
        .with_column("first_pay_date",
                     ParseDateFixed(col("first_pay_date"), "MM/yyyy"))


def run_etl(session, data_dir: str):
    """Run.csv / CleanAcquisitionPrime: the full feature ETL."""
    perf = _prepare_performance(read_performance(session, data_dir))
    acq = _acquisition(session, read_acquisition(session, data_dir))
    cleaned = _performance_delinquency(session, perf)
    acq = acq.select(
        col("loan_id").alias("acq_loan_id"),
        col("quarter").alias("acq_quarter"),
        col("seller_name"), col("orig_interest_rate"), col("orig_upb"),
        col("orig_loan_term"), col("orig_date"), col("first_pay_date"),
        col("orig_ltv"), col("dti"), col("borrower_credit_score"),
        col("zip"))
    return cleaned.join(acq, on=[("loan_id", "acq_loan_id"),
                                 ("quarter", "acq_quarter")],
                        how="inner") \
        .order_by(("loan_id", True), ("monthly_reporting_period", True)) \
        .limit(10000)


def simple_aggregates(session, data_dir: str):
    """SimpleAggregates (MortgageSpark.scala:350-366)."""
    dfp = read_performance(session, data_dir)
    dfa = read_acquisition(session, data_dir)
    max_rate = dfp.with_column(
        "monthval",
        Month(ParseDateFixed(col("monthly_reporting_period"),
                             "MM/dd/yyyy"))) \
        .group_by("monthval", "loan_id") \
        .agg(Max(col("interest_rate")).alias("max_monthly_rate"))
    joined = max_rate.select(
        col("loan_id").alias("p_loan_id"), col("monthval"),
        col("max_monthly_rate")) \
        .join(dfa, on=[("p_loan_id", "loan_id")])
    return joined.group_by("zip", "monthval") \
        .agg(Min(col("max_monthly_rate")).alias("min_max_monthly_rate")) \
        .order_by(("zip", True), ("monthval", True))


def aggregates_with_percentiles(session, data_dir: str):
    """AggregatesWithPercentiles (:368-393): interest-rate stats +
    exact percentiles per anonymized loan (hex(hash(loan_id)))."""
    dfp = read_performance(session, data_dir)
    anon = dfp.with_column("loan_id_hash",
                           Hex(Murmur3Hash(col("loan_id")))) \
        .select(col("loan_id_hash"), col("interest_rate"))
    r = col("interest_rate")
    return anon.group_by("loan_id_hash").agg(
        Round(Min(r), 4).alias("interest_rate_min"),
        Round(Max(r), 4).alias("interest_rate_max"),
        Round(Average(r), 4).alias("interest_rate_avg"),
        Round(Percentile(r, 0.5), 4).alias("interest_rate_50p"),
        Round(Percentile(r, 0.75), 4).alias("interest_rate_75p"),
        Round(Percentile(r, 0.90), 4).alias("interest_rate_90p"),
        Round(Percentile(r, 0.99), 4).alias("interest_rate_99p")) \
        .order_by(("loan_id_hash", True)).limit(1000)


def aggregates_with_join(session, data_dir: str):
    """AggregatesWithJoin (:395-421)."""
    dfp = read_performance(session, data_dir)
    dfa = read_acquisition(session, data_dir)
    a = dfp.with_column("loan_id_hash",
                        Hex(Murmur3Hash(col("loan_id")))) \
        .group_by("loan_id_hash") \
        .agg(Min(col("interest_rate")).alias("min_int_rate"))
    b = dfa.with_column("loan_id_hash",
                        Hex(Murmur3Hash(col("loan_id")))) \
        .group_by("loan_id_hash") \
        .agg(First(col("orig_interest_rate"), ignore_nulls=True)
             .alias("first_int_rate"),
             Coalesce(Max(col("dti")), lit(0.0)).alias("max_dti")) \
        .select(col("loan_id_hash").alias("b_hash"),
                col("first_int_rate"), col("max_dti"))
    return a.join(b, on=[("loan_id_hash", "b_hash")], how="left") \
        .order_by(("loan_id_hash", True)).limit(1000)


MORTGAGE_QUERIES = {
    "etl": run_etl,
    "simple_agg": simple_aggregates,
    "percentiles": aggregates_with_percentiles,
    "agg_join": aggregates_with_join,
}


def build_mortgage_query(name: str, session, data_dir: str):
    return MORTGAGE_QUERIES[name](session, data_dir)


def train_pipeline(session, data_dir: str, steps: int = 200) -> dict:
    """Mortgage ETL -> columnar handoff -> jitted training loop
    (BASELINE config 5; reference docs/ml-integration.md:8-11 +
    ColumnarRdd.scala:42-49 hand the plugin's device table straight to
    XGBoost).  Here the engine's device batches flow through
    ``interop.to_jax`` with no host round trip and train a jitted
    logistic-regression delinquency model on the chip.

    Returns a verified record: the loss must strictly decrease and the
    trained model must beat the majority-class baseline on accuracy."""
    import time

    import jax
    import jax.numpy as jnp

    from spark_rapids_tpu import interop

    t0 = time.perf_counter()
    perf = read_performance(session, data_dir)
    acq = read_acquisition(session, data_dir)
    # per-loan label: ever delinquent; features from acquisition
    labels = perf.group_by("loan_id").agg(
        Max(col("current_loan_delinquency_status")).alias("max_status"))
    labels = labels.select(
        col("loan_id").alias("l_loan_id"),
        (col("max_status") >= lit(1)).alias("delinquent"))
    feats = acq.select(
        col("loan_id"), col("orig_interest_rate"), col("orig_upb"),
        col("orig_loan_term"), col("orig_ltv"), col("dti"),
        col("borrower_credit_score")) \
        .join(labels, on=[("loan_id", "l_loan_id")], how="inner")
    cols = interop.to_jax(feats)
    etl_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    names = ["orig_interest_rate", "orig_upb", "orig_loan_term",
             "orig_ltv", "dti", "borrower_credit_score"]
    feat_arrays = []
    for nm in names:
        v, valid = cols[nm]
        x = jnp.where(valid, v.astype(jnp.float64), jnp.nan)
        mean = jnp.nanmean(x)
        std = jnp.nanstd(x) + 1e-9
        feat_arrays.append(jnp.where(jnp.isnan(x), 0.0, (x - mean) / std))
    X = jnp.stack(feat_arrays, axis=1).astype(jnp.float32)
    yv, yvalid = cols["delinquent"]
    y = (yv & yvalid).astype(jnp.float32)
    n, k = X.shape

    def loss_fn(w, b):
        z = X @ w + b
        # numerically-stable BCE with logits
        return jnp.mean(jnp.maximum(z, 0) - z * y +
                        jnp.log1p(jnp.exp(-jnp.abs(z))))

    grad_fn = jax.jit(jax.value_and_grad(loss_fn, argnums=(0, 1)))

    @jax.jit
    def step(w, b, lr):
        l, (gw, gb) = jax.value_and_grad(loss_fn, argnums=(0, 1))(w, b)
        return w - lr * gw, b - lr * gb, l

    w = jnp.zeros(k, jnp.float32)
    b = jnp.asarray(0.0, jnp.float32)
    loss0 = float(grad_fn(w, b)[0])
    losses = []
    for i in range(steps):
        w, b, l = step(w, b, jnp.float32(0.5))
        if i % 50 == 0 or i == steps - 1:
            losses.append(float(l))
    pred = (X @ w + b) > 0
    acc = float(jnp.mean(pred == (y > 0.5)))
    base = float(jnp.maximum(jnp.mean(y), 1 - jnp.mean(y)))
    train_s = time.perf_counter() - t0

    rec = {
        "pipeline": "mortgage_etl_to_train",
        "rows": int(n), "features": int(k), "steps": steps,
        "loss0": round(loss0, 6), "loss_final": round(losses[-1], 6),
        "accuracy": round(acc, 4),
        "majority_baseline": round(base, 4),
        "etl_s": round(etl_s, 3), "train_s": round(train_s, 3),
        "backend": jax.default_backend(),
        "ok": bool(losses[-1] < loss0 and acc >= base),
    }
    return rec

"""TPC-DS benchmark runner: per-query timing + JSON reports.

Reference: BenchmarkRunner.scala (collect/writeParquet modes, iteration
timing) + BenchUtils.scala (JSON report per run) + CompareResults.scala
(CPU-vs-accelerator output verification).  Here verification is the
host-oracle backend of the same plan (the round-trip the test suite
uses), selected with ``--verify``.

CLI:
    python -m spark_rapids_tpu.bench.runner --sf 0.1 --queries q3,q6 \
        --data-dir /tmp/tpcds --iterations 2 --verify
"""
from __future__ import annotations

import argparse
import json
import os
import time

__all__ = ["run_benchmark"]


def _collect_rows(df, backend: str, plan=None, metrics_out: dict | None = None,
                  obs_out: dict | None = None):
    from spark_rapids_tpu.exec.core import (ExecCtx, collect_device,
                                            collect_host, device_to_host,
                                            _rows_from_host)
    if plan is None:
        ov, meta = df._overridden(quiet=True)
        plan = meta.exec_node

    def make_ctx() -> ExecCtx:
        ctx = ExecCtx(backend=backend, conf=df._s.conf)
        if backend == "device":
            # session-owned cluster pool (cluster/driver.py); the host
            # oracle stays single-process on purpose
            cluster = df._s._cluster()
            if cluster is not None:
                ctx.cache["cluster"] = cluster
        return ctx

    if metrics_out is None:
        if backend == "host":
            return collect_host(plan, df._s.conf)
        return collect_device(plan, df._s.conf, ctx=make_ctx())
    # metrics-capturing run (reference BenchUtils JSON reports include
    # per-exec SQL metrics, docs/benchmarks.md:149-163)
    with make_ctx() as ctx:
        from spark_rapids_tpu.obs.registry import get_registry
        before = get_registry().snapshot() if obs_out is not None else None
        out = []
        for b in plan.execute(ctx):
            hb = device_to_host(b) if backend == "device" else b
            out.extend(_rows_from_host(hb))
        for key, m in ctx.metrics.items():
            name = key.split("@")[0]
            agg = metrics_out.setdefault(name, {})
            for k, v in m.values.items():
                agg[k] = round(agg.get(k, 0.0) + v, 4)
        cat = ctx.cache.get("catalog")
        if cat is not None:
            # memory-plane counters (spills, oom_retries/oom_splits,
            # device_bytes_peak) live on the BufferCatalog, not on any
            # one exec — report them alongside the per-exec metrics
            metrics_out["BufferCatalog"] = dict(cat.metrics)
        if obs_out is not None:
            # full observability record: registry counter MOVEMENT over
            # this run (the process registry is cumulative), ids tying
            # the report to any exported trace, and the analyzed plan
            from spark_rapids_tpu.plan.overrides import explain_analyze
            obs_out["query_id"] = ctx.query_id
            obs_out["trace_id"] = ctx.trace_id
            obs_out["registry"] = get_registry().delta(before)
            cluster = ctx.cache.get("cluster")
            if cluster is not None:
                # per-worker registry movement (heartbeat snapshots
                # diffed against each worker's first) — the cluster
                # bench rungs report these alongside the driver's delta
                obs_out["cluster_workers"] = \
                    cluster.worker_registry_deltas()
            obs_out["plan_analyzed"] = explain_analyze(
                plan, ctx).splitlines()
            prof = ctx.cache.get("profiler")
            if prof is not None:
                # cost-attribution artifact (obs/profile.py): the same
                # schema-checked document the profile dir export writes
                obs_out["profile"] = prof.artifact()
        return out


def _plan_of(df):
    ov, meta = df._overridden(quiet=True)
    return meta.exec_node


def _norm(rows, digits=6):
    """Order-insensitive row normalization with float tolerance: device
    and oracle may sum doubles in different orders (streaming joins /
    concurrent partials), and on-chip f64 is a float32 pair (~48-bit
    mantissa, docs/compatibility.md), so floats compare at ``digits``
    significant digits (reference asserts.py approximate_float)."""
    def cell(x):
        if isinstance(x, float):
            return (x is None, f"{x:.{digits}g}")
        return (x is None, str(x))
    return sorted(tuple(cell(x) for x in r) for r in rows)


def _rows_match(got, want, strict: bool | None = None) -> bool:
    """Exact significant-digit match, falling back to a PAIRED
    relative comparison: fixed-digit formatting is boundary-brittle —
    1-ulp summation-order noise on a value sitting exactly at a digit
    boundary (q47's 103.1275, q20's HALF_UP money ratios) flips the
    formatted string while the values agree to 1e-10.  The fallback
    buckets rows by their NON-float cells and greedily pairs each got
    row with an unused want row whose floats all agree within a
    relative tolerance (reference approximate_float semantics,
    asserts.py) — no float takes part in any ordering, so
    boundary/NaN/mixed-type sort brittleness cannot mispair rows.

    The tolerance is keyed on the device backend: on true-f64 platforms
    (XLA:CPU) the only legitimate noise is summation order, so floats
    compare at 12 digits / rel 1e-9; the loose 6-digit / rel 1e-5
    tier applies only when the f32-pair f64 emulation is in play (TPU
    backend, ~48-bit mantissa)."""
    import math
    from collections import defaultdict
    if strict is None:
        import jax
        strict = jax.default_backend() not in ("tpu", "axon")
    digits, rel, abst = (12, 1e-9, 1e-11) if strict else (6, 1e-5, 1e-7)
    if _norm(got, digits) == _norm(want, digits):
        return True
    if len(got) != len(want):
        return False

    def fixed(r):
        return tuple((i, x is None, str(x)) for i, x in enumerate(r)
                     if not isinstance(x, float))

    def floats(r):
        return [(i, x) for i, x in enumerate(r) if isinstance(x, float)]

    def close(a, b):
        fa, fb = floats(a), floats(b)
        if [i for i, _ in fa] != [i for i, _ in fb]:
            return False
        for (_, x), (_, y) in zip(fa, fb):
            if math.isnan(x) and math.isnan(y):
                continue
            if math.isnan(x) or math.isnan(y):
                return False
            if not math.isclose(x, y, rel_tol=rel, abs_tol=abst):
                return False
        return True

    buckets = defaultdict(list)
    for r in want:
        buckets[fixed(r)].append(r)
    for r in got:
        cands = buckets.get(fixed(r))
        if not cands:
            return False
        for i, w in enumerate(cands):
            if close(r, w):
                cands.pop(i)
                break
        else:
            return False
    return True


def run_benchmark(data_dir: str, sf: float, queries, iterations: int = 1,
                  verify: bool = False, session_conf: dict | None = None,
                  generate: bool = True, suite: str = "tpcds") -> list[dict]:
    """Run each query ``iterations`` times on the device engine; report
    per-query wall times (median), row counts, and optional host-oracle
    verification. Returns a list of per-query report dicts.
    ``suite`` selects the workload: "tpcds" (default), "tpch",
    "tpcxbb", or "mortgage" (reference BenchmarkRunner supports the
    same suites, BenchmarkRunner.scala)."""
    from spark_rapids_tpu.session import TpuSession
    if suite == "tpch":
        from spark_rapids_tpu.bench.tpch_gen import generate_tpch as gen
        from spark_rapids_tpu.bench.tpch_queries import (
            build_tpch_query as build_query)
    elif suite == "mortgage":
        from spark_rapids_tpu.bench.mortgage import (
            build_mortgage_query as build_query, generate_mortgage as gen)
    elif suite == "tpcxbb":
        from spark_rapids_tpu.bench.tpcxbb_gen import (
            generate_tpcxbb as gen)
        from spark_rapids_tpu.bench.tpcxbb_queries import (
            build_tpcxbb_query as build_query)
    else:
        from spark_rapids_tpu.bench.tpcds_gen import generate_tpcds as gen
        from spark_rapids_tpu.bench.tpcds_queries import build_query

    if generate:
        t0 = time.perf_counter()
        gen(data_dir, sf=sf)
        gen_s = time.perf_counter() - t0
    else:
        gen_s = 0.0

    reports = []
    for name in queries:
        session = TpuSession(dict(session_conf or {}))
        rec = {"query": name, "sf": sf, "gen_s": round(gen_s, 3)}
        try:
            times = []
            rows = None
            # ONE plan reused across iterations: the reference's kernels
            # are precompiled library entry points, so the steady-state
            # analog here is traced-and-compiled programs, not re-tracing
            # a fresh expression tree per run
            df = build_query(name, session, data_dir)
            plan = _plan_of(df)
            metrics: dict = {}
            obs: dict = {}
            for it in range(max(1, iterations)):
                t0 = time.perf_counter()
                # last iteration captures per-operator metrics + plan
                # (reference BenchmarkRunner JSON reports)
                last = it == iterations - 1
                rows = _collect_rows(
                    df, "device", plan,
                    metrics_out=metrics if last else None,
                    obs_out=obs if last else None)
                times.append(time.perf_counter() - t0)
            times.sort()
            rec["device_s"] = round(times[len(times) // 2], 4)
            rec["device_s_all"] = [round(t, 4) for t in times]
            rec["rows"] = len(rows)
            rec["plan"] = plan.tree_string().strip().splitlines()
            rec["metrics"] = metrics
            rec["observability"] = obs
            if verify:
                t0 = time.perf_counter()
                oracle = _collect_rows(df, "host", plan)
                rec["oracle_s"] = round(time.perf_counter() - t0, 4)
                rec["speedup"] = round(rec["oracle_s"] / rec["device_s"], 3)
                rec["ok"] = _rows_match(rows, oracle)
            else:
                rec["ok"] = True
        except Exception as e:  # noqa: BLE001 - per-query isolation
            from spark_rapids_tpu.exec.lifecycle import QueryLifecycleError
            if isinstance(e, QueryLifecycleError):
                # cancellation / deadline / shutdown apply to the whole
                # run — recording them as a per-query failure and moving
                # on would keep benchmarking a killed session.  Other
                # terminal errors (e.g. unrecoverable map-output loss)
                # kill only THIS query and are part of the report.
                raise
            rec["error"] = f"{type(e).__name__}: {e}"
            rec["ok"] = False
        finally:
            # release per-query session resources NOW, not at interpreter
            # exit — in cluster mode each session owns a pool of worker
            # subprocesses that would otherwise pile up across queries
            session.shutdown(drain=False)
        reports.append(rec)
    return reports


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--data-dir", default=os.environ.get(
        "TPCDS_DATA_DIR", "/tmp/tpcds_data"))
    ap.add_argument("--sf", type=float, default=0.01)
    ap.add_argument("--queries", default="q3,q6,q42,q52,q55")
    ap.add_argument("--iterations", type=int, default=1)
    ap.add_argument("--verify", action="store_true")
    ap.add_argument("--suite", default="tpcds", choices=("tpcds", "tpch", "mortgage", "tpcxbb"))
    ap.add_argument("--train", action="store_true",
                    help="mortgage suite: run the ETL -> to_jax -> "
                         "jitted training pipeline (BASELINE config 5)")
    ap.add_argument("--report", default=None,
                    help="write the JSON report to this path")
    args = ap.parse_args()

    data_dir = os.path.join(args.data_dir, f"sf{args.sf:g}")
    if args.train:
        assert args.suite == "mortgage", "--train is a mortgage mode"
        from spark_rapids_tpu.bench.mortgage import (generate_mortgage,
                                                     train_pipeline)
        from spark_rapids_tpu.session import TpuSession
        generate_mortgage(data_dir, sf=args.sf)
        rec = train_pipeline(TpuSession({}), data_dir)
        out = json.dumps(rec, indent=2)
        print(out)
        if args.report:
            with open(args.report, "w") as f:
                f.write(out + "\n")
        return
    reports = run_benchmark(data_dir, args.sf,
                            [q.strip() for q in args.queries.split(",")],
                            iterations=args.iterations, verify=args.verify,
                            suite=args.suite)
    out = json.dumps(reports, indent=2)
    print(out)
    if args.report:
        with open(args.report, "w") as f:
            f.write(out + "\n")


if __name__ == "__main__":
    main()

"""SF-scalable TPC-DS-shaped data generator (column-pruned, parquet).

Generates the tables the 20-query slice uses — store_sales, catalog_sales,
web_sales, date_dim, time_dim, item, customer, customer_address, store,
customer_demographics, household_demographics, promotion — with
dsdgen-like row counts, key ranges, null fractions, and surrogate-key
conventions (d_date_sk epoch 2415022 = 1900-01-01, store_sales ~2.88M
rows/SF).  Columns are pruned to those the queries touch; distributions
are synthetic (deterministic numpy, seeded), NOT dsdgen bit-exact — this
measures engine speed, not dsdgen conformance.  Reference harness:
TpcdsLikeSpark.scala (explicit schemas + csv-to-parquet conversion),
docs/benchmarks.md:104-147.
"""
from __future__ import annotations

import os
import zlib
from typing import Sequence

import numpy as np

__all__ = ["generate_tpcds", "table_row_counts", "TABLES"]

TABLES = ("date_dim", "time_dim", "item", "customer", "customer_address",
          "store", "customer_demographics", "household_demographics",
          "promotion", "store_sales", "catalog_sales", "web_sales")

#: bump when generated schemas change; tables regenerate on mismatch
_SCHEMA_VERSION = "v4"

_DATE_SK_EPOCH = 2415022            # dsdgen: d_date_sk of 1900-01-01
_DATE_DIM_DAYS = 73049              # 1900-01-01 .. 2099-12-31
_SALES_DATE_LO = 35794              # days(1998-01-01 - 1900-01-01)
_SALES_DATE_HI = 37985              # days(2003-12-31 - 1900-01-01)
_UNIX_EPOCH_OFF = 25567             # days(1970-01-01 - 1900-01-01)

_CATEGORIES = ["Books", "Children", "Electronics", "Home", "Jewelry",
               "Men", "Music", "Shoes", "Sports", "Women"]
_CLASSES = ["accent", "bedding", "birdal", "blinds/shades", "classical",
            "computers", "curtains/drapes", "decor", "dresses", "earings",
            "fiction", "fragrances", "furniture", "glassware", "history",
            "infants", "jewelry boxes", "kids", "maternity", "mattresses",
            "mens", "musical", "mystery", "pants", "pendants", "pop",
            "reference", "rock", "romance", "rugs", "scanners", "shirts",
            "swimwear", "tables", "wallpaper", "womens"]
_STATES = ["AL", "AK", "AZ", "AR", "CA", "CO", "CT", "DE", "FL", "GA",
           "HI", "ID", "IL", "IN", "IA", "KS", "KY", "LA", "ME", "MD",
           "MA", "MI", "MN", "MS", "MO", "MT", "NE", "NV", "NH", "NJ",
           "NM", "NY", "NC", "ND", "OH", "OK", "OR", "PA", "RI", "SC",
           "SD", "TN", "TX", "UT", "VT", "VA", "WA", "WV", "WI", "WY"]
_FIRST = ["James", "Mary", "John", "Patricia", "Robert", "Jennifer",
          "Michael", "Linda", "William", "Elizabeth", "David", "Barbara"]
_LAST = ["Smith", "Johnson", "Williams", "Brown", "Jones", "Garcia",
         "Miller", "Davis", "Rodriguez", "Martinez", "Hernandez", "Lopez"]


def table_row_counts(sf: float) -> dict[str, int]:
    """dsdgen-like scaling: fact tables linear in SF; dimensions
    sublinear (item SF1=18k, customer SF1=100k)."""
    sf = max(sf, 0.001)
    n_cust = max(200, int(100_000 * sf ** 0.7))
    return {
        "date_dim": _DATE_DIM_DAYS,
        "time_dim": 86_400,
        "item": max(100, int(18_000 * sf ** 0.5)),
        "customer": n_cust,
        "customer_address": max(100, n_cust // 2),
        "store": max(4, int(12 * sf ** 0.5)),
        "customer_demographics": max(500, int(50_000 * sf ** 0.5)),
        "household_demographics": 7_200,
        "promotion": max(30, int(300 * sf ** 0.5)),
        "store_sales": max(1000, int(2_880_000 * sf)),
        "catalog_sales": max(500, int(1_440_000 * sf)),
        "web_sales": max(250, int(720_000 * sf)),
    }


def _gen_date_dim(counts) -> dict[str, np.ndarray]:
    days = np.arange(_DATE_DIM_DAYS, dtype=np.int64)
    dates = np.datetime64("1900-01-01") + days
    y = dates.astype("datetime64[Y]").astype(int) + 1970
    m = dates.astype("datetime64[M]").astype(int) % 12 + 1
    dom = (dates - dates.astype("datetime64[M]")).astype(int) + 1
    dow = (days + 1) % 7            # 1900-01-01 was a Monday; 0 = Sunday
    return {
        "d_date_sk": (days + _DATE_SK_EPOCH).astype(np.int32),
        "d_date": (days - _UNIX_EPOCH_OFF).astype(np.int32),  # DateType
        "d_year": y.astype(np.int32),
        "d_moy": m.astype(np.int32),
        "d_dom": dom.astype(np.int32),
        "d_dow": dow.astype(np.int32),
        "d_month_seq": ((y - 1900) * 12 + (m - 1)).astype(np.int32),
        "d_qoy": ((m - 1) // 3 + 1).astype(np.int32),
    }


def _gen_time_dim(_counts) -> dict[str, np.ndarray]:
    secs = np.arange(86_400, dtype=np.int64)
    return {
        "t_time_sk": secs.astype(np.int32),
        "t_hour": (secs // 3600).astype(np.int32),
        "t_minute": ((secs // 60) % 60).astype(np.int32),
    }


def _with_nulls(rng, arr: np.ndarray, frac: float) -> np.ndarray:
    """Object array with ~frac nulls (None)."""
    out = arr.astype(object)
    if frac > 0:
        out[rng.random(len(arr)) < frac] = None
    return out


def _gen_item(rng, n: int) -> dict[str, np.ndarray]:
    brand_id = rng.integers(1001001, 1010016, n).astype(np.int32)
    cat_idx = rng.integers(0, len(_CATEGORIES), n)
    cls_idx = rng.integers(0, len(_CLASSES), n)
    manu = rng.integers(1, 1001, n).astype(np.int32)
    return {
        "i_item_sk": np.arange(1, n + 1, dtype=np.int32),
        "i_item_id": np.array([f"AAAAAAAA{k:08d}" for k in range(1, n + 1)],
                              dtype=object),
        "i_item_desc": np.array(
            [f"desc {k} {_CLASSES[c]}" for k, c in enumerate(cls_idx)],
            dtype=object),
        "i_brand_id": brand_id,
        "i_brand": np.array([f"Brand#{b % 100}" for b in brand_id],
                            dtype=object),
        "i_class_id": (cls_idx + 1).astype(np.int32),
        "i_class": np.array([_CLASSES[i] for i in cls_idx], dtype=object),
        "i_category_id": (cat_idx + 1).astype(np.int32),
        "i_category": _with_nulls(
            rng, np.array([_CATEGORIES[i] for i in cat_idx], dtype=object),
            0.005),
        "i_current_price": _with_nulls(
            rng, np.round(rng.uniform(0.09, 99.99, n), 2), 0.01),
        "i_manufact_id": manu,
        "i_manufact": np.array([f"manufact#{v}" for v in manu], dtype=object),
        "i_manager_id": rng.integers(1, 101, n).astype(np.int32),
    }


def _gen_customer(rng, n: int, n_addr: int, n_cdemo: int,
                  n_hdemo: int) -> dict[str, np.ndarray]:
    return {
        "c_customer_sk": np.arange(1, n + 1, dtype=np.int32),
        "c_customer_id": np.array(
            [f"AAAAAAAA{k:08d}" for k in range(1, n + 1)], dtype=object),
        "c_current_addr_sk": _with_nulls(
            rng, rng.integers(1, n_addr + 1, n).astype(np.int32), 0.01),
        "c_current_cdemo_sk": _with_nulls(
            rng, rng.integers(1, n_cdemo + 1, n).astype(np.int32), 0.01),
        "c_current_hdemo_sk": _with_nulls(
            rng, rng.integers(1, n_hdemo + 1, n).astype(np.int32), 0.01),
        "c_first_name": _with_nulls(
            rng, np.array([_FIRST[i] for i in
                           rng.integers(0, len(_FIRST), n)], dtype=object),
            0.01),
        "c_last_name": _with_nulls(
            rng, np.array([_LAST[i] for i in
                           rng.integers(0, len(_LAST), n)], dtype=object),
            0.01),
    }


def _gen_customer_address(rng, n: int) -> dict[str, np.ndarray]:
    return {
        "ca_address_sk": np.arange(1, n + 1, dtype=np.int32),
        "ca_state": _with_nulls(
            rng, np.array([_STATES[i] for i in
                           rng.integers(0, len(_STATES), n)], dtype=object),
            0.01),
        "ca_city": np.array([f"City{v:03d}" for v in
                             rng.integers(0, 400, n)], dtype=object),
        "ca_county": np.array([f"County{v:03d}" for v in
                               rng.integers(0, 200, n)], dtype=object),
        "ca_zip": np.array([f"{v:05d}" for v in
                            rng.integers(10000, 99999, n)], dtype=object),
        "ca_gmt_offset": rng.choice([-10.0, -9.0, -8.0, -7.0, -6.0, -5.0],
                                    n),
    }


def _gen_store(rng, n: int) -> dict[str, np.ndarray]:
    return {
        "s_store_sk": np.arange(1, n + 1, dtype=np.int32),
        "s_store_id": np.array([f"AAAAAAAA{k:08d}" for k in range(1, n + 1)],
                               dtype=object),
        "s_store_name": np.array(
            [["ought", "able", "pri", "ese", "anti", "cally", "ation",
              "eing"][k % 8] for k in range(n)], dtype=object),
        "s_state": np.array([_STATES[i] for i in
                             rng.integers(0, 10, n)], dtype=object),
        "s_county": np.array([f"County{v:03d}" for v in
                              rng.integers(0, 30, n)], dtype=object),
        "s_city": np.array([f"City{v:03d}" for v in
                            rng.integers(0, 40, n)], dtype=object),
        "s_company_id": rng.integers(1, 7, n).astype(np.int32),
        "s_company_name": np.array(["Unknown"] * n, dtype=object),
        "s_gmt_offset": np.array([(-8.0, -7.0, -6.0, -5.0)[k % 4]
                                  for k in range(n)]),
    }


def _gen_customer_demographics(rng, n: int) -> dict[str, np.ndarray]:
    eds = ["Primary", "Secondary", "College", "2 yr Degree", "4 yr Degree",
           "Advanced Degree", "Unknown"]
    return {
        "cd_demo_sk": np.arange(1, n + 1, dtype=np.int32),
        "cd_gender": np.array([("M", "F")[v] for v in
                               rng.integers(0, 2, n)], dtype=object),
        "cd_marital_status": np.array(
            [("M", "S", "D", "W", "U")[v] for v in rng.integers(0, 5, n)],
            dtype=object),
        "cd_education_status": np.array(
            [eds[v] for v in rng.integers(0, len(eds), n)], dtype=object),
        "cd_purchase_estimate": (rng.integers(1, 21, n) * 500).astype(
            np.int32),
        "cd_credit_rating": np.array(
            [("Low Risk", "Good", "High Risk", "Unknown")[v]
             for v in rng.integers(0, 4, n)], dtype=object),
    }


def _gen_household_demographics(rng, n: int) -> dict[str, np.ndarray]:
    return {
        "hd_demo_sk": np.arange(1, n + 1, dtype=np.int32),
        "hd_dep_count": rng.integers(0, 10, n).astype(np.int32),
        "hd_vehicle_count": rng.integers(-1, 5, n).astype(np.int32),
        "hd_buy_potential": np.array(
            [(">10000", "5001-10000", "1001-5000", "501-1000", "0-500",
              "Unknown")[v] for v in rng.integers(0, 6, n)], dtype=object),
    }


def _gen_promotion(rng, n: int) -> dict[str, np.ndarray]:
    yn = lambda frac: np.array(  # noqa: E731
        [("Y" if v else "N") for v in rng.random(n) < frac], dtype=object)
    return {
        "p_promo_sk": np.arange(1, n + 1, dtype=np.int32),
        "p_channel_email": yn(0.1),
        "p_channel_event": yn(0.15),
        "p_channel_dmail": yn(0.1),
        "p_channel_tv": yn(0.1),
    }


def _sales_common(rng, n, counts, prefix):
    qty = rng.integers(1, 101, n).astype(np.int32)
    price = np.round(np.exp(rng.normal(2.5, 1.0, n)).clip(0.01, 300.0), 2)
    wholesale = np.round(price * rng.uniform(0.3, 0.9, n), 2)
    ext = np.round(price * qty, 2)
    return qty, price, wholesale, ext


def _gen_store_sales(rng, n: int, counts) -> dict[str, np.ndarray]:
    qty, price, wholesale, ext = _sales_common(rng, n, counts, "ss")
    return {
        "ss_sold_date_sk": _with_nulls(
            rng, (rng.integers(_SALES_DATE_LO, _SALES_DATE_HI + 1, n)
                  + _DATE_SK_EPOCH).astype(np.int32), 0.02),
        "ss_sold_time_sk": _with_nulls(
            rng, rng.integers(0, 86_400, n).astype(np.int32), 0.02),
        "ss_item_sk": rng.integers(1, counts["item"] + 1, n).astype(np.int32),
        "ss_customer_sk": _with_nulls(
            rng, rng.integers(1, counts["customer"] + 1, n).astype(np.int32),
            0.04),
        "ss_cdemo_sk": _with_nulls(
            rng, rng.integers(1, counts["customer_demographics"] + 1,
                              n).astype(np.int32), 0.04),
        "ss_hdemo_sk": _with_nulls(
            rng, rng.integers(1, counts["household_demographics"] + 1,
                              n).astype(np.int32), 0.04),
        "ss_store_sk": _with_nulls(
            rng, rng.integers(1, counts["store"] + 1, n).astype(np.int32),
            0.02),
        "ss_promo_sk": _with_nulls(
            rng, rng.integers(1, counts["promotion"] + 1, n).astype(np.int32),
            0.02),
        "ss_ticket_number": rng.integers(1, max(n // 3, 2),
                                         n).astype(np.int64),
        "ss_quantity": qty,
        "ss_list_price": np.round(price * rng.uniform(1.0, 1.5, n), 2),
        "ss_sales_price": price,
        "ss_ext_sales_price": ext,
        "ss_wholesale_cost": wholesale,
        "ss_ext_wholesale_cost": np.round(wholesale * qty, 2),
        "ss_coupon_amt": np.round(
            ext * rng.choice([0.0, 0.0, 0.0, 0.1, 0.3], n), 2),
        "ss_net_profit": np.round(ext - wholesale * qty, 2),
    }


def _gen_catalog_sales(rng, n: int, counts) -> dict[str, np.ndarray]:
    qty, price, wholesale, ext = _sales_common(rng, n, counts, "cs")
    return {
        "cs_sold_date_sk": _with_nulls(
            rng, (rng.integers(_SALES_DATE_LO, _SALES_DATE_HI + 1, n)
                  + _DATE_SK_EPOCH).astype(np.int32), 0.02),
        "cs_item_sk": rng.integers(1, counts["item"] + 1, n).astype(np.int32),
        "cs_bill_customer_sk": _with_nulls(
            rng, rng.integers(1, counts["customer"] + 1, n).astype(np.int32),
            0.03),
        "cs_bill_cdemo_sk": _with_nulls(
            rng, rng.integers(1, counts["customer_demographics"] + 1,
                              n).astype(np.int32), 0.03),
        "cs_promo_sk": _with_nulls(
            rng, rng.integers(1, counts["promotion"] + 1, n).astype(np.int32),
            0.02),
        "cs_quantity": qty,
        "cs_list_price": np.round(price * rng.uniform(1.0, 1.5, n), 2),
        "cs_sales_price": price,
        "cs_ext_sales_price": ext,
        "cs_coupon_amt": np.round(
            ext * rng.choice([0.0, 0.0, 0.0, 0.1, 0.3], n), 2),
    }


def _gen_web_sales(rng, n: int, counts) -> dict[str, np.ndarray]:
    qty, price, wholesale, ext = _sales_common(rng, n, counts, "ws")
    return {
        "ws_sold_date_sk": _with_nulls(
            rng, (rng.integers(_SALES_DATE_LO, _SALES_DATE_HI + 1, n)
                  + _DATE_SK_EPOCH).astype(np.int32), 0.02),
        "ws_item_sk": rng.integers(1, counts["item"] + 1, n).astype(np.int32),
        "ws_bill_customer_sk": _with_nulls(
            rng, rng.integers(1, counts["customer"] + 1, n).astype(np.int32),
            0.03),
        "ws_quantity": qty,
        "ws_list_price": np.round(price * rng.uniform(1.0, 1.5, n), 2),
        "ws_sales_price": price,
        "ws_ext_sales_price": ext,
    }


_GENERATORS = {
    "date_dim": lambda rng, counts: _gen_date_dim(counts),
    "time_dim": lambda rng, counts: _gen_time_dim(counts),
    "item": lambda rng, counts: _gen_item(rng, counts["item"]),
    "customer": lambda rng, counts: _gen_customer(
        rng, counts["customer"], counts["customer_address"],
        counts["customer_demographics"],
        counts["household_demographics"]),
    "customer_address": lambda rng, counts: _gen_customer_address(
        rng, counts["customer_address"]),
    "store": lambda rng, counts: _gen_store(rng, counts["store"]),
    "customer_demographics": lambda rng, counts: _gen_customer_demographics(
        rng, counts["customer_demographics"]),
    "household_demographics": lambda rng, counts:
        _gen_household_demographics(rng, counts["household_demographics"]),
    "promotion": lambda rng, counts: _gen_promotion(rng, counts["promotion"]),
    "store_sales": lambda rng, counts: _gen_store_sales(
        rng, counts["store_sales"], counts),
    "catalog_sales": lambda rng, counts: _gen_catalog_sales(
        rng, counts["catalog_sales"], counts),
    "web_sales": lambda rng, counts: _gen_web_sales(
        rng, counts["web_sales"], counts),
}


def _write_parquet(path: str, data: dict, rows_per_file: int,
                   date_cols: Sequence[str] = ()) -> None:
    import pyarrow as pa
    import pyarrow.parquet as pq
    os.makedirs(path, exist_ok=True)
    n = len(next(iter(data.values())))
    cols = {}
    for name, arr in data.items():
        if name in date_cols:
            cols[name] = pa.array(np.asarray(arr, dtype=np.int32),
                                  type=pa.int32()).cast(pa.date32())
        elif arr.dtype == object:
            base = next((x for x in arr if x is not None), 0)
            if isinstance(base, str):
                cols[name] = pa.array(list(arr), type=pa.string())
            elif isinstance(base, float):
                cols[name] = pa.array(
                    [None if x is None else float(x) for x in arr],
                    type=pa.float64())
            else:
                cols[name] = pa.array(
                    [None if x is None else int(x) for x in arr],
                    type=pa.int32())
        else:
            cols[name] = pa.array(arr)
    table = pa.table(cols)
    nfiles = max(1, -(-n // rows_per_file))
    for i in range(nfiles):
        part = table.slice(i * rows_per_file,
                           min(rows_per_file, n - i * rows_per_file))
        pq.write_table(part, os.path.join(path, f"part-{i:05d}.parquet"))


def generate_tpcds(data_dir: str, sf: float = 0.01, seed: int = 42,
                   tables: Sequence[str] = TABLES,
                   rows_per_file: int = 1 << 20) -> dict[str, int]:
    """Generate the pruned TPC-DS tables under ``data_dir/<table>/``.

    Returns {table: rows}.  Skips tables already generated at the current
    schema version (marker file); regenerates on version mismatch.
    """
    counts = table_row_counts(sf)
    written = {}
    for t in tables:
        out = os.path.join(data_dir, t)
        written[t] = counts[t]
        marker = os.path.join(out, f"_{_SCHEMA_VERSION}")
        if os.path.isdir(out) and os.path.exists(marker):
            continue
        if os.path.isdir(out):
            import shutil
            shutil.rmtree(out)
        rng = np.random.default_rng(seed + zlib.crc32(t.encode()) % 1000)
        data = _GENERATORS[t](rng, counts)
        _write_parquet(out, data, rows_per_file,
                       date_cols=("d_date",) if t == "date_dim" else ())
        with open(marker, "w") as f:
            f.write(_SCHEMA_VERSION + "\n")
    return written

"""SF-scalable TPC-DS-shaped data generator (column-pruned, parquet).

Generates the five tables the query slice uses — store_sales, date_dim,
item, customer, customer_address — with dsdgen-like row counts, key
ranges, null fractions, and surrogate-key conventions (d_date_sk epoch
2415022 = 1900-01-01, store_sales ~2.88M rows/SF).  Columns are pruned
to those the queries touch; distributions are synthetic (deterministic
numpy, seeded), NOT dsdgen bit-exact — this measures engine speed, not
dsdgen conformance.  Reference harness: TpcdsLikeSpark.scala (explicit
schemas + csv-to-parquet conversion), docs/benchmarks.md:104-147.
"""
from __future__ import annotations

import os
import zlib
from typing import Sequence

import numpy as np

__all__ = ["generate_tpcds", "table_row_counts", "TABLES"]

TABLES = ("date_dim", "item", "customer", "customer_address", "store_sales")

_DATE_SK_EPOCH = 2415022            # dsdgen: d_date_sk of 1900-01-01
_DATE_DIM_DAYS = 73049              # 1900-01-01 .. 2099-12-31
_SALES_DATE_LO = 35794              # days(1998-01-01 - 1900-01-01)
_SALES_DATE_HI = 37985              # days(2003-12-31 - 1900-01-01)

_CATEGORIES = ["Books", "Children", "Electronics", "Home", "Jewelry",
               "Men", "Music", "Shoes", "Sports", "Women"]
_STATES = ["AL", "AK", "AZ", "AR", "CA", "CO", "CT", "DE", "FL", "GA",
           "HI", "ID", "IL", "IN", "IA", "KS", "KY", "LA", "ME", "MD",
           "MA", "MI", "MN", "MS", "MO", "MT", "NE", "NV", "NH", "NJ",
           "NM", "NY", "NC", "ND", "OH", "OK", "OR", "PA", "RI", "SC",
           "SD", "TN", "TX", "UT", "VT", "VA", "WA", "WV", "WI", "WY"]


def table_row_counts(sf: float) -> dict[str, int]:
    """dsdgen-like scaling: store_sales linear in SF; dimensions sublinear
    (item SF1=18k/SF10~57k, customer SF1=100k/SF10~500k)."""
    sf = max(sf, 0.001)
    n_cust = max(200, int(100_000 * sf ** 0.7))
    return {
        "date_dim": _DATE_DIM_DAYS,
        "item": max(100, int(18_000 * sf ** 0.5)),
        "customer": n_cust,
        "customer_address": max(100, n_cust // 2),
        "store_sales": max(1000, int(2_880_000 * sf)),
    }


def _gen_date_dim(counts) -> dict[str, np.ndarray]:
    days = np.arange(_DATE_DIM_DAYS, dtype=np.int64)
    dates = np.datetime64("1900-01-01") + days
    y = dates.astype("datetime64[Y]").astype(int) + 1970
    m = dates.astype("datetime64[M]").astype(int) % 12 + 1
    dom = (dates - dates.astype("datetime64[M]")).astype(int) + 1
    return {
        "d_date_sk": (days + _DATE_SK_EPOCH).astype(np.int32),
        "d_year": y.astype(np.int32),
        "d_moy": m.astype(np.int32),
        "d_dom": dom.astype(np.int32),
        "d_month_seq": ((y - 1900) * 12 + (m - 1)).astype(np.int32),
        "d_qoy": ((m - 1) // 3 + 1).astype(np.int32),
    }


def _with_nulls(rng, arr: np.ndarray, frac: float) -> np.ndarray:
    """Object array with ~frac nulls (None)."""
    out = arr.astype(object)
    if frac > 0:
        out[rng.random(len(arr)) < frac] = None
    return out


def _gen_item(rng, n: int) -> dict[str, np.ndarray]:
    brand_id = rng.integers(1001001, 1010016, n).astype(np.int32)
    cat_idx = rng.integers(0, len(_CATEGORIES), n)
    return {
        "i_item_sk": np.arange(1, n + 1, dtype=np.int32),
        "i_brand_id": brand_id,
        "i_brand": np.array([f"Brand#{b % 100}" for b in brand_id],
                            dtype=object),
        "i_category_id": (cat_idx + 1).astype(np.int32),
        "i_category": _with_nulls(
            rng, np.array([_CATEGORIES[i] for i in cat_idx], dtype=object),
            0.005),
        "i_current_price": _with_nulls(
            rng, np.round(rng.uniform(0.09, 99.99, n), 2), 0.01),
        "i_manufact_id": rng.integers(1, 1001, n).astype(np.int32),
        "i_manager_id": rng.integers(1, 101, n).astype(np.int32),
    }


def _gen_customer(rng, n: int, n_addr: int) -> dict[str, np.ndarray]:
    return {
        "c_customer_sk": np.arange(1, n + 1, dtype=np.int32),
        "c_current_addr_sk": _with_nulls(
            rng, rng.integers(1, n_addr + 1, n).astype(np.int32), 0.01),
    }


def _gen_customer_address(rng, n: int) -> dict[str, np.ndarray]:
    return {
        "ca_address_sk": np.arange(1, n + 1, dtype=np.int32),
        "ca_state": _with_nulls(
            rng, np.array([_STATES[i] for i in
                           rng.integers(0, len(_STATES), n)], dtype=object),
            0.01),
    }


def _gen_store_sales(rng, n: int, n_items: int, n_cust: int):
    qty = rng.integers(1, 101, n).astype(np.int32)
    price = np.round(np.exp(rng.normal(2.5, 1.0, n)).clip(0.01, 300.0), 2)
    return {
        "ss_sold_date_sk": _with_nulls(
            rng, (rng.integers(_SALES_DATE_LO, _SALES_DATE_HI + 1, n)
                  + _DATE_SK_EPOCH).astype(np.int32), 0.02),
        "ss_item_sk": rng.integers(1, n_items + 1, n).astype(np.int32),
        "ss_customer_sk": _with_nulls(
            rng, rng.integers(1, n_cust + 1, n).astype(np.int32), 0.04),
        "ss_quantity": qty,
        "ss_sales_price": price,
        "ss_ext_sales_price": np.round(price * qty, 2),
    }


def _write_parquet(path: str, data: dict, rows_per_file: int) -> None:
    import pyarrow as pa
    import pyarrow.parquet as pq
    os.makedirs(path, exist_ok=True)
    n = len(next(iter(data.values())))
    cols = {}
    for name, arr in data.items():
        if arr.dtype == object:
            base = next((x for x in arr if x is not None), 0)
            if isinstance(base, str):
                cols[name] = pa.array(list(arr), type=pa.string())
            elif isinstance(base, float):
                cols[name] = pa.array(
                    [None if x is None else float(x) for x in arr],
                    type=pa.float64())
            else:
                cols[name] = pa.array(
                    [None if x is None else int(x) for x in arr],
                    type=pa.int32())
        else:
            cols[name] = pa.array(arr)
    table = pa.table(cols)
    nfiles = max(1, -(-n // rows_per_file))
    for i in range(nfiles):
        part = table.slice(i * rows_per_file,
                           min(rows_per_file, n - i * rows_per_file))
        pq.write_table(part, os.path.join(path, f"part-{i:05d}.parquet"))


def generate_tpcds(data_dir: str, sf: float = 0.01, seed: int = 42,
                   tables: Sequence[str] = TABLES,
                   rows_per_file: int = 1 << 20) -> dict[str, int]:
    """Generate the pruned TPC-DS tables under ``data_dir/<table>/``.

    Returns {table: rows}.  Skips tables whose directory already exists
    (delete the dir to regenerate).
    """
    counts = table_row_counts(sf)
    written = {}
    for t in tables:
        out = os.path.join(data_dir, t)
        written[t] = counts[t]
        if os.path.isdir(out) and os.listdir(out):
            continue
        rng = np.random.default_rng(seed + zlib.crc32(t.encode()) % 1000)
        if t == "date_dim":
            data = _gen_date_dim(counts)
        elif t == "item":
            data = _gen_item(rng, counts["item"])
        elif t == "customer":
            data = _gen_customer(rng, counts["customer"],
                                 counts["customer_address"])
        elif t == "customer_address":
            data = _gen_customer_address(rng, counts["customer_address"])
        elif t == "store_sales":
            data = _gen_store_sales(rng, counts["store_sales"],
                                    counts["item"], counts["customer"])
        else:
            raise ValueError(f"unknown table {t}")
        _write_parquet(out, data, rows_per_file)
    return written
